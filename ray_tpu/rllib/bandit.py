"""Contextual bandits: LinUCB and linear Thompson sampling.

Ref analogue: rllib/algorithms/bandit (bandit_linucb.py BanditLinUCB,
bandit_lints.py BanditLinTS over the DisjointLinearUCB/TS exploration
models). One-step decision problems: the env's observation is the
context x, actions are discrete arms, episodes are length-1 (the env
may also be a plain gymnasium env — only (obs, action, reward) rows
are consumed; bootstrapping never crosses steps).

Per-arm ridge regression kept in closed form on the driver (numpy —
these are tiny d x d solves, not MXU work): A_a = I*lam + sum x x^T,
b_a = sum r x.
  LinUCB picks argmax_a  theta_a^T x + alpha * sqrt(x^T A_a^-1 x).
  LinTS  picks argmax_a  theta~^T x,  theta~ ~ N(theta_a, v^2 A_a^-1).
Exploration state (A, b) lives in the learner; rollout actors get the
derived (theta, A_inv) matrices broadcast like any policy weights.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .env_runner import TransitionEnvRunner
from .sample_batch import ACTIONS, OBS, REWARDS, SampleBatch


class _LinearBanditPolicy:
    """Rollout-side arm chooser; numpy, interchangeable with the other
    policies (compute_action signature)."""

    def __init__(self, num_arms: int, dim: int, *, alpha: float,
                 ts_scale: float, mode: str, seed: int = 0):
        self.num_arms = num_arms
        self.dim = dim
        self.alpha = alpha
        self.ts_scale = ts_scale
        self.mode = mode  # "ucb" | "ts"
        self.weights = {
            "theta": np.zeros((num_arms, dim), np.float32),
            "a_inv": np.stack([np.eye(dim, dtype=np.float32)
                               for _ in range(num_arms)]),
        }

    def set_weights(self, weights):
        self.weights = weights

    def get_weights(self):
        return self.weights

    def compute_action(self, obs: np.ndarray,
                       rng: np.random.RandomState):
        x = np.asarray(obs, np.float32).reshape(-1)
        theta = self.weights["theta"]
        a_inv = self.weights["a_inv"]
        if self.mode == "ucb":
            mean = theta @ x
            bonus = np.sqrt(np.einsum("i,aij,j->a", x, a_inv, x))
            scores = mean + self.alpha * bonus
        else:
            scores = np.array([
                rng.multivariate_normal(
                    theta[a], (self.ts_scale ** 2) * a_inv[a]
                ) @ x
                for a in range(self.num_arms)
            ])
        return int(np.argmax(scores)), 0.0, 0.0


class BanditConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.num_env_runners = 1
        self.rollout_fragment_length = 32
        self.alpha: float = 1.0        # LinUCB exploration width
        self.ts_scale: float = 1.0     # LinTS posterior scale
        self.ridge_lambda: float = 1.0
        self.mode: str = "ucb"

    def build(self) -> "Bandit":
        return Bandit(self.copy())


class BanditLinUCBConfig(BanditConfig):
    def __init__(self):
        super().__init__()
        self.mode = "ucb"


class BanditLinTSConfig(BanditConfig):
    def __init__(self):
        super().__init__()
        self.mode = "ts"


class Bandit(Algorithm):
    """training_step: sample contexts with the current arm posteriors,
    then fold the (x, a, r) rows into the per-arm ridge state and
    broadcast fresh (theta, A_inv)."""

    def _make_policy_factory(self, obs_dim: int, num_actions: int):
        self._require_discrete()
        c = self.config

        def policy_factory(num_arms=num_actions, dim=obs_dim,
                           alpha=c.alpha, ts=c.ts_scale, mode=c.mode,
                           seed=c.seed):
            return _LinearBanditPolicy(
                num_arms, dim, alpha=alpha, ts_scale=ts, mode=mode,
                seed=seed,
            )

        return policy_factory

    def _runner_class(self):
        return TransitionEnvRunner

    def _build_learner(self, policy):
        c = self.config
        d, k = self._obs_dim, self._num_actions
        self._A = np.stack([
            np.eye(d, dtype=np.float64) * c.ridge_lambda
            for _ in range(k)
        ])
        self._b = np.zeros((k, d), np.float64)
        self._steps = 0
        self._reward_sum = 0.0
        return None  # closed-form: no gradient learner

    def get_weights(self):
        a_inv = np.stack([np.linalg.inv(A) for A in self._A])
        theta = np.einsum("aij,aj->ai", a_inv, self._b)
        return {
            "theta": theta.astype(np.float32),
            "a_inv": a_inv.astype(np.float32),
        }

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        batches: List[SampleBatch] = ray_tpu.get(
            [r.sample.remote() for r in self.runners]
        )
        for batch in batches:
            obs = np.asarray(batch[OBS], np.float64)
            acts = np.asarray(batch[ACTIONS], np.int64)
            rews = np.asarray(batch[REWARDS], np.float64)
            for a in range(self._num_actions):
                m = acts == a
                if not m.any():
                    continue
                X = obs[m]
                self._A[a] += X.T @ X
                self._b[a] += rews[m] @ X
            self._steps += len(acts)
            self._reward_sum += float(rews.sum())

        weights = self.get_weights()
        ray_tpu.get(
            [r.set_weights.remote(weights) for r in self.runners]
        )
        return {
            "num_env_steps_sampled": self._steps,
            "mean_reward": self._reward_sum / max(1, self._steps),
        }
