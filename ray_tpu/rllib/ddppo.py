"""DD-PPO: decentralized distributed PPO.

Ref analogue: rllib/algorithms/ddppo (Wijmans 2019). Standard PPO
ships all rollouts to one central learner; DD-PPO removes that
bottleneck by giving EVERY rollout worker its own learner — each
worker samples its env, computes PPO gradients on its OWN batch, and
the gradients are averaged across workers each round (the reference
allreduces via torch.distributed inside the workers; here the
worker-learners return gradient pytrees and the driver averages and
broadcasts — same data flow, with the driver standing in for the
allreduce since workers are CPU actors, and on-TPU training goes
through the SPMD JaxTrainer path instead).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import AlgorithmConfig
from .core import ActorCriticModule
from .env_runner import EnvRunner
from .ppo import PPOConfig, PPOLearner
from .sample_batch import ACTIONS, ADVANTAGES, LOGPS, OBS, RETURNS


class DDPPOConfig(PPOConfig):
    def __init__(self):
        super().__init__()
        self.num_env_runners = 2
        self.sgd_rounds_per_iteration: int = 4

    def build(self) -> "DDPPO":
        return DDPPO(self.copy())


class _WorkerLearner(EnvRunner):
    """Rollout worker WITH an embedded PPO learner: samples its env,
    computes clipped-surrogate gradients on its own fresh batch, and
    applies externally averaged updates (ref: the per-worker learner
    in ddppo.py — "no central bottleneck")."""

    def __init__(self, env_creator, policy_factory, *, lr, clip,
                 vf_coeff, ent_coeff, seed=0,
                 rollout_fragment_length=200, gamma=0.99, lam=0.95):
        super().__init__(env_creator, policy_factory, seed,
                         rollout_fragment_length, gamma, lam)
        self._learner = PPOLearner(self.policy, lr, clip, vf_coeff,
                                   ent_coeff)
        self._grad_fn = None
        self._np_rng = np.random.RandomState(seed + 7)

    def _build_grad(self):
        import jax

        learner = self._learner

        def loss(params, batch):
            total, _ = learner.compute_loss(params, {}, batch)
            return total

        self._grad_fn = jax.jit(jax.value_and_grad(loss))

    def sample_and_grad(self) -> Dict[str, Any]:
        """One round: fresh rollout -> gradient pytree on it."""
        import jax
        import jax.numpy as jnp

        if self._grad_fn is None:
            self._build_grad()
        batch = self.sample()
        jb = {
            "obs": jnp.asarray(batch[OBS]),
            "actions": jnp.asarray(np.asarray(batch[ACTIONS],
                                              np.int32)),
            "old_logp": jnp.asarray(batch[LOGPS]),
            "adv": jnp.asarray(batch[ADVANTAGES]),
            "returns": jnp.asarray(batch[RETURNS]),
        }
        loss, grads = self._grad_fn(self._learner._params, jb)
        return {
            "grads": jax.tree.map(np.asarray, grads),
            "loss": float(loss),
            "count": batch.count,
        }

    def apply_gradients(self, avg_grads) -> None:
        """Apply the averaged gradient with the local optimizer (every
        worker holds identical params + opt state, so updates stay in
        lockstep — the DD-PPO invariant)."""
        import jax
        import jax.numpy as jnp
        import optax

        learner = self._learner
        grads = jax.tree.map(jnp.asarray, avg_grads)
        updates, learner._opt_state = learner._tx.update(
            grads, learner._opt_state, learner._params
        )
        learner._params = optax.apply_updates(learner._params, updates)
        self.policy.set_weights(
            jax.tree.map(np.asarray, learner._params)
        )

    def get_weights(self):
        return self._learner.get_weights()


class DDPPO:
    def __init__(self, config: DDPPOConfig):
        import ray_tpu

        self.config = config
        self.iteration = 0
        c = config
        creator = c.env_creator()
        probe = creator()
        obs_dim = int(np.prod(probe.observation_space.shape))
        if not hasattr(probe.action_space, "n"):
            raise ValueError("DDPPO supports discrete action spaces")
        num_actions = int(probe.action_space.n)
        if hasattr(probe, "close"):
            probe.close()

        def policy_factory(obs_dim=obs_dim, num_actions=num_actions,
                           hidden=c.hidden_size, seed=c.seed):
            from .policy import MLPPolicy

            # SAME seed everywhere: DD-PPO requires identical initial
            # params on every worker.
            return MLPPolicy(obs_dim, num_actions, hidden, seed)

        worker_cls = ray_tpu.remote(_WorkerLearner)
        self.workers = [
            worker_cls.remote(
                creator, policy_factory,
                lr=c.lr, clip=c.clip_param, vf_coeff=c.vf_loss_coeff,
                ent_coeff=c.entropy_coeff, seed=c.seed + i,
                rollout_fragment_length=c.rollout_fragment_length,
                gamma=c.gamma, lam=c.lambda_,
            )
            for i in range(c.num_env_runners)
        ]
        self._env_steps = 0

    def train(self) -> Dict[str, Any]:
        import ray_tpu

        self.iteration += 1
        c = self.config
        losses: List[float] = []
        for _ in range(c.sgd_rounds_per_iteration):
            outs = ray_tpu.get([
                w.sample_and_grad.remote() for w in self.workers
            ])
            self._env_steps += sum(o["count"] for o in outs)
            losses.append(float(np.mean([o["loss"] for o in outs])))
            # The stand-in allreduce: average gradient pytrees.
            grads = [o["grads"] for o in outs]

            def avg(*gs):
                return np.mean(np.stack(gs), axis=0)

            import jax

            avg_grads = jax.tree.map(avg, *grads)
            ray_tpu.get([
                w.apply_gradients.remote(avg_grads)
                for w in self.workers
            ])

        ep_stats = ray_tpu.get(
            [w.episode_stats.remote() for w in self.workers]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": self._env_steps,
            "loss": losses[-1] if losses else float("nan"),
        }

    def get_weights(self):
        import ray_tpu

        return ray_tpu.get(self.workers[0].get_weights.remote())

    def stop(self):
        import ray_tpu

        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
