"""PG: vanilla policy gradient (REINFORCE).

Ref analogue: rllib/algorithms/pg — the minimal on-policy baseline:
no critic, no clipping, no epochs; the gradient is
grad log pi(a|s) * R_t with monte-carlo returns (GAE with lambda=1 /
values=0 reduces to exactly this, so the runner plane is shared with
A2C/PPO and the learner drops the value head terms).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .a2c import A2C
from .algorithm import AlgorithmConfig
from .core import ActorCriticModule, Learner


class PGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3

    def build(self) -> "PG":
        return PG(self.copy())


class PGLearner(Learner):
    """-E[log pi(a|s) * R] — returns as the signal, no baseline."""

    def __init__(self, policy, lr: float):
        super().__init__(policy.get_weights(), lr=lr)

    def compute_loss(self, params, target, batch):
        import jax
        import jax.numpy as jnp

        logits, _ = ActorCriticModule.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=1
        )[:, 0]
        ret = batch["returns"]
        ret_n = (ret - ret.mean()) / (ret.std() + 1e-8)
        pi_loss = -(logp * ret_n).mean()
        return pi_loss, {"policy_loss": pi_loss}


class PG(A2C):
    """Shares A2C's synchronous driver; only the loss differs."""

    def _build_learner(self, policy):
        return PGLearner(policy, self.config.lr)

    def update_minibatch(self, mb) -> Dict[str, Any]:
        from .sample_batch import ACTIONS, OBS, RETURNS

        return self.learner.update_device({
            "obs": mb[OBS],
            "actions": np.asarray(mb[ACTIONS], dtype=np.int32),
            "returns": mb[RETURNS],
        })
