"""MARWIL: monotonic advantage re-weighted imitation learning (offline).

Ref analogue: rllib/algorithms/marwil (Wang 2018) — behavior cloning
weighted by exp(beta * advantage): a learned value head estimates
V(s), the advantage A = R - V(s) against the logged monte-carlo return
column, and the policy term up-weights better-than-average logged
actions. ``beta = 0`` reduces exactly to BC (the reference implements
BC as a MARWIL subclass; here both sit on the offline Dataset
pipeline). Discrete action spaces; trains the shared ActorCriticModule
pytree so the result drops into MLPPolicy rollouts.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .algorithm import AlgorithmConfig
from .core import ActorCriticModule, Learner


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.dataset = None          # ray_tpu.data Dataset of logged rows
        self.obs_column = "obs"
        self.action_column = "action"
        self.return_column = "return"  # per-row monte-carlo return R_t
        self.beta: float = 1.0         # 0.0 -> plain BC
        self.vf_coeff: float = 1.0
        self.num_actions: Optional[int] = None
        # Advantages are normalized by a running estimate of E[A^2]
        # (the paper's c^2 normalizer) so beta is scale-free.
        self.moving_average_sqd_adv_norm_update_rate: float = 1e-2

    def offline_data(self, dataset, *, obs_column="obs",
                     action_column="action",
                     return_column="return") -> "MARWILConfig":
        self.dataset = dataset
        self.obs_column = obs_column
        self.action_column = action_column
        self.return_column = return_column
        return self

    def build(self) -> "MARWIL":
        if self.dataset is None:
            raise ValueError(
                "MARWILConfig.offline_data(dataset=...) required"
            )
        if self.num_actions is None:
            raise ValueError("MARWILConfig.training(num_actions=...) "
                             "required (discrete)")
        return MARWIL(self.copy())


class MARWILLearner(Learner):
    """Loss = -E[exp(beta * A / c) * logp(a|s)] + c_v * mse(V, R),
    with A = R - V(s) (stop-grad through the policy term) and c the
    running sqrt(E[A^2]) normalizer carried in the batch."""

    def __init__(self, params, *, lr: float, beta: float,
                 vf_coeff: float):
        super().__init__(params, lr=lr)
        self._beta = beta
        self._vf_coeff = vf_coeff

    def compute_loss(self, params, target, batch):
        import jax
        import jax.numpy as jnp

        logits, values = ActorCriticModule.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=1
        )[:, 0]
        adv = batch["returns"] - values
        vf_loss = (adv ** 2).mean()
        # exp-weights use the stop-gradded advantage over the running
        # normalizer; clip the exponent for numerical safety.
        w = jnp.exp(jnp.clip(
            self._beta * jax.lax.stop_gradient(adv) / batch["adv_norm"],
            -10.0, 10.0,
        ))
        pi_loss = -(w * logp).mean()
        return pi_loss + self._vf_coeff * vf_loss, {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "mean_weight": w.mean(),
            "sqd_adv": jax.lax.stop_gradient((adv ** 2).mean()),
        }


class MARWIL:
    """Offline trainer: train() = one pass of minibatch updates over the
    dataset's batch iterator (same driver shape as BC)."""

    def __init__(self, config: MARWILConfig):
        c = config
        self.config = c
        self.iteration = 0
        probe = next(iter(
            c.dataset.iter_batches(batch_size=1, batch_format="numpy")
        ))
        obs = np.asarray(probe[c.obs_column])
        self._obs_dim = int(np.prod(obs.shape[1:]))
        module = ActorCriticModule(self._obs_dim, int(c.num_actions),
                                  c.hidden_size, c.seed)
        self.learner = MARWILLearner(
            module.init_params(), lr=c.lr, beta=c.beta,
            vf_coeff=c.vf_coeff,
        )
        self._sqd_adv_norm = 1.0  # running E[A^2]

    def train(self) -> Dict[str, Any]:
        c = self.config
        self.iteration += 1
        stats: Dict[str, Any] = {}
        rows = 0
        rate = c.moving_average_sqd_adv_norm_update_rate
        for batch in c.dataset.iter_batches(
            batch_size=c.minibatch_size, batch_format="numpy"
        ):
            obs = np.asarray(batch[c.obs_column], np.float32)
            obs = obs.reshape(len(obs), -1)
            stats = self.learner.update_device({
                "obs": obs,
                "actions": np.asarray(batch[c.action_column], np.int32),
                "returns": np.asarray(batch[c.return_column],
                                      np.float32),
                "adv_norm": np.float32(
                    np.sqrt(self._sqd_adv_norm) + 1e-8
                ),
            })
            # Running normalizer update needs the batch's E[A^2]: one
            # small host sync per minibatch (scalar).
            self._sqd_adv_norm += rate * (
                float(stats["sqd_adv"]) - self._sqd_adv_norm
            )
            rows += len(obs)
        out = {k: float(v) for k, v in stats.items()}
        out.update({
            "training_iteration": self.iteration,
            "num_rows_trained": rows,
            "sqd_adv_norm": self._sqd_adv_norm,
        })
        return out

    def get_weights(self):
        return self.learner.get_weights()

    def get_policy(self):
        """Rollout-ready MLPPolicy carrying the trained weights."""
        from .policy import MLPPolicy

        c = self.config
        policy = MLPPolicy(self._obs_dim, int(c.num_actions),
                           c.hidden_size, c.seed)
        policy.set_weights(self.get_weights())
        return policy

    def stop(self):
        pass
