"""ARS: augmented random search.

Ref analogue: rllib/algorithms/ars (Mania 2018 "Simple random search
provides a competitive approach to RL"). Same antithetic
parameter-space exploration plane as ES (es.py EpisodeEvaluator + seed
shipping) with ARS's two changes: only the TOP-K directions by
max(F+, F-) contribute, and the step is normalized by the standard
deviation of the selected returns instead of rank shaping:
    theta += alpha / (k * sigma_R) * sum_topk (F+ - F-) * eps.
"""

from __future__ import annotations

import numpy as np

from .es import ESConfig, _EvolutionBase


class ARSConfig(ESConfig):
    def __init__(self):
        super().__init__()
        self.sigma = 0.1
        self.step_size = 0.05
        self.top_directions: int = 8   # k <= episodes_per_batch

    def build(self) -> "ARS":
        return ARS(self.copy())


class ARS(_EvolutionBase):
    def _apply_update(self, seeds, f_pos, f_neg):
        c = self.config
        k = min(c.top_directions, len(seeds))
        order = np.argsort(np.maximum(f_pos, f_neg))[::-1][:k]
        used = np.concatenate([f_pos[order], f_neg[order]])
        sigma_r = float(used.std()) + 1e-8
        g = np.zeros_like(self.theta)
        for i in order:
            g += (f_pos[i] - f_neg[i]) * self._noise(seeds[i])
        self.theta = self.theta + c.step_size / (k * sigma_r) * g
