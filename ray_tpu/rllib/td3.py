"""TD3: twin-delayed deterministic policy gradient (continuous control).

Ref analogue: rllib/algorithms/td3 — DDPG plus the three TD3 fixes
(Fujimoto 2018): twin critics with min-target, target-policy smoothing
(clipped noise on the target action), and delayed actor updates. Built
on the shared Learner layer (core.py): the critic TD loss is
``compute_loss`` with polyak targets handled by the base class; the
delayed actor step is a second jitted update applied every
``policy_delay`` critic steps.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .core import (
    DeterministicActorModule,
    QModule,
    TwinCriticLearner,
)
from .env_runner import NEXT_OBS, TransitionEnvRunner
from .replay_buffers import ReplayBuffer
from .sample_batch import ACTIONS, DONES, OBS, REWARDS, SampleBatch


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_size: int = 100_000
        self.num_steps_sampled_before_learning_starts: int = 500
        self.num_updates_per_iteration: int = 64
        self.tau: float = 0.005
        self.policy_delay: int = 2
        self.target_noise: float = 0.2
        self.target_noise_clip: float = 0.5
        self.exploration_noise: float = 0.1

    def build(self) -> "TD3":
        return TD3(self.copy())


class TD3Learner(TwinCriticLearner):
    """TD3's critic loss (min-target + target-policy smoothing) on the
    shared twin-critic machinery (core.py TwinCriticLearner: masked
    actor subtree, own actor optimizer, critic-preserving round-trips);
    the actor step is DELAYED by the algorithm loop."""

    def __init__(self, policy, cfg, obs_dim: int, act_dim: int,
                 low, high):
        import jax.numpy as jnp

        super().__init__(
            policy.get_weights(), obs_dim=obs_dim, act_dim=act_dim,
            hidden=cfg.hidden_size, lr=cfg.lr, tau=cfg.tau,
            seed=cfg.seed,
        )
        self._gamma = cfg.gamma
        self._noise = cfg.target_noise
        self._noise_clip = cfg.target_noise_clip
        self._low = jnp.asarray(np.asarray(low, np.float32))
        self._high = jnp.asarray(np.asarray(high, np.float32))
        self._rng = np.random.RandomState(cfg.seed + 3)

    # Actions are stored in ENV units; critics consume [-1, 1].
    def _from_env(self, a):
        import jax.numpy as jnp

        u = (a - self._low) / (self._high - self._low) * 2.0 - 1.0
        return jnp.clip(u, -1.0, 1.0)

    def compute_loss(self, params, target, batch):
        import jax
        import jax.numpy as jnp

        obs, nxt = batch["obs"], batch["next_obs"]
        act = self._from_env(batch["actions"])
        # Target-policy smoothing: clipped noise on the target action.
        a2 = DeterministicActorModule.forward(target["actor"], nxt)
        noise = jnp.clip(
            batch["eps"] * self._noise,
            -self._noise_clip, self._noise_clip,
        )
        a2 = jnp.clip(a2 + noise, -1.0, 1.0)
        tq = jnp.minimum(
            QModule.forward(target["q1"], nxt, a2),
            QModule.forward(target["q2"], nxt, a2),
        )
        backup = jax.lax.stop_gradient(
            batch["rew"] + self._gamma * (1.0 - batch["done"]) * tq
        )
        q1 = QModule.forward(params["q1"], obs, act)
        q2 = QModule.forward(params["q2"], obs, act)
        critic_loss = ((q1 - backup) ** 2 + (q2 - backup) ** 2).mean()
        return critic_loss, {
            "critic_loss": critic_loss,
            "q1_mean": q1.mean(),
        }

    def learn_on_batch(self, batch: SampleBatch, *, do_actor: bool
                       ) -> Dict[str, Any]:
        """One critic step (+ delayed actor step). Stats stay ON DEVICE
        — the caller float()s once per iteration, so the 64-update inner
        loop stays async-dispatched (core.py update_device)."""
        n = batch.count
        eps = self._rng.randn(n, self._act_dim).astype(np.float32)
        np_batch = {
            "obs": batch[OBS],
            "actions": np.asarray(batch[ACTIONS], np.float32),
            "rew": batch[REWARDS],
            "done": np.asarray(batch[DONES], np.float32),
            "next_obs": batch[NEXT_OBS],
            "eps": eps,
        }
        stats = self.update_device(np_batch)
        if do_actor:
            stats = {**stats, **self.actor_update(np_batch)}
        return stats


class _TD3EnvRunner(TransitionEnvRunner):
    """Transition collection with the deterministic + noise policy."""


class TD3(Algorithm):
    def _make_policy_factory(self, obs_dim: int, act_dim: int):
        from .policy import DeterministicPolicy

        if not getattr(self, "_continuous", False):
            raise ValueError(
                "TD3 supports Box (continuous) action spaces only"
            )
        config = self.config
        low, high = self._action_low, self._action_high

        def policy_factory(obs_dim=obs_dim, act_dim=act_dim,
                           hidden=config.hidden_size, seed=config.seed,
                           noise=config.exploration_noise):
            return DeterministicPolicy(
                obs_dim, act_dim, low, high, hidden, seed,
                exploration_noise=noise,
            )

        return policy_factory

    def _runner_class(self):
        return _TD3EnvRunner

    def _build_learner(self, policy):
        c = self.config
        self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._env_steps = 0
        return TD3Learner(policy, c, self._obs_dim, self._num_actions,
                          self._action_low, self._action_high)

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        batches: List[SampleBatch] = ray_tpu.get(
            [r.sample.remote() for r in self.runners]
        )
        for b in batches:
            self.buffer.add(b)
            self._env_steps += b.count

        stats: Dict[str, Any] = {}
        num_updates = 0
        if self._env_steps >= c.num_steps_sampled_before_learning_starts:
            for i in range(c.num_updates_per_iteration):
                mb = self.buffer.sample(c.minibatch_size)
                # Merge (not replace): the delayed actor step only runs
                # every policy_delay updates — its stats must survive
                # the critic-only updates after it.
                stats.update(self.learner.learn_on_batch(
                    mb, do_actor=(i % c.policy_delay == 0)
                ))
                num_updates += 1
            # ONE host sync for the whole update loop.
            stats = {k: float(v) for k, v in stats.items()}
            weights = self.learner.get_weights()
            ray_tpu.get(
                [r.set_weights.remote(weights) for r in self.runners]
            )

        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": self._env_steps,
            "num_learner_updates": num_updates,
            "buffer_size": len(self.buffer),
            **stats,
        }
