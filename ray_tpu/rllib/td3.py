"""TD3: twin-delayed deterministic policy gradient (continuous control).

Ref analogue: rllib/algorithms/td3 — DDPG plus the three TD3 fixes
(Fujimoto 2018): twin critics with min-target, target-policy smoothing
(clipped noise on the target action), and delayed actor updates. Built
on the shared Learner layer (core.py): the critic TD loss is
``compute_loss`` with polyak targets handled by the base class; the
delayed actor step is a second jitted update applied every
``policy_delay`` critic steps.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .core import DeterministicActorModule, Learner, QModule
from .env_runner import NEXT_OBS, TransitionEnvRunner
from .replay_buffers import ReplayBuffer
from .sample_batch import ACTIONS, DONES, OBS, REWARDS, SampleBatch


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_size: int = 100_000
        self.num_steps_sampled_before_learning_starts: int = 500
        self.num_updates_per_iteration: int = 64
        self.tau: float = 0.005
        self.policy_delay: int = 2
        self.target_noise: float = 0.2
        self.target_noise_clip: float = 0.5
        self.exploration_noise: float = 0.1

    def build(self) -> "TD3":
        return TD3(self.copy())


class TD3Learner(Learner):
    """Critic loss through the shared Learner plumbing; the delayed
    actor step is its own jitted function updating actor params + its
    polyak target."""

    def __init__(self, policy, cfg, obs_dim: int, act_dim: int,
                 low, high):
        import jax
        import jax.numpy as jnp
        import optax

        seed = cfg.seed
        params = {
            "actor": policy.get_weights(),
            "q1": QModule(obs_dim, act_dim, cfg.hidden_size,
                          seed + 1).init_params(),
            "q2": QModule(obs_dim, act_dim, cfg.hidden_size,
                          seed + 2).init_params(),
        }
        # Critic targets polyak in the base update; the ACTOR target is
        # seeded below and synced ONLY by the delayed actor step — the
        # base passes non-listed target entries through untouched.
        super().__init__(params, lr=cfg.lr, target_keys=("q1", "q2"),
                         tau=cfg.tau)
        self._target["actor"] = self._params["actor"]
        # The base optimizer must NOT touch actor params: a shared Adam
        # would keep applying actor momentum on every critic-only step
        # (zero grads != zero update under Adam), silently defeating the
        # delayed-policy mechanism. Mask the actor subtree; the delayed
        # actor step below has its own optimizer + state.
        labels = {
            k: jax.tree.map(
                lambda _: "frozen" if k == "actor" else "train", v
            )
            for k, v in self._params.items()
        }
        self._tx = optax.multi_transform(
            {"train": optax.adam(cfg.lr), "frozen": optax.set_to_zero()},
            labels,
        )
        self._opt_state = self._tx.init(self._params)
        self._atx = optax.adam(cfg.lr)
        self._aopt_state = self._atx.init(self._params["actor"])
        self._gamma = cfg.gamma
        self._noise = cfg.target_noise
        self._noise_clip = cfg.target_noise_clip
        self._low = jnp.asarray(np.asarray(low, np.float32))
        self._high = jnp.asarray(np.asarray(high, np.float32))
        self._rng = np.random.RandomState(seed + 3)
        self._act_dim = act_dim
        self._jit_actor = None

    # Actions are stored in ENV units; critics consume [-1, 1].
    def _from_env(self, a):
        import jax.numpy as jnp

        u = (a - self._low) / (self._high - self._low) * 2.0 - 1.0
        return jnp.clip(u, -1.0, 1.0)

    def compute_loss(self, params, target, batch):
        import jax
        import jax.numpy as jnp

        obs, nxt = batch["obs"], batch["next_obs"]
        act = self._from_env(batch["actions"])
        # Target-policy smoothing: clipped noise on the target action.
        a2 = DeterministicActorModule.forward(target["actor"], nxt)
        noise = jnp.clip(
            batch["eps"] * self._noise,
            -self._noise_clip, self._noise_clip,
        )
        a2 = jnp.clip(a2 + noise, -1.0, 1.0)
        tq = jnp.minimum(
            QModule.forward(target["q1"], nxt, a2),
            QModule.forward(target["q2"], nxt, a2),
        )
        backup = jax.lax.stop_gradient(
            batch["rew"] + self._gamma * (1.0 - batch["done"]) * tq
        )
        q1 = QModule.forward(params["q1"], obs, act)
        q2 = QModule.forward(params["q2"], obs, act)
        critic_loss = ((q1 - backup) ** 2 + (q2 - backup) ** 2).mean()
        return critic_loss, {
            "critic_loss": critic_loss,
            "q1_mean": q1.mean(),
        }

    def actor_update(self, batch: Dict[str, np.ndarray]
                     ) -> Dict[str, Any]:
        """Delayed policy step: maximize Q1(s, pi(s)) with the actor's
        OWN optimizer/state, then polyak-sync the actor target (its only
        sync point — critic targets sync in the base update)."""
        import jax
        import jax.numpy as jnp
        import optax

        if self._jit_actor is None:
            tau = self._tau

            def aloss(actor, q1, obs):
                a = DeterministicActorModule.forward(actor, obs)
                return -QModule.forward(q1, obs, a).mean()

            def upd(actor, aopt_state, q1, atarget, obs):
                loss, grads = jax.value_and_grad(aloss)(
                    actor, jax.lax.stop_gradient(q1), obs,
                )
                updates, aopt_state = self._atx.update(
                    grads, aopt_state, actor
                )
                actor = optax.apply_updates(actor, updates)
                atarget = jax.tree.map(
                    lambda t, p: (1.0 - tau) * t + tau * p,
                    atarget, actor,
                )
                return actor, aopt_state, atarget, loss

            self._jit_actor = jax.jit(upd)
        actor, self._aopt_state, atarget, loss = self._jit_actor(
            self._params["actor"], self._aopt_state,
            self._params["q1"], self._target["actor"],
            jnp.asarray(batch["obs"]),
        )
        self._params = {**self._params, "actor": actor}
        self._target = {**self._target, "actor": atarget}
        return {"actor_loss": loss}  # device value; caller syncs

    def learn_on_batch(self, batch: SampleBatch, *, do_actor: bool
                       ) -> Dict[str, Any]:
        """One critic step (+ delayed actor step). Stats stay ON DEVICE
        — the caller float()s once per iteration, so the 64-update inner
        loop stays async-dispatched (core.py update_device)."""
        n = batch.count
        eps = self._rng.randn(n, self._act_dim).astype(np.float32)
        np_batch = {
            "obs": batch[OBS],
            "actions": np.asarray(batch[ACTIONS], np.float32),
            "rew": batch[REWARDS],
            "done": np.asarray(batch[DONES], np.float32),
            "next_obs": batch[NEXT_OBS],
            "eps": eps,
        }
        stats = self.update_device(np_batch)
        if do_actor:
            stats = {**stats, **self.actor_update(np_batch)}
        return stats

    def get_weights(self):
        """ACTOR weights only — what runners' rollout policy consumes."""
        import jax

        return jax.tree.map(np.asarray, self._params["actor"])

    def set_weights(self, weights):
        """Accepts either a full {actor, q1, q2} tree or (matching
        get_weights) an actor-only tree, merged into the full params —
        the inherited round-trip must not drop the critics."""
        import jax
        import jax.numpy as jnp

        if isinstance(weights, dict) and "q1" in weights:
            super().set_weights(weights)
        else:
            self._params = {
                **self._params,
                "actor": jax.tree.map(jnp.asarray, weights),
            }

    def get_state(self):
        import jax

        return {
            "params": jax.tree.map(np.asarray, self._params),
            "target": jax.tree.map(np.asarray, self._target),
            "num_updates": self.num_updates,
        }


class _TD3EnvRunner(TransitionEnvRunner):
    """Transition collection with the deterministic + noise policy."""


class TD3(Algorithm):
    def _make_policy_factory(self, obs_dim: int, act_dim: int):
        from .policy import DeterministicPolicy

        if not getattr(self, "_continuous", False):
            raise ValueError(
                "TD3 supports Box (continuous) action spaces only"
            )
        config = self.config
        low, high = self._action_low, self._action_high

        def policy_factory(obs_dim=obs_dim, act_dim=act_dim,
                           hidden=config.hidden_size, seed=config.seed,
                           noise=config.exploration_noise):
            return DeterministicPolicy(
                obs_dim, act_dim, low, high, hidden, seed,
                exploration_noise=noise,
            )

        return policy_factory

    def _runner_class(self):
        return _TD3EnvRunner

    def _build_learner(self, policy):
        c = self.config
        self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._env_steps = 0
        return TD3Learner(policy, c, self._obs_dim, self._num_actions,
                          self._action_low, self._action_high)

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        batches: List[SampleBatch] = ray_tpu.get(
            [r.sample.remote() for r in self.runners]
        )
        for b in batches:
            self.buffer.add(b)
            self._env_steps += b.count

        stats: Dict[str, Any] = {}
        num_updates = 0
        if self._env_steps >= c.num_steps_sampled_before_learning_starts:
            for i in range(c.num_updates_per_iteration):
                mb = self.buffer.sample(c.minibatch_size)
                # Merge (not replace): the delayed actor step only runs
                # every policy_delay updates — its stats must survive
                # the critic-only updates after it.
                stats.update(self.learner.learn_on_batch(
                    mb, do_actor=(i % c.policy_delay == 0)
                ))
                num_updates += 1
            # ONE host sync for the whole update loop.
            stats = {k: float(v) for k, v in stats.items()}
            weights = self.learner.get_weights()
            ray_tpu.get(
                [r.set_weights.remote(weights) for r in self.runners]
            )

        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": self._env_steps,
            "num_learner_updates": num_updates,
            "buffer_size": len(self.buffer),
            **stats,
        }
