"""BC: offline behavior cloning from a logged-experience Dataset.

Ref analogue: rllib/algorithms/bc (+ the offline data stack in
rllib/offline/): instead of EnvRunners, training consumes a
``ray_tpu.data`` Dataset of logged (obs, action) rows — the offline
pipeline IS the data layer, streaming batches into a jax supervised
update on the accelerator. Discrete actions train a categorical policy
(cross-entropy); continuous actions train a squashed-Gaussian mean
(tanh-MSE), so the resulting weights drop into the same rollout
policies the online algorithms use.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .algorithm import AlgorithmConfig


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.dataset = None          # ray_tpu.data Dataset of logged rows
        self.obs_column = "obs"
        self.action_column = "action"
        # "discrete" (int actions -> categorical CE) or "continuous"
        # (float vectors -> tanh-squashed mean regression).
        self.action_space: str = "discrete"
        self.num_actions: Optional[int] = None   # discrete only
        self.action_low = None                   # continuous only
        self.action_high = None

    def offline_data(self, dataset, *, obs_column="obs",
                     action_column="action") -> "BCConfig":
        self.dataset = dataset
        self.obs_column = obs_column
        self.action_column = action_column
        return self

    def build(self) -> "BC":
        if self.dataset is None:
            raise ValueError("BCConfig.offline_data(dataset=...) required")
        return BC(self.copy())


class BC:
    """Offline trainer: train() = one pass of minibatch updates over the
    dataset's batch iterator."""

    def __init__(self, config: BCConfig):
        import jax

        self.config = config
        self.iteration = 0
        c = config
        probe = next(iter(
            c.dataset.iter_batches(batch_size=1, batch_format="numpy")
        ))
        obs = np.asarray(probe[c.obs_column])
        self._obs_dim = int(np.prod(obs.shape[1:]))
        if c.action_space == "discrete":
            if c.num_actions is None:
                raise ValueError("discrete BC needs num_actions")
            self._act_dim = int(c.num_actions)
        else:
            act = np.asarray(probe[c.action_column])
            self._act_dim = int(np.prod(act.shape[1:])) or 1
        self._build_learner()

    # ---- learner -----------------------------------------------------------

    def _build_learner(self):
        import jax
        import jax.numpy as jnp
        import optax

        c = self.config
        from .policy import (
            MLPPolicy,
            SquashedGaussianPolicy,
            init_mlp_params,
        )

        if c.action_space == "discrete":
            self._policy = MLPPolicy(self._obs_dim, self._act_dim,
                                     c.hidden_size, c.seed)
        else:
            low = (np.asarray(c.action_low, np.float32)
                   if c.action_low is not None
                   else -np.ones(self._act_dim, np.float32))
            high = (np.asarray(c.action_high, np.float32)
                    if c.action_high is not None
                    else np.ones(self._act_dim, np.float32))
            self._policy = SquashedGaussianPolicy(
                self._obs_dim, self._act_dim, low, high,
                c.hidden_size, c.seed,
            )
            self._low, self._high = jnp.asarray(low), jnp.asarray(high)
        params = jax.tree.map(jnp.asarray, self._policy.get_weights())
        self._tx = optax.adam(c.lr)
        self._params = params
        self._opt_state = self._tx.init(params)
        discrete = c.action_space == "discrete"

        def mlp(ps, x):
            for W, b in ps:
                x = jnp.tanh(x @ W + b)
            return x

        def loss_fn(p, obs, act):
            h = mlp(p["trunk"], obs)
            if discrete:
                (W, b), = p["pi"]
                logits = h @ W + b
                logp = jax.nn.log_softmax(logits)
                return -jnp.take_along_axis(
                    logp, act[:, None].astype(jnp.int32), axis=1
                ).mean()
            (Wm, bm), = p["mu"]
            mu = jnp.tanh(h @ Wm + bm)
            target = (act - self._low) / (self._high - self._low) * 2 - 1
            return ((mu - target) ** 2).mean()

        def update(p, opt_state, obs, act):
            loss, grads = jax.value_and_grad(loss_fn)(p, obs, act)
            updates, opt_state = self._tx.update(grads, opt_state)
            return optax.apply_updates(p, updates), opt_state, loss

        self._update = jax.jit(update)

    # ---- training ----------------------------------------------------------

    def train(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        c = self.config
        self.iteration += 1
        losses = []
        rows = 0
        for batch in c.dataset.iter_batches(
            batch_size=c.minibatch_size, batch_format="numpy"
        ):
            obs = np.asarray(batch[c.obs_column], np.float32)
            obs = obs.reshape(len(obs), -1)
            act = np.asarray(batch[c.action_column])
            self._params, self._opt_state, loss = self._update(
                self._params, self._opt_state,
                jnp.asarray(obs), jnp.asarray(act),
            )
            losses.append(float(loss))
            rows += len(obs)
        return {
            "training_iteration": self.iteration,
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "num_rows_trained": rows,
        }

    def get_weights(self):
        import jax

        weights = jax.tree.map(np.asarray, self._params)
        if self.config.action_space == "continuous":
            # BC trains only the mean; rollouts of the cloned policy
            # should be near-deterministic, so export a tight std
            # (log_std head -> constant -3) instead of the random init.
            (W, b), = weights["log_std"]
            weights["log_std"] = [
                (np.zeros_like(W), np.full_like(b, -3.0))
            ]
        self._policy.set_weights(weights)
        return weights

    def get_policy(self):
        """Rollout-ready policy carrying the trained weights."""
        self.get_weights()
        return self._policy

    def stop(self):
        pass
