"""Cluster dashboard.

Ref analogue: the dashboard/ package (dashboard.py + modules serving the
state/metrics APIs to the UI). One stdlib HTTP server in the driver/head
process: ``/api/*`` endpoints return the live state API tables as JSON;
``/`` renders a self-refreshing overview page. No build step, no
dependencies — the data layer is the same fan-out state query the CLI and
``ray_tpu.util.state`` use.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

_server: Optional[ThreadingHTTPServer] = None
_thread: Optional[threading.Thread] = None

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body { font-family: monospace; margin: 2em; background: #fafafa; }
 h1 { font-size: 1.2em; } h2 { font-size: 1em; margin-top: 1.5em; }
 table { border-collapse: collapse; }
 td, th { border: 1px solid #ccc; padding: 4px 8px; text-align: left; }
 th { background: #eee; }
</style></head>
<body>
<h1>ray_tpu cluster</h1>
<div id="content">loading…</div>
<script>
async function refresh() {
  const [nodes, tasks, actors, objects] = await Promise.all([
    fetch('/api/nodes').then(r => r.json()),
    fetch('/api/summary/tasks').then(r => r.json()),
    fetch('/api/summary/actors').then(r => r.json()),
    fetch('/api/summary/objects').then(r => r.json()),
  ]);
  let html = '<h2>nodes</h2><table><tr><th>id</th><th>alive</th>' +
             '<th>host</th><th>resources</th><th>labels</th></tr>';
  for (const n of nodes) {
    html += `<tr><td>${n.NodeID.slice(0,8)}</td><td>${n.Alive}</td>` +
            `<td>${n.Host||''}</td>` +
            `<td>${JSON.stringify(n.Resources)}</td>` +
            `<td>${JSON.stringify(n.Labels||{})}</td></tr>`;
  }
  html += '</table><h2>tasks by state</h2><pre>' +
          JSON.stringify(tasks, null, 1) + '</pre>' +
          '<h2>actors by state</h2><pre>' +
          JSON.stringify(actors, null, 1) + '</pre>' +
          '<h2>objects</h2><pre>' +
          JSON.stringify(objects, null, 1) + '</pre>';
  document.getElementById('content').innerHTML = html;
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet
        pass

    def _json(self, payload: Any, code: int = 200):
        body = json.dumps(payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — stdlib API
        from .util import metrics, state

        try:
            path = self.path.split("?")[0].rstrip("/")
            if path in ("", "/index.html"):
                # Single-page UI (dashboard_ui.py — the no-build-step
                # equivalent of the reference's React client); the old
                # minimal page stays at /simple.
                from .dashboard_ui import PAGE

                body = PAGE.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path == "/simple":
                body = _PAGE.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path == "/api/objects":
                # Cluster object census via the GCS ObjectService
                # fan-out, flattened to one row per object (size,
                # state, owner, refs, age + holder node) so the table
                # shape stays what the UI always consumed. Falls back
                # to the flat list_objects table when no runtime is
                # attached. ?limit=500 bounds the per-node rows.
                from urllib.parse import parse_qs, urlparse

                from .core import runtime_context

                rt = runtime_context.current_runtime_or_none()
                if rt is not None and hasattr(rt, "cluster_objects"):
                    q = parse_qs(urlparse(self.path).query)
                    census = rt.cluster_objects(
                        limit=int((q.get("limit") or ["500"])[0])
                    )
                    rows = []
                    for node in census.get("nodes", ()):
                        for r in node.get("objects", ()):
                            r = dict(r)
                            r["node_id"] = node.get("node_id", "")
                            rows.append(r)
                    rows.sort(
                        key=lambda r: -(r.get("size_bytes") or 0))
                    self._json(rows)
                    return
                self._json(state.list_objects())
                return
            routes = {
                "/api/nodes": state.list_nodes,
                "/api/tasks": state.list_tasks,
                "/api/actors": state.list_actors,
                "/api/workers": state.list_workers,
                "/api/summary/tasks": state.summarize_tasks,
                "/api/summary/actors": state.summarize_actors,
                "/api/summary/objects": state.summarize_objects,
            }
            if path == "/api/events":
                # Aggregated cluster event log from the head store (ref:
                # dashboard events REST surface over the GCS export-event
                # channel). ?severity=ERROR&source=TASK&limit=200
                from urllib.parse import parse_qs, urlparse

                q = parse_qs(urlparse(self.path).query)
                rows = state.list_cluster_events(
                    severity=(q.get("severity") or [None])[0],
                    source=(q.get("source") or [None])[0],
                    limit=int((q.get("limit") or ["1000"])[0]),
                )
                self._json({"events": rows})
                return
            if path == "/api/serve":
                # Serve application state (ref: dashboard/modules/serve
                # REST surface over the controller).
                try:
                    import ray_tpu.serve as serve

                    self._json({"deployments": serve.details()})
                except Exception as e:
                    self._json({"deployments": {},
                                "note": f"serve not running: {e}"})
                return
            if path == "/api/agents":
                # Registered per-node agents (ref: dashboard head's
                # DataSource of agent addresses).
                from .dashboard_agent import agent_addresses

                self._json(agent_addresses())
                return
            if path.startswith("/api/agent/"):
                # Proxy /api/agent/<node_hex>/<rest> to that node's
                # agent (ref: head -> dashboard_agent fan-out).
                import urllib.request

                from .dashboard_agent import agent_addresses

                rest = path[len("/api/agent/"):]
                node_hex, _, sub = rest.partition("/")
                addr = agent_addresses().get(node_hex)
                if addr is None:
                    self._json({"error": f"no agent for {node_hex}"},
                               404)
                    return
                query = self.path.partition("?")[2]
                url = (f"http://{addr}/api/local/{sub}"
                       + (f"?{query}" if query else ""))
                with urllib.request.urlopen(url, timeout=35) as r:
                    body = r.read()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path == "/api/stacks":
                # Cluster-wide one-shot stack dumps: head + every node
                # manager + every live worker, via the GCS
                # ProfileService (ref analogue: `ray stack`).
                from urllib.parse import parse_qs, urlparse

                from .core import runtime_context
                from .util import profiler

                q = parse_qs(urlparse(self.path).query)
                try:
                    timeout = float(q.get("timeout", ["5"])[0])
                except (TypeError, ValueError):
                    self._json({"error": "timeout must be numeric"}, 400)
                    return
                rt = runtime_context.current_runtime_or_none()
                if rt is None or not hasattr(rt, "cluster_stacks"):
                    # No cluster runtime: this process's threads only.
                    self._json({"nodes": [{
                        "node_id": "local", "is_head": True,
                        "procs": [{"pid": os.getpid(), "kind": "driver",
                                   "worker_id": None,
                                   "threads": profiler.dump_stacks()}],
                    }], "errors": {}})
                    return
                self._json(rt.cluster_stacks(timeout=min(timeout, 30.0)))
                return
            if path == "/api/profile":
                # Cluster-wide sampled wall-clock profile (ref analogue:
                # dashboard reporter profile_manager.py's py-spy
                # endpoint, generalized to every node + worker). Each
                # node samples OFF its event loop; this process's share
                # comes from a dedicated sampler thread — never the
                # request thread (make check-obs lints for that).
                from urllib.parse import parse_qs, urlparse

                from .core import runtime_context
                from .core.config import get_config
                from .util import profiler

                q = parse_qs(urlparse(self.path).query)
                try:
                    seconds = float(q.get("seconds", ["2"])[0])
                    hz = int(q.get("hz", ["100"])[0])
                except (TypeError, ValueError):
                    self._json(
                        {"error": "seconds and hz must be numeric"}, 400
                    )
                    return
                cap = getattr(get_config(), "profile_max_seconds", 15.0)
                seconds = max(0.1, min(seconds, cap))
                hz = max(1, min(hz, profiler.MAX_SAMPLE_HZ))

                def top_stacks(counts, n=500):
                    # Bound the JSON payload: the heaviest stacks are
                    # the ones a flamegraph reader cares about.
                    return dict(sorted(counts.items(),
                                       key=lambda kv: -kv[1])[:n])

                rt = runtime_context.current_runtime_or_none()
                if rt is None or not hasattr(rt, "cluster_profile"):
                    # Same response shape as the cluster path: top-level
                    # counts/samples plus per-node metadata.
                    prof = profiler.sample_in_thread(seconds, hz)
                    self._json({
                        "nodes": [{"node_id": "local",
                                   "samples": prof["samples"]}],
                        "errors": {},
                        "counts": top_stacks(prof["counts"]),
                        "samples": prof["samples"],
                    })
                    return
                reply = rt.cluster_profile(seconds=seconds, hz=hz)
                merged = profiler.merge_cluster_profile(reply)
                self._json({
                    # Per-node counts fold into the merged map; shipping
                    # them twice would double an already-large payload.
                    "nodes": [
                        {k: v for k, v in n.items() if k != "counts"}
                        for n in reply.get("nodes", [])
                    ],
                    "errors": reply.get("errors", {}),
                    "counts": top_stacks(merged["counts"]),
                    "samples": merged["samples"],
                })
                return
            if path == "/api/traces":
                # Tail-sampled flight recorder (util/flight_recorder.py):
                # retained request records cluster-wide, or one trace's
                # full waterfall. ?reason=slow|shed|expired|error|chaos
                # &limit=200, or ?trace_id=<id>.
                from urllib.parse import parse_qs, urlparse

                from .util import flight_recorder

                q = parse_qs(urlparse(self.path).query)
                trace_id = (q.get("trace_id") or [None])[0]
                if trace_id:
                    self._json(flight_recorder.waterfall(trace_id))
                    return
                self._json({
                    "records": flight_recorder.list_cluster(
                        reason=(q.get("reason") or [None])[0],
                        limit=int((q.get("limit") or ["200"])[0]),
                    ),
                    "slow_threshold_s": flight_recorder.get_recorder()
                    .stats()["slow_threshold_s"],
                })
                return
            if path == "/metrics":
                # Prometheus text exposition (ref analogue:
                # _private/prometheus_exporter.py endpoint).
                from .util import prometheus

                body = prometheus.render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path == "/api/metrics":
                self._json(_report_json(metrics.get_metrics_report()))
                return
            if path == "/api/serve_metrics":
                # Serve data-path telemetry: the ray_tpu_serve_* series
                # (latency histograms, ongoing/queue gauges, status
                # counters) plus deployment state in one payload (ref:
                # dashboard/modules/serve REST surface).
                payload = {
                    "metrics": _report_json(
                        metrics.get_metrics_report(),
                        prefix="ray_tpu_serve",
                    )
                }
                try:
                    import ray_tpu.serve as serve

                    payload["deployments"] = serve.details()
                except Exception as e:  # noqa: BLE001
                    payload["deployments"] = {}
                    payload["note"] = f"serve not running: {e}"
                self._json(payload)
                return
            if path == "/api/timeseries":
                # Head TSDB query (ref: dashboard Grafana-backed charts,
                # served here from the in-process ring-buffer store).
                # ?name=...&since=...&limit=...&tag.deployment=echo ;
                # without a name: series names + store accounting.
                from urllib.parse import parse_qs, urlparse

                from .core import runtime_context

                q = parse_qs(urlparse(self.path).query)
                tags = {k[len("tag."):]: v[0] for k, v in q.items()
                        if k.startswith("tag.") and v}
                rt = runtime_context.current_runtime_or_none()
                if rt is None or not hasattr(rt, "timeseries_query"):
                    self._json({"error": "no runtime attached"}, 503)
                    return
                self._json(rt.timeseries_query(
                    name=(q.get("name") or [""])[0],
                    tags=tags or None,
                    since=float((q.get("since") or ["0"])[0]),
                    limit=int((q.get("limit") or ["0"])[0]),
                ))
                return
            if path == "/api/slo":
                # The SLO engine's latest per-deployment evaluation
                # (goodput, burn rates, budget, alert state).
                from .core import runtime_context

                rt = runtime_context.current_runtime_or_none()
                if rt is None or not hasattr(rt, "slo_status"):
                    self._json({"error": "no runtime attached"}, 503)
                    return
                self._json(rt.slo_status())
                return
            if path == "/api/dispatch":
                # Control-plane dispatch health: the raw series behind
                # `rtpu rpc` — per-op stage histograms, backlog/
                # inflight gauges, loop lag, GIL ratio. ?window=60
                # controls the p99 derivation window.
                from urllib.parse import parse_qs, urlparse

                from .core import runtime_context

                rt = runtime_context.current_runtime_or_none()
                if rt is None or not hasattr(rt, "timeseries_query"):
                    self._json({"error": "no runtime attached"}, 503)
                    return
                q = parse_qs(urlparse(self.path).query)
                window = float((q.get("window") or ["60"])[0])
                payload = {}
                for key, name in (
                        ("rpc", "ray_tpu_rpc_server_seconds"),
                        ("backlog", "ray_tpu_rpc_backlog"),
                        ("inflight", "ray_tpu_rpc_inflight"),
                        ("loop_lag", "ray_tpu_event_loop_lag_seconds"),
                        ("gil", "ray_tpu_gil_wait_ratio")):
                    try:
                        payload[key] = rt.timeseries_query(
                            name=name)["series"]
                    except Exception:  # noqa: BLE001
                        payload[key] = []
                try:
                    payload["p99"] = rt.timeseries_query(
                        name="ray_tpu_rpc_server_seconds",
                        tags={"stage": "handler"},
                        quantile=0.99, window=window).get("derived")
                except Exception:  # noqa: BLE001
                    payload["p99"] = None
                self._json(payload)
                return
            if path == "/api/devices":
                # Device telemetry: this process's live JAX device
                # snapshot + every worker's published ray_tpu_device_*
                # series (HBM, compiles, collectives). Never IMPORT jax
                # here: on a TPU host that would seize the chip from a
                # colocated worker (libtpu is exclusive per process).
                import sys as _sys

                from .util import device_metrics

                local = (device_metrics.sample()
                         if "jax" in _sys.modules else [])
                self._json({
                    "local": local,
                    "cluster": _report_json(
                        metrics.get_metrics_report(),
                        prefix="ray_tpu_device",
                    ),
                })
                return
            fn = routes.get(path)
            if fn is None:
                self._json({"error": f"unknown path {path}"}, 404)
                return
            self._json(fn())
        except Exception as e:  # noqa: BLE001
            self._json({"error": repr(e)}, 500)


def _report_json(report: dict, prefix: str = "") -> dict:
    """Metrics report with JSON-safe series keys, optionally filtered to
    names starting with ``prefix``."""
    return {
        name: {
            "type": m["type"],
            "help": m.get("help", ""),
            "series": {
                json.dumps(dict(k)): v for k, v in m["series"].items()
            },
        }
        for name, m in report.items()
        if not prefix or name.startswith(prefix)
    }


def start_dashboard(port: int = 8265, host: str = "127.0.0.1") -> int:
    """Start the dashboard server; returns the bound port (ref: the
    dashboard agent on :8265)."""
    global _server, _thread
    if _server is not None:
        return _server.server_address[1]
    _server = ThreadingHTTPServer((host, port), _Handler)
    _thread = threading.Thread(target=_server.serve_forever, daemon=True)
    _thread.start()
    return _server.server_address[1]


def stop_dashboard() -> None:
    global _server, _thread
    if _server is not None:
        _server.shutdown()
        _server = None
        _thread = None
