"""Durable workflows.

Ref analogue: python/ray/workflow/ — ``workflow.run(dag)`` executes a
task DAG with per-step durability: every step's output is checkpointed to
storage before its consumers run, so a crashed/interrupted workflow
resumed by id SKIPS completed steps and continues where it stopped
(exactly-once step semantics under driver failure).

Storage layout: <storage>/<workflow_id>/{workflow.pkl, status.json,
steps/<step_id>.pkl}.
"""

from __future__ import annotations

import json
import os
import tempfile
import uuid
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from .dag import ClassMethodNode, ClassNode, DAGNode, FunctionNode, InputNode


def _default_storage() -> str:
    return os.path.join(tempfile.gettempdir(), "ray_tpu", "workflows")


def _step_order(root: DAGNode) -> List[DAGNode]:
    """Deterministic post-order over the DAG (children before parents);
    step ids derive from this order, so re-running the same workflow
    object maps steps stably."""
    seen: Dict[int, DAGNode] = {}
    order: List[DAGNode] = []

    def visit(node: DAGNode):
        if id(node) in seen:
            return
        seen[id(node)] = node
        for child in node._children():
            visit(child)
        order.append(node)

    visit(root)
    return order


def _step_id(index: int, node: DAGNode) -> str:
    name = ""
    if isinstance(node, FunctionNode):
        name = getattr(node._fn, "__name__", "fn")
    elif isinstance(node, ClassMethodNode):
        name = node._method
    elif isinstance(node, ClassNode):
        name = getattr(node._actor_class, "__name__", "actor")
    elif isinstance(node, InputNode):
        name = "input"
    return f"{index:04d}_{name}"


class _WorkflowRunner:
    def __init__(self, workflow_id: str, storage: str):
        self.workflow_id = workflow_id
        self.dir = os.path.join(storage, workflow_id)
        self.steps_dir = os.path.join(self.dir, "steps")
        os.makedirs(self.steps_dir, exist_ok=True)

    # -- persistence --

    def _step_path(self, step_id: str) -> str:
        return os.path.join(self.steps_dir, f"{step_id}.pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self._step_path(step_id))

    def load_step(self, step_id: str):
        with open(self._step_path(step_id), "rb") as f:
            return cloudpickle.load(f)

    def save_step(self, step_id: str, value) -> None:
        tmp = self._step_path(step_id) + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, self._step_path(step_id))

    def set_status(self, status: str, message: str = "") -> None:
        with open(os.path.join(self.dir, "status.json"), "w") as f:
            json.dump({"status": status, "message": message}, f)

    def save_dag(self, root: DAGNode, input_val) -> None:
        with open(os.path.join(self.dir, "workflow.pkl"), "wb") as f:
            cloudpickle.dump({"dag": root, "input": input_val}, f)

    # -- execution --

    def execute(self, root: DAGNode, input_val) -> Any:
        import ray_tpu

        order = _step_order(root)
        results: Dict[int, Any] = {}
        for i, node in enumerate(order):
            sid = _step_id(i, node)
            if isinstance(node, InputNode):
                results[id(node)] = input_val
                continue
            if self.has_step(sid):
                results[id(node)] = self.load_step(sid)
                continue
            args = tuple(
                results[id(a)] if isinstance(a, DAGNode) else a
                for a in node._bound_args
            )
            kwargs = {
                k: results[id(v)] if isinstance(v, DAGNode) else v
                for k, v in node._bound_kwargs.items()
            }
            if isinstance(node, FunctionNode):
                value = ray_tpu.get(node._fn.remote(*args, **kwargs))
            elif isinstance(node, ClassNode):
                # Actors are runtime state, not durable data: recreate on
                # every (re)run and never checkpoint the handle.
                results[id(node)] = node._actor_class.remote(
                    *args, **kwargs
                )
                continue
            elif isinstance(node, ClassMethodNode):
                handle = results[id(node._class_node)]
                value = ray_tpu.get(
                    getattr(handle, node._method).remote(*args, **kwargs)
                )
            else:
                raise TypeError(f"unsupported node {type(node).__name__}")
            self.save_step(sid, value)
            results[id(node)] = value
        return results[id(root)]


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        storage: Optional[str] = None, input: Any = None) -> Any:
    """Execute a DAG durably; returns the root's VALUE (ref:
    workflow.run). Interrupt + ``resume(workflow_id)`` to continue."""
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:10]}"
    runner = _WorkflowRunner(workflow_id, storage or _default_storage())
    runner.save_dag(dag, input)
    runner.set_status("RUNNING")
    try:
        value = runner.execute(dag, input)
    except BaseException as e:
        runner.set_status("FAILED", repr(e))
        raise
    runner.set_status("SUCCEEDED")
    return value


def resume(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    """Continue an interrupted workflow: completed steps load from
    storage; the rest execute (ref: workflow.resume)."""
    storage = storage or _default_storage()
    with open(os.path.join(storage, workflow_id, "workflow.pkl"),
              "rb") as f:
        payload = cloudpickle.load(f)
    runner = _WorkflowRunner(workflow_id, storage)
    runner.set_status("RUNNING")
    try:
        value = runner.execute(payload["dag"], payload["input"])
    except BaseException as e:
        runner.set_status("FAILED", repr(e))
        raise
    runner.set_status("SUCCEEDED")
    return value


def get_status(workflow_id: str, *,
               storage: Optional[str] = None) -> Dict[str, Any]:
    storage = storage or _default_storage()
    try:
        with open(os.path.join(storage, workflow_id, "status.json")) as f:
            return json.load(f)
    except OSError:
        return {"status": "NOT_FOUND"}


def list_all(*, storage: Optional[str] = None) -> List[Tuple[str, str]]:
    storage = storage or _default_storage()
    out = []
    if os.path.isdir(storage):
        for wid in sorted(os.listdir(storage)):
            out.append((wid, get_status(wid, storage=storage)["status"]))
    return out


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              storage: Optional[str] = None, input: Any = None):
    """Start the workflow without blocking; returns an object ref for
    the root value (ref: workflow.run_async — the reference returns an
    ObjectRef the same way)."""
    import ray_tpu

    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:10]}"
    blob = cloudpickle.dumps((dag, input))

    def _drive(blob, workflow_id, storage):
        import cloudpickle as _cp

        from ray_tpu import workflow as wf

        dag, input_val = _cp.loads(blob)
        return wf.run(dag, workflow_id=workflow_id, storage=storage,
                      input=input_val)

    task = ray_tpu.remote(_drive)
    return task.remote(blob, workflow_id, storage)


def get_output(workflow_id: str, *,
               storage: Optional[str] = None) -> Any:
    """The root step's persisted value of a SUCCEEDED workflow (ref:
    workflow.get_output)."""
    storage = storage or _default_storage()
    status = get_status(workflow_id, storage=storage)
    if status.get("status") != "SUCCEEDED":
        raise RuntimeError(
            f"workflow {workflow_id} is {status.get('status')}, "
            f"not SUCCEEDED"
        )
    with open(os.path.join(storage, workflow_id, "workflow.pkl"),
              "rb") as f:
        payload = cloudpickle.load(f)
    runner = _WorkflowRunner(workflow_id, storage)
    order = _step_order(payload["dag"])
    root_step = _step_id(len(order) - 1, order[-1])
    if not runner.has_step(root_step):
        raise RuntimeError(f"workflow {workflow_id} has no persisted "
                           f"root value")
    return runner.load_step(root_step)


def resume_all(*, storage: Optional[str] = None
               ) -> List[Tuple[str, Any]]:
    """Resume every workflow that is not SUCCEEDED (ref:
    workflow.resume_all); returns [(workflow_id, value)] for the ones
    that completed."""
    storage = storage or _default_storage()
    out: List[Tuple[str, Any]] = []
    for wid, status in list_all(storage=storage):
        if status in ("SUCCEEDED",):
            continue
        try:
            out.append((wid, resume(wid, storage=storage)))
        except BaseException:
            continue  # stays FAILED; caller inspects get_status
    return out


def delete(workflow_id: str, *, storage: Optional[str] = None) -> bool:
    """Drop a workflow's persisted state (ref: workflow.delete)."""
    import shutil

    storage = storage or _default_storage()
    path = os.path.join(storage, workflow_id)
    if not os.path.isdir(path):
        return False
    shutil.rmtree(path)
    return True
