"""Pipeline parallelism: stage-sharded execution with microbatch rotation.

Absent from the reference (SURVEY.md §2.5 — Ray ships no PP); built
TPU-native: layer stages live on the "pp" mesh axis, activations move
stage-to-stage with collective-permute inside a lax.scan shift register
(GPipe schedule: n_micro + n_stages - 1 ticks, bubble at the ends). Because
the schedule is plain differentiable JAX (scan + ppermute), jax.grad gives
the pipelined backward pass for free; wrap the stage body in jax.checkpoint
to trade recompute for activation memory.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_shard(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,          # this device's stage parameters
    x: jax.Array,               # [n_micro, mb, ...] microbatched input (replicated)
    *,
    axis_name: str = "pp",
    remat: bool = True,
) -> jax.Array:
    """Call INSIDE shard_map. Every device runs the same schedule; stage 0
    injects microbatches, the last stage's outputs are gathered into
    [n_micro, mb, ...] (valid only on the last stage; callers psum-select)."""
    n_stages = jax.lax.axis_size(axis_name)
    stage_idx = jax.lax.axis_index(axis_name)
    n_micro = x.shape[0]
    ticks = n_micro + n_stages - 1
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        state, outbuf = carry
        # Stage 0 reads microbatch t (clamped; masked out past the end).
        mb_idx = jnp.minimum(t, n_micro - 1)
        fresh = jax.lax.dynamic_index_in_dim(x, mb_idx, axis=0, keepdims=False)
        inp = jnp.where(stage_idx == 0, fresh, state)
        out = body(stage_params, inp)
        # Last stage writes its finished microbatch t - (n_stages - 1).
        done_idx = t - (n_stages - 1)
        write_idx = jnp.clip(done_idx, 0, n_micro - 1)
        should_write = done_idx >= 0
        prev = jax.lax.dynamic_index_in_dim(
            outbuf, write_idx, axis=0, keepdims=False
        )
        outbuf = jax.lax.dynamic_update_index_in_dim(
            outbuf, jnp.where(should_write, out, prev), write_idx, axis=0
        )
        state = jax.lax.ppermute(out, axis_name, fwd_perm)
        return (state, outbuf), None

    state0 = jnp.zeros_like(x[0])
    outbuf0 = jnp.zeros_like(x)
    (_, outbuf), _ = jax.lax.scan(tick, (state0, outbuf0), jnp.arange(ticks))
    return outbuf


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,        # pytree, leading dim n_stages on every leaf
    x: jax.Array,               # [batch, ...] global input
    mesh: Mesh,
    *,
    n_microbatches: int,
    axis_name: str = "pp",
    remat: bool = True,
) -> jax.Array:
    """Global-view pipeline: shards stacked stage params over "pp", splits
    the batch into microbatches, returns [batch, ...] outputs."""
    n_stages = mesh.shape[axis_name]
    B = x.shape[0]
    assert B % n_microbatches == 0, (B, n_microbatches)
    xm = x.reshape(n_microbatches, B // n_microbatches, *x.shape[1:])

    def run(params_stacked, xm_local):
        # Each device holds params_stacked with leading dim 1: its stage.
        my_params = jax.tree.map(lambda p: p[0], params_stacked)
        outbuf = pipeline_shard(
            stage_fn, my_params, xm_local, axis_name=axis_name, remat=remat
        )
        # Only the last stage's buffer is valid; broadcast it to all stages
        # so the result is replicated over pp.
        last = jax.lax.axis_size(axis_name) - 1
        mask = (jax.lax.axis_index(axis_name) == last).astype(outbuf.dtype)
        return jax.lax.psum(outbuf * mask, axis_name)

    param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    out = jax.shard_map(
        run,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, xm)
    return out.reshape(B, *out.shape[2:])


def pipeline_stage_params_spec(stacked_params: Any, axis_name: str = "pp"):
    """PartitionSpec pytree for stage-stacked parameters."""
    return jax.tree.map(lambda _: P(axis_name), stacked_params)
