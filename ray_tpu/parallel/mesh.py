"""Device mesh construction for TPU slices.

This replaces the reference's process-group world (torch.distributed NCCL
groups set up by train/torch/config.py:62 _setup_torch_process_group) with
the TPU-native model: one global `jax.sharding.Mesh` whose named axes carry
every parallelism strategy (SURVEY.md §2.5):

    dp    — data parallel (replica groups)
    fsdp  — fully-sharded data parallel (ZeRO-equivalent parameter sharding)
    ep    — expert parallel (MoE expert placement)
    pp    — pipeline parallel (layer stages)
    sp    — sequence/context parallel (ring attention axis)
    tp    — tensor parallel (innermost: highest-bandwidth ICI neighbors)

Axis order puts tp last so tensor-parallel collectives ride adjacent ICI
links (jax orders devices so the trailing mesh dims are nearest neighbors).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER: Tuple[str, ...] = ("dp", "fsdp", "ep", "pp", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes per parallelism axis; -1 on at most one axis means "absorb all
    remaining devices"."""

    dp: int = -1
    fsdp: int = 1
    ep: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1

    def sizes(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXIS_ORDER)

    def resolve(self, n_devices: int) -> "MeshConfig":
        sizes = list(self.sizes())
        wildcard = [i for i, s in enumerate(sizes) if s == -1]
        if len(wildcard) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(s for s in sizes if s != -1)
        if wildcard:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            sizes[wildcard[0]] = n_devices // fixed
        elif fixed > n_devices:
            raise ValueError(
                f"mesh {dict(zip(AXIS_ORDER, sizes))} needs {fixed} devices, "
                f"have {n_devices}"
            )
        # fixed < n_devices with all axes explicit: use a device subset.
        return MeshConfig(**dict(zip(AXIS_ORDER, sizes)))


def make_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    **axis_sizes: int,
) -> Mesh:
    """Build the global mesh.

    make_mesh(dp=2, tp=4) or make_mesh(MeshConfig(...)). Unspecified axes
    default to 1; dp absorbs leftover devices unless explicitly set.
    """
    if config is None:
        config = MeshConfig(**axis_sizes) if axis_sizes else MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    config = config.resolve(len(devices))
    shape = config.sizes()
    dev_array = np.asarray(devices[: math.prod(shape)]).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    device = device or jax.devices()[0]
    return Mesh(np.asarray([device]).reshape((1,) * len(AXIS_ORDER)), AXIS_ORDER)


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.shape else 1


def data_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Axes across which the global batch is split (dp + fsdp: fsdp ranks
    see distinct data shards, ZeRO-style)."""
    return tuple(a for a in ("dp", "fsdp") if mesh_axis_size(mesh, a) > 1) or ("dp",)


def num_data_shards(mesh: Mesh) -> int:
    return mesh_axis_size(mesh, "dp") * mesh_axis_size(mesh, "fsdp")
