"""Logical-axis sharding rules.

The reference delegates parameter sharding to per-worker frameworks (FSDP/
DeepSpeed configs inside the train loop — SURVEY.md §2.5); here sharding is
first-class: model code annotates arrays with *logical* axis names
("batch", "embed", "heads", …) and a rules table maps them to mesh axes.
pjit/XLA then emits the collectives. This is the t5x/flax-partitioning
idiom, which is the TPU-native replacement for wrapper classes like
RayFSDPStrategy (ref: train/lightning/_lightning_utils.py:91).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .mesh import mesh_axis_size

# (logical axis, mesh axis or tuple of mesh axes or None)
Rule = Tuple[str, Union[str, Tuple[str, ...], None]]

# Default rules for transformer training:
#  - batch splits over dp+fsdp (each fsdp rank sees different data)
#  - sequence splits over sp (ring attention axis)
#  - attention heads + mlp hidden split over tp (Megatron-style)
#  - embed (params' fsdp shard dim) splits over fsdp: ZeRO-3-equivalent
#  - experts split over ep
#  - layer stages split over pp (for stacked-layer pipeline params)
DEFAULT_RULES: Tuple[Rule, ...] = (
    ("batch", ("dp", "fsdp")),
    ("seq", "sp"),
    ("kv_seq", "sp"),
    ("embed", "fsdp"),
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("head_dim", None),
    ("mlp", "tp"),
    ("vocab", "tp"),
    ("expert", "ep"),
    ("layers", "pp"),
    ("norm", None),
)


def logical_to_spec(
    logical_axes: Sequence[Optional[str]],
    rules: Sequence[Rule] = DEFAULT_RULES,
) -> PartitionSpec:
    """Map logical axis names to a PartitionSpec via the rules table. A
    mesh axis may be consumed at most once per spec (first match wins)."""
    table = dict(rules)
    used: set = set()
    out = []
    for name in logical_axes:
        mesh_axis = table.get(name) if name is not None else None
        if mesh_axis is None:
            out.append(None)
            continue
        axes = (mesh_axis,) if isinstance(mesh_axis, str) else tuple(mesh_axis)
        free = tuple(a for a in axes if a not in used)
        if not free:
            out.append(None)
            continue
        used.update(free)
        out.append(free if len(free) > 1 else free[0])
    return PartitionSpec(*out)


def named_sharding(
    mesh: Mesh,
    logical_axes: Sequence[Optional[str]],
    rules: Sequence[Rule] = DEFAULT_RULES,
) -> NamedSharding:
    return NamedSharding(mesh, prune_spec(mesh, logical_to_spec(logical_axes, rules)))


def prune_spec(mesh: Mesh, spec: PartitionSpec) -> PartitionSpec:
    """Drop mesh axes of size 1 from a spec (XLA treats them as replicated
    anyway; pruning keeps specs readable and avoids missing-axis errors on
    small meshes)."""
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, str):
            out.append(entry if mesh_axis_size(mesh, entry) > 1 else None)
        else:
            kept = tuple(a for a in entry if mesh_axis_size(mesh, a) > 1)
            out.append(kept if len(kept) > 1 else (kept[0] if kept else None))
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def with_logical_constraint(
    x: jax.Array,
    logical_axes: Sequence[Optional[str]],
    *,
    mesh: Optional[Mesh] = None,
    rules: Sequence[Rule] = DEFAULT_RULES,
):
    """Annotate an intermediate activation with its sharding (ref analogue
    in spirit: torch.distributed tensor placement; here it's
    jax.lax.with_sharding_constraint so XLA propagates/reshards)."""
    mesh = mesh or _current_mesh()
    if mesh is None:
        return x
    spec = prune_spec(mesh, logical_to_spec(logical_axes, rules))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _current_mesh() -> Optional[Mesh]:
    try:
        from jax._src import mesh as mesh_lib

        m = mesh_lib.thread_resources.env.physical_mesh
        return None if m.empty else m
    except Exception:
        return None


def is_logical_axes(x) -> bool:
    """True for a leaf of a logical-axes pytree: a tuple of axis names
    (str) and Nones — e.g. ("layers", "embed", "heads", "head_dim")."""
    return isinstance(x, tuple) and all(
        e is None or isinstance(e, str) for e in x
    )


def tree_shardings(mesh: Mesh, logical_axes_tree, rules=DEFAULT_RULES):
    """Map a pytree of logical-axis tuples to a matching pytree of
    NamedShardings (the in/out_shardings argument shape pjit wants).
    Tuples of axis names are leaves here, not nested pytrees."""
    return jax.tree.map(
        lambda axes: named_sharding(mesh, axes, rules),
        logical_axes_tree,
        is_leaf=is_logical_axes,
    )


def constrain_pytree(tree, mesh: Mesh, logical_axes_tree,
                     rules=DEFAULT_RULES):
    """with_sharding_constraint over a whole pytree of traced values —
    the in-graph counterpart of :func:`shard_pytree` (used to pin params
    and optimizer state inside a compiled init so every buffer
    materializes with its final layout)."""
    shardings = tree_shardings(mesh, logical_axes_tree, rules)
    return jax.tree.map(
        jax.lax.with_sharding_constraint, tree, shardings
    )


def shard_pytree(tree, mesh: Mesh, logical_axes_tree, rules=DEFAULT_RULES):
    """Device-put a pytree of host arrays onto the mesh according to a
    matching pytree of logical-axis tuples."""
    def _place(x, axes):
        return jax.device_put(x, named_sharding(mesh, axes, rules))

    return jax.tree.map(_place, tree, logical_axes_tree)
