"""Ring attention: sequence-parallel exact attention over the ICI ring.

Absent from the reference (SURVEY.md §5.7 — no ring attention, Ulysses or
context parallelism exists in Ray); built new here as first-class TPU
capability. Design: the sequence axis is sharded over mesh axis "sp"; each
device holds Q/K/V blocks [B, S/n, H, D]. n steps of online-softmax
(flash-style) accumulation; between steps the KV block rotates one hop
around the ring via ppermute, so every query block sees every KV block
while per-device memory stays O(S/n) — the XLA scheduler overlaps the
ppermute with the current block's compute.

Also here: Ulysses-style all-to-all attention (reshard seq→heads, local
attention, reshard back) — cheaper at moderate sequence lengths, limited
by head count.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import (
    attention_block_accumulate,
    attention_finalize,
    mha_attention,
)
from .collectives import shift


def ring_attention_shard(
    q: jax.Array,  # [B, Sl, H, D] local query block
    k: jax.Array,  # [B, Sl, Hkv, D]
    v: jax.Array,  # [B, Sl, Hkv, D]
    *,
    axis_name: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Call INSIDE shard_map with the sequence dim sharded over
    ``axis_name``. Exact (not approximate) attention."""
    B, Sl, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else D ** -0.5
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)

    m0 = jnp.full((B, H, Sl), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((B, H, Sl), dtype=jnp.float32)
    acc0 = jnp.zeros((B, Sl, H, D), dtype=jnp.float32)

    q_pos = my * Sl + jnp.arange(Sl)  # global query positions

    def step(carry, s):
        k_cur, v_cur, m, l, acc = carry
        kv_idx = (my - s) % n
        if causal:
            k_pos = kv_idx * Sl + jnp.arange(Sl)
            mask = k_pos[None, :] <= q_pos[:, None]  # [Sl, Sl]

            # A KV block strictly ahead of this device's query block is
            # FULLY masked: skip its two matmuls entirely instead of
            # computing then discarding (r1 VERDICT: the jnp ring wasted
            # ~2x FLOPs in the causal case). lax.cond executes only the
            # taken branch at runtime.
            def do(args):
                q_, k_, v_, m_, l_, a_ = args
                return attention_block_accumulate(
                    q_, k_, v_, m_, l_, a_, scale=scale, mask=mask
                )

            def skip(args):
                _, _, _, m_, l_, a_ = args
                return m_, l_, a_

            m, l, acc = jax.lax.cond(
                kv_idx <= my, do, skip, (q, k_cur, v_cur, m, l, acc)
            )
        else:
            m, l, acc = attention_block_accumulate(
                q, k_cur, v_cur, m, l, acc, scale=scale, mask=None
            )
        # Rotate KV one hop; overlapped with the next block's compute by XLA.
        k_nxt = shift(k_cur, axis_name, 1)
        v_nxt = shift(v_cur, axis_name, 1)
        return (k_nxt, v_nxt, m, l, acc), None

    (_, _, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n)
    )
    return attention_finalize(l, acc).astype(q.dtype)


def ulysses_attention_shard(
    q: jax.Array,  # [B, Sl, H, D]
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """DeepSpeed-Ulysses-style: all-to-all reshard [B,S/n,H,D] →
    [B,S,H/n,D], local full-sequence attention on a head subset, reshard
    back. Requires H % n == 0. Two all-to-alls instead of n ppermutes."""
    n = jax.lax.axis_size(axis_name)
    H = q.shape[2]
    assert H % n == 0, f"ulysses needs heads({H}) % sp({n}) == 0"
    # split heads, gather sequence
    qg = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kg = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vg = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = mha_attention(qg, kg, vg, causal=causal, scale=scale)
    # gather heads back, re-split sequence
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def ring_attention(
    q: jax.Array,  # [B, S, H, D] global
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    impl: str = "ring",
) -> jax.Array:
    """Global-view entry: shards B over (dp, fsdp), S over sp, heads over tp
    and runs the sequence-parallel kernel under shard_map."""
    from .sharding import prune_spec

    qspec = prune_spec(mesh, P(("dp", "fsdp"), "sp", "tp", None))
    fn = ring_attention_shard if impl == "ring" else ulysses_attention_shard
    wrapped = jax.shard_map(
        functools.partial(fn, causal=causal, scale=scale, axis_name="sp"),
        mesh=mesh,
        in_specs=(qspec, qspec, qspec),
        out_specs=qspec,
        check_vma=False,
    )
    return wrapped(q, k, v)
