"""Collective communication.

The reference ships ray.util.collective with NCCL/Gloo process groups
(ref: util/collective/collective.py:258-615 — allreduce/allgather/
reducescatter/broadcast/send/recv; NCCL group at
collective_group/nccl_collective_group.py:127). On TPU the tensor plane is
XLA over ICI: inside an SPMD region these are jax.lax collectives and XLA
schedules them; there is no process-group object to manage. This module
provides:

1. The in-graph API (allreduce/allgather/...) — thin, named-axis versions
   of jax.lax collectives for use under shard_map/pjit.
2. A host-level CollectiveGroup with barrier/broadcast over the control
   plane KV store, replacing the reference's NCCLUniqueIDStore named-actor
   rendezvous (ref: nccl_collective_group.py:571).
"""

from __future__ import annotations

import time
from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp

AxisName = Union[str, Sequence[str]]


def _count(op: str, nbytes=None):
    """Telemetry tap (ray_tpu_device_collective_*): in-graph ops fire
    once per TRACE (python runs only while jit traces), host-level ops
    once per call with payload bytes."""
    try:
        from ..util import device_metrics

        device_metrics.record_collective(op, nbytes)
    except Exception:
        pass


# ---------------------------------------------------------------- in-graph

def allreduce(x, axis: AxisName = "dp", op: str = "sum"):
    _count("allreduce")
    if op == "sum":
        return jax.lax.psum(x, axis)
    if op == "mean":
        return jax.lax.pmean(x, axis)
    if op == "max":
        return jax.lax.pmax(x, axis)
    if op == "min":
        return jax.lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op!r}")


def allgather(x, axis: AxisName = "dp", *, tiled: bool = True, gather_dim: int = 0):
    _count("allgather")
    return jax.lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reducescatter(x, axis: AxisName = "dp", *, scatter_dim: int = 0):
    _count("reducescatter")
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


def broadcast(x, axis: AxisName = "dp", root: int = 0):
    """Every rank takes root's value (in-graph select over axis index)."""
    _count("broadcast")
    idx = jax.lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis)


def ppermute(x, axis: AxisName, perm):
    _count("ppermute")
    return jax.lax.ppermute(x, axis, perm)


def shift(x, axis: AxisName, offset: int = 1):
    """Rotate values around the ring by ``offset`` (the KV-rotation
    primitive of ring attention)."""
    n = jax.lax.axis_size(axis)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)


def all_to_all(x, axis: AxisName, *, split_axis: int, concat_axis: int,
               tiled: bool = True):
    _count("all_to_all")
    return jax.lax.all_to_all(
        x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


def axis_index(axis: AxisName = "dp"):
    return jax.lax.axis_index(axis)


def axis_size(axis: AxisName = "dp"):
    return jax.lax.axis_size(axis)


# ------------------------------------------------------------- host level

class CollectiveGroup:
    """Host-side rendezvous/barrier/broadcast between actors of an SPMD
    group, built on the control-plane KV (ref analogue: the
    init_collective_group + NCCLUniqueIDStore rendezvous in
    util/collective/collective.py:120; here no communicator needs creating —
    this only synchronizes host processes around jax.distributed and
    checkpoint/restore edges)."""

    def __init__(self, group_name: str, world_size: int, rank: int):
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._epoch = 0

    def _kv(self):
        from ..core.runtime_context import current_runtime

        return current_runtime()

    def barrier(self, timeout_s: float = 120.0):
        _count("host_barrier")
        rt = self._kv()
        self._epoch += 1
        key = f"__collective__/{self.group_name}/barrier/{self._epoch}/{self.rank}"
        rt.kv_put(key, b"1")
        deadline = time.monotonic() + timeout_s
        prefix = f"__collective__/{self.group_name}/barrier/{self._epoch}/"
        while time.monotonic() < deadline:
            arrived = sum(
                1
                for r in range(self.world_size)
                if rt.kv_get(prefix + str(r)) is not None
            )
            if arrived >= self.world_size:
                return
            time.sleep(0.01)
        raise TimeoutError(
            f"collective barrier {self.group_name!r} timed out "
            f"({self.world_size} ranks expected)"
        )

    def broadcast_obj(self, obj: Any = None, root: int = 0, timeout_s: float = 120.0):
        import cloudpickle

        rt = self._kv()
        key = f"__collective__/{self.group_name}/bcast/{self._epoch}"
        if self.rank == root:
            blob = cloudpickle.dumps(obj)
            _count("host_broadcast", len(blob))
            rt.kv_put(key, blob)
            return obj
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            blob = rt.kv_get(key)
            if blob is not None:
                return cloudpickle.loads(blob)
            time.sleep(0.01)
        raise TimeoutError(f"broadcast in {self.group_name!r} timed out")


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> CollectiveGroup:
    return CollectiveGroup(group_name, world_size, rank)
