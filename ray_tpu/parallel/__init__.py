"""ray_tpu.parallel: meshes, shardings, collectives, sequence parallelism.

TPU-native replacement for the reference's parallelism stack (SURVEY.md
§2.5): instead of NCCL process groups + DDP/FSDP wrapper classes, every
strategy is a named mesh axis + sharding rule, and collectives are emitted
by XLA over ICI.
"""

from .mesh import (  # noqa: F401
    AXIS_ORDER,
    MeshConfig,
    data_axes,
    make_mesh,
    mesh_axis_size,
    num_data_shards,
    single_device_mesh,
)
from .sharding import (  # noqa: F401
    DEFAULT_RULES,
    constrain_pytree,
    logical_to_spec,
    named_sharding,
    prune_spec,
    shard_pytree,
    tree_shardings,
    with_logical_constraint,
)
from .collectives import (  # noqa: F401
    CollectiveGroup,
    all_to_all,
    allgather,
    allreduce,
    axis_index,
    axis_size,
    broadcast,
    init_collective_group,
    ppermute,
    reducescatter,
    shift,
)
from .ring_attention import (  # noqa: F401
    ring_attention,
    ring_attention_shard,
    ulysses_attention_shard,
)
