"""Mixture-of-experts with expert parallelism.

Absent from the reference (SURVEY.md §2.5 — no EP/MoE in Ray); built
TPU-native: Switch/Top-k routing with *static capacity* (XLA needs static
shapes — no ragged dispatch), experts sharded over the "ep" mesh axis via
logical axis "expert". The dispatch/combine einsums carry sharding
constraints so XLA emits the all-to-alls over ICI (the reference-world
equivalent would be NCCL all-to-all in e.g. DeepSpeed-MoE).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .sharding import with_logical_constraint


def top_k_routing(
    router_logits: jax.Array,  # [tokens, E]
    k: int,
    capacity: int,
    token_mask: Optional[jax.Array] = None,  # [T] 1=route, 0=ignore
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Compute dispatch/combine tensors for top-k token→expert routing with
    per-expert capacity. Returns (dispatch [T,E,C] bool-ish, combine
    [T,E,C] float weights, aux_loss scalar: Switch load-balancing loss).

    ``token_mask`` removes tokens from routing entirely — they claim no
    expert capacity and produce zero output (the decode-engine case:
    inactive batch slots must not steal capacity from live requests)."""
    T, E = router_logits.shape
    probs = jax.nn.softmax(router_logits, axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, k)            # [T,k]
    # One-hot per choice: [k, T, E]
    onehot = jax.nn.one_hot(expert_idx.T, E, dtype=jnp.float32)
    if token_mask is not None:
        onehot = onehot * token_mask.astype(jnp.float32)[None, :, None]
    # Position of each token within its expert's queue, counted over the
    # flattened (choice-major, then token) order so earlier choices win.
    flat = onehot.reshape(k * T, E)
    pos = jnp.cumsum(flat, axis=0) - flat                       # [k*T, E]
    within_cap = (pos < capacity) * flat
    pos_clamped = jnp.minimum(pos, capacity - 1).astype(jnp.int32)
    cap_onehot = jax.nn.one_hot(pos_clamped, capacity, dtype=jnp.float32)
    disp_flat = within_cap[..., None] * cap_onehot              # [k*T, E, C]
    dispatch = disp_flat.reshape(k, T, E, capacity).sum(axis=0)  # [T,E,C]
    gates = (onehot * gate_vals.T[..., None]).reshape(k * T, E)
    combine_flat = (gates * within_cap)[..., None] * cap_onehot
    combine = combine_flat.reshape(k, T, E, capacity).sum(axis=0)
    # Switch aux loss: E * sum_e f_e * p_e (fraction routed × mean prob).
    frac = onehot[0].mean(axis=0) if k == 1 else onehot.sum(0).mean(0) / k
    mean_prob = probs.mean(axis=0)
    aux_loss = E * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux_loss


def moe_ffn(
    x: jax.Array,           # [B, S, M]
    router_w: jax.Array,    # [M, E]
    w_in: jax.Array,        # [E, M, F]   (gate/up fused optional: see w_gate)
    w_out: jax.Array,       # [E, F, M]
    *,
    k: int = 2,
    capacity_factor: float = 1.25,
    w_gate: Optional[jax.Array] = None,  # [E, M, F] for gated (SwiGLU) experts
    activation=jax.nn.silu,
    token_mask: Optional[jax.Array] = None,  # [B, S] 1=route, 0=ignore
) -> Tuple[jax.Array, jax.Array]:
    """Expert-parallel FFN block (Mixtral-style when w_gate given).
    Returns (output [B,S,M], aux_loss)."""
    B, S, M = x.shape
    E = router_w.shape[1]
    T = B * S
    capacity = max(1, int(capacity_factor * k * T / E))
    xt = x.reshape(T, M)
    router_logits = jnp.einsum(
        "tm,me->te", xt.astype(jnp.float32), router_w.astype(jnp.float32)
    )
    dispatch, combine, aux = top_k_routing(
        router_logits, k, capacity,
        token_mask=(token_mask.reshape(T) if token_mask is not None
                    else None),
    )
    # Dispatch tokens to expert buffers: [E, C, M]; "expert" shards over ep.
    expert_in = jnp.einsum("tec,tm->ecm", dispatch.astype(x.dtype), xt)
    expert_in = with_logical_constraint(expert_in, ("expert", None, None))
    h = jnp.einsum("ecm,emf->ecf", expert_in, w_in)
    if w_gate is not None:
        g = jnp.einsum("ecm,emf->ecf", expert_in, w_gate)
        h = activation(g) * h
    else:
        h = activation(h)
    expert_out = jnp.einsum("ecf,efm->ecm", h, w_out)
    expert_out = with_logical_constraint(expert_out, ("expert", None, None))
    out = jnp.einsum("tec,ecm->tm", combine.astype(x.dtype), expert_out)
    return out.reshape(B, S, M), aux
