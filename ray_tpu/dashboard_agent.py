"""Per-node dashboard agent.

Ref analogue: dashboard/agent.py — every node runs an agent the head
dashboard fans out to for node-local data: log files, process stats,
and on-demand profiles (the reference spawns it as a separate process
from the raylet, dashboard/modules/reporter/; here it is an HTTP
thread inside the node-manager process — same surface, one fewer
process). The agent registers ``host:port`` under
``__dashboard_agent__/<node_hex>`` in the cluster KV; the head
dashboard's ``/api/agent/<node_hex>/<path>`` proxies to it.

Endpoints (all JSON):
  /api/local/logs              — log files in this node's session dir
  /api/local/logs/<name>?tail= — tail of one log file
  /api/local/stats             — process cpu/rss + store/loop stats
  /api/local/profile?seconds=  — collapsed-stack samples of this node
  /api/local/stacks            — one-shot stack dump of this process
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional


class _AgentHandler(BaseHTTPRequestHandler):
    node_manager = None  # class attr, set at server build

    def log_message(self, *args):
        pass

    def _json(self, payload: Any, code: int = 200):
        body = json.dumps(payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — stdlib API
        from urllib.parse import parse_qs, urlparse

        try:
            parsed = urlparse(self.path)
            path = parsed.path.rstrip("/")
            q = parse_qs(parsed.query)
            nm = self.node_manager
            logs_dir = os.path.join(nm.session_dir, "logs")
            if path == "/api/local/logs":
                files = []
                if os.path.isdir(logs_dir):
                    for name in sorted(os.listdir(logs_dir)):
                        p = os.path.join(logs_dir, name)
                        files.append({
                            "name": name,
                            "size": os.path.getsize(p),
                        })
                self._json({"node_id": nm.node_id.hex(),
                            "files": files})
                return
            if path.startswith("/api/local/logs/"):
                name = os.path.basename(path.rsplit("/", 1)[-1])
                p = os.path.join(logs_dir, name)
                if not os.path.isfile(p):
                    self._json({"error": f"no log {name}"}, 404)
                    return
                tail = int(q.get("tail", ["200"])[0])
                with open(p, "r", errors="replace") as f:
                    lines = f.readlines()[-tail:]
                self._json({"name": name, "lines": lines})
                return
            if path == "/api/local/stats":
                self._json(self._stats())
                return
            if path == "/api/local/profile":
                # Sampler runs on its own thread (util/profiler), never
                # this request thread (make check-obs lints for that).
                from .util import profiler

                try:
                    seconds = min(profiler.MAX_SAMPLE_SECONDS,
                                  float(q.get("seconds", ["2"])[0]))
                    hz = min(profiler.MAX_SAMPLE_HZ,
                             int(q.get("hz", ["100"])[0]))
                except (TypeError, ValueError):
                    self._json(
                        {"error": "seconds and hz must be numeric"}, 400
                    )
                    return
                self._json(profiler.sample_in_thread(seconds, hz))
                return
            if path == "/api/local/stacks":
                from .util import profiler

                self._json({"node_id": nm.node_id.hex(),
                            "pid": os.getpid(),
                            "threads": profiler.dump_stacks()})
                return
            self._json({"error": f"unknown path {path}"}, 404)
        except Exception as e:  # noqa: BLE001
            self._json({"error": repr(e)}, 500)

    def _stats(self) -> dict:
        """cpu/rss from /proc (psutil-free), plus node-manager gauges
        (ref: dashboard/modules/reporter's per-node stats)."""
        nm = self.node_manager
        out: dict = {"node_id": nm.node_id.hex(), "pid": os.getpid()}
        try:
            with open("/proc/self/statm") as f:
                pages = int(f.read().split()[1])
            out["rss_bytes"] = pages * os.sysconf("SC_PAGE_SIZE")
            with open("/proc/self/stat") as f:
                parts = f.read().split()
            tick = os.sysconf("SC_CLK_TCK")
            out["cpu_seconds"] = (int(parts[13]) + int(parts[14])) / tick
            out["num_threads"] = int(parts[19])
        except Exception:
            pass
        try:
            out["load_avg"] = list(os.getloadavg())
        except Exception:
            pass
        try:
            out["num_workers"] = len(nm.workers)
        except Exception:
            pass
        return out


class DashboardAgent:
    def __init__(self, node_manager, host: str = "127.0.0.1",
                 port: int = 0):
        handler = type(
            "_BoundAgentHandler", (_AgentHandler,),
            {"node_manager": node_manager},
        )
        self._server = ThreadingHTTPServer((host, port), handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="dashboard-agent",
        )
        self.host = host
        self.port = self._server.server_address[1]
        self._nm = node_manager

    def start(self) -> "DashboardAgent":
        self._thread.start()
        # Register in the cluster KV so the head dashboard can proxy.
        nm = self._nm

        async def register():
            if nm._gcs is not None:
                await nm._gcs.kv_put(
                    f"__dashboard_agent__/{nm.node_id.hex()}",
                    f"{self.host}:{self.port}".encode(),
                    True,
                )

        try:
            nm.call_sync(register(), timeout=10)
        except Exception:
            pass
        return self

    def stop(self):
        try:
            self._server.shutdown()
        except Exception:
            pass


def agent_addresses() -> dict:
    """{node_hex: "host:port"} of registered agents (driver-side)."""
    from .core import runtime_context

    rt = runtime_context.current_runtime()
    out = {}
    for key in rt.kv_keys("__dashboard_agent__/"):
        v = rt.kv_get(key)
        if v:
            out[key.rsplit("/", 1)[-1]] = v.decode()
    return out
