"""Framework microbenchmark harness.

Ref analogue: python/ray/_private/ray_perf.py (task/actor-call/put
throughput) with the timeit runner of ray_microbenchmark_helpers.py:14.
Run as ``python -m ray_tpu.perf`` for the full table, or call
``run_microbenchmarks`` programmatically (bench.py and tests use reduced
iteration counts).

Each entry reports ops/s (mean of ``repeat`` timed windows). The suite
exercises the real control plane: driver puts/gets through the shm arena,
task submission through the node manager, actor round-trips over the worker
socket protocol, and (when a cluster fixture adds nodes) cross-node object
pulls.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


def timeit(
    name: str,
    fn: Callable[[], None],
    multiplier: float = 1.0,
    *,
    warmup: int = 1,
    repeat: int = 3,
    min_window_s: float = 0.5,
) -> Tuple[str, float]:
    """Run ``fn`` in timed windows and return (name, ops_per_sec * multiplier)
    (ref analogue: _private/ray_microbenchmark_helpers.py timeit)."""
    for _ in range(warmup):
        fn()
    rates: List[float] = []
    for _ in range(repeat):
        start = time.perf_counter()
        count = 0
        while True:
            fn()
            count += 1
            elapsed = time.perf_counter() - start
            if elapsed >= min_window_s:
                break
        rates.append(count * multiplier / elapsed)
    return name, sum(rates) / len(rates)


def run_microbenchmarks(
    *,
    batch: int = 100,
    payload_mb: int = 10,
    repeat: int = 3,
    min_window_s: float = 0.5,
    include: Optional[List[str]] = None,
) -> Dict[str, float]:
    """Run the suite against the already-initialized runtime. Returns
    {benchmark_name: ops_per_sec}."""
    import ray_tpu

    results: Dict[str, float] = {}

    def record(name, fn, multiplier=1.0):
        if include and not any(pat in name for pat in include):
            return
        n, rate = timeit(
            name, fn, multiplier, repeat=repeat, min_window_s=min_window_s
        )
        results[n] = rate

    # --- object store (small) ---------------------------------------------
    small_ref = ray_tpu.put(b"x")

    def get_small():
        ray_tpu.get(small_ref)

    record("single client get calls", get_small)

    def put_small():
        ray_tpu.put(0)

    record("single client put calls", put_small)

    # --- tasks ------------------------------------------------------------
    @ray_tpu.remote
    def small_value():
        return b"ok"

    def task_batch():
        ray_tpu.get([small_value.remote() for _ in range(batch)])

    record("tasks submit+get throughput", task_batch, batch)

    # --- actors -----------------------------------------------------------
    @ray_tpu.remote
    class Sink:
        def ping(self):
            return b"ok"

        def ping_arg(self, x):
            return b"ok"

    a = Sink.remote()
    ray_tpu.get(a.ping.remote())  # actor creation outside the window

    def actor_sync():
        ray_tpu.get(a.ping.remote())

    record("actor calls sync round-trip", actor_sync)

    def actor_async_batch():
        ray_tpu.get([a.ping.remote() for _ in range(batch)])

    record("actor calls pipelined throughput", actor_async_batch, batch)

    ref = ray_tpu.put(b"payload")

    def actor_arg_batch():
        ray_tpu.get([a.ping_arg.remote(ref) for _ in range(batch)])

    record("actor calls with object arg", actor_arg_batch, batch)

    # --- object store (large) — LAST: the ~GB of dead 10 MiB objects this
    # creates sits at zero refs until the GC grace passes and would spill-
    # thrash every benchmark that ran after it.
    arr = np.zeros(payload_mb * 1024 * 1024 // 8, dtype=np.int64)

    def put_large():
        ray_tpu.put(arr)

    record("single client put gigabytes", put_large, payload_mb / 1024.0)

    return results


def driver_rss_bytes() -> int:
    """Resident set size of this (driver) process. Recorded around the
    queued-task probe so the footprint of a deep queue shows up in the
    perf JSON next to its throughput (delegates to the profiler plane's
    /proc reader rather than growing a second parser)."""
    import os

    from .util.profiler import process_stats

    return int(process_stats(os.getpid()).get("rss_bytes", 0))


def run_envelope_probes(
    *,
    num_args: int = 10_000,
    num_queued: int = 100_000,
    num_returns: int = 3000,
    num_get: int = 10_000,
) -> Dict[str, float]:
    """Scalability-envelope probes at FULL reference magnitude for
    args/returns/get (ref: release/benchmarks/README.md — 10k+ object
    args to one task, 3k+ returns from one task, 10k+ plasma objects in
    one get). The queue probe defaults to 100k for suite runtime; the
    1M+ reference headline is exercised by the dedicated run
    (num_queued=1_000_000 — r5 measured 1M submit 18.7k ops/s, drain
    5.0k ops/s, 4.4 GB RSS on the 1-core sandbox)."""
    import ray_tpu

    results: Dict[str, float] = {}

    # Warm the worker pool first: a cold probe would time worker spawn
    # (~2s/process on hosts with heavy sitecustomize), not the envelope.
    @ray_tpu.remote
    def _warm():
        return None

    ray_tpu.get([_warm.remote() for _ in range(20)])

    # --- N object args to a single task (ref envelope: 10k+) -------------
    refs = [ray_tpu.put(i) for i in range(num_args)]

    @ray_tpu.remote
    def count(*xs):
        return len(xs)

    t0 = time.perf_counter()
    assert ray_tpu.get(count.remote(*refs), timeout=300) == num_args
    results[f"{num_args} object args to one task seconds"] = (
        time.perf_counter() - t0
    )
    del refs

    # --- N tasks queued on one node (ref envelope: 1M+) ------------------
    @ray_tpu.remote
    def noop():
        return None

    rss_before = driver_rss_bytes()
    t0 = time.perf_counter()
    queued = [noop.remote() for _ in range(num_queued)]
    submit_dt = time.perf_counter() - t0
    results[f"{num_queued} queued tasks submit ops/s"] = num_queued / submit_dt
    results[f"{num_queued} queued tasks rss before gb"] = rss_before / 1e9
    results[f"{num_queued} queued tasks rss after submit gb"] = (
        driver_rss_bytes() / 1e9
    )
    ray_tpu.get(queued, timeout=600)
    results[f"{num_queued} queued tasks drain ops/s"] = num_queued / (
        time.perf_counter() - t0
    )
    del queued

    # --- N returns from a single task (ref envelope: 3k+) ----------------
    @ray_tpu.remote(num_returns=num_returns)
    def fan_out():
        return tuple(range(num_returns))

    t0 = time.perf_counter()
    out = ray_tpu.get(list(fan_out.remote()), timeout=300)
    assert len(out) == num_returns
    results[f"{num_returns} returns from one task seconds"] = (
        time.perf_counter() - t0
    )

    # --- N objects in a single get (ref envelope: 10k+) ------------------
    refs = [ray_tpu.put(i) for i in range(num_get)]
    t0 = time.perf_counter()
    vals = ray_tpu.get(refs, timeout=300)
    assert len(vals) == num_get
    results[f"{num_get} objects in one get seconds"] = (
        time.perf_counter() - t0
    )
    return results


def run_cluster_benchmarks(
    cluster, *, payload_mb: int = 10, repeat: int = 3, min_window_s: float = 0.5
) -> Dict[str, float]:
    """Cross-node benchmarks over a cluster fixture with at least one node
    carrying a ``{"gadget": 1}`` resource (object pull over the peer plane)."""
    import ray_tpu

    results: Dict[str, float] = {}
    nbytes = payload_mb * 1024 * 1024

    @ray_tpu.remote(resources={"gadget": 1})
    def produce():
        return np.zeros(nbytes // 8, dtype=np.int64)

    def transfer():
        # New object each window iteration: a cached pull would measure
        # nothing.
        ray_tpu.get(produce.remote(), timeout=120)

    name, rate = timeit(
        "cross-node object transfer gigabytes",
        transfer,
        payload_mb / 1024.0,
        repeat=repeat,
        min_window_s=min_window_s,
    )
    results[name] = rate
    return results


def main():
    import ray_tpu

    ray_tpu.init(ignore_reinit_error=True)
    results = run_microbenchmarks()
    width = max(len(k) for k in results)
    for name, rate in results.items():
        unit = "GB/s" if "gigabytes" in name else "ops/s"
        print(f"{name.ljust(width)}  {rate:12.2f} {unit}")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
