"""ray_tpu: a TPU-native distributed AI framework.

A ground-up rebuild of the capabilities of Ray (reference: vitsai/ray) for
TPU pods: a task/actor/object runtime orchestrating SPMD JAX/XLA programs,
with sharding-first parallelism (dp/fsdp/tp/pp/sp/ep over jax.sharding.Mesh),
XLA collectives over ICI instead of NCCL, Pallas kernels for the hot ops,
streaming data ingest into HBM, and TPU-serving with continuous batching.

Public surface mirrors the reference's `ray.*` core API
(ref: python/ray/__init__.py:172-203) plus the TPU-first libraries:
``ray_tpu.parallel``, ``ray_tpu.ops``, ``ray_tpu.models``, ``ray_tpu.train``,
``ray_tpu.data``, ``ray_tpu.tune``, ``ray_tpu.serve``.
"""

from ._version import __version__  # noqa: F401
from . import dag  # noqa: F401
from . import dashboard  # noqa: F401
from . import job_submission  # noqa: F401
from . import util  # noqa: F401
from . import workflow  # noqa: F401
from .core import (  # noqa: F401
    method,
    ActorClass,
    ActorDiedError,
    ActorHandle,
    GetTimeoutError,
    ObjectRef,
    ObjectRefGenerator,
    RayTpuError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
    available_resources,
    cancel,
    cluster_resources,
    drain_node,
    get,
    get_actor,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    kv_get,
    kv_put,
    nodes,
    put,
    remote,
    shutdown,
    timeline,
    timeline_otlp,
    wait,
)
from .core import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    SpmdActorGroup,
    SpmdGroupError,
    tpu,
)

__all__ = [
    "__version__",
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "get_runtime_context",
    "cluster_resources",
    "available_resources",
    "nodes",
    "drain_node",
    "timeline",
    "timeline_otlp",
    "kv_put",
    "kv_get",
    "ObjectRef",
    "ObjectRefGenerator",
    "ActorClass",
    "ActorHandle",
    "RayTpuError",
    "TaskError",
    "ActorDiedError",
    "WorkerCrashedError",
    "GetTimeoutError",
    "TaskCancelledError",
    "SpmdActorGroup",
    "SpmdGroupError",
    "tpu",
]
