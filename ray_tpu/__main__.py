"""``python -m ray_tpu`` → the rtpu CLI (ref: the `ray` console script)."""

import sys

from .scripts.cli import main

if __name__ == "__main__":
    sys.exit(main())
