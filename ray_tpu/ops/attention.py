"""Attention ops (local/single-shard).

The XLA fallback path: einsum attention with numerically-stable softmax.
XLA fuses this well on TPU (the MXU does the two einsums; the softmax is
fused elementwise); the Pallas flash kernel (ops/flash_attention.py) is the
HBM-optimal path for long sequences. Both share this signature.

No counterpart exists in the reference — it delegates attention to user
frameworks; this framework owns its compute path (SURVEY.md §5.7).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def mha_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Skv, Hkv, D]
    v: jax.Array,  # [B, Skv, Hkv, D]
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset: int = 0,
    kv_offset: int = 0,
    bias: Optional[jax.Array] = None,
) -> jax.Array:
    """Multi-head attention with optional GQA (Hkv divides H) and causal
    masking in *global* coordinates: query position i is q_offset + i,
    key position j is kv_offset + j — offsets make the same kernel correct
    for sharded sequence blocks (ring attention) and decode steps."""
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    scale = scale if scale is not None else D ** -0.5
    if Hkv != H:
        assert H % Hkv == 0, f"GQA requires H % Hkv == 0, got {H=} {Hkv=}"
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        scores = scores + bias
    if causal:
        q_pos = q_offset + jnp.arange(Sq)[:, None]
        k_pos = kv_offset + jnp.arange(k.shape[1])[None, :]
        mask = k_pos <= q_pos  # [Sq, Skv]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    out = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", out, v)


def attention_block_accumulate(
    q: jax.Array,        # [B, Sq, H, D]
    k: jax.Array,        # [B, Skv, H, D]
    v: jax.Array,        # [B, Skv, H, D]
    m: jax.Array,        # [B, H, Sq]   running max (start: -inf)
    l: jax.Array,        # [B, H, Sq]   running denominator (start: 0)
    acc: jax.Array,      # [B, Sq, H, D] running numerator (start: 0)
    *,
    scale: float,
    mask: Optional[jax.Array] = None,  # [Sq, Skv] True = attend
):
    """One online-softmax (flash) accumulation step against a KV block.
    This is the inner update of both ring attention (block = remote KV
    shard) and the Pallas flash kernel (block = VMEM tile)."""
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    # Correction guards: fully-masked-so-far rows have m == -inf.
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    correction = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    p = jnp.where(
        jnp.isfinite(scores), jnp.exp(scores - safe_m[..., None]), 0.0
    )  # [B,H,Sq,Skv]
    l_new = l * correction + p.sum(axis=-1)
    acc_new = (
        acc * correction.transpose(0, 2, 1)[..., None]
        + jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    )
    return m_new, l_new, acc_new


def attention_finalize(l: jax.Array, acc: jax.Array) -> jax.Array:
    """Divide the numerator by the accumulated denominator."""
    denom = jnp.maximum(l, 1e-37).transpose(0, 2, 1)[..., None]
    return (acc / denom).astype(acc.dtype)
