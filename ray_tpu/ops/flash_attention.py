"""Flash attention as a Pallas TPU kernel.

The HBM-optimal attention path (SURVEY.md §5.7): QK^T logits never
materialize in HBM — each query block streams KV blocks through VMEM with
online-softmax accumulation (flash attention v2 schedule), so memory is
O(S·D) instead of O(S²) and both matmuls hit the MXU back-to-back. Causal
masking skips fully-masked KV blocks (the loop's upper bound is computed
per query block), recovering the ~2x causal FLOP saving.

No counterpart exists in the reference — it delegates attention to user
frameworks; this framework owns its compute path. Falls back to the XLA
einsum implementation (ops/attention.py) off-TPU or for shapes the kernel
doesn't tile.

Training: the backward is a fused Pallas kernel pair (flash attention v2
backward schedule): the forward additionally emits the per-row logsumexp,
and two kernels recompute P block-wise in VMEM — one accumulating dQ over
KV blocks, one accumulating dK/dV over Q blocks — so the S^2 probability
matrix never hits HBM in either direction. The dK/dV kernel runs on a
KV-HEAD grid: all ``rep`` query heads of a GQA group stay resident in
VMEM and the group reduction happens in the f32 accumulator, so dK/dV is
written to HBM once per KV head (not per query head + external sum). A
per-query-head fallback kernel covers shapes whose grouped Q block would
not fit VMEM.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale: float,
                      causal: bool, q_offset: int, kv_offset: int,
                      block_k: int):
    from jax.experimental import pallas as pl

    block_q = q_ref.shape[1]
    head_dim = q_ref.shape[2]
    skv = k_ref.shape[1]
    nk = skv // block_k
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale  # [Bq, D]

    q_start = q_offset + qi * block_q  # global position of this q block

    if causal:
        # KV blocks whose first position exceeds this q block's last
        # position are fully masked: bound the loop instead of masking.
        last_q = q_start + block_q - 1
        hi = jnp.clip((last_q - kv_offset) // block_k + 1, 0, nk)
    else:
        hi = nk

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = q @ k.T  # [Bq, Bk] on the MXU
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kv_offset + j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=1)
        acc_new = acc * alpha[:, None] + p @ v  # second MXU matmul
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((block_q,), dtype=jnp.float32)
    acc0 = jnp.zeros((block_q, head_dim), dtype=jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
    # Guard the all-masked case (possible when kv_offset > q positions).
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe[:, None]
    o_ref[0] = out.astype(o_ref.dtype)
    # Per-row logsumexp: the backward recomputes P = exp(S - lse) from it.
    lse_ref[0, 0] = m + jnp.log(l_safe)


def _flash_fwd(q3, k3, v3, *, heads: int, kv_heads: int, scale: float,
               causal: bool, q_offset: int, kv_offset: int,
               block_q: int, block_k: int, interpret: bool = False):
    """q3: [B*H, Sq, D]; k3/v3: [B*Hkv, Skv, D] → [B*H, Sq, D]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, d = q3.shape
    skv = k3.shape[1]
    rep = heads // kv_heads
    grid = (bh, sq // block_q)

    def kv_index(i, j):
        # GQA: query head h reads kv head h // rep of the same batch.
        b = i // heads
        h = i % heads
        return (b * kv_heads + h // rep, 0, 0)

    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal,
        q_offset=q_offset, kv_offset=kv_offset, block_k=block_k,
    )
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
            # [bh, 1, sq]: a (1, 1, block) tile satisfies the TPU
            # (8, 128)-divisible-or-full block rule; flat [bh, sq] can't.
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, skv, d), kv_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, skv, d), kv_index,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(q3, k3, v3)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, scale: float, causal: bool,
                         q_offset: int, kv_offset: int, block_k: int):
    from jax.experimental import pallas as pl

    block_q = q_ref.shape[1]
    skv = k_ref.shape[1]
    nk = skv // block_k
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32)        # [Bq, D] (unscaled)
    do = do_ref[0].astype(jnp.float32)      # [Bq, D]
    lse = lse_ref[0, 0]                     # [Bq]
    delta = delta_ref[0, 0]                 # [Bq] = rowsum(dO * O)
    q_start = q_offset + qi * block_q
    if causal:
        last_q = q_start + block_q - 1
        hi = jnp.clip((last_q - kv_offset) // block_k + 1, 0, nk)
    else:
        hi = nk

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = (q @ k.T) * scale
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kv_offset + j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])       # masked entries -> 0
        dp = do @ v.T                       # [Bq, Bk]
        ds = p * (dp - delta[:, None])
        return dq + (ds @ k) * scale

    dq0 = jnp.zeros_like(q)
    dq = jax.lax.fori_loop(0, hi, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, scale: float, causal: bool,
                          q_offset: int, kv_offset: int, block_q: int):
    """dK/dV on a KV-head grid. q_ref/do_ref hold ALL ``rep`` query heads
    of this KV group ([rep, Sq, D]); the GQA reduction happens in the f32
    accumulator so each dK/dV block is written to HBM exactly once."""
    from jax.experimental import pallas as pl

    rep = q_ref.shape[0]
    block_k = k_ref.shape[1]
    sq = q_ref.shape[1]
    nq = sq // block_q
    ki = pl.program_id(1)
    head_dim = q_ref.shape[2]

    k = k_ref[0].astype(jnp.float32)        # [Bk, D]
    v = v_ref[0].astype(jnp.float32)        # [Bk, D]
    k_start = kv_offset + ki * block_k
    if causal:
        # First q block whose LAST position reaches this kv block.
        lo = jnp.clip((k_start - q_offset) // block_q, 0, nq)
    else:
        lo = 0

    def body_for_head(r):
        def body(j, carry):
            dk, dv = carry
            q = q_ref[r, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
            do = do_ref[r, pl.ds(j * block_q, block_q), :].astype(
                jnp.float32)
            lse = lse_ref[r, 0, pl.ds(j * block_q, block_q)]
            delta = delta_ref[r, 0, pl.ds(j * block_q, block_q)]
            s = (q @ k.T) * scale               # [Bq, Bk]
            if causal:
                q_pos = q_offset + j * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                k_pos = k_start + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1
                )
                s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
            p = jnp.exp(s - lse[:, None])
            dv = dv + p.T @ do
            dp = do @ v.T
            ds = p * (dp - delta[:, None])
            dk = dk + (ds.T @ q) * scale
            return dk, dv
        return body

    dk = jnp.zeros((block_k, head_dim), dtype=jnp.float32)
    dv = jnp.zeros((block_k, head_dim), dtype=jnp.float32)
    for r in range(rep):  # static unroll over the group's query heads
        dk, dv = jax.lax.fori_loop(lo, nq, body_for_head(r), (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# Grouped Q/dO blocks larger than this fall back to the per-head kernel
# (VMEM is ~16 MiB/core; leave room for K/V blocks, f32 casts and the
# accumulators).
_DKV_GROUP_VMEM_BUDGET = 10 * 1024 * 1024


def _flash_bwd(q3, k3, v3, do3, lse, delta, *, heads: int, kv_heads: int,
               scale: float, causal: bool, q_offset: int, kv_offset: int,
               block_q: int, block_k: int, interpret: bool = False):
    """Fused backward. q3/do3: [B*H, Sq, D]; k3/v3: [B*Hkv, Skv, D];
    lse/delta: [B*H, 1, Sq]. Returns (dq3 [B*H, Sq, D],
    dk3/dv3 [B*Hkv, Skv, D] — already reduced over each KV group)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, d = q3.shape
    skv = k3.shape[1]
    rep = heads // kv_heads

    def kv_index(i, j):
        b = i // heads
        h = i % heads
        return (b * kv_heads + h // rep, 0, 0)

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, scale=scale, causal=causal,
        q_offset=q_offset, kv_offset=kv_offset, block_k=block_k,
    )
    dq3 = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
        grid=(bh, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, skv, d), kv_index, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, skv, d), kv_index, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)

    bkv = (bh // heads) * kv_heads
    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, scale=scale, causal=causal,
        q_offset=q_offset, kv_offset=kv_offset, block_q=block_q,
    )
    grouped_bytes = 2 * rep * sq * d * q3.dtype.itemsize  # q + do resident
    if grouped_bytes <= _DKV_GROUP_VMEM_BUDGET:
        # KV-head grid: q3 rows of group g are contiguous ([g*rep,
        # (g+1)*rep) since g = b*kv_heads + hk and H = kv_heads*rep), so a
        # [rep, Sq, D] block at block-row g picks exactly the group. The
        # index maps are constant in j — Q/dO stay VMEM-resident across
        # the whole KV sweep of a group.
        dk3, dv3 = pl.pallas_call(
            dkv_kernel,
            out_shape=(
                jax.ShapeDtypeStruct((bkv, skv, d), k3.dtype),
                jax.ShapeDtypeStruct((bkv, skv, d), v3.dtype),
            ),
            grid=(bkv, skv // block_k),
            in_specs=[
                pl.BlockSpec((rep, sq, d), lambda i, j: (i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((rep, sq, d), lambda i, j: (i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((rep, 1, sq), lambda i, j: (i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((rep, 1, sq), lambda i, j: (i, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=(
                pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0),
                             memory_space=pltpu.VMEM),
            ),
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)
        return dq3, dk3, dv3

    def kv_blk_index(i, j):
        b = i // heads
        h = i % heads
        return (b * kv_heads + h // rep, j, 0)

    # Per-query-head fallback: the grouped kernel with rep=1 blocks
    # (q_ref.shape[0] == 1) is exactly the per-head computation; the
    # GQA group sum happens outside.
    dk3h, dv3h = pl.pallas_call(
        dkv_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((bh, skv, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, skv, d), v3.dtype),
        ),
        grid=(bh, skv // block_k),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), kv_blk_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), kv_blk_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sq, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, sq), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, sq), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    b = bh // heads
    dk3 = dk3h.reshape(b, kv_heads, rep, skv, d).sum(
        axis=2).reshape(bkv, skv, d).astype(k3.dtype)
    dv3 = dv3h.reshape(b, kv_heads, rep, skv, d).sum(
        axis=2).reshape(bkv, skv, d).astype(v3.dtype)
    return dq3, dk3, dv3


def _reference(q, k, v, *, causal, scale, q_offset, kv_offset):
    from .attention import mha_attention

    return mha_attention(q, k, v, causal=causal, scale=scale,
                         q_offset=q_offset, kv_offset=kv_offset)


def _to_heads3(x):
    """[B, S, H, D] -> [B*H, S, D]."""
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9)
)
def _flash_attention_core(q, k, v, causal, scale, q_offset, kv_offset,
                          block_q, block_k, interpret=False):
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    o3, _lse = _flash_fwd(
        _to_heads3(q), _to_heads3(k), _to_heads3(v),
        heads=H, kv_heads=Hkv, scale=scale, causal=causal,
        q_offset=q_offset, kv_offset=kv_offset,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return o3.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)


def _core_fwd(q, k, v, causal, scale, q_offset, kv_offset, block_q,
              block_k, interpret=False):
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    q3, k3, v3 = _to_heads3(q), _to_heads3(k), _to_heads3(v)
    o3, lse = _flash_fwd(
        q3, k3, v3, heads=H, kv_heads=Hkv, scale=scale, causal=causal,
        q_offset=q_offset, kv_offset=kv_offset,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    out = o3.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)
    return out, (q3, k3, v3, o3, lse, B, H, Hkv)


def _core_bwd(causal, scale, q_offset, kv_offset, block_q, block_k,
              interpret, res, g):
    """Fused flash backward: P recomputed block-wise in VMEM from the
    saved logsumexp; dK/dV reduced over each GQA group inside the kernel
    (KV-head grid)."""
    q3, k3, v3, o3, lse, B, H, Hkv = res
    Sq, D = q3.shape[1], q3.shape[2]
    do3 = _to_heads3(g)
    delta = (do3.astype(jnp.float32) * o3.astype(jnp.float32)).sum(
        -1
    )[:, None, :]  # [bh, 1, sq] to match the lse tiling
    dq3, dk3, dv3 = _flash_bwd(
        q3, k3, v3, do3, lse, delta, heads=H, kv_heads=Hkv, scale=scale,
        causal=causal, q_offset=q_offset, kv_offset=kv_offset,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    Skv = k3.shape[1]
    dq = dq3.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)
    dk = dk3.reshape(B, Hkv, Skv, D).transpose(0, 2, 1, 3)
    dv = dv3.reshape(B, Hkv, Skv, D).transpose(0, 2, 1, 3)
    return dq, dk, dv


_flash_attention_core.defvjp(_core_fwd, _core_bwd)


def flash_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Skv, Hkv, D]
    v: jax.Array,  # [B, Skv, Hkv, D]
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset: int = 0,
    kv_offset: int = 0,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention with GQA and global-coordinate causal masking
    (same signature as ops.attention.mha_attention). Dispatches to the
    Pallas kernel when running on TPU with tileable shapes, else to the
    XLA einsum path."""
    B, Sq, H, D = q.shape
    Skv, Hkv = k.shape[1], k.shape[2]
    scale = scale if scale is not None else D ** -0.5

    block_q = min(block_q, Sq)
    block_k = min(block_k, Skv)
    tileable = (
        Sq % block_q == 0
        and Skv % block_k == 0
        and D <= 256
        and H % Hkv == 0
    )
    if not tileable or (not _on_tpu() and not interpret):
        return _reference(q, k, v, causal=causal, scale=scale,
                          q_offset=q_offset, kv_offset=kv_offset)
    return _flash_attention_core(
        q, k, v, causal, scale, q_offset, kv_offset, block_q, block_k,
        interpret,
    )


def _on_tpu() -> bool:
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False
