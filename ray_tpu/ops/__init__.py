"""ray_tpu.ops: compute kernels (XLA reference paths + Pallas TPU kernels)."""

from .attention import (  # noqa: F401
    attention_block_accumulate,
    attention_finalize,
    mha_attention,
)
from .flash_attention import flash_attention  # noqa: F401
