"""Paged-attention decode as a Pallas TPU kernel.

Gather-free decode over a paged KV pool (models/generation.py
PagedKVCache): instead of materializing each slot's pages with
``pool[page_table]`` ([B, Pmax, page, Hkv, Dh] in HBM) and attending
densely, one kernel program per (slot, kv-head) WALKS the slot's page
table — the grid's page dimension uses scalar-prefetched page ids as the
pool block index, so each page streams HBM→VMEM exactly once and the
gathered view never exists. Online softmax accumulates across pages in
VMEM scratch (flash-attention schedule over the page walk). This is the
TPU-static analogue of vLLM's PagedAttention kernel; no reference
counterpart exists (Ray delegates model compute to user code).

Falls back to the XLA gather path off-TPU or for shapes the kernel does
not tile (models/generation.py keeps that path as `_attend_paged_xla`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _paged_decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, page: int,
                         scale: float):
    """Grid (B, Hkv, Pmax); p innermost. q_ref [1, 1, rep, D] (the GQA
    group's query rows), k_ref/v_ref [1, page, D] = the page the scalar-
    prefetched table named for (b, p); o_ref [1, 1, rep, D] constant over
    p. Scratch carries the online-softmax state across the page walk."""
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    p = pl.program_id(2)
    n_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[b]  # keys at positions 0..length are valid

    @pl.when(p * page <= length)
    def _attend_page():
        q = q_ref[...].reshape(q_ref.shape[-2:]).astype(
            jnp.float32) * scale                       # [rep, D]
        k = k_ref[...].reshape(k_ref.shape[-2:]).astype(jnp.float32)
        v = v_ref[...].reshape(v_ref.shape[-2:]).astype(jnp.float32)
        s = q @ k.T                                   # [rep, page]
        t = p * page + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        s = jnp.where(t <= length, s, _NEG_INF)
        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        prob = jnp.exp(s - m_new)
        l_scr[...] = alpha * l_scr[...] + prob.sum(axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + prob @ v
        m_scr[...] = m_new

    @pl.when(p == n_pages - 1)
    def _finish():
        l_safe = jnp.where(l_scr[...] == 0.0, 1.0, l_scr[...])
        o_ref[0, 0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


def paged_decode_attention(
    q: jax.Array,           # [B, H, D] one query row per slot
    k_pool: jax.Array,      # [Hkv, P, page, D] or [L, Hkv, P, page, D]
    v_pool: jax.Array,      # (with ``layer`` naming the static L index)
    page_table: jax.Array,  # [B, Pmax] int32
    lengths: jax.Array,     # [B] int32 — key positions <= lengths[b] attend
    *,
    layer: int | None = None,
    scale: float | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Returns [B, H, D] attention outputs. The caller has already
    scattered the current token's K/V into each slot's page cell (so
    ``lengths`` is the PRE-increment length and position ``lengths[b]``
    holds the new token)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, D = q.shape
    if layer is None:
        Hkv, P_total, page, _ = k_pool.shape

        def kv_index(b, h, p, pt_ref, len_ref):
            return (h, pt_ref[b, p], 0, 0)

        kv_block = (1, 1, page, D)
    else:
        # Full [L, Hkv, P, page, D] pool with a STATIC layer baked into
        # the index map: no layer slice is ever materialized for the
        # custom call (a sliced operand would copy pool/L bytes).
        _L, Hkv, P_total, page, _ = k_pool.shape

        def kv_index(b, h, p, pt_ref, len_ref):
            return (layer, h, pt_ref[b, p], 0, 0)

        kv_block = (1, 1, 1, page, D)
    Pmax = page_table.shape[1]
    rep = H // Hkv
    scale = scale if scale is not None else D ** -0.5

    q4 = q.reshape(B, Hkv, rep, D)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, Pmax),
        in_specs=[
            pl.BlockSpec((1, 1, rep, D),
                         lambda b, h, p, pt, ln: (b, h, 0, 0)),
            pl.BlockSpec(kv_block, kv_index),
            pl.BlockSpec(kv_block, kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, rep, D),
                               lambda b, h, p, pt, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, 1), jnp.float32),   # running max
            pltpu.VMEM((rep, 1), jnp.float32),   # running denominator
            pltpu.VMEM((rep, D), jnp.float32),   # output accumulator
        ],
    )
    kernel = functools.partial(_paged_decode_kernel, page=page,
                               scale=scale)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rep, D), q.dtype),
        interpret=interpret,
    )(page_table, lengths, q4, k_pool, v_pool)
    return out.reshape(B, H, D)


def pageable(page: int, head_dim: int) -> bool:
    """Whether the kernel tiles these shapes (TPU tile rules: head_dim
    a multiple of 128 for the lane dim, page a multiple of 8 for the
    sublane dim)."""
    return head_dim % 128 == 0 and page % 8 == 0


def on_tpu() -> bool:
    from .flash_attention import _on_tpu

    return _on_tpu()
