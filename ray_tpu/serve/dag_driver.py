"""DAGDriver: HTTP ingress deployment routing to deployment graphs.

Ref analogue: serve/drivers.py DAGDriver — one ingress deployment
that maps route prefixes to bound deployment graphs and applies an
http adapter to the raw request before calling the matched graph:

    serve.run(DAGDriver.bind({
        "/add": adder_graph,
        "/mul": multiplier_graph,
    }, http_adapter=json_request))

The driver deploys like any other deployment (replicas, autoscaling,
rolling updates apply); nested graphs deploy first via the
deployment-graph build and arrive as live handles.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .deployment import Deployment


def json_request(request: Any) -> Any:
    """Default http adapter: pass the parsed JSON body through (ref:
    serve.http_adapters.json_request)."""
    return request


class _DAGDriverImpl:
    """The ingress callable: route -> handle dispatch."""

    def __init__(self, routes: Dict[str, Any],
                 http_adapter: Optional[Callable] = None):
        # Values arrive as live DeploymentHandles (BoundDeployment
        # resolution happens in the replica).
        self._routes = {self._norm(k): v for k, v in routes.items()}
        self._adapter = http_adapter or json_request

    @staticmethod
    def _norm(route: str) -> str:
        return "/" + route.strip("/")

    def __call__(self, request: Any, *, route: str = "") -> Any:
        """Dispatch ``request`` to the graph mounted at ``route``.
        With a single mounted route, ``route`` may be omitted."""
        key = self._norm(route) if route else None
        if key is None:
            if len(self._routes) == 1:
                key = next(iter(self._routes))
            else:
                raise ValueError(
                    f"route required; mounted: "
                    f"{sorted(self._routes)}"
                )
        handle = self._routes.get(key)
        if handle is None:
            raise KeyError(
                f"no graph mounted at {key!r}; mounted: "
                f"{sorted(self._routes)}"
            )
        value = self._adapter(request)
        # The nested graph call runs under THIS request's remaining
        # deadline budget (ambient, installed from the call frame on
        # the driver replica); the serve_default_request_timeout_s knob
        # seeds it when no budget arrived — deadline propagation keeps
        # multi-hop graphs inside one end-to-end budget.
        from ..core.config import get_config
        from ..util import overload

        return handle.remote(value).result(timeout=overload.remaining(
            get_config().serve_default_request_timeout_s
        ))

    def routes(self) -> list:
        return sorted(self._routes)


class DAGDriver:
    """Builder: ``DAGDriver.bind({route: graph, ...})`` returns a
    Deployment whose replicas dispatch to the mounted graphs."""

    @staticmethod
    def bind(routes: Dict[str, Any],
             http_adapter: Optional[Callable] = None,
             **deployment_options: Any) -> Deployment:
        if not routes:
            raise ValueError("DAGDriver.bind needs at least one route")
        for k, v in routes.items():
            if not isinstance(v, Deployment):
                raise TypeError(
                    f"route {k!r} must map to a bound Deployment, "
                    f"got {type(v).__name__}"
                )
        dep = Deployment(
            _DAGDriverImpl, deployment_options.pop("name", "DAGDriver"),
            **deployment_options,
        )
        return dep.bind(routes, http_adapter=http_adapter)
