"""Serve controller + application state.

Ref analogue: serve/_private/controller.py ServeController (:88) owning
ApplicationState/DeploymentState (deployment_state.py:1193 — replica state
machine, scaling). The controller is a named actor; deploy/scale/delete
reconcile the replica actor set.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import cloudpickle

CONTROLLER_NAME = "__serve_controller__"


class ServeControllerActor:
    """Runs as a named actor; holds deployment → replica handles."""

    def __init__(self):
        self._deployments: Dict[str, Dict[str, Any]] = {}

    def deploy(self, name: str, blob: bytes, init_args, init_kwargs,
               num_replicas: int, ray_actor_options: Dict[str, Any],
               batch_config: Optional[Dict[str, Any]]) -> List[Any]:
        import ray_tpu
        from .replica import Replica

        existing = self._deployments.get(name)
        if existing:
            for h in existing["replicas"]:
                try:
                    ray_tpu.kill(h)
                except Exception:
                    pass
        opts = dict(ray_actor_options)
        actor_cls = ray_tpu.remote(**opts)(Replica) if opts else \
            ray_tpu.remote(Replica)
        replicas = [
            actor_cls.remote(blob, init_args, init_kwargs)
            for _ in range(num_replicas)
        ]
        # Block until every replica's constructor finished (gang readiness).
        ray_tpu.get([r.ping.remote() for r in replicas])
        self._deployments[name] = {
            "blob": blob,
            "init_args": init_args,
            "init_kwargs": init_kwargs,
            "replicas": replicas,
            "ray_actor_options": ray_actor_options,
            "batch_config": batch_config,
        }
        return replicas

    def scale(self, name: str, num_replicas: int) -> List[Any]:
        import ray_tpu
        from .replica import Replica

        d = self._deployments[name]
        cur = d["replicas"]
        if num_replicas > len(cur):
            opts = dict(d["ray_actor_options"])
            actor_cls = ray_tpu.remote(**opts)(Replica) if opts else \
                ray_tpu.remote(Replica)
            new = [
                actor_cls.remote(d["blob"], d["init_args"], d["init_kwargs"])
                for _ in range(num_replicas - len(cur))
            ]
            ray_tpu.get([r.ping.remote() for r in new])
            cur.extend(new)
        elif num_replicas < len(cur):
            for h in cur[num_replicas:]:
                try:
                    ray_tpu.kill(h)
                except Exception:
                    pass
            del cur[num_replicas:]
        return cur

    def get_replicas(self, name: str) -> List[Any]:
        return self._deployments[name]["replicas"]

    def get_batch_config(self, name: str):
        return self._deployments[name]["batch_config"]

    def list_deployments(self) -> Dict[str, int]:
        return {k: len(v["replicas"]) for k, v in self._deployments.items()}

    def delete(self, name: str):
        import ray_tpu

        d = self._deployments.pop(name, None)
        if d:
            for h in d["replicas"]:
                try:
                    ray_tpu.kill(h)
                except Exception:
                    pass

    def shutdown(self):
        for name in list(self._deployments):
            self.delete(name)
        return "ok"
