"""Serve controller + deployment state machine.

Ref analogue: serve/_private/controller.py ServeController (:88) owning
ApplicationState/DeploymentState (deployment_state.py:1193 — replica state
machine, scaling, rolling updates), autoscaling_policy.py (queue-depth
driven replica count), long_poll.py (push of route changes to handles).

The controller is a named actor created with max_concurrency so that
long-poll calls from many handles block their own threads without stalling
deploy/scale. A daemon reconcile thread drives autoscaling from metrics
pushed by handles (ref analogue: handle-side autoscaling metrics,
serve/_private/router.py metrics pusher).

Rolling updates (ref: deployment_state.py _check_and_update_replicas):
deploying a NEW VERSION over a live deployment starts one new-version
replica at a time, waits for readiness, then retires one old-version
replica — the route set never drops below the target count, so in-flight
traffic always has somewhere to go (zero-downtime).
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional

from ..util import events as cluster_events

CONTROLLER_NAME = "__serve_controller__"
CONTROLLER_MAX_CONCURRENCY = 16

RECONCILE_INTERVAL_S = 0.2
# Handle metric reports older than this are dropped (handle died / idle).
METRIC_STALENESS_S = 2.0
HEALTH_CHECK_PERIOD_S = 1.0
HEALTH_CHECK_TIMEOUT_S = 2.0
# How long one read of the SLO engine's `__slo_status__` blob serves the
# autoscaler loop (the engine only refreshes it every slo_eval_interval_s).
SLO_STATUS_TTL_S = 1.0
# Downscaling is held while ANY window still burns faster than this —
# scale-in during recovery re-lights the very alert that just cleared.
SLO_DOWNSCALE_BURN_MAX = 0.5


class _DeploymentState:
    """Target + actual state for one deployment."""

    def __init__(self):
        self.name: str = ""  # deployment name (metric/trace tag)
        self.blob: bytes = b""
        self.init_args = ()
        self.init_kwargs: Dict[str, Any] = {}
        self.target_replicas: int = 1
        self.ray_actor_options: Dict[str, Any] = {}
        self.batch_config: Optional[Dict[str, Any]] = None
        self.autoscaling: Optional[Dict[str, float]] = None
        # Normalized SLO spec (util/slo.normalize_spec output) or None.
        self.slo: Optional[Dict[str, Any]] = None
        self.is_asgi: bool = False  # raw-HTTP ingress deployment
        self.version: str = ""
        # Ceiling for each replica's adaptive concurrency limiter.
        self.max_concurrent_queries: int = 8
        # replica actor hex -> {"since": ts, "last": ts, "state": str}
        # from handle routers reporting non-closed circuit breakers; a
        # replica continuously OPEN past serve_breaker_eject_s is
        # ejected through the drain machinery.
        self.breaker_reports: Dict[str, Dict[str, Any]] = {}
        # Live replica handles, each tagged with the version it was
        # started under: list of (handle, version).
        self.replicas: List[Any] = []
        self.replica_versions: List[str] = []
        # Bumped whenever the routable replica set changes; handles
        # long-poll on this (ref: long_poll.py snapshot ids).
        self.route_version: int = 0
        # Autoscaler smoothing state.
        self.upscale_since: Optional[float] = None
        self.downscale_since: Optional[float] = None
        # handle_id -> (total_outstanding, timestamp)
        self.handle_metrics: Dict[str, Any] = {}


class ServeControllerActor:
    """Runs as a named actor; reconciles replica sets toward target state."""

    def __init__(self):
        self._deployments: Dict[str, _DeploymentState] = {}
        self._lock = threading.RLock()
        self._route_cond = threading.Condition(self._lock)
        self._stopped = False
        # Runtime override of serve_breaker_eject_s (ops/test hook; the
        # config knob seeds this process's default when None).
        self._breaker_eject_override: Optional[float] = None
        # Cached `__slo_status__` read for the autoscale loop.
        self._slo_status: Dict[str, Any] = {}
        self._slo_status_ts: float = 0.0
        self._reconciler = threading.Thread(
            target=self._reconcile_loop, daemon=True
        )
        self._reconciler.start()

    # ---- replica lifecycle helpers ----------------------------------------

    def _start_replicas(self, st: _DeploymentState, n: int,
                        version: str) -> List[Any]:
        import ray_tpu
        from .replica import Replica

        opts = dict(st.ray_actor_options)
        actor_cls = ray_tpu.remote(**opts)(Replica) if opts else \
            ray_tpu.remote(Replica)
        new = [
            actor_cls.remote(st.blob, st.init_args, st.init_kwargs,
                             version, st.name, st.max_concurrent_queries)
            for _ in range(n)
        ]
        # Block until every replica's constructor finished (readiness gate;
        # ref: deployment_state.py waiting for replicas to be RUNNING).
        ray_tpu.get([r.ping.remote() for r in new])
        return new

    def _kill_replica(self, handle) -> None:
        import ray_tpu

        try:
            ray_tpu.kill(handle)
        except Exception as e:
            # A replica we failed to kill may keep serving a retired
            # version (or leak a worker) — say so instead of hiding it.
            cluster_events.emit(
                cluster_events.WARNING, cluster_events.SERVE,
                f"replica kill failed ({type(e).__name__}: {e}); the "
                f"worker may be leaked",
            )

    def _bump_route(self, st: _DeploymentState) -> None:
        st.route_version += 1
        self._route_cond.notify_all()

    # ---- public control API ------------------------------------------------

    def deploy(self, name: str, blob: bytes, init_args, init_kwargs,
               num_replicas: int, ray_actor_options: Dict[str, Any],
               batch_config: Optional[Dict[str, Any]],
               autoscaling: Optional[Dict[str, float]] = None,
               version: Optional[str] = None,
               is_asgi: bool = False,
               max_concurrent_queries: int = 8,
               slo: Optional[Dict[str, Any]] = None) -> List[Any]:
        from ..util import slo as slo_mod

        # Validate at deploy time — a typo'd spec must fail the deploy
        # (the ValueError propagates to the caller through ray_tpu.get),
        # not silently disable the objective at eval time.
        slo_spec = slo_mod.normalize_spec(slo) if slo is not None else None
        if version is None:
            version = hashlib.sha1(
                blob + repr((init_args, init_kwargs)).encode()
            ).hexdigest()[:12]

        with self._lock:
            st = self._deployments.get(name)
            fresh = st is None
            if fresh:
                st = _DeploymentState()
                self._deployments[name] = st
            st.name = name
            old_version = st.version
            st.blob = blob
            st.is_asgi = is_asgi
            st.init_args = init_args
            st.init_kwargs = dict(init_kwargs)
            st.ray_actor_options = dict(ray_actor_options)
            st.batch_config = batch_config
            st.autoscaling = dict(autoscaling) if autoscaling else None
            st.slo = slo_spec
            st.version = version
            st.max_concurrent_queries = max(1, int(max_concurrent_queries))
            if st.autoscaling:
                lo = int(st.autoscaling.get("min_replicas", 1))
                hi = int(st.autoscaling.get("max_replicas", num_replicas))
                num_replicas = min(max(num_replicas, lo), hi)
            st.target_replicas = num_replicas

        self._publish_slo_spec(name, slo_spec)
        cluster_events.emit(
            cluster_events.INFO, cluster_events.SERVE,
            f"deployment '{name}' deploy: version={version} "
            f"target={num_replicas}"
            + ("" if fresh else f" (was {old_version or 'unversioned'})"),
            custom_fields={"deployment": name, "version": version,
                           "target_replicas": num_replicas,
                           "fresh": fresh},
        )
        if fresh or not st.replicas:
            new = self._start_replicas(st, num_replicas, version)
            with self._lock:
                st.replicas = new
                st.replica_versions = [version] * len(new)
                self._bump_route(st)
            cluster_events.emit(
                cluster_events.INFO, cluster_events.SERVE,
                f"deployment '{name}': {len(new)} replica(s) running "
                f"(version {version})",
                custom_fields={"deployment": name,
                               "num_replicas": len(new)},
            )
            return list(st.replicas)

        if old_version == version:
            # Same code + args: just converge the replica count.
            self._converge_count(name)
            with self._lock:
                return list(st.replicas)

        self._rolling_update(name, version)
        with self._lock:
            return list(st.replicas)

    def _rolling_update(self, name: str, version: str) -> None:
        """Replace old-version replicas one at a time, new-first."""
        while True:
            with self._lock:
                st = self._deployments.get(name)
                if st is None or st.version != version:
                    return  # deleted or superseded by a newer deploy
                stale = [
                    i for i, v in enumerate(st.replica_versions)
                    if v != version
                ]
                if not stale and len(st.replicas) >= st.target_replicas:
                    return
            # Surge: start the replacement before retiring the old one so
            # capacity never dips (ref: max_surge semantics).
            new = self._start_replicas(st, 1, version)
            with self._lock:
                if st.version != version:
                    break  # superseded mid-update; new replica is orphaned
                st.replicas.extend(new)
                st.replica_versions.extend([version] * len(new))
                stale = [
                    i for i, v in enumerate(st.replica_versions)
                    if v != version
                ]
                victim = None
                if stale and len(st.replicas) > st.target_replicas:
                    i = stale[0]
                    victim = st.replicas.pop(i)
                    st.replica_versions.pop(i)
                self._bump_route(st)
            if victim is not None:
                # Retired from the route set first; grace period lets
                # in-flight calls drain before the actor dies.
                cluster_events.emit(
                    cluster_events.INFO, cluster_events.SERVE,
                    f"deployment '{name}' rolling update: replaced one "
                    f"replica with version {version}",
                    custom_fields={"deployment": name,
                                   "version": version},
                )
                self._drain_and_kill(victim)
        # Superseded: clean up the orphan we just made.
        for h in new:
            self._kill_replica(h)

    def _drain_and_kill(self, handle) -> None:
        import ray_tpu

        try:
            ray_tpu.get(handle.prepare_shutdown.remote(), timeout=30.0)
        except Exception as e:
            # The replica is killed regardless, but a shutdown hook that
            # failed (or timed out with requests in flight) must leave a
            # trace — those are the requests that died with it.
            cluster_events.emit(
                cluster_events.WARNING, cluster_events.SERVE,
                f"replica prepare_shutdown failed before kill: {e!r}",
                custom_fields={"error_type": type(e).__name__},
            )
        self._kill_replica(handle)

    def _converge_count(self, name: str) -> None:
        """Bring the live replica count to target_replicas."""
        with self._lock:
            st = self._deployments.get(name)
            if st is None:
                return
            cur = len(st.replicas)
            target = st.target_replicas
            version = st.version
            victims = []
            if cur > target:
                victims = st.replicas[target:]
                del st.replicas[target:]
                del st.replica_versions[target:]
                self._bump_route(st)
        if cur < target:
            new = self._start_replicas(st, target - cur, version)
            with self._lock:
                if self._deployments.get(name) is st:
                    st.replicas.extend(new)
                    st.replica_versions.extend([version] * len(new))
                    self._bump_route(st)
                else:
                    victims = new
        for h in victims:
            self._drain_and_kill(h)

    def drain_replicas(self, actor_ids: List[str]) -> Dict[str, int]:
        """Node-drain migration (ref analogue: deployment_state.py's
        drain-based replica migration behind the GCS DrainNode RPC):
        surge-replace every replica whose actor id is in ``actor_ids``
        (a draining node's), bump the route set so handles stop picking
        the victims, then gracefully drain and kill them. The route set
        never drops below target, so in-flight traffic always has
        somewhere to go — the same zero-downtime discipline as a
        rolling update."""
        wanted = set(actor_ids)
        moved: Dict[str, int] = {}
        for name in list(self._deployments):
            with self._lock:
                st = self._deployments.get(name)
                if st is None:
                    continue
                victims = [r for r in st.replicas
                           if r._actor_id.hex() in wanted]
                version = st.version
            if not victims:
                continue
            # Surge first: replacements come up (placed off the draining
            # node — it is unschedulable by now) before any victim
            # leaves the route set.
            new = self._start_replicas(st, len(victims), version)
            victim_ids = {id(r) for r in victims}
            with self._lock:
                if self._deployments.get(name) is not st \
                        or st.version != version:
                    orphans = new  # superseded mid-drain
                else:
                    keep = [
                        (r, v) for r, v in zip(st.replicas,
                                               st.replica_versions)
                        if id(r) not in victim_ids
                    ]
                    st.replicas = [r for r, _ in keep] + new
                    st.replica_versions = (
                        [v for _, v in keep] + [version] * len(new)
                    )
                    self._bump_route(st)
                    orphans = []
            if orphans:
                for h in orphans:
                    self._kill_replica(h)
                continue
            cluster_events.emit(
                cluster_events.INFO, cluster_events.SERVE,
                f"deployment '{name}' drain: migrating "
                f"{len(victims)} replica(s) off a draining node",
                custom_fields={"deployment": name,
                               "migrated": len(victims)},
            )
            for h in victims:
                self._drain_and_kill(h)
            moved[name] = len(victims)
        return moved

    def scale(self, name: str, num_replicas: int) -> List[Any]:
        with self._lock:
            st = self._deployments[name]
            st.target_replicas = num_replicas
        self._converge_count(name)
        with self._lock:
            return list(st.replicas)

    # ---- autoscaling -------------------------------------------------------

    def record_handle_metrics(self, name: str, handle_id: str,
                              outstanding: int) -> None:
        """Handles push their outstanding-request totals here (ref:
        handle-side autoscaling metrics push, serve/_private/router.py)."""
        with self._lock:
            st = self._deployments.get(name)
            if st is not None:
                st.handle_metrics[handle_id] = (outstanding, time.monotonic())

    # Report gaps longer than this end a breaker-open episode (the
    # handle's breaker closed, or the handle died).
    BREAKER_REPORT_STALE_S = 5.0

    def report_breakers(self, name: str, handle_id: str,
                        open_map: Dict[str, str]) -> None:
        """Handle routers report replicas whose circuit breakers are not
        closed ({replica actor hex: state}). The reconcile loop ejects
        replicas continuously OPEN past ``serve_breaker_eject_s``
        through the drain machinery (ref analogue: deployment_state.py
        health-based replica replacement, envoy outlier ejection)."""
        now = time.monotonic()
        with self._lock:
            st = self._deployments.get(name)
            if st is None:
                return
            for replica_hex, state_name in open_map.items():
                rec = st.breaker_reports.get(replica_hex)
                if rec is None or \
                        now - rec["last"] > self.BREAKER_REPORT_STALE_S:
                    st.breaker_reports[replica_hex] = {
                        "since": now, "last": now, "state": state_name,
                    }
                else:
                    rec["last"] = now
                    rec["state"] = state_name

    def set_breaker_eject_s(self, seconds: float) -> str:
        """Override the breaker-ejection threshold at runtime (ops/test
        hook; serve_breaker_eject_s seeds the default)."""
        self._breaker_eject_override = float(seconds)
        return "ok"

    def _eject_broken_once(self, name: str) -> None:
        """Replace replicas whose breakers have been reported OPEN
        continuously for serve_breaker_eject_s, via the PR 6 drain
        machinery (surge-replace, route-set swap, graceful drain+kill)."""
        from ..core.config import get_config

        eject_s = (self._breaker_eject_override
                   if self._breaker_eject_override is not None
                   else get_config().serve_breaker_eject_s)
        if eject_s <= 0:
            return
        now = time.monotonic()
        with self._lock:
            st = self._deployments.get(name)
            if st is None or not st.replicas:
                return
            live = {r._actor_id.hex() for r in st.replicas}
            # Age out reports for gone replicas / healed breakers.
            for hex_id in list(st.breaker_reports):
                rec = st.breaker_reports[hex_id]
                if hex_id not in live or \
                        now - rec["last"] > 6 * self.BREAKER_REPORT_STALE_S:
                    del st.breaker_reports[hex_id]
            victims = [
                hex_id for hex_id, rec in st.breaker_reports.items()
                if rec["state"] == "open"
                and now - rec["since"] >= eject_s
                and now - rec["last"] <= self.BREAKER_REPORT_STALE_S
            ]
            # Never eject below one live replica per surge step; the
            # drain path surges first, so all victims are safe to hand
            # over at once.
            if not victims:
                return
            for hex_id in victims:
                del st.breaker_reports[hex_id]
        cluster_events.emit(
            cluster_events.WARNING, cluster_events.SERVE,
            f"deployment '{name}': ejecting {len(victims)} "
            f"persistently-unhealthy replica(s) (circuit breaker open "
            f"> {eject_s:.0f}s); surge-replacing via drain",
            custom_fields={"deployment": name, "ejected": len(victims)},
        )
        self.drain_replicas(victims)

    def _publish_slo_spec(self, name: str,
                          spec: Optional[Dict[str, Any]]) -> None:
        """(Un)declare the deployment's SLO to the head engine via the
        `__slo__/<name>` KV key (util/slo reads it each eval tick)."""
        import json

        from ..core import runtime_context
        from ..util import slo as slo_mod

        rt = runtime_context.current_runtime_or_none()
        if rt is None:
            return  # unit-tested outside a cluster: nothing to publish to
        key = f"{slo_mod.SPEC_PREFIX}{name}"
        try:
            if spec is None:
                rt.kv_del(key)
            else:
                rt.kv_put(key, json.dumps(spec).encode())
        except Exception as e:
            # A lost spec means silent non-evaluation — surface it.
            cluster_events.emit(
                cluster_events.WARNING, cluster_events.SERVE,
                f"deployment '{name}': SLO spec publish failed "
                f"({type(e).__name__}: {e})",
                custom_fields={"deployment": name},
            )

    def _slo_status_cached(self) -> Dict[str, Any]:
        """The engine's `__slo_status__` blob, re-read at most every
        SLO_STATUS_TTL_S (callers hold self._lock)."""
        now = time.monotonic()
        if now - self._slo_status_ts >= self.SLO_STATUS_TTL_S:
            from ..core import runtime_context
            from ..util import slo as slo_mod

            self._slo_status_ts = now
            rt = runtime_context.current_runtime_or_none()
            self._slo_status = (
                slo_mod.read_status(rt.kv_get) if rt is not None else {}
            )
        return self._slo_status

    SLO_STATUS_TTL_S = SLO_STATUS_TTL_S
    SLO_DOWNSCALE_BURN_MAX = SLO_DOWNSCALE_BURN_MAX

    def _autoscale_once(self, name: str) -> None:
        import math

        with self._lock:
            st = self._deployments.get(name)
            if st is None or not st.autoscaling or not st.replicas:
                return
            cfg = st.autoscaling
            now = time.monotonic()
            total = sum(
                v for v, ts in st.handle_metrics.values()
                if now - ts < METRIC_STALENESS_S
            )
            cur = st.target_replicas
            target_ongoing = float(cfg.get("target_ongoing_requests", 2.0))
            desired = math.ceil(total / max(target_ongoing, 1e-9))
            desired = min(
                max(desired, int(cfg.get("min_replicas", 1))),
                int(cfg.get("max_replicas", cur)),
            )
            # SLO signal beside queue depth: a firing fast pair means the
            # latency objective is burning NOW — add capacity even if the
            # queues look fine; and never scale in while any window still
            # burns (the cleared alert would re-light).
            slo_reason = None
            slo_state = (self._slo_status_cached().get(name)
                         if st.slo is not None else None)
            if slo_state:
                burns = [float(b) for b in
                         (slo_state.get("burn") or {}).values()]
                burn_max = max(burns) if burns else 0.0
                if slo_state.get("fast_burn_active"):
                    boosted = min(cur + 1, int(cfg.get("max_replicas", cur)))
                    if boosted > desired:
                        desired = boosted
                        slo_reason = "slo_burn"
                if desired < cur and burn_max > self.SLO_DOWNSCALE_BURN_MAX:
                    desired = cur
            if desired > cur:
                st.downscale_since = None
                if st.upscale_since is None:
                    st.upscale_since = now
                if now - st.upscale_since < float(
                        cfg.get("upscale_delay_s", 2.0)):
                    return
            elif desired < cur:
                st.upscale_since = None
                if st.downscale_since is None:
                    st.downscale_since = now
                if now - st.downscale_since < float(
                        cfg.get("downscale_delay_s", 10.0)):
                    return
            else:
                st.upscale_since = None
                st.downscale_since = None
                return
            st.upscale_since = None
            st.downscale_since = None
            st.target_replicas = desired
        cluster_events.emit(
            cluster_events.INFO, cluster_events.SERVE,
            f"deployment '{name}' autoscale: {cur} -> {desired} "
            f"replica(s) (outstanding={total})"
            + (f" [{slo_reason}]" if slo_reason else ""),
            custom_fields={"deployment": name, "from": cur,
                           "to": desired, "outstanding": total,
                           **({"reason": slo_reason} if slo_reason
                              else {})},
        )
        self._converge_count(name)

    def _health_check_once(self, name: str) -> None:
        """Remove replicas whose actor died (worker crash, node loss) from
        the route set and start replacements (ref: deployment_state.py
        health checking + replica recovery). A ping that merely times out
        is 'busy', not dead — only actor-death errors evict."""
        import ray_tpu
        from ray_tpu.core.exceptions import (
            ActorDiedError,
            WorkerCrashedError,
        )

        with self._lock:
            st = self._deployments.get(name)
            if st is None or not st.replicas:
                return
            reps = list(st.replicas)
        pings = [(r, r.ping.remote()) for r in reps]
        dead = []
        for r, ref in pings:
            try:
                ray_tpu.get(ref, timeout=HEALTH_CHECK_TIMEOUT_S)
            except (ActorDiedError, WorkerCrashedError):
                dead.append(r)
            # Health-probe timeout on a live actor: slow/busy is not
            # dead, and eviction on slowness is the breaker's job
            # (serve_breaker_*), not the health checker's.
            except Exception:  # rtlint: disable=swallowed-failure
                pass
        if not dead:
            return
        cluster_events.emit(
            cluster_events.ERROR, cluster_events.SERVE,
            f"deployment '{name}': {len(dead)} replica(s) died; evicting "
            f"from the route set and starting replacements",
            custom_fields={"deployment": name, "dead": len(dead)},
        )
        dead_ids = {id(r) for r in dead}
        with self._lock:
            st = self._deployments.get(name)
            if st is None:
                return
            keep = [
                (r, v) for r, v in zip(st.replicas, st.replica_versions)
                if id(r) not in dead_ids
            ]
            st.replicas = [r for r, _ in keep]
            st.replica_versions = [v for _, v in keep]
            self._bump_route(st)
        self._converge_count(name)

    def _reconcile_loop(self) -> None:
        # Wait for the worker runtime to finish wiring this actor up before
        # issuing nested remote calls from a background thread.
        time.sleep(RECONCILE_INTERVAL_S)
        last_health = 0.0
        while not self._stopped:
            try:
                check_health = (
                    time.monotonic() - last_health > HEALTH_CHECK_PERIOD_S
                )
                if check_health:
                    last_health = time.monotonic()
                for name in list(self._deployments):
                    self._autoscale_once(name)
                    if check_health:
                        self._health_check_once(name)
                        self._eject_broken_once(name)
            except Exception as e:
                # A reconcile crash silently freezing autoscaling +
                # health checks was rtlint's top swallowed-failure
                # finding: surface every iteration's failure as a
                # cluster event, then keep reconciling.
                cluster_events.emit(
                    cluster_events.WARNING, cluster_events.SERVE,
                    f"serve controller reconcile iteration failed: {e!r}",
                    custom_fields={"error_type": type(e).__name__},
                )
            time.sleep(RECONCILE_INTERVAL_S)

    # ---- handle-facing query API -------------------------------------------

    def get_routing(self, name: str) -> Dict[str, Any]:
        with self._lock:
            st = self._deployments[name]
            return {
                "version": st.route_version,
                "replicas": list(st.replicas),
                "batch_config": st.batch_config,
                "is_asgi": st.is_asgi,
            }

    def listen_for_route_change(self, name: str, known_version: int,
                                timeout_s: float = 10.0) -> Dict[str, Any]:
        """Long-poll: returns as soon as the route set changes, or after
        timeout with the current snapshot (ref: long_poll.py
        LongPollClient/Host)."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while True:
                st = self._deployments.get(name)
                if st is None:
                    return {"version": -1, "replicas": [],
                            "batch_config": None}
                if st.route_version != known_version:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._route_cond.wait(remaining)
            return {
                "version": st.route_version,
                "replicas": list(st.replicas),
                "batch_config": st.batch_config,
                "is_asgi": st.is_asgi,
            }

    def get_replicas(self, name: str) -> List[Any]:
        with self._lock:
            return list(self._deployments[name].replicas)

    def get_batch_config(self, name: str):
        with self._lock:
            return self._deployments[name].batch_config

    def list_deployments(self) -> Dict[str, int]:
        with self._lock:
            return {
                k: len(v.replicas) for k, v in self._deployments.items()
            }

    def describe(self) -> Dict[str, Dict[str, Any]]:
        """Rich status (ref: serve.status() ApplicationDetails)."""
        with self._lock:
            return {
                name: {
                    "num_replicas": len(st.replicas),
                    "target_replicas": st.target_replicas,
                    "version": st.version,
                    "replica_versions": list(st.replica_versions),
                    "autoscaling": st.autoscaling,
                    "slo": st.slo,
                    "route_version": st.route_version,
                }
                for name, st in self._deployments.items()
            }

    def delete(self, name: str):
        with self._lock:
            st = self._deployments.pop(name, None)
            if st is not None:
                victims = list(st.replicas)
                st.replicas = []
                st.replica_versions = []
                self._bump_route(st)
        if st is not None:
            self._publish_slo_spec(name, None)
            cluster_events.emit(
                cluster_events.INFO, cluster_events.SERVE,
                f"deployment '{name}' deleted "
                f"({len(victims)} replica(s) retired)",
                custom_fields={"deployment": name,
                               "replicas": len(victims)},
            )
            for h in victims:
                self._kill_replica(h)

    def shutdown(self):
        self._stopped = True
        for name in list(self._deployments):
            self.delete(name)
        return "ok"
