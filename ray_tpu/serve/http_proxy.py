"""HTTP ingress.

Ref analogue: serve/_private/proxy.py ProxyActor (:1097) — the reference
runs uvicorn/ASGI per node; here a threaded stdlib HTTP server in the
driver process routes ``POST /<deployment>`` with a JSON body to the
deployment handle and returns the JSON result. (uvicorn isn't a baked
dependency; the stdlib server keeps ingress dependency-free.)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from .handle import DeploymentHandle


class _ProxyState:
    def __init__(self):
        self.routes: Dict[str, DeploymentHandle] = {}


_state = _ProxyState()
_server: Optional[ThreadingHTTPServer] = None
_thread: Optional[threading.Thread] = None


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # silence request logging
        pass

    def _reply(self, code: int, payload):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/-/routes":
            self._reply(200, sorted(_state.routes))
        elif self.path == "/-/healthz":
            self._reply(200, "ok")
        else:
            self.do_POST()

    def do_POST(self):
        name = self.path.strip("/").split("/")[0]
        handle = _state.routes.get(name)
        if handle is None:
            self._reply(404, {"error": f"no deployment {name!r}"})
            return
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b"null"
        try:
            arg = json.loads(raw) if raw else None
        except json.JSONDecodeError:
            self._reply(400, {"error": "invalid JSON body"})
            return
        try:
            result = handle.remote(arg).result(timeout=60)
            self._reply(200, {"result": result})
        except Exception as e:  # noqa: BLE001
            self._reply(500, {"error": str(e)})


def start_proxy(port: int = 8000) -> int:
    global _server, _thread
    if _server is not None:
        return _server.server_address[1]
    _server = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
    _thread = threading.Thread(target=_server.serve_forever, daemon=True)
    _thread.start()
    return _server.server_address[1]


def register_route(name: str, handle: DeploymentHandle):
    _state.routes[name] = handle


def stop_proxy():
    global _server, _thread
    if _server is not None:
        _server.shutdown()
        _server = None
        _thread = None
    _state.routes.clear()
