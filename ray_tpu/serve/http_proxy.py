"""HTTP ingress.

Ref analogue: serve/_private/proxy.py ProxyActor (:1097) — the reference
runs uvicorn/ASGI per node; here a threaded stdlib HTTP server in the
driver process routes ``POST /<deployment>`` with a JSON body to the
deployment handle and returns the JSON result. (uvicorn isn't a baked
dependency; the stdlib server keeps ingress dependency-free.)
"""

from __future__ import annotations

import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..core.exceptions import DeadlineExceededError, OverloadedError
from ..util import overload
from .handle import DeploymentHandle


class _ProxyState:
    def __init__(self):
        self.routes: Dict[str, DeploymentHandle] = {}
        self.asgi_routes: set = set()  # route names forwarding raw HTTP


_state = _ProxyState()
_server: Optional[ThreadingHTTPServer] = None
_thread: Optional[threading.Thread] = None


def _make_gate(name: str) -> overload.AdmissionGate:
    """Per-deployment admission gate; sheds map to 503 + Retry-After
    (ref analogue: the proxy's queue-length admission)."""
    from ..core.config import get_config

    return overload.gate_from_config(get_config())


_gates = overload.GateRegistry(_make_gate)


def _request_deadline(headers) -> float:
    """Absolute deadline for one ingress request: an explicit
    ``X-Request-Timeout-S`` budget when the client sent one, else the
    ``serve_default_request_timeout_s`` knob — the single source of
    truth that seeds deadline propagation through handle and replica."""
    from ..core.config import get_config

    default = get_config().serve_default_request_timeout_s
    budget = default
    raw = headers.get("X-Request-Timeout-S")
    if raw:
        try:
            # Clients may only SHORTEN the budget (mirror of the gRPC
            # path): an unclamped header would let one client pin proxy
            # threads and admission slots for arbitrarily long.
            budget = min(default, max(0.001, float(raw)))
        except ValueError:
            pass
    return time.time() + budget


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # silence request logging
        pass

    def send_response(self, code, message=None):
        # Remember the status for the request metrics recorded in
        # do_POST's finally — covers the JSON, ASGI, and SSE paths.
        self._obs_status = code
        super().send_response(code, message)
        # Every response names its trace (W3C traceparent), so a
        # user-visible 504/503 correlates to its recorded waterfall
        # (`rtpu trace <id>`) in one hop. ONE site covers the JSON,
        # ASGI, SSE, and overload-shed reply paths.
        trace = getattr(self, "_obs_trace", None)
        if trace is not None:
            from ..core.timeline import format_traceparent

            self.send_header("traceparent",
                             format_traceparent(trace[0], trace[1]))

    def _reply(self, code: int, payload):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        # HEAD responses (incl. errors) must never carry a body — a
        # keep-alive client would parse it as the next response.
        if self.command != "HEAD":
            self.wfile.write(body)

    def do_GET(self):
        if self.path in ("/-/routes", "/-/healthz"):
            # Not a traced request: clear any trace left by an earlier
            # request on this keep-alive connection so the header
            # cannot name a stale waterfall.
            self._obs_trace = None
        if self.path == "/-/routes":
            self._reply(200, sorted(_state.routes))
        elif self.path == "/-/healthz":
            self._reply(200, "ok")
        else:
            self.do_POST()

    def do_PUT(self):  # noqa: N802 — stdlib API
        self.do_POST()

    def do_DELETE(self):  # noqa: N802
        self.do_POST()

    def do_PATCH(self):  # noqa: N802
        self.do_POST()

    def _asgi_forward(self, name: str, handle):
        """Raw HTTP relay to an ASGI deployment (ref: the uvicorn proxy
        path in serve/_private/http_util.py): everything after /<name>
        becomes the app's path; the response passes through verbatim."""
        from urllib.parse import urlparse

        parsed = urlparse(self.path)
        sub = parsed.path[len(name) + 1:] or "/"
        if not sub.startswith("/"):
            sub = "/" + sub
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        request = {
            "method": self.command,
            "path": sub,
            "query_string": (parsed.query or "").encode(),
            "headers": [[k, v] for k, v in self.headers.items()],
            "body": body,
        }
        try:
            # Bounded by the request's remaining deadline budget
            # (installed by _route_request; the config default seeds it).
            resp = handle.options(method="handle_http").remote(
                request
            ).result(timeout=overload.remaining(120.0))
        except OverloadedError as e:
            # Shed downstream (replica limiter / breakers) — counted at
            # its shed site; here it just maps to 503 + Retry-After.
            self._reply_overloaded(e)
            return
        except (DeadlineExceededError, TimeoutError) as e:
            from . import _telemetry

            _telemetry.observe_deadline_exceeded(name, "ingress")
            self._reply(504, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001
            self._reply(500, {"error": str(e)})
            return
        body = resp.get("body", b"") or b""
        if isinstance(body, str):
            body = body.encode()
        self.send_response(int(resp.get("status", 200)))
        for k, v in resp.get("headers", []):
            if k.lower() in ("content-length", "connection"):
                continue
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        # A HEAD response carries headers (incl. the Content-Length the
        # GET would have) but MUST NOT carry a body — writing one
        # desynchronizes HTTP keep-alive connections.
        if self.command != "HEAD":
            self.wfile.write(body)

    def _stream_reply(self, handle, arg):
        """Server-sent events: one `data:` frame per item the replica's
        generator yields, flushed as produced (ref analogue: proxy.py
        RESPONSE_STREAMING over ASGI; `curl -N` shows tokens live)."""
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        try:
            for item in handle.stream(arg):
                self.wfile.write(
                    f"data: {json.dumps(item)}\n\n".encode()
                )
                self.wfile.flush()
            self.wfile.write(b"event: end\ndata: null\n\n")
            self.wfile.flush()
        except BrokenPipeError:
            pass  # client went away mid-stream
        except Exception as e:  # noqa: BLE001
            try:
                self.wfile.write(
                    f"event: error\ndata: {json.dumps(str(e))}\n\n".encode()
                )
                self.wfile.flush()
            except Exception:
                pass

    def do_OPTIONS(self):  # noqa: N802 — stdlib API
        self.do_POST()

    def do_HEAD(self):  # noqa: N802
        self.do_POST()

    def do_POST(self):
        """Instrumented ingress entry (ref analogue: the proxy's request
        span + ray_serve_num_http_requests in serve/_private/proxy.py):
        opens the request's ROOT span — honoring an incoming W3C
        ``traceparent`` so an upstream gateway owns the trace — installs
        it as this thread's context (the handle stamps it onto the task
        spec, the replica parents to it), and records the e2e latency
        histogram + status-code counter on the way out."""
        from urllib.parse import urlparse

        from ..core.timeline import (
            enter_span,
            exit_span,
            get_buffer,
            new_span_id,
            new_trace_id,
            parse_traceparent,
        )
        from . import _telemetry

        name = urlparse(self.path).path.strip("/").split("/")[0]
        parent = parse_traceparent(self.headers.get("traceparent"))
        trace_id = parent[0] if parent else new_trace_id()
        span_id = new_span_id()
        prev = enter_span(trace_id, span_id)
        # Per-request reset: the handler instance is reused across a
        # keep-alive connection, so a request that dies before
        # send_response must not inherit the previous request's status.
        self._obs_status = 500
        self._obs_trace = (trace_id, span_id)
        started = time.time()
        try:
            self._route_request(name)
        finally:
            exit_span(prev)
            ended = time.time()
            code = getattr(self, "_obs_status", 500)
            # Unknown routes record under ONE fixed label: attacker- or
            # crawler-chosen paths must not mint unbounded metric series
            # (the registry never prunes).
            dep_label = (name or "/") if code != 404 else "__unknown__"
            _telemetry.observe_ingress(
                dep_label, "http", code, started, ended,
                trace_id=trace_id,
            )
            try:
                get_buffer().record(
                    f"http:{name or '/'}", started, ended, "",
                    trace_id=trace_id, span_id=span_id,
                    parent_id=parent[1] if parent else "",
                )
            except Exception:
                pass
            # Tail-sampled flight recorder: keep the full record for
            # shed (503), deadline-expired (504), errored, or
            # rolling-p99-slow requests; everything else is dropped.
            from ..util import flight_recorder

            reason = None
            if code == 503:
                reason = "shed"
            elif code == 504:
                reason = "expired"
            elif code >= 500:
                reason = "error"
            flight_recorder.observe_request(
                f"http:{name or '/'}", trace_id, started, ended,
                status=code, reason=reason, surface="http",
            )

    def _route_request(self, name: str):
        from urllib.parse import urlparse

        parts = urlparse(self.path).path.strip("/").split("/")
        streaming = (
            (len(parts) > 1 and parts[1] == "stream")
            or "text/event-stream" in (self.headers.get("Accept") or "")
        )
        handle = _state.routes.get(name)
        if handle is None:
            # Dynamic discovery: any live deployment is routable without
            # explicit registration (ref: the proxy's route table pushed
            # by long-poll — here resolved lazily through the controller
            # and cached, after which the handle long-polls on its own).
            # A stray request must never SPAWN a controller, and a
            # transient controller failure is 503, not 404.
            import ray_tpu

            from . import api as serve_api
            from .controller import CONTROLLER_NAME

            try:
                ray_tpu.get_actor(CONTROLLER_NAME)
            except ValueError:
                self._reply(404, {"error": "serve is not running"})
                return
            try:
                handle = serve_api.get_deployment_handle(name)
                _state.routes[name] = handle
            except KeyError:
                self._reply(404, {"error": f"no deployment {name!r}"})
                return
            except Exception as e:  # noqa: BLE001
                self._reply(503, {"error": f"controller error: {e}"})
                return
        if handle is None:
            self._reply(404, {"error": f"no deployment {name!r}"})
            return
        # Protocol decision follows the ROUTING SNAPSHOT (refreshed by
        # the handle's long-poll), so a redeploy that flips a name
        # between ASGI and JSON is honored without restarting proxies;
        # the explicit-registration set covers driver-local routes.
        is_asgi = (getattr(handle._state, "is_asgi", False)
                   or name in _state.asgi_routes)
        if not is_asgi and self.command in ("HEAD", "OPTIONS"):
            # Non-ASGI deployments speak the JSON envelope only; do NOT
            # execute them on preflight/health probes, and never write a
            # body to a HEAD response (keep-alive desync).
            self.send_response(405)
            self.send_header("Allow", "GET, POST")
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        # ---- overload control: deadline + admission ------------------
        # Shed BEFORE dispatch: past the adaptive concurrency limit and
        # the bounded queue, the request never reaches a handle thread.
        from . import _telemetry

        deadline_ts = _request_deadline(self.headers)
        gate = _gates.get(name)
        try:
            gate.acquire(deadline_ts=deadline_ts)
        except OverloadedError as e:
            _telemetry.observe_shed(name, "proxy")
            self._reply_overloaded(e)
            return
        t0 = time.monotonic()
        prev_dl = overload.set_ambient_deadline(deadline_ts)
        try:
            if is_asgi:
                self._asgi_forward(name, handle)
                return
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b"null"
            try:
                arg = json.loads(raw) if raw else None
            except json.JSONDecodeError:
                self._reply(400, {"error": "invalid JSON body"})
                return
            if streaming:
                # /<name>/<method> routes to that method (e.g.
                # /llm/stream → the deployment's generator endpoint);
                # bare /<name> with an SSE Accept header streams
                # __call__'s result as one event.
                if len(parts) > 1:
                    handle = handle.options(method=parts[1])
                self._stream_reply(handle, arg)
                return
            try:
                result = handle.remote(arg).result(
                    timeout=overload.remaining(60.0)
                )
                self._reply(200, {"result": result})
            except OverloadedError as e:
                # Shed downstream (replica limiter / all breakers open).
                self._reply_overloaded(e)
            except (DeadlineExceededError, TimeoutError) as e:
                _telemetry.observe_deadline_exceeded(name, "ingress")
                self._reply(504, {"error": str(e)})
            except Exception as e:  # noqa: BLE001
                self._reply(500, {"error": str(e)})
        finally:
            overload.set_ambient_deadline(prev_dl)
            code = getattr(self, "_obs_status", 500)
            # Only downstream pushback (503: replica shed / breakers
            # open) shrinks the gate. A 504 means the CLIENT's budget
            # was too small — one client sending tiny X-Request-
            # Timeout-S values must not collapse the shared limit.
            gate.release(time.monotonic() - t0,
                         overloaded=code == 503)

    def _reply_overloaded(self, e: OverloadedError):
        """503 + Retry-After (integer seconds, RFC 9110). The request
        body may be unread at this point: close the connection so a
        keep-alive client cannot desync on the stray bytes."""
        body = json.dumps({"error": str(e)}).encode()
        self.send_response(503)
        retry_after = getattr(e, "retry_after_s", 1.0)
        self.send_header("Retry-After",
                         str(max(1, int(math.ceil(retry_after)))))
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.close_connection = True
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(body)


class _TLSHTTPServer(ThreadingHTTPServer):
    """HTTPS ingress with mutual TLS. The LISTENING socket stays plain:
    each connection is wrapped and handshaken in ITS OWN handler thread
    with a timeout — wrapping the listener would run handshakes in the
    single accept loop, letting one stalled client (TCP open, no
    ClientHello) block the whole ingress."""

    _HANDSHAKE_TIMEOUT_S = 10.0

    def __init__(self, addr, handler, tls_ctx):
        self._tls_ctx = tls_ctx
        super().__init__(addr, handler)

    def finish_request(self, request, client_address):
        request.settimeout(self._HANDSHAKE_TIMEOUT_S)
        try:
            request = self._tls_ctx.wrap_socket(
                request, server_side=True,
                do_handshake_on_connect=False,
            )
            request.do_handshake()
        except Exception:
            try:
                request.close()
            except Exception:
                pass
            return
        request.settimeout(None)
        try:
            super().finish_request(request, client_address)
        finally:
            # wrap_socket DETACHED the original fd, so socketserver's
            # shutdown_request/close_request (called with the original
            # socket object) are no-ops — close the wrapped socket
            # explicitly or its fd lives until GC.
            try:
                request.close()
            except Exception:
                pass


def _make_http_server(addr) -> ThreadingHTTPServer:
    """Plain HTTP — or mutual-TLS HTTPS when the cluster runs mTLS
    (plaintext ingress beside an encrypted control plane would be the
    one door left open)."""
    from ..core.tls import server_ssl_context

    ctx = server_ssl_context()
    if ctx is not None:
        return _TLSHTTPServer(addr, _Handler, ctx)
    return ThreadingHTTPServer(addr, _Handler)


def start_proxy(port: int = 8000) -> int:
    global _server, _thread
    if _server is not None:
        return _server.server_address[1]
    _server = _make_http_server(("127.0.0.1", port))
    _thread = threading.Thread(target=_server.serve_forever, daemon=True)
    _thread.start()
    return _server.server_address[1]


def register_route(name: str, handle: DeploymentHandle,
                   *, asgi: bool = False):
    _state.routes[name] = handle
    if asgi:
        _state.asgi_routes.add(name)
    else:
        _state.asgi_routes.discard(name)  # name may be redeployed non-ASGI


def stop_proxy():
    global _server, _thread
    if _server is not None:
        _server.shutdown()
        _server = None
        _thread = None
    _state.routes.clear()
    _state.asgi_routes.clear()
    _gates.clear()


# ---------------------------------------------------------- per-node proxy

class ProxyActor:
    """One HTTP ingress per node (ref: serve/_private/proxy.py ProxyActor
    — the reference runs one proxy on every node so any host serves
    traffic). Runs the same threaded server inside an actor process;
    routes resolve dynamically through the controller."""

    def __init__(self, port: int = 0):
        self._server = _make_http_server(("0.0.0.0", port))
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def port(self) -> int:
        return self._server.server_address[1]

    def ping(self) -> str:
        return "ok"

    def shutdown(self) -> str:
        self._server.shutdown()
        return "ok"


def start_per_node_actors(actor_cls, port: int,
                          *, timeout: float = 60.0):
    """Launch one ingress actor per alive node (node-affinity pinned)
    and gather their bound ports IN PARALLEL; a node that died since the
    snapshot is skipped after ``timeout`` instead of hanging startup.
    Shared by the HTTP and gRPC per-node proxies."""
    import ray_tpu
    from ray_tpu.core.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    spawned = {}
    for node in ray_tpu.nodes():
        if not node.get("Alive", False):
            continue
        nid = node["NodeID"]
        actor = ray_tpu.remote(
            scheduling_strategy=NodeAffinitySchedulingStrategy(nid),
            max_concurrency=16,
        )(actor_cls).remote(port)
        spawned[nid] = (actor, actor.port.remote())
    proxies = {}
    for nid, (actor, port_ref) in spawned.items():
        try:
            proxies[nid] = (actor, ray_tpu.get(port_ref,
                                               timeout=timeout))
        except Exception:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
    return proxies


def start_per_node_proxies(port: int = 8000):
    """Launch one ProxyActor on every alive node; returns
    {node_id: (actor, port)} (ref: proxies on each node serving the
    same route table)."""
    return start_per_node_actors(ProxyActor, port)
