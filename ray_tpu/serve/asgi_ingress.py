"""ASGI ingress: serve any ASGI-3 application behind the HTTP proxy.

Ref analogue: serve's FastAPI/ASGI integration (`@serve.ingress(app)` +
the uvicorn-backed proxy in serve/_private/http_util.py). The image
ships no uvicorn/starlette, so the bridge is self-contained: each
replica hosts the user's ASGI app on a private event loop; the per-node
proxy forwards the RAW request (method, path remainder, query, headers,
body) and relays the app's response verbatim — any framework speaking
the ASGI protocol works, no JSON envelope involved.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Callable, Dict, List, Tuple


class ASGIReplica:
    """Deployment class wrapping one ASGI app instance."""

    _rtpu_asgi = True

    def __init__(self, app_factory: Callable[[], Any]):
        self._app = self._resolve_app(app_factory)
        self._loop = asyncio.new_event_loop()
        t = threading.Thread(target=self._loop.run_forever, daemon=True)
        t.start()
        # Lag watchdog: a blocking route handler stalls every in-flight
        # request multiplexed onto this replica's loop.
        from ..util import loop_monitor

        loop_monitor.attach("serve_asgi", self._loop)

    @staticmethod
    def _resolve_app(obj):
        """Accept an ASGI app OR a zero-arg factory. Every ASGI-3 app
        is itself callable, so "callable == factory" would invoke the
        app with no arguments; distinguish by arity instead."""
        import inspect

        try:
            target = obj if inspect.isfunction(obj) or inspect.ismethod(
                obj) else getattr(obj, "__call__", obj)
            params = [
                pm for pm in inspect.signature(target).parameters.values()
                if pm.kind in (pm.POSITIONAL_ONLY,
                               pm.POSITIONAL_OR_KEYWORD)
                and pm.default is pm.empty
            ]
            n_required = len(params)
        except (TypeError, ValueError):
            n_required = None
        if n_required == 0:
            return obj()  # zero-arg factory
        return obj        # the app itself (scope, receive, send)

    def handle_http(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """One request through the app. ``request``: {method, path,
        query_string, headers: [[name, value], ...], body: bytes}.
        Returns {status, headers: [[name, value], ...], body: bytes}.

        The wait is bounded by the request's remaining deadline budget
        (installed around execution from the call frame's deadline) —
        the ``serve_default_request_timeout_s`` knob seeds it when the
        client sent no explicit budget."""
        import concurrent.futures

        from ..core.config import get_config
        from ..core.exceptions import DeadlineExceededError
        from ..util import overload

        fut = asyncio.run_coroutine_threadsafe(
            self._run_app(request), self._loop
        )
        try:
            return fut.result(timeout=overload.remaining(
                get_config().serve_default_request_timeout_s
            ))
        except concurrent.futures.TimeoutError:
            # On py3.10 this is NOT the builtin TimeoutError: translate
            # so the proxy's 504 mapping (and the breaker's infra-fault
            # accounting) see a deadline expiry, not a generic error.
            fut.cancel()
            raise DeadlineExceededError(
                "ASGI app response exceeded the request's deadline "
                "budget"
            )

    async def _run_app(self, request: Dict[str, Any]) -> Dict[str, Any]:
        scope = {
            "type": "http",
            "asgi": {"version": "3.0", "spec_version": "2.3"},
            "http_version": "1.1",
            "method": request["method"],
            "scheme": "http",
            "path": request["path"],
            "raw_path": request["path"].encode(),
            "query_string": request.get("query_string", b"") or b"",
            "root_path": "",
            # HTTP header bytes are latin-1, not UTF-8 (RFC 9110).
            "headers": [
                (k.lower().encode("latin-1"), v.encode("latin-1"))
                for k, v in request.get("headers", [])
            ],
            "client": ("127.0.0.1", 0),
            "server": ("127.0.0.1", 80),
        }
        body = request.get("body", b"") or b""
        sent_body = False

        async def receive():
            nonlocal sent_body
            if sent_body:
                # ASGI spec: after the request body, receive() resolves
                # only on a real disconnect. Frameworks run disconnect
                # watchers on it — returning early would cancel their
                # in-flight responses. Our requests are fully buffered,
                # so block until the handler is torn down (bounded by
                # the caller's overall timeout).
                await asyncio.Future()
            sent_body = True
            return {"type": "http.request", "body": body,
                    "more_body": False}

        status = 500
        headers: List[Tuple[str, str]] = []
        chunks: List[bytes] = []

        async def send(message):
            nonlocal status, headers
            if message["type"] == "http.response.start":
                status = int(message["status"])
                headers = [
                    (k.decode("latin-1"), v.decode("latin-1"))
                    for k, v in message.get("headers", [])
                ]
            elif message["type"] == "http.response.body":
                chunks.append(bytes(message.get("body", b"")))

        await self._app(scope, receive, send)
        return {"status": status, "headers": headers,
                "body": b"".join(chunks)}

    def ping(self) -> str:
        return "ok"
