"""Model multiplexing.

Ref analogue: serve/api.py @serve.multiplexed + _private/
request_router's model-aware routing: one deployment serves MANY models;
each replica lazily loads the models it is asked for and keeps an LRU of
``max_num_models_per_replica``; the router prefers replicas that already
hold the requested model (cache affinity), so hot models stay loaded.
"""

from __future__ import annotations

import contextvars
import functools
from collections import OrderedDict
from typing import Any, Callable, Optional

_current_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "ray_tpu_serve_multiplexed_model_id", default=""
)


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id the caller targeted (ref:
    serve.get_multiplexed_model_id)."""
    return _current_model_id.get()


def _set_model_id(model_id: str):
    _current_model_id.set(model_id)


def multiplexed(_func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorate a per-model LOADER (usually a method of the deployment
    class taking a model id). Calls are LRU-cached per replica; loading a
    model beyond the cap evicts the least-recently-used one (its
    ``__del__``/GC releases resources)."""

    def wrap(load_fn):
        cache: "OrderedDict[str, Any]" = OrderedDict()

        @functools.wraps(load_fn)
        def loader(*args):
            # Support plain functions and methods (self, model_id).
            model_id = args[-1]
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            model = load_fn(*args)
            cache[model_id] = model
            if len(cache) > max_num_models_per_replica:
                cache.popitem(last=False)  # evict LRU
            return model

        loader._is_multiplexed = True
        return loader

    if _func is not None:
        return wrap(_func)
    return wrap
