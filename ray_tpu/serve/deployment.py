"""Deployment definitions.

Ref analogue: python/ray/serve/deployment.py + api.py — @serve.deployment
decorator producing a Deployment; ``.bind(*args)`` captures init args
(the reference's graph-build API); ``.options()`` overrides config.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple


@dataclasses.dataclass
class AutoscalingConfig:
    """Ref: serve/config.py AutoscalingConfig (queue-depth driven)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0


class Deployment:
    def __init__(
        self,
        func_or_class: Callable,
        name: str,
        *,
        num_replicas: int = 1,
        max_concurrent_queries: int = 8,
        ray_actor_options: Optional[Dict[str, Any]] = None,
        autoscaling_config: Optional[AutoscalingConfig] = None,
        slo: Optional[Dict[str, Any]] = None,
    ):
        self.func_or_class = func_or_class
        self.name = name
        self.num_replicas = num_replicas
        self.max_concurrent_queries = max_concurrent_queries
        self.ray_actor_options = ray_actor_options or {}
        self.autoscaling_config = autoscaling_config
        # SLO spec dict (util/slo.normalize_spec keys); validated at
        # deploy time by the controller, evaluated by the head GCS.
        self.slo = slo
        self._init_args: Tuple = ()
        self._init_kwargs: Dict[str, Any] = {}

    def options(self, **kw) -> "Deployment":
        d = Deployment(
            self.func_or_class,
            kw.pop("name", self.name),
            num_replicas=kw.pop("num_replicas", self.num_replicas),
            max_concurrent_queries=kw.pop(
                "max_concurrent_queries", self.max_concurrent_queries
            ),
            ray_actor_options=kw.pop(
                "ray_actor_options", dict(self.ray_actor_options)
            ),
            autoscaling_config=kw.pop(
                "autoscaling_config", self.autoscaling_config
            ),
            slo=kw.pop("slo", self.slo),
        )
        if kw:
            raise TypeError(f"unknown deployment options: {list(kw)}")
        d._init_args = self._init_args
        d._init_kwargs = self._init_kwargs
        return d

    def bind(self, *args, **kwargs) -> "Deployment":
        d = self.options()
        d._init_args = args
        d._init_kwargs = kwargs
        return d


def deployment(_func_or_class=None, *, name: Optional[str] = None, **kw):
    """@serve.deployment decorator (ref: serve/api.py deployment)."""

    def wrap(target):
        return Deployment(target, name or target.__name__, **kw)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
