"""Continuous-batched LLM serving on TPU with a paged KV cache.

The capability the reference lacks (SURVEY.md §7 hard parts: "continuous
batching + paged KV cache on TPU for Serve; reference has only
request-level batching"): an engine where requests JOIN and LEAVE the
running decode loop — each decode step batches every active slot into one
[B, 1] forward pass (HBM-bandwidth bound; batching amortizes the weight
reads), while prefill runs per admission into power-of-two length buckets.

KV memory is PAGED (models/generation.py PagedKVCache): a shared pool of
fixed-size token pages with a per-slot page table. A request reserves only
the pages its prompt + max_new_tokens need — not a dense max_len row — so
total KV is bounded by actual demand, long-context requests coexist with
short ones, and pages recycle the moment a request finishes. Admission
waits for pages instead of OOMing. All shapes stay static for XLA.
"""

from __future__ import annotations

import math
import queue
import threading
from typing import Any, Dict, Iterator, List, Optional

import numpy as np


class _Request:
    def __init__(self, prompt: List[int], max_new_tokens: int,
                 eos_token: Optional[int]):
        self.prompt = list(prompt)
        self.max_new_tokens = max_new_tokens
        self.eos_token = eos_token
        self.output: List[int] = []
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.ttft_s: Optional[float] = None
        self._t0 = None
        # Incremental consumers (token streaming) read from here; None is
        # the end-of-stream sentinel.
        self._live: "queue.Queue[Optional[int]]" = queue.Queue()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if self.error:
            raise self.error
        return self.output

    def tokens(self, timeout: Optional[float] = None) -> Iterator[int]:
        """Yield tokens as the decode loop produces them."""
        while True:
            tok = self._live.get(timeout=timeout)
            if tok is None:
                if self.error:
                    raise self.error
                return
            yield tok


class LLMEngine:
    """Paged continuous-batching decode engine over the Llama family."""

    def __init__(self, cfg, params, *, max_batch: int = 8,
                 max_len: int = 512, temperature: float = 0.0,
                 page_size: int = 16, total_pages: Optional[int] = None):
        import jax
        import jax.numpy as jnp

        from ..models.generation import (
            PagedKVCache,
            paged_decode,
            paged_prefill,
            sample_logits,
        )

        self.cfg = cfg
        self.params = params
        self.max_batch = max_batch
        self.max_len = max_len
        self.temperature = temperature
        if max_len % page_size != 0:
            # paged_prefill reshapes bucket rows into whole pages; a
            # clamped bucket that is not a page multiple would blow up
            # inside the jitted reshape with an opaque XLA error.
            raise ValueError(
                f"max_len ({max_len}) must be a multiple of page_size "
                f"({page_size})"
            )
        self.page_size = page_size
        self.max_pages_per_seq = math.ceil(max_len / page_size)
        # Default pool: enough for every slot at max_len (same worst case
        # as a dense cache); pass a smaller total_pages to oversubscribe.
        self.total_pages = total_pages or (
            max_batch * self.max_pages_per_seq
        )
        self._jnp = jnp
        self._jax = jax

        self.cache = PagedKVCache.create(
            cfg, max_batch, self.total_pages, page_size,
            self.max_pages_per_seq,
        )
        self._free_pages: List[int] = list(range(self.total_pages))
        self._table = np.zeros(
            (max_batch, self.max_pages_per_seq), dtype=np.int32
        )
        self._slot_free = list(range(max_batch))
        self._slot_req: Dict[int, _Request] = {}
        self._slot_pages: Dict[int, List[int]] = {}
        self._last_tok = np.zeros((max_batch,), dtype=np.int32)
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._waiting: List[_Request] = []  # admitted-but-no-pages
        self._lock = threading.Lock()
        self._stop = False
        self._step_count = 0

        def decode_step(params, cache, last_tok, active, key):
            logits, cache = paged_decode(
                params, last_tok, cache, cfg, active=active
            )
            nxt = sample_logits(logits, key, temperature=temperature)
            return nxt, cache

        from ..util.device_metrics import instrumented_jit

        # Donate the cache: the paged pool updates IN PLACE instead of
        # being copied every step (a pool-sized copy per step would make
        # paging cost scale with pool size). Jit through the instrumented
        # compile path: serving recompiles (shape changes, evictions)
        # surface as ray_tpu_device_jit_* series instead of silent
        # latency spikes. The per-token tap rides a ring flushed once
        # every 64 steps (and at every burst boundary — see _loop /
        # stats), not per token: polling the executable cache around
        # every [B,1] decode step was the remaining slice of the
        # 695→652 tok/s regression (PERF_r06, partially recovered).
        self._decode = instrumented_jit(decode_step, donate_argnums=(1,),
                                        tap_stride=64)

        def prefill(params, cache, tokens, real_len, slot, pages):
            logits, cache = paged_prefill(
                params, tokens, real_len, cache, cfg, slot, pages
            )
            nxt = sample_logits(logits, jax.random.PRNGKey(0),
                                temperature=temperature)
            return cache, nxt[0]

        self._prefill = instrumented_jit(prefill, donate_argnums=(1,))
        self._rng = jax.random.PRNGKey(0)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ---- public API --------------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int = 32,
               eos_token: Optional[int] = None) -> _Request:
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt({len(prompt)}) + max_new({max_new_tokens}) exceeds "
                f"engine max_len({self.max_len})"
            )
        req = _Request(prompt, max_new_tokens, eos_token)
        need = self._pages_needed(req, self._bucket(len(prompt)))
        if need > self.total_pages:
            # Unsatisfiable EVER: waiting would head-of-line block the
            # admission queue forever.
            raise ValueError(
                f"request needs {need} pages but the pool has only "
                f"{self.total_pages} (page_size={self.page_size})"
            )
        import time

        req._t0 = time.perf_counter()
        self._queue.put(req)
        return req

    def generate(self, prompt: List[int], max_new_tokens: int = 32,
                 eos_token: Optional[int] = None,
                 timeout: float = 300.0) -> List[int]:
        return self.submit(prompt, max_new_tokens, eos_token).result(timeout)

    def stats(self) -> Dict[str, Any]:
        # Telemetry read: publish whatever the decode tap ring has
        # accumulated so /metrics never lags a long burst.
        self._decode.flush_taps()
        with self._lock:
            return {
                "active_slots": len(self._slot_req),
                "free_slots": len(self._slot_free),
                "decode_steps": self._step_count,
                "free_pages": len(self._free_pages),
                "total_pages": self.total_pages,
                "page_size": self.page_size,
            }

    def shutdown(self):
        self._stop = True
        self._thread.join(timeout=5)
        try:
            self._decode.flush_taps()
        except Exception:
            pass

    # ---- page accounting ---------------------------------------------------

    def _pages_needed(self, req: _Request, bucket: int) -> int:
        decode_span = math.ceil(
            (len(req.prompt) + req.max_new_tokens) / self.page_size
        )
        return max(bucket // self.page_size, decode_span)

    def _reset_cache(self, cause: Exception):
        """Recover from a failed donated call: the old pool's buffers
        are gone, so rebuild a fresh cache and fail in-flight requests
        with the root cause (they cannot be resumed without their KV)."""
        from ..models.generation import PagedKVCache

        with self._lock:
            victims = list(self._slot_req.items())
            self._slot_req.clear()
            self._slot_free = list(range(self.max_batch))
            self._free_pages = list(range(self.total_pages))
            self._slot_pages.clear()
            self._table[:] = 0
        for _slot, req in victims:
            if not req.done.is_set():
                req.error = RuntimeError(
                    f"engine cache reset after runtime failure: {cause!r}"
                )
                req.done.set()
                req._live.put(None)
        self.cache = PagedKVCache.create(
            self.cfg, self.max_batch, self.total_pages, self.page_size,
            self.max_pages_per_seq,
        )

    def _release_slot(self, slot: int):
        pages = self._slot_pages.pop(slot, [])
        self._free_pages.extend(pages)
        self._table[slot, :] = 0
        self._slot_free.append(slot)

    # ---- engine loop -------------------------------------------------------

    def _bucket(self, n: int) -> int:
        bucket = self.page_size
        while bucket < n:
            bucket *= 2
        return min(bucket, self.max_len)

    def _admit(self):
        import time

        jnp = self._jnp
        while self._slot_free:
            if self._waiting:
                req = self._waiting.pop(0)
            else:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    return
            real_len = len(req.prompt)
            bucket = self._bucket(real_len)
            need = self._pages_needed(req, bucket)
            if need > len(self._free_pages):
                # Paged admission control: wait for pages to recycle
                # instead of OOMing or over-reserving a dense max_len row.
                self._waiting.insert(0, req)
                return
            slot = self._slot_free.pop()
            pages = [self._free_pages.pop() for _ in range(need)]
            self._slot_pages[slot] = pages
            self._table[slot, :] = 0
            self._table[slot, :need] = pages
            prefill_pages = pages[: bucket // self.page_size]
            self.cache = self.cache._replace(
                page_table=jnp.asarray(self._table)
            )
            padded = req.prompt + [0] * (bucket - real_len)
            tokens = jnp.asarray([padded], dtype=jnp.int32)
            try:
                self.cache, first = self._prefill(
                    self.params, self.cache, tokens,
                    jnp.asarray(real_len, dtype=jnp.int32),
                    jnp.asarray(slot, dtype=jnp.int32),
                    jnp.asarray(prefill_pages, dtype=jnp.int32),
                )
                first = int(first)
            except Exception as e:  # noqa: BLE001
                req.error = e
                req.done.set()
                req._live.put(None)
                self._release_slot(slot)
                # The cache was DONATED into the failed call — its
                # buffers may already be invalid. Rebuild the pool and
                # fail every in-flight request rather than serving from
                # dead buffers (engine reset; callers see clean errors).
                self._reset_cache(e)
                continue
            req.ttft_s = time.perf_counter() - req._t0
            req.output.append(first)
            req._live.put(first)
            with self._lock:
                self._slot_req[slot] = req
            self._last_tok[slot] = first
            self._finish_if_done(slot, req, first)

    def _finish_if_done(self, slot: int, req: _Request, tok: int):
        if (len(req.output) >= req.max_new_tokens
                or (req.eos_token is not None and tok == req.eos_token)):
            with self._lock:
                self._slot_req.pop(slot, None)
            self._release_slot(slot)
            req.done.set()
            req._live.put(None)

    def _loop(self):
        import time

        jnp = self._jnp
        jax = self._jax
        while not self._stop:
            self._admit()
            with self._lock:
                active_slots = dict(self._slot_req)
            if not active_slots:
                # Burst boundary: the decode loop went idle — flush the
                # batched metric taps accumulated over the burst.
                self._decode.flush_taps()
                time.sleep(0.002)
                continue
            active = np.zeros((self.max_batch,), dtype=bool)
            for s in active_slots:
                active[s] = True
            self._rng, key = jax.random.split(self._rng)
            try:
                nxt, self.cache = self._decode(
                    self.params,
                    self.cache,
                    jnp.asarray(self._last_tok),
                    jnp.asarray(active),
                    key,
                )
            except Exception as e:  # noqa: BLE001
                # The cache was donated into the failed call — recover
                # like the prefill path: rebuild the pool, fail in-flight
                # requests cleanly, keep the loop alive for new work.
                self._reset_cache(e)
                continue
            self._step_count += 1
            nxt = np.asarray(nxt)
            for slot, req in active_slots.items():
                tok = int(nxt[slot])
                req.output.append(tok)
                req._live.put(tok)
                self._last_tok[slot] = tok
                self._finish_if_done(slot, req, tok)


class LLMDeployment:
    """Serve deployment wrapping an engine; deploy with
    ray_actor_options={"max_concurrency": N} so concurrent requests join
    the running decode loop (continuous batching). ``stream`` yields
    tokens as generated — route it through the proxy's SSE path
    (``POST /<name>/stream``) for live token streaming."""

    def __init__(self, cfg=None, params=None, *, checkpoint_path=None,
                 max_batch: int = 8, max_len: int = 512,
                 temperature: float = 0.0, seed: int = 0,
                 page_size: int = 16,
                 total_pages: Optional[int] = None):
        from ..models import LlamaConfig, init_params

        if cfg is None:
            cfg = LlamaConfig.tiny()
        if params is None and checkpoint_path:
            from ..train.checkpoint import Checkpoint

            params = Checkpoint(checkpoint_path).as_pytree()
        if params is None:
            import jax

            params = init_params(cfg, jax.random.PRNGKey(seed))
        self.engine = LLMEngine(cfg, params, max_batch=max_batch,
                                max_len=max_len, temperature=temperature,
                                page_size=page_size,
                                total_pages=total_pages)

    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        tokens = self.engine.generate(
            list(request["prompt"]),
            int(request.get("max_new_tokens", 32)),
            request.get("eos_token"),
        )
        return {"tokens": tokens}

    def stream(self, request: Dict[str, Any]):
        """Generator endpoint: one token per yield, as decoded."""
        req = self.engine.submit(
            list(request["prompt"]),
            int(request.get("max_new_tokens", 32)),
            request.get("eos_token"),
        )
        for tok in req.tokens(timeout=300.0):
            yield {"token": tok}

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()
