"""Continuous-batched LLM serving on TPU.

The capability the reference lacks (SURVEY.md §7 hard parts: "continuous
batching + paged KV cache on TPU for Serve; reference has only
request-level batching"): an engine with a static-shape slotted KV cache
where requests JOIN and LEAVE the running decode loop — each decode step
batches every active slot into one [B, 1] forward pass (HBM-bandwidth
bound; batching amortizes the weight reads), while prefill runs per
admission. All shapes static for XLA: the cache is [L, B_max, T_max, ...]
and slot activity is a boolean mask.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, List, Optional

import numpy as np


class _Request:
    def __init__(self, prompt: List[int], max_new_tokens: int,
                 eos_token: Optional[int]):
        self.prompt = list(prompt)
        self.max_new_tokens = max_new_tokens
        self.eos_token = eos_token
        self.output: List[int] = []
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.ttft_s: Optional[float] = None
        self._t0 = None

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if self.error:
            raise self.error
        return self.output


class LLMEngine:
    """Slotted continuous-batching decode engine over the Llama family."""

    def __init__(self, cfg, params, *, max_batch: int = 8,
                 max_len: int = 512, temperature: float = 0.0):
        import jax
        import jax.numpy as jnp

        from ..models.generation import (
            KVCache,
            forward_with_cache,
            sample_logits,
        )

        self.cfg = cfg
        self.params = params
        self.max_batch = max_batch
        self.max_len = max_len
        self.temperature = temperature
        self._jnp = jnp
        self._jax = jax

        self.cache = KVCache.create(cfg, max_batch, max_len)
        self._slot_free = list(range(max_batch))
        self._slot_req: Dict[int, _Request] = {}
        self._last_tok = np.zeros((max_batch,), dtype=np.int32)
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._lock = threading.Lock()
        self._stop = False
        self._step_count = 0

        def decode_step(params, cache, last_tok, active, key):
            logits, cache = forward_with_cache(
                params, last_tok[:, None], cache, cfg, active=active
            )
            nxt = sample_logits(logits, key, temperature=temperature)
            return nxt, cache

        self._decode = jax.jit(decode_step)

        # Prefill for one slot: compute a single-row cache then scatter its
        # rows into the big cache at the slot index. Prompts are PADDED to
        # power-of-two length buckets, so XLA compiles one program per
        # bucket — O(log max_len) compilations — instead of one per
        # distinct prompt length (r1 VERDICT weakness #7). last_index /
        # append_len keep logits and cache lengths exact under padding.
        def prefill(params, cache, tokens, real_len, slot):
            from ..models.generation import KVCache as KC

            small = KC.create(cfg, 1, max_len)
            logits, small = forward_with_cache(
                params, tokens, small, cfg,
                last_index=real_len[None] - 1,
                append_len=real_len,
            )
            k = jax.lax.dynamic_update_slice(
                cache.k, small.k, (0, slot, 0, 0, 0)
            )
            v = jax.lax.dynamic_update_slice(
                cache.v, small.v, (0, slot, 0, 0, 0)
            )
            lengths = cache.lengths.at[slot].set(small.lengths[0])
            nxt = sample_logits(logits, jax.random.PRNGKey(0),
                                temperature=temperature)
            return KC(k, v, lengths), nxt[0]

        self._prefill = jax.jit(prefill)
        self._rng = jax.random.PRNGKey(0)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ---- public API --------------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int = 32,
               eos_token: Optional[int] = None) -> _Request:
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt({len(prompt)}) + max_new({max_new_tokens}) exceeds "
                f"engine max_len({self.max_len})"
            )
        req = _Request(prompt, max_new_tokens, eos_token)
        import time

        req._t0 = time.perf_counter()
        self._queue.put(req)
        return req

    def generate(self, prompt: List[int], max_new_tokens: int = 32,
                 eos_token: Optional[int] = None,
                 timeout: float = 300.0) -> List[int]:
        return self.submit(prompt, max_new_tokens, eos_token).result(timeout)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "active_slots": len(self._slot_req),
                "free_slots": len(self._slot_free),
                "decode_steps": self._step_count,
            }

    def shutdown(self):
        self._stop = True
        self._thread.join(timeout=5)

    # ---- engine loop -------------------------------------------------------

    def _admit(self):
        import time

        while self._slot_free:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            slot = self._slot_free.pop()
            jnp = self._jnp
            real_len = len(req.prompt)
            bucket = 16
            while bucket < real_len:
                bucket *= 2
            bucket = min(bucket, self.max_len)
            padded = req.prompt + [0] * (bucket - real_len)
            tokens = jnp.asarray([padded], dtype=jnp.int32)
            try:
                self.cache, first = self._prefill(
                    self.params, self.cache, tokens,
                    jnp.asarray(real_len, dtype=jnp.int32), slot
                )
                first = int(first)
            except Exception as e:  # noqa: BLE001
                req.error = e
                req.done.set()
                self._slot_free.append(slot)
                continue
            req.ttft_s = time.perf_counter() - req._t0
            req.output.append(first)
            with self._lock:
                self._slot_req[slot] = req
            self._last_tok[slot] = first
            self._finish_if_done(slot, req, first)

    def _finish_if_done(self, slot: int, req: _Request, tok: int):
        if (len(req.output) >= req.max_new_tokens
                or (req.eos_token is not None and tok == req.eos_token)):
            with self._lock:
                self._slot_req.pop(slot, None)
            self._slot_free.append(slot)
            req.done.set()

    def _loop(self):
        import time

        jnp = self._jnp
        jax = self._jax
        while not self._stop:
            self._admit()
            with self._lock:
                active_slots = dict(self._slot_req)
            if not active_slots:
                time.sleep(0.002)
                continue
            active = np.zeros((self.max_batch,), dtype=bool)
            for s in active_slots:
                active[s] = True
            self._rng, key = jax.random.split(self._rng)
            nxt, self.cache = self._decode(
                self.params,
                self.cache,
                jnp.asarray(self._last_tok),
                jnp.asarray(active),
                key,
            )
            self._step_count += 1
            nxt = np.asarray(nxt)
            for slot, req in active_slots.items():
                tok = int(nxt[slot])
                req.output.append(tok)
                self._last_tok[slot] = tok
                self._finish_if_done(slot, req, tok)


class LLMDeployment:
    """Serve deployment wrapping an engine; deploy with
    ray_actor_options={"max_concurrency": N} so concurrent requests join
    the running decode loop (continuous batching)."""

    def __init__(self, cfg=None, params=None, *, checkpoint_path=None,
                 max_batch: int = 8, max_len: int = 512,
                 temperature: float = 0.0, seed: int = 0):
        from ..models import LlamaConfig, init_params

        if cfg is None:
            cfg = LlamaConfig.tiny()
        if params is None and checkpoint_path:
            from ..train.checkpoint import Checkpoint

            params = Checkpoint(checkpoint_path).as_pytree()
        if params is None:
            import jax

            params = init_params(cfg, jax.random.PRNGKey(seed))
        self.engine = LLMEngine(cfg, params, max_batch=max_batch,
                                max_len=max_len, temperature=temperature)

    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        tokens = self.engine.generate(
            list(request["prompt"]),
            int(request.get("max_new_tokens", 32)),
            request.get("eos_token"),
        )
        return {"tokens": tokens}

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()
