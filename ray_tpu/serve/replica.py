"""Replica actor: hosts one copy of a deployment.

Ref analogue: python/ray/serve/_private/replica.py RayServeReplica (:510,
call_user_method:851). Function deployments are called directly; class
deployments are instantiated once and called via __call__ or a named
method. ``handle_batch`` is the vectorized entry used by the router's
dynamic batcher (ref analogue: serve/batching.py _BatchQueue flushing into
the user's batch method).
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, List, Tuple

import cloudpickle


class Replica:
    def __init__(self, blob: bytes, init_args, init_kwargs):
        target = cloudpickle.loads(blob)
        if inspect.isclass(target):
            self._callable = target(*init_args, **init_kwargs)
            self._is_class = True
        else:
            self._callable = target
            self._is_class = False
        self._num_handled = 0

    def handle_request(self, method: str, args: Tuple, kwargs: Dict) -> Any:
        self._num_handled += 1
        if self._is_class and method != "__call__":
            fn = getattr(self._callable, method)
        else:
            fn = self._callable
        return fn(*args, **kwargs)

    def handle_batch(self, method: str, batched_args: List[Tuple]) -> List[Any]:
        """One call per batch: user function receives a list of first
        positional args and must return a list of equal length."""
        self._num_handled += len(batched_args)
        if self._is_class and method != "__call__":
            fn = getattr(self._callable, method)
        else:
            fn = self._callable
        items = [a[0][0] if a[0] else None for a in batched_args]
        out = fn(items)
        if not isinstance(out, (list, tuple)) or len(out) != len(items):
            raise ValueError(
                "batched deployment must return a list matching input length"
            )
        return list(out)

    def stats(self) -> Dict[str, Any]:
        return {"num_handled": self._num_handled}

    def ping(self) -> str:
        return "pong"
