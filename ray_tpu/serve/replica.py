"""Replica actor: hosts one copy of a deployment.

Ref analogue: python/ray/serve/_private/replica.py RayServeReplica (:510,
call_user_method:851). Function deployments are called directly; class
deployments are instantiated once and called via __call__ or a named
method. ``handle_batch`` is the vectorized entry used by the router's
dynamic batcher (ref analogue: serve/batching.py _BatchQueue flushing into
the user's batch method). Each replica carries the deployment version it
was started under (ref: deployment_version.py) so the controller can
drive rolling updates.
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Any, Dict, List, Tuple

import cloudpickle


class BoundDeployment:
    """Picklable stand-in for a DeploymentHandle riding in init args
    (live handles carry threads/locks and cannot pickle). Replicas
    resolve it to a real handle at construction time — this is what
    makes ``Child.bind()`` inside ``Parent.bind(child)`` work (ref
    analogue: the deployment-graph build's handle injection,
    serve/_private/deployment_graph_build.py)."""

    def __init__(self, name: str):
        self.name = name

    def resolve(self):
        from .api import get_deployment_handle

        return get_deployment_handle(self.name)


def _resolve_bound(value):
    if isinstance(value, BoundDeployment):
        return value.resolve()
    if isinstance(value, dict):
        return {k: _resolve_bound(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return type(value)(_resolve_bound(v) for v in value)
    return value


class Replica:
    def __init__(self, blob: bytes, init_args, init_kwargs,
                 version: str = "", deployment_name: str = "",
                 max_concurrent: int = 8):
        target = cloudpickle.loads(blob)
        init_args = tuple(_resolve_bound(a) for a in init_args)
        init_kwargs = {k: _resolve_bound(v)
                       for k, v in init_kwargs.items()}
        if inspect.isclass(target):
            self._callable = target(*init_args, **init_kwargs)
            self._is_class = True
        else:
            self._callable = target
            self._is_class = False
        self._num_handled = 0
        self._version = version
        self._deployment = deployment_name
        self._ongoing = 0
        self._lock = threading.Lock()
        # Identity tag for the ongoing gauge: gauges merge last-writer-
        # wins across processes, so replicas of one deployment must not
        # share a tag set (sum over `replica` for the total).
        import os

        from ..core.config import get_config
        from ..util import device_metrics
        from ..util.overload import AIMDLimiter

        self._replica_id = f"{device_metrics.node_tag()}:{os.getpid()}"
        # Adaptive admission: the deployment's max_concurrent_queries is
        # the ceiling; observed latency shrinks the admitted concurrency
        # below it (AIMD), and excess sheds with OverloadedError so the
        # handle routes it to a less-loaded replica instead of queueing
        # it here (ref analogue: replica-side max_ongoing_requests).
        self._limiter = AIMDLimiter(
            initial=max(1, int(max_concurrent)),
            min_limit=1,
            max_limit=max(1, int(max_concurrent)),
            latency_target_s=get_config().serve_aimd_latency_target_s,
        )

    def _resolve(self, method: str):
        if self._is_class and method != "__call__":
            return getattr(self._callable, method)
        return self._callable

    def _gauge_tags(self):
        return {"deployment": self._deployment or "anonymous",
                "replica": self._replica_id}

    def _begin(self, n: int = 1) -> None:
        from . import _telemetry

        with self._lock:
            self._num_handled += n
            self._ongoing += 1
            ongoing = self._ongoing
        _telemetry.REPLICA_ONGOING.set(float(ongoing),
                                       tags=self._gauge_tags())

    def _end(self, method: str, submit_ts: float, started: float) -> None:
        from . import _telemetry
        from ..util import device_metrics

        with self._lock:
            self._ongoing -= 1
            ongoing = self._ongoing
        _telemetry.REPLICA_ONGOING.set(float(ongoing),
                                       tags=self._gauge_tags())
        _telemetry.observe_replica_request(
            self._deployment, method, submit_ts, started, time.time()
        )
        # Natural sampling edge for accelerator state (throttled; no-op
        # in replicas that never imported jax).
        device_metrics.maybe_sample()

    def _admit(self, method: str) -> None:
        """Overload-control entry run before ANY user code: refuse
        deadline-expired work (it spent its budget queued — a dead
        request must never occupy the TPU), then enforce the adaptive
        concurrency limit (shed -> the handle retries a less-loaded
        replica)."""
        from ..core.exceptions import OverloadedError
        from ..util import overload
        from . import _telemetry

        overload.check_deadline(f"{self._deployment or 'replica'}.{method}")
        if not self._limiter.try_acquire():
            _telemetry.observe_shed(self._deployment, "replica")
            raise OverloadedError(
                f"replica {self._replica_id} of "
                f"{self._deployment or 'anonymous'!r} at adaptive "
                f"concurrency limit {self._limiter.limit}",
                retry_after_s=max(
                    0.1, self._limiter.ewma_latency_s or 0.5
                ),
            )

    def _chaos(self, method: str) -> None:
        """Chaos injection point INSIDE the measured request window, so
        an armed latency/error spec degrades this replica exactly like a
        slow or faulty one — feeding the caller's breaker and this
        replica's AIMD limiter (scope to one replica via
        ``match={"replica": <id>}``)."""
        from ..util import faults

        delay = faults.fire(
            faults.SERVE_REPLICA,
            deployment=self._deployment or "anonymous",
            replica=self._replica_id, method=method,
        )
        if delay:
            time.sleep(delay)

    def handle_request(self, method: str, args: Tuple, kwargs: Dict,
                       model_id: str = "", submit_ts: float = 0.0) -> Any:
        from ..util import overload
        from .multiplex import _set_model_id

        self._admit(method)
        self._begin()
        started = time.time()
        _set_model_id(model_id)
        try:
            self._chaos(method)
            # Injected (or real queueing) latency may have spent the
            # budget: cancel before user code runs, not after.
            overload.check_deadline(
                f"{self._deployment or 'replica'}.{method}"
            )
            return self._resolve(method)(*args, **kwargs)
        finally:
            self._limiter.release(time.time() - started)
            self._end(method, submit_ts, started)

    def handle_request_streaming(self, method: str, args: Tuple,
                                 kwargs: Dict, model_id: str = "",
                                 submit_ts: float = 0.0):
        """Generator entry: invoked with num_returns="streaming" by the
        handle so each yielded item seals as its own object and streams to
        the caller as produced (ref analogue: replica.py
        call_user_generator + the proxy's RESPONSE_STREAMING path).
        Deadline enforcement between items happens at the executor's
        stream-item seams (core/executor.py), so an expired stream stops
        producing instead of generating into the void."""
        from .multiplex import _set_model_id

        self._admit(method)
        self._begin()
        started = time.time()
        _set_model_id(model_id)
        try:
            self._chaos(method)
            out = self._resolve(method)(*args, **kwargs)
            if inspect.isgenerator(out) or hasattr(out, "__next__"):
                yield from out
            else:
                yield out
        finally:
            self._limiter.release(time.time() - started)
            self._end(method, submit_ts, started)

    def handle_batch(self, method: str, batched_args: List[Tuple],
                     model_id: str = "",
                     submit_ts: float = 0.0) -> List[Any]:
        """One call per batch: user function receives a list of first
        positional args and must return a list of equal length."""
        from .multiplex import _set_model_id

        self._admit(method)
        self._begin(len(batched_args))
        started = time.time()
        _set_model_id(model_id)
        try:
            self._chaos(method)
            fn = self._resolve(method)
            items = [a[0][0] if a[0] else None for a in batched_args]
            out = fn(items)
            if not isinstance(out, (list, tuple)) or len(out) != len(items):
                raise ValueError(
                    "batched deployment must return a list matching input "
                    "length"
                )
            return list(out)
        finally:
            self._limiter.release(time.time() - started)
            self._end(method, submit_ts, started)

    def stats(self) -> Dict[str, Any]:
        return {
            "num_handled": self._num_handled,
            "ongoing": self._ongoing,
            "version": self._version,
            "replica_id": self._replica_id,
            "concurrency_limit": self._limiter.limit,
            "sheds": self._limiter.sheds,
        }

    def version(self) -> str:
        return self._version

    def prepare_shutdown(self, timeout_s: float = 25.0) -> str:
        """Drain hook. For concurrency-1 replicas, per-submitter call
        ordering already guarantees earlier queued requests ran before
        this one; for concurrent replicas (and long-lived STREAMING
        generators) it additionally waits until no request is in flight,
        bounded by ``timeout_s`` (ref analogue: proxy/replica graceful
        drain on rolling update, serve/_private/proxy.py:1097)."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            with self._lock:
                if self._ongoing == 0:
                    return "drained"
            _time.sleep(0.05)
        return f"timeout ({self._ongoing} ongoing)"

    def ping(self) -> str:
        return "pong"
