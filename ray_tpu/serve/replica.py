"""Replica actor: hosts one copy of a deployment.

Ref analogue: python/ray/serve/_private/replica.py RayServeReplica (:510,
call_user_method:851). Function deployments are called directly; class
deployments are instantiated once and called via __call__ or a named
method. ``handle_batch`` is the vectorized entry used by the router's
dynamic batcher (ref analogue: serve/batching.py _BatchQueue flushing into
the user's batch method). Each replica carries the deployment version it
was started under (ref: deployment_version.py) so the controller can
drive rolling updates.
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Any, Dict, List, Tuple

import cloudpickle


class BoundDeployment:
    """Picklable stand-in for a DeploymentHandle riding in init args
    (live handles carry threads/locks and cannot pickle). Replicas
    resolve it to a real handle at construction time — this is what
    makes ``Child.bind()`` inside ``Parent.bind(child)`` work (ref
    analogue: the deployment-graph build's handle injection,
    serve/_private/deployment_graph_build.py)."""

    def __init__(self, name: str):
        self.name = name

    def resolve(self):
        from .api import get_deployment_handle

        return get_deployment_handle(self.name)


def _resolve_bound(value):
    if isinstance(value, BoundDeployment):
        return value.resolve()
    if isinstance(value, dict):
        return {k: _resolve_bound(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return type(value)(_resolve_bound(v) for v in value)
    return value


class Replica:
    def __init__(self, blob: bytes, init_args, init_kwargs,
                 version: str = "", deployment_name: str = ""):
        target = cloudpickle.loads(blob)
        init_args = tuple(_resolve_bound(a) for a in init_args)
        init_kwargs = {k: _resolve_bound(v)
                       for k, v in init_kwargs.items()}
        if inspect.isclass(target):
            self._callable = target(*init_args, **init_kwargs)
            self._is_class = True
        else:
            self._callable = target
            self._is_class = False
        self._num_handled = 0
        self._version = version
        self._deployment = deployment_name
        self._ongoing = 0
        self._lock = threading.Lock()
        # Identity tag for the ongoing gauge: gauges merge last-writer-
        # wins across processes, so replicas of one deployment must not
        # share a tag set (sum over `replica` for the total).
        import os

        from ..util import device_metrics

        self._replica_id = f"{device_metrics.node_tag()}:{os.getpid()}"

    def _resolve(self, method: str):
        if self._is_class and method != "__call__":
            return getattr(self._callable, method)
        return self._callable

    def _gauge_tags(self):
        return {"deployment": self._deployment or "anonymous",
                "replica": self._replica_id}

    def _begin(self, n: int = 1) -> None:
        from . import _telemetry

        with self._lock:
            self._num_handled += n
            self._ongoing += 1
            ongoing = self._ongoing
        _telemetry.REPLICA_ONGOING.set(float(ongoing),
                                       tags=self._gauge_tags())

    def _end(self, method: str, submit_ts: float, started: float) -> None:
        from . import _telemetry
        from ..util import device_metrics

        with self._lock:
            self._ongoing -= 1
            ongoing = self._ongoing
        _telemetry.REPLICA_ONGOING.set(float(ongoing),
                                       tags=self._gauge_tags())
        _telemetry.observe_replica_request(
            self._deployment, method, submit_ts, started, time.time()
        )
        # Natural sampling edge for accelerator state (throttled; no-op
        # in replicas that never imported jax).
        device_metrics.maybe_sample()

    def handle_request(self, method: str, args: Tuple, kwargs: Dict,
                       model_id: str = "", submit_ts: float = 0.0) -> Any:
        from .multiplex import _set_model_id

        self._begin()
        started = time.time()
        _set_model_id(model_id)
        try:
            return self._resolve(method)(*args, **kwargs)
        finally:
            self._end(method, submit_ts, started)

    def handle_request_streaming(self, method: str, args: Tuple,
                                 kwargs: Dict, model_id: str = "",
                                 submit_ts: float = 0.0):
        """Generator entry: invoked with num_returns="streaming" by the
        handle so each yielded item seals as its own object and streams to
        the caller as produced (ref analogue: replica.py
        call_user_generator + the proxy's RESPONSE_STREAMING path)."""
        from .multiplex import _set_model_id

        self._begin()
        started = time.time()
        _set_model_id(model_id)
        try:
            out = self._resolve(method)(*args, **kwargs)
            if inspect.isgenerator(out) or hasattr(out, "__next__"):
                yield from out
            else:
                yield out
        finally:
            self._end(method, submit_ts, started)

    def handle_batch(self, method: str, batched_args: List[Tuple],
                     model_id: str = "",
                     submit_ts: float = 0.0) -> List[Any]:
        """One call per batch: user function receives a list of first
        positional args and must return a list of equal length."""
        from .multiplex import _set_model_id

        self._begin(len(batched_args))
        started = time.time()
        _set_model_id(model_id)
        try:
            fn = self._resolve(method)
            items = [a[0][0] if a[0] else None for a in batched_args]
            out = fn(items)
            if not isinstance(out, (list, tuple)) or len(out) != len(items):
                raise ValueError(
                    "batched deployment must return a list matching input "
                    "length"
                )
            return list(out)
        finally:
            self._end(method, submit_ts, started)

    def stats(self) -> Dict[str, Any]:
        return {
            "num_handled": self._num_handled,
            "ongoing": self._ongoing,
            "version": self._version,
        }

    def version(self) -> str:
        return self._version

    def prepare_shutdown(self, timeout_s: float = 25.0) -> str:
        """Drain hook. For concurrency-1 replicas, per-submitter call
        ordering already guarantees earlier queued requests ran before
        this one; for concurrent replicas (and long-lived STREAMING
        generators) it additionally waits until no request is in flight,
        bounded by ``timeout_s`` (ref analogue: proxy/replica graceful
        drain on rolling update, serve/_private/proxy.py:1097)."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            with self._lock:
                if self._ongoing == 0:
                    return "drained"
            _time.sleep(0.05)
        return f"timeout ({self._ongoing} ongoing)"

    def ping(self) -> str:
        return "pong"
