"""gRPC ingress: call deployments as gRPC methods.

Ref analogue: serve's gRPC proxy (serve/_private/proxy.py gRPC path +
src/ray/protobuf/serve.proto). Routing is generic — no protoc step: a
``GenericRpcHandler`` maps ``/<deployment>/<method>`` to the deployment
handle's method with RAW request bytes, and replies with the method's
bytes result (non-bytes results are JSON-encoded). Clients use any gRPC
stack with identity (de)serializers, or protoc-generated stubs whose
messages they serialize themselves — the wire contract is bytes in /
bytes out, exactly what a generated stub produces.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, Optional

from ..core.exceptions import DeadlineExceededError, OverloadedError
from ..util import overload
from .handle import DeploymentHandle

_server = None
_lock = threading.Lock()
_routes: Dict[str, DeploymentHandle] = {}


def _make_gate(name: str) -> overload.AdmissionGate:
    """Per-deployment admission gate (mirror of the HTTP proxy's):
    sheds map to RESOURCE_EXHAUSTED instead of queueing."""
    from ..core.config import get_config

    return overload.gate_from_config(get_config())


_gates = overload.GateRegistry(_make_gate)


def _rpc_deadline(context) -> float:
    """Absolute deadline for one RPC: the client's gRPC deadline when
    set (context.time_remaining()), else the configured serve default."""
    from ..core.config import get_config

    budget = get_config().serve_default_request_timeout_s
    try:
        tr = context.time_remaining()
        if tr is not None:
            budget = min(budget, max(0.001, tr))
    except Exception:
        pass
    return time.time() + budget


class _ControllerDown(Exception):
    """Serve isn't running or the controller errored (UNAVAILABLE)."""


def _resolve(name: str) -> Optional[DeploymentHandle]:
    handle = _routes.get(name)
    if handle is not None:
        return handle
    # Dynamic discovery, mirroring the HTTP proxy: any live deployment
    # is routable — but a stray request must never SPAWN a controller,
    # and a transient controller failure is UNAVAILABLE, not NOT_FOUND.
    import ray_tpu

    from . import api as serve_api
    from .controller import CONTROLLER_NAME

    try:
        ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        raise _ControllerDown("serve is not running")
    try:
        handle = serve_api.get_deployment_handle(name)
    except KeyError:
        return None
    except Exception as e:  # noqa: BLE001
        raise _ControllerDown(f"controller error: {e}")
    _routes[name] = handle
    return handle


def _encode(item) -> bytes:
    if isinstance(item, (bytes, bytearray)):
        return bytes(item)
    return json.dumps(item, default=str).encode()


class _GenericHandler:
    """grpc.GenericRpcHandler routing /<deployment>/<method>.

    Methods whose name ends in ``stream`` (case-insensitive — e.g.
    ``stream``, ``TokenStream``) are SERVER-STREAMING: the replica
    method must be a generator, and every yielded item becomes one
    response message (bytes pass through; anything else JSON-encodes) —
    the gRPC mirror of the HTTP proxy's SSE route (ref: serve's
    RESPONSE_STREAMING over the gRPC proxy)."""

    def service(self, handler_call_details):
        import grpc

        parts = handler_call_details.method.strip("/").split("/")
        if len(parts) != 2:
            return None
        dep_name, method = parts
        streaming = method.lower().endswith("stream")

        def _handle_or_abort(context, status):
            try:
                handle = _resolve(dep_name)
            except _ControllerDown as e:
                status[0] = "UNAVAILABLE"
                context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
            if handle is None:
                status[0] = "NOT_FOUND"
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"no deployment {dep_name!r}")
            return handle

        def _begin_observation(context):
            """Root span for the RPC (honoring an incoming traceparent
            from the gRPC metadata) + e2e latency/status accounting —
            the gRPC mirror of the HTTP proxy's do_POST wrapper."""
            from ..core.timeline import (
                enter_span,
                exit_span,
                format_traceparent,
                get_buffer,
                new_span_id,
                new_trace_id,
                parse_traceparent,
            )

            md = {}
            try:
                md = {k: v for k, v in
                      (context.invocation_metadata() or ())}
            except Exception:
                pass
            parent = parse_traceparent(md.get("traceparent"))
            trace_id = parent[0] if parent else new_trace_id()
            span_id = new_span_id()
            prev = enter_span(trace_id, span_id)
            started = time.time()
            # The RPC's trace id returns to the caller as trailing
            # metadata (the gRPC mirror of the HTTP traceparent response
            # header): a user-visible DEADLINE_EXCEEDED / RESOURCE_
            # EXHAUSTED correlates to its recorded waterfall in one hop.
            try:
                context.set_trailing_metadata((
                    ("traceparent",
                     format_traceparent(trace_id, span_id)),
                ))
            except Exception:
                pass

            def finish(status_code: str):
                from . import _telemetry
                from ..util import flight_recorder

                exit_span(prev)
                ended = time.time()
                # Unknown services share one label — bounded cardinality
                # against attacker-chosen method paths.
                dep_label = (dep_name if status_code != "NOT_FOUND"
                             else "__unknown__")
                _telemetry.observe_ingress(
                    dep_label, "grpc", status_code, started, ended,
                    trace_id=trace_id,
                )
                try:
                    get_buffer().record(
                        f"grpc:{dep_name}", started, ended, "",
                        trace_id=trace_id, span_id=span_id,
                        parent_id=parent[1] if parent else "",
                    )
                except Exception:
                    pass
                reason = {
                    "RESOURCE_EXHAUSTED": "shed",
                    "DEADLINE_EXCEEDED": "expired",
                    "INTERNAL": "error",
                    "UNAVAILABLE": "error",
                }.get(status_code)
                flight_recorder.observe_request(
                    f"grpc:{dep_name}", trace_id, started, ended,
                    status=status_code, reason=reason, surface="grpc",
                )

            return finish

        def _admit_or_abort(context, status):
            """Overload admission (shed BEFORE dispatch) + deadline
            computation; aborts with RESOURCE_EXHAUSTED on shed."""
            from . import _telemetry

            deadline_ts = _rpc_deadline(context)
            gate = _gates.get(dep_name)
            try:
                gate.acquire(deadline_ts=deadline_ts)
            except OverloadedError as e:
                _telemetry.observe_shed(dep_name, "proxy")
                status[0] = "RESOURCE_EXHAUSTED"
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
            return gate, deadline_ts

        def unary_unary(request: bytes, context):
            from . import _telemetry

            status = ["OK"]
            finish = _begin_observation(context)
            try:
                handle = _handle_or_abort(context, status)
                gate, deadline_ts = _admit_or_abort(context, status)
                t0 = time.monotonic()
                prev_dl = overload.set_ambient_deadline(deadline_ts)
                try:
                    h = handle if method == "__call__" else handle.options(
                        method=method
                    )
                    result = h.remote(request).result(
                        timeout=overload.remaining(120.0)
                    )
                except OverloadedError as e:
                    status[0] = "RESOURCE_EXHAUSTED"
                    context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                  str(e))
                    return b""
                except (DeadlineExceededError, TimeoutError) as e:
                    _telemetry.observe_deadline_exceeded(
                        dep_name, "ingress"
                    )
                    status[0] = "DEADLINE_EXCEEDED"
                    context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                                  str(e))
                    return b""
                except Exception as e:  # noqa: BLE001
                    status[0] = "INTERNAL"
                    context.abort(grpc.StatusCode.INTERNAL, str(e))
                    return b""
                finally:
                    overload.set_ambient_deadline(prev_dl)
                    # Only downstream pushback shrinks the gate; a
                    # DEADLINE_EXCEEDED means the client's budget was
                    # too small, not that the server is overloaded.
                    gate.release(time.monotonic() - t0,
                                 overloaded=status[0] ==
                                 "RESOURCE_EXHAUSTED")
                return _encode(result)
            finally:
                finish(status[0])

        def unary_stream(request: bytes, context):
            from . import _telemetry

            status = ["OK"]
            finish = _begin_observation(context)
            try:
                handle = _handle_or_abort(context, status)
                gate, deadline_ts = _admit_or_abort(context, status)
                t0 = time.monotonic()
                prev_dl = overload.set_ambient_deadline(deadline_ts)
                try:
                    it = handle.options(method=method).stream(request)
                    for item in it:
                        yield _encode(item)
                except GeneratorExit:
                    # Client cancelled mid-stream: gRPC closes the
                    # generator; an aborted partial stream is not an OK.
                    status[0] = "CANCELLED"
                    raise
                except OverloadedError as e:
                    status[0] = "RESOURCE_EXHAUSTED"
                    context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                  str(e))
                except (DeadlineExceededError, TimeoutError) as e:
                    _telemetry.observe_deadline_exceeded(
                        dep_name, "ingress"
                    )
                    status[0] = "DEADLINE_EXCEEDED"
                    context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                                  str(e))
                except Exception as e:  # noqa: BLE001
                    status[0] = "INTERNAL"
                    context.abort(grpc.StatusCode.INTERNAL, str(e))
                finally:
                    overload.set_ambient_deadline(prev_dl)
                    # Only downstream pushback shrinks the gate; a
                    # DEADLINE_EXCEEDED means the client's budget was
                    # too small, not that the server is overloaded.
                    gate.release(time.monotonic() - t0,
                                 overloaded=status[0] ==
                                 "RESOURCE_EXHAUSTED")
            finally:
                finish(status[0])

        if streaming:
            return grpc.unary_stream_rpc_method_handler(
                unary_stream,
                request_deserializer=None,   # identity: raw bytes
                response_serializer=None,
            )
        return grpc.unary_unary_rpc_method_handler(
            unary_unary,
            request_deserializer=None,   # identity: raw bytes
            response_serializer=None,
        )


# grpc.GenericRpcHandler is an ABC registered at import time; subclass
# lazily so importing this module does not require grpcio.
def _make_handler():
    import grpc

    class Handler(_GenericHandler, grpc.GenericRpcHandler):
        pass

    return Handler()


def start_grpc_ingress(port: int = 0, *, host: str = "127.0.0.1",
                       max_workers: int = 8,
                       max_concurrent_rpcs: Optional[int] = 64) -> int:
    """Start (or return) the gRPC ingress; returns the bound port.

    Admission is BOUNDED: at most ``max_workers`` RPCs execute while up
    to ``max_concurrent_rpcs`` are admitted (queued on the pool); beyond
    that gRPC rejects with RESOURCE_EXHAUSTED instead of stacking
    unbounded blocked work (ref: the proxy's queue-length admission).
    When the cluster runs mutual TLS (core/tls.py), the ingress binds a
    TLS port requiring CA-signed client certificates — the ingress is
    the one channel a remote attacker actually reaches, so it must not
    stay plaintext while the control plane is encrypted."""
    global _server
    from concurrent import futures

    import grpc

    with _lock:
        if _server is not None:
            return _server._rtpu_port
        server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            maximum_concurrent_rpcs=max_concurrent_rpcs,
        )
        server.add_generic_rpc_handlers((_make_handler(),))
        from ..core.config import get_config
        from ..core.tls import tls_enabled

        if tls_enabled():
            cfg = get_config()
            with open(cfg.tls_key_path, "rb") as f:
                key = f.read()
            with open(cfg.tls_cert_path, "rb") as f:
                crt = f.read()
            with open(cfg.tls_ca_path, "rb") as f:
                ca = f.read()
            creds = grpc.ssl_server_credentials(
                [(key, crt)], root_certificates=ca,
                require_client_auth=True,  # mutual, like the cluster
            )
            bound = server.add_secure_port(f"{host}:{port}", creds)
        else:
            bound = server.add_insecure_port(f"{host}:{port}")
        if bound == 0:
            # gRPC signals bind failure by returning port 0, it does
            # not raise — fail loudly like the HTTP mirror would.
            raise OSError(
                f"gRPC ingress could not bind {host}:{port}"
            )
        server.start()
        server._rtpu_port = bound
        _server = server
        return bound


def register_route(name: str, handle: DeploymentHandle):
    _routes[name] = handle


class GrpcProxyActor:
    """One gRPC ingress per node (mirror of http_proxy.ProxyActor):
    routes resolve dynamically through the controller."""

    def __init__(self, port: int = 0):
        # Per-node ingress serves remote clients: bind all interfaces
        # (the driver-local default stays loopback).
        self._port = start_grpc_ingress(port, host="0.0.0.0")

    def port(self) -> int:
        return self._port

    def ping(self) -> str:
        return "ok"

    def shutdown(self) -> str:
        stop_grpc_ingress()
        return "ok"


def start_per_node_grpc_proxies(port: int = 0):
    """Launch one GrpcProxyActor on every alive node; returns
    {node_id: (actor, port)}."""
    from .http_proxy import start_per_node_actors

    return start_per_node_actors(GrpcProxyActor, port)


def stop_grpc_ingress():
    global _server
    with _lock:
        if _server is not None:
            _server.stop(grace=1.0)
            _server = None
        _routes.clear()
        _gates.clear()
