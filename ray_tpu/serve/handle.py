"""DeploymentHandle + router.

Ref analogue: serve/handle.py DeploymentHandle → _private/router.py Router
(:893) with PowerOfTwoChoicesReplicaScheduler (:290): each request samples
two replicas and picks the one with fewer outstanding requests (queue
lengths tracked by the caller; the reference queries replicas — local
tracking is the single-process simplification of the same policy).

Routing state (replica set + queue depths) lives in one shared
``_RouterState`` per handle family: ``handle.options(method=...)`` clones
share it, so a scale/rolling-update seen by any of them is seen by all.
The state keeps itself fresh via a LONG-POLL to the controller (ref
analogue: long_poll.py LongPollClient): a daemon thread blocks in
``listen_for_route_change`` and swaps the routable set the moment the
controller scales or rolls a deployment. The same thread pushes the
handle's outstanding-request total to the controller, which is the input
to queue-depth autoscaling (ref: handle-side autoscaling metrics). The
thread holds only a WEAK reference to the state — dropping every handle
ends the poller instead of leaking it.

Requests that land on a replica retired mid-flight (rolling update,
downscale, worker crash) evict that replica locally and retry against the
refreshed set — this is what makes redeploys zero-downtime and replica
crashes invisible to the caller.

Dynamic batching lives here too (ref analogue: serve/batching.py
_BatchQueue:65): requests buffer until max_batch_size or batch_wait_timeout_s
and flush as ONE replica call — on TPU this is what keeps the MXU fed with
batched forward passes instead of single-row calls.

REQUEST ROBUSTNESS (util/overload.py mechanisms): every request carries
an absolute deadline (the ingress's ambient budget, else the
``serve_default_request_timeout_s`` default) that is installed on the
router thread, stamped onto the replica call's task spec, and enforced
replica-side (refuse-before-execute + cooperative cancellation). The
router keeps a per-replica CIRCUIT BREAKER fed by every outcome — an
open breaker takes the replica out of the pick set (half-open probes
re-admit it), and non-closed breakers are reported to the controller,
which ejects persistently-unhealthy replicas through the drain
machinery. Retries ride a jittered backoff and a token-bucket RETRY
BUDGET so they cannot amplify an outage.

HOT PATH CONTRACT: replicas are plain actor handles, so every
``replica.handle_request.remote(...)`` + ``ray_tpu.get(...)`` pair rides
the direct actor-call plane (runtime._DirectChannel) once the replica's
channel engages — a steady-state request is submit -> framed channel ->
inline reply, with NO node-manager round-trip. Blocking NM calls
(``force_refresh``, ``call_sync``, KV ops, ...) are allowed ONLY inside
except-handler recovery blocks (dead replica, stale route); the
``make check-obs`` lint (tools/check_metric_names.py
validate_serve_hot_path) enforces this for the request-path functions.
"""

from __future__ import annotations

import os
import random
import threading
import time
import uuid
import weakref
from typing import Any, Dict, List, Optional, Tuple

MAX_DEATH_RETRIES = 3
# Per-item deadline for streaming responses (overridable via env);
# guards proxy/consumer threads against a wedged replica generator.
STREAM_ITEM_TIMEOUT_S = float(
    os.environ.get("RAY_TPU_SERVE_STREAM_ITEM_TIMEOUT_S", "120")
)
# How long an evicted replica key stays filtered out of snapshots (covers
# the gap until the controller's health check removes it server-side).
DEAD_REPLICA_TTL_S = 10.0


def _replica_key(replica) -> Any:
    return getattr(replica, "_actor_id", None) or id(replica)


class _RouterState:
    """Shared routing view for one deployment (all handle clones)."""

    def __init__(self, deployment_name: str, replicas: List[Any],
                 controller, route_version: int):
        from ..core.config import get_config
        from ..util.overload import RetryBudget

        self.deployment_name = deployment_name
        self.lock = threading.Lock()
        self.replicas = list(replicas)
        self.route_version = route_version
        self.outstanding: Dict[Any, int] = {}
        self.controller = controller
        self.handle_id = uuid.uuid4().hex[:12]
        self.closed = False
        self._cfg = get_config()
        # Per-replica circuit breakers (keyed like `outstanding`): a
        # sick replica's breaker opens instead of letting retries
        # hammer it; half-open probes re-admit it after heal. The
        # shared retry budget caps retry amplification handle-wide.
        self.breakers: Dict[Any, Any] = {}
        self.retry_budget = RetryBudget(
            ratio=self._cfg.serve_retry_budget_ratio
        )
        # Keys of replicas we observed dead, with eviction time: filtered
        # out of controller snapshots until the health checker has had time
        # to remove them server-side (prevents re-routing to a corpse).
        self.dead: Dict[Any, float] = {}
        # Raw-HTTP (ASGI) deployment? Refreshed by every routing
        # snapshot so proxies follow protocol changes across redeploys.
        self.is_asgi: bool = False
        # multiplexed model id -> replica key that last served it.
        self.model_affinity: Dict[str, Any] = {}
        if controller is not None:
            t = threading.Thread(
                target=_refresh_loop, args=(weakref.ref(self),), daemon=True
            )
            t.start()

    # ---- replica selection (power of two choices) -------------------------

    MAX_TRACKED_MODELS = 256
    # A model spills onto another replica when its current holders are
    # this many requests deeper than the cluster's least-loaded replica.
    AFFINITY_SPILL_DEPTH = 2

    def _breaker(self, key):
        """Breaker for one replica key (caller holds ``self.lock``)."""
        br = self.breakers.get(key)
        if br is None:
            from ..util.overload import CircuitBreaker

            cfg = self._cfg
            key_str = key.hex() if hasattr(key, "hex") else str(key)

            def on_transition(state, _key=key_str):
                from . import _telemetry

                _telemetry.record_breaker_state(
                    self.deployment_name, self.handle_id, _key, state
                )

            br = CircuitBreaker(
                error_threshold=cfg.serve_breaker_error_threshold,
                min_volume=cfg.serve_breaker_min_volume,
                open_base_s=cfg.serve_breaker_open_s,
                latency_trip_s=0.0,
                on_transition=on_transition,
            )
            self.breakers[key] = br
        return br

    def _drop_breaker(self, key) -> None:
        """Remove a replica's breaker (caller holds ``self.lock``),
        zeroing its gauge series — an ejected replica must not read as
        permanently open in `rtpu metrics --serve`."""
        br = self.breakers.pop(key, None)
        if br is not None and br.state != "closed":
            from . import _telemetry

            key_str = key.hex() if hasattr(key, "hex") else str(key)
            _telemetry.record_breaker_state(
                self.deployment_name, self.handle_id, key_str, "closed"
            )

    def record_result(self, replica, ok: bool,
                      latency_s: Optional[float] = None) -> None:
        """Feed one request outcome into the replica's breaker."""
        with self.lock:
            br = self._breaker(_replica_key(replica))
        br.record(ok, latency_s)

    def breaker_states(self) -> Dict[str, str]:
        """Non-closed breakers, keyed by replica hex (reported to the
        controller by the refresh loop for persistent-unhealth
        ejection)."""
        with self.lock:
            out = {}
            for k, br in self.breakers.items():
                if br.state != "closed":
                    key_str = k.hex() if hasattr(k, "hex") else str(k)
                    out[key_str] = br.state
            return out

    def pick(self, model_id: Optional[str] = None):
        """Power of two choices on local outstanding counts; multiplexed
        requests prefer replicas that already hold their model (cache
        affinity) but SPILL onto additional replicas when those are
        saturated — affinity must not defeat load balancing (ref:
        model-multiplex-aware request routing). Replicas with an OPEN
        circuit breaker are not routable; when every breaker is open,
        one due half-open probe may go through, otherwise the request
        fails fast with ``OverloadedError`` (shed, not queued)."""
        from ray_tpu.core.exceptions import OverloadedError

        with self.lock:
            all_reps = self.replicas
            if not all_reps:
                raise RuntimeError(
                    f"deployment {self.deployment_name!r} has no replicas"
                )
            reps = []
            probe = None
            for r in all_reps:
                br = self._breaker(_replica_key(r))
                if br.allow():
                    reps.append(r)
                elif probe is None and br.probe_due():
                    probe = (r, br)
            if probe is not None:
                # A due half-open probe takes priority over normal
                # routing: exactly one live request goes to the sick
                # replica so a healed one can rejoin — even while
                # healthy replicas are absorbing the traffic.
                probe[1].begin_probe()
                return probe[0]
            if not reps:
                # Every breaker open and no probe due yet: shed fast
                # instead of hammering sick replicas.
                raise OverloadedError(
                    f"deployment {self.deployment_name!r}: all "
                    f"{len(all_reps)} replica circuit breaker(s) open",
                    retry_after_s=self._cfg.serve_breaker_open_s,
                )

            def depth(r):
                return self.outstanding.get(_replica_key(r), 0)

            def p2c(cands):
                if len(cands) == 1:
                    return cands[0]
                a, b = random.sample(range(len(cands)), 2)
                return (cands[a] if depth(cands[a]) <= depth(cands[b])
                        else cands[b])

            if not model_id:
                return p2c(reps)
            live_keys = {_replica_key(r) for r in reps}
            holders = self.model_affinity.setdefault(model_id, [])
            holders[:] = [k for k in holders if k in live_keys]
            holding = [r for r in reps if _replica_key(r) in holders]
            min_depth = min((depth(r) for r in reps), default=0)
            if holding and (
                min(depth(r) for r in holding)
                <= min_depth + self.AFFINITY_SPILL_DEPTH
            ):
                return p2c(holding)
            # Saturated (or no holder yet): spread onto a new replica.
            chosen = p2c(reps)
            k = _replica_key(chosen)
            if k not in holders:
                holders.append(k)
            if len(self.model_affinity) > self.MAX_TRACKED_MODELS:
                self.model_affinity.pop(
                    next(iter(self.model_affinity))
                )
            return chosen

    def begin(self, replica) -> None:
        with self.lock:
            k = _replica_key(replica)
            self.outstanding[k] = self.outstanding.get(k, 0) + 1

    def end(self, replica) -> None:
        with self.lock:
            k = _replica_key(replica)
            n = self.outstanding.get(k, 0) - 1
            if n <= 0:
                self.outstanding.pop(k, None)
            else:
                self.outstanding[k] = n

    def evict(self, replica) -> None:
        """Drop a replica observed dead so retries don't re-pick it."""
        k = _replica_key(replica)
        with self.lock:
            self.dead[k] = time.monotonic()
            self._drop_breaker(k)
            self.replicas = [
                r for r in self.replicas if _replica_key(r) != k
            ]

    def apply_snapshot(self, snap: Dict[str, Any]) -> None:
        now = time.monotonic()
        with self.lock:
            if "is_asgi" in snap:
                self.is_asgi = bool(snap["is_asgi"])
            for k, ts in list(self.dead.items()):
                if now - ts > DEAD_REPLICA_TTL_S:
                    del self.dead[k]
            self.route_version = snap["version"]
            self.replicas = [
                r for r in snap["replicas"]
                if _replica_key(r) not in self.dead
            ]
            # Breakers follow the replica set: entries for replicas no
            # longer routable are dropped (a retired replica must not
            # pin breaker state against a reused key), zeroing their
            # gauge series on the way out.
            live = {_replica_key(r) for r in self.replicas}
            for k in list(self.breakers):
                if k not in live:
                    self._drop_breaker(k)

    def force_refresh(self) -> None:
        """Synchronous route refresh after observing a dead replica."""
        import ray_tpu

        if self.controller is None:
            return
        try:
            snap = ray_tpu.get(
                self.controller.get_routing.remote(self.deployment_name),
                timeout=5.0,
            )
            self.apply_snapshot(snap)
        except Exception:
            pass


def _refresh_loop(state_ref: "weakref.ref[_RouterState]") -> None:
    """Long-poll the controller for route changes and push metrics.

    Holds only a weakref: when the last handle sharing the state is
    garbage-collected, the loop exits — no immortal poller threads.
    """
    import ray_tpu

    while True:
        state = state_ref()
        if state is None or state.closed:
            return
        try:
            with state.lock:
                outstanding = dict(state.outstanding)
                known = state.route_version
            total = sum(outstanding.values())
            controller = state.controller
            name = state.deployment_name
            handle_id = state.handle_id
            # Gauges publish from HERE (~2Hz), not the per-request
            # begin/end hot path: in-flight/queue-depth need freshness,
            # not per-event registry traffic under the router lock.
            from . import _telemetry

            _telemetry.update_router_gauges(name, handle_id, outstanding)
            controller.record_handle_metrics.remote(name, handle_id, total)
            # Breaker telemetry rides the same ~2Hz cadence: the
            # controller ejects replicas whose breakers stay open
            # (persistently unhealthy) through the drain machinery.
            open_breakers = state.breaker_states()
            if open_breakers:
                controller.report_breakers.remote(
                    name, handle_id, open_breakers
                )
            ref = controller.listen_for_route_change.remote(name, known, 0.5)
            del state  # don't pin the state across the blocking poll
            snap = ray_tpu.get(ref, timeout=10.0)
            state = state_ref()
            if state is None or state.closed:
                return
            if snap["version"] < 0:
                # Deployment deleted: back off instead of spinning on the
                # controller's immediate not-found replies (it may come
                # back on a future serve.run with the same name).
                del state
                time.sleep(0.5)
                continue
            if snap["version"] != known:
                state.apply_snapshot(snap)
            del state
        except Exception:
            time.sleep(0.2)


def _retry_backoff():
    """Jittered backoff between replica-evict/shed retries (satellite of
    the overload plane: the old loop retried immediately, unboundedly)."""
    from ..util.backoff import Backoff

    return Backoff(base=0.02, factor=2.0, max_delay=0.5, jitter=0.5)


def _pick_with_refresh(state: _RouterState, model_id, attempt: int,
                       bo=None):
    """Shared pick step: on an empty replica set (stale snapshot /
    just-created handle) force-refresh and signal retry by returning
    None; raises only once retries are exhausted."""
    try:
        return state.pick(model_id)
    except RuntimeError:
        if attempt < MAX_DEATH_RETRIES:
            state.force_refresh()
            if bo is not None:
                bo.sleep()
            else:
                time.sleep(0.05 * (attempt + 1))
            return None
        raise


def _spend_retry(state: _RouterState, deadline_ts: float) -> bool:
    """Gate one retry: never past the request's deadline, never beyond
    the handle's retry budget (retry amplification cap)."""
    from . import _telemetry

    if deadline_ts and time.time() >= deadline_ts:
        return False
    if not state.retry_budget.try_spend():
        _telemetry.observe_shed(state.deployment_name, "retry_budget")
        return False
    _telemetry.observe_retry(state.deployment_name)
    return True


def _route_with_retry(state: _RouterState, submit, deliver, deliver_error,
                      model_id: Optional[str] = None):
    """Shared request path: pick a replica (p2c + model affinity, open
    breakers excluded), submit, deliver the result. Recovery ladder:
    actor death -> evict + refresh + retry elsewhere; replica shed /
    transport fault -> breaker-recorded failure + retry elsewhere
    (jittered backoff, retry-budget capped); deadline expiry -> fail
    fast, no retry (the budget is spent). Every outcome feeds the
    picked replica's circuit breaker."""
    import ray_tpu
    from ray_tpu.core.exceptions import (
        ActorDiedError,
        DeadlineExceededError,
        GetTimeoutError,
        OverloadedError,
        WorkerCrashedError,
    )

    from ..util import overload
    from . import _telemetry

    state.retry_budget.record_request()
    deadline_ts = overload.ambient_deadline()
    bo = _retry_backoff()
    last_err: Optional[BaseException] = None
    attempt = 0
    # Only attempts that actually SUBMITTED to a replica charge the
    # retry budget — an empty-set snapshot refresh is not a retry, and
    # cold handles must not fail for lack of tokens.
    needs_budget = False
    while attempt <= MAX_DEATH_RETRIES:
        if needs_budget and not _spend_retry(state, deadline_ts):
            break  # budget/deadline exhausted: surface the last error
        try:
            replica = _pick_with_refresh(state, model_id, attempt, bo)
        except (RuntimeError, OverloadedError) as e:
            if isinstance(e, OverloadedError):
                _telemetry.observe_shed(state.deployment_name, "router")
            deliver_error(last_err or e)
            return
        if replica is None:
            attempt += 1
            continue  # refreshed after an empty set; try again
        state.begin(replica)
        t0 = time.monotonic()
        try:
            timeout = None
            if deadline_ts:
                # Bound the wait by the remaining budget plus a grace
                # second for the replica's own refusal to arrive.
                timeout = max(0.0, deadline_ts - time.time()) + 1.0
            deliver(ray_tpu.get(submit(replica), timeout=timeout))
            state.record_result(replica, True, time.monotonic() - t0)
            return
        except (ActorDiedError, WorkerCrashedError) as e:
            # Replica retired/crashed under us (rolling update, node
            # loss): evict it locally, refresh, retry elsewhere.
            last_err = e
            state.record_result(replica, False)
            state.evict(replica)
            state.force_refresh()
            bo.sleep()
        except OverloadedError as e:
            # Replica shed us (adaptive concurrency limit): a less
            # loaded replica may still have room.
            last_err = e
            state.record_result(replica, False, time.monotonic() - t0)
            bo.sleep()
        except DeadlineExceededError as e:
            # Refused or cancelled replica-side: the budget is spent,
            # retrying cannot meet it. The failure still counts against
            # the replica — a healthy one would have answered in time.
            state.record_result(replica, False, time.monotonic() - t0)
            _telemetry.observe_deadline_exceeded(
                state.deployment_name, "replica"
            )
            deliver_error(e)
            return
        except GetTimeoutError:
            state.record_result(replica, False, time.monotonic() - t0)
            _telemetry.observe_deadline_exceeded(
                state.deployment_name, "caller"
            )
            deliver_error(DeadlineExceededError(
                f"deployment {state.deployment_name!r}: request "
                f"deadline expired waiting for a replica reply"
            ))
            return
        except ConnectionError as e:
            # Transport fault (incl. injected chaos) with the actor
            # alive: count against the breaker, retry elsewhere.
            last_err = e
            state.record_result(replica, False, time.monotonic() - t0)
            bo.sleep()
        except BaseException as e:  # noqa: BLE001
            # Application errors (user exceptions, TaskError wrappers)
            # mean the replica did its job — success against the
            # breaker. Remaining FRAMEWORK faults (ObjectLostError,
            # ActorUnavailableError, ...) count as failures, same
            # classification as the streaming path.
            from ray_tpu.core.exceptions import RayTpuError, TaskError

            app_error = isinstance(e, TaskError) or not isinstance(
                e, (RayTpuError, ConnectionError, TimeoutError)
            )
            state.record_result(replica, app_error,
                                time.monotonic() - t0)
            deliver_error(e)
            return
        finally:
            state.end(replica)
        # Fall-through = a retryable failure after a real submit: the
        # next attempt is a genuine retry and must spend budget.
        needs_budget = True
        attempt += 1
    deliver_error(last_err or RuntimeError(
        f"deployment {state.deployment_name!r}: retries exhausted"
    ))


class _PendingBatch:
    def __init__(self):
        # [(payload, future, caller trace span | None, deadline_ts), ...]
        self.items: List[Tuple[Any, "ServeFuture", Any, float]] = []
        self.created = time.monotonic()


class ServeFuture:
    """Resolves to the result of a routed request."""

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        self._ref = None

    def _set_ref(self, ref):
        self._ref = ref
        self._event.set()

    def _set_value(self, value):
        self._value = value
        self._event.set()

    def _set_error(self, err):
        self._error = err
        self._event.set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("serve request timed out")
        if self._error is not None:
            raise self._error
        if self._ref is not None:
            import ray_tpu

            return ray_tpu.get(self._ref, timeout=timeout)
        return self._value


class DeploymentHandle:
    def __init__(self, deployment_name: str, replicas: List[Any],
                 *, batch_config: Optional[Dict[str, Any]] = None,
                 method: str = "__call__", controller=None,
                 route_version: int = 0, _state: Optional[_RouterState] = None,
                 multiplexed_model_id: str = ""):
        self.deployment_name = deployment_name
        self._state = _state or _RouterState(
            deployment_name, replicas, controller, route_version
        )
        self._method = method
        self._model_id = multiplexed_model_id
        self._batch = batch_config
        self._batch_lock = threading.Lock()
        self._pending: Optional[_PendingBatch] = None

    def close(self):
        self._state.closed = True

    # ---- request path ------------------------------------------------------

    def options(self, method: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None
                ) -> "DeploymentHandle":
        """Clone bound to another method / multiplexed model id; shares
        routing + queue-depth state with the parent (one long-poller per
        handle family)."""
        return DeploymentHandle(
            self.deployment_name, [],
            batch_config=self._batch, method=method or self._method,
            _state=self._state,
            multiplexed_model_id=(self._model_id
                                  if multiplexed_model_id is None
                                  else multiplexed_model_id),
        )

    def _request_deadline(self) -> float:
        """The request's absolute deadline: the caller's ambient budget
        when one is installed (ingress-set, or a nested call inside a
        deadlined request), else the configured serve default — every
        serve request carries a budget."""
        from ..util import overload

        dl = overload.ambient_deadline()
        if dl:
            return dl
        return time.time() + self._state._cfg.serve_default_request_timeout_s

    def remote(self, *args, **kwargs) -> ServeFuture:
        if self._batch:
            return self._remote_batched(args, kwargs)
        from ..core.timeline import current_span

        fut = ServeFuture()
        # The submit happens on a router thread: capture the CALLER's
        # span AND deadline here so the replica task parents to the
        # proxy/driver span and carries the request's remaining budget
        # (ref: tracing context stamped onto the task spec at submit).
        threading.Thread(
            target=self._run_with_retry,
            args=(fut, self._method, args, kwargs, current_span(),
                  self._request_deadline()),
            daemon=True,
        ).start()
        return fut

    def _run_with_retry(self, fut: ServeFuture, method, args, kwargs,
                        span=None, deadline_ts: float = 0.0):
        from ..core.timeline import enter_span, exit_span
        from ..util import overload

        model_id = self._model_id
        prev = enter_span(*span) if span else None
        prev_dl = overload.set_ambient_deadline(deadline_ts)
        try:
            _route_with_retry(
                self._state,
                lambda replica: replica.handle_request.remote(
                    method, args, kwargs, model_id, time.time()
                ),
                fut._set_value,
                fut._set_error,
                model_id=model_id or None,
            )
        finally:
            overload.set_ambient_deadline(prev_dl)
            if span:
                exit_span(prev)

    def stream(self, *args, **kwargs):
        """Streaming request: yields response items as the replica
        produces them (ref analogue: handle.options(stream=True) over the
        replica's generator path + RESPONSE_STREAMING in proxy.py:1097).
        Routing (p2c, model affinity, dead-replica retry) happens on the
        first item; once a replica has started yielding, a mid-stream
        death surfaces to the caller rather than silently replaying
        side effects."""
        import ray_tpu
        from ray_tpu.core.exceptions import OverloadedError

        from ..util import overload

        model_id = self._model_id
        state = self._state
        # The generator body runs on the CONSUMER's thread (proxy SSE /
        # gRPC handler), where the ingress installed the request's
        # deadline; fall back to the serve default budget.
        deadline_ts = self._request_deadline()
        state.retry_budget.record_request()
        bo = _retry_backoff()
        last_err = None
        attempt = 0
        # Mirror of _route_with_retry: only post-submit retries charge
        # the budget; empty-set refreshes are free.
        needs_budget = False
        while attempt <= MAX_DEATH_RETRIES:
            if needs_budget and not _spend_retry(state, deadline_ts):
                break
            try:
                replica = _pick_with_refresh(
                    state, model_id or None, attempt, bo
                )
            except (RuntimeError, OverloadedError) as e:
                if isinstance(e, OverloadedError):
                    from . import _telemetry

                    _telemetry.observe_shed(
                        state.deployment_name, "router"
                    )
                raise (last_err or e)
            if replica is None:
                attempt += 1
                continue  # refreshed after an empty set; try again
            state.begin(replica)
            started = False
            t0 = time.monotonic()
            try:
                with overload.deadline_scope(deadline_ts):
                    gen = replica.handle_request_streaming.options(
                        num_returns="streaming"
                    ).remote(self._method, args, kwargs, model_id,
                             time.time())
                # Per-item production deadline: a wedged replica
                # generator surfaces a timeout instead of pinning the
                # consumer (e.g. a proxy SSE thread) forever — bounded
                # further by the request's remaining budget.
                gen.item_timeout_s = STREAM_ITEM_TIMEOUT_S
                for ref in gen:
                    item_timeout = STREAM_ITEM_TIMEOUT_S
                    if deadline_ts:
                        item_timeout = min(
                            item_timeout,
                            max(0.0, deadline_ts - time.time()) + 1.0,
                        )
                    value = ray_tpu.get(ref, timeout=item_timeout)
                    started = True
                    yield value
                state.record_result(replica, True,
                                    time.monotonic() - t0)
                return
            except Exception as e:  # noqa: BLE001
                from ray_tpu.core.exceptions import (
                    ActorDiedError,
                    OverloadedError,
                    WorkerCrashedError,
                )

                if isinstance(e, (ActorDiedError, WorkerCrashedError)) \
                        and not started:
                    last_err = e
                    state.record_result(replica, False)
                    state.evict(replica)
                    state.force_refresh()
                    bo.sleep()
                    needs_budget = True
                    attempt += 1
                    continue
                if isinstance(e, OverloadedError) and not started:
                    # Replica shed us before producing anything: a less
                    # loaded replica may still have room (mirror of the
                    # non-streaming retry ladder).
                    last_err = e
                    state.record_result(replica, False,
                                        time.monotonic() - t0)
                    bo.sleep()
                    needs_budget = True
                    attempt += 1
                    continue
                # Infra faults count against the breaker; application
                # errors mid-stream do not (the replica did its job).
                infra = isinstance(
                    e, (ActorDiedError, WorkerCrashedError,
                        OverloadedError, ConnectionError, TimeoutError)
                )
                state.record_result(replica, not infra,
                                    time.monotonic() - t0)
                raise
            finally:
                state.end(replica)
        raise last_err if last_err is not None else RuntimeError(
            f"deployment {state.deployment_name!r}: streaming retries "
            f"exhausted"
        )

    # ---- dynamic batching --------------------------------------------------

    def _remote_batched(self, args, kwargs) -> ServeFuture:
        from ..core.timeline import current_span

        fut = ServeFuture()
        flush: Optional[_PendingBatch] = None
        with self._batch_lock:
            if self._pending is None:
                self._pending = _PendingBatch()
                self._start_flusher()
            self._pending.items.append(
                ((args, kwargs), fut, current_span(),
                 self._request_deadline())
            )
            if len(self._pending.items) >= self._batch["max_batch_size"]:
                flush = self._pending
                self._pending = None
        if flush is not None:
            self._flush(flush)
        return fut

    def _start_flusher(self):
        wait_s = self._batch["batch_wait_timeout_s"]

        def run():
            time.sleep(wait_s)
            with self._batch_lock:
                flush, self._pending = self._pending, None
            if flush is not None:
                self._flush(flush)

        threading.Thread(target=run, daemon=True).start()

    def _flush(self, batch: _PendingBatch):
        from ..core.timeline import enter_span, exit_span
        from ..util import overload

        payload = [item for item, _fut, _span, _dl in batch.items]
        model_id = self._model_id
        # A flush carries many callers' requests in one replica call;
        # parent the batch task to the first item's span (the others
        # still share its trace through the ingress-side spans). The
        # batch executes under the LOOSEST item deadline: one expired
        # straggler must not get the whole batch refused (items were
        # admitted within batch_wait_timeout_s of each other, so the
        # spread is small).
        span = next((s for _, _, s, _dl in batch.items if s), None)
        deadline_ts = max((dl for _, _, _s, dl in batch.items), default=0.0)

        def deliver(results):
            for (_, fut, _s, _dl), value in zip(batch.items, results):
                fut._set_value(value)

        def deliver_error(err):
            for _, fut, _s, _dl in batch.items:
                fut._set_error(err)

        def run():
            prev = enter_span(*span) if span else None
            prev_dl = overload.set_ambient_deadline(deadline_ts)
            try:
                _route_with_retry(
                    self._state,
                    lambda replica: replica.handle_batch.remote(
                        self._method, payload, model_id, time.time()
                    ),
                    deliver,
                    deliver_error,
                    model_id=model_id or None,
                )
            finally:
                overload.set_ambient_deadline(prev_dl)
                if span:
                    exit_span(prev)

        threading.Thread(target=run, daemon=True).start()

    # ---- introspection -----------------------------------------------------

    def num_replicas(self) -> int:
        with self._state.lock:
            return len(self._state.replicas)

    def queue_depths(self) -> Dict[Any, int]:
        with self._state.lock:
            return dict(self._state.outstanding)
