"""DeploymentHandle + router.

Ref analogue: serve/handle.py DeploymentHandle → _private/router.py Router
(:893) with PowerOfTwoChoicesReplicaScheduler (:290): each request samples
two replicas and picks the one with fewer outstanding requests (queue
lengths tracked by the caller; the reference queries replicas — local
tracking is the single-process simplification of the same policy).

Dynamic batching lives here too (ref analogue: serve/batching.py
_BatchQueue:65): requests buffer until max_batch_size or batch_wait_timeout_s
and flush as ONE replica call — on TPU this is what keeps the MXU fed with
batched forward passes instead of single-row calls.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class _PendingBatch:
    def __init__(self):
        self.items: List[Tuple[Any, "ServeFuture"]] = []
        self.created = time.monotonic()


class ServeFuture:
    """Resolves to the result of a routed request."""

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        self._ref = None

    def _set_ref(self, ref):
        self._ref = ref
        self._event.set()

    def _set_value(self, value):
        self._value = value
        self._event.set()

    def _set_error(self, err):
        self._error = err
        self._event.set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("serve request timed out")
        if self._error is not None:
            raise self._error
        if self._ref is not None:
            import ray_tpu

            return ray_tpu.get(self._ref, timeout=timeout)
        return self._value


class DeploymentHandle:
    def __init__(self, deployment_name: str, replicas: List[Any],
                 *, batch_config: Optional[Dict[str, Any]] = None,
                 method: str = "__call__"):
        self.deployment_name = deployment_name
        self._replicas = list(replicas)
        self._outstanding: Dict[int, int] = {
            i: 0 for i in range(len(replicas))
        }
        self._lock = threading.Lock()
        self._method = method
        self._batch = batch_config
        self._pending: Optional[_PendingBatch] = None
        self._flusher: Optional[threading.Thread] = None

    # ---- replica selection -------------------------------------------------

    def _pick_replica(self) -> int:
        """Power of two choices on local outstanding counts."""
        with self._lock:
            n = len(self._replicas)
            if n == 1:
                return 0
            a, b = random.sample(range(n), 2)
            return a if self._outstanding[a] <= self._outstanding[b] else b

    def _track(self, idx: int, ref) -> None:
        import ray_tpu

        with self._lock:
            self._outstanding[idx] += 1

        def _done():
            try:
                ray_tpu.wait([ref], num_returns=1, timeout=None)
            finally:
                with self._lock:
                    self._outstanding[idx] -= 1

        threading.Thread(target=_done, daemon=True).start()

    # ---- request path ------------------------------------------------------

    def options(self, method: Optional[str] = None) -> "DeploymentHandle":
        h = DeploymentHandle(
            self.deployment_name, self._replicas,
            batch_config=self._batch, method=method or self._method,
        )
        h._outstanding = self._outstanding  # share queue-depth view
        h._lock = self._lock
        return h

    def remote(self, *args, **kwargs) -> ServeFuture:
        if self._batch:
            return self._remote_batched(args, kwargs)
        fut = ServeFuture()
        idx = self._pick_replica()
        replica = self._replicas[idx]
        ref = replica.handle_request.remote(self._method, args, kwargs)
        self._track(idx, ref)
        fut._set_ref(ref)
        return fut

    # ---- dynamic batching --------------------------------------------------

    def _remote_batched(self, args, kwargs) -> ServeFuture:
        fut = ServeFuture()
        flush: Optional[_PendingBatch] = None
        with self._lock:
            if self._pending is None:
                self._pending = _PendingBatch()
                self._start_flusher()
            self._pending.items.append(((args, kwargs), fut))
            if len(self._pending.items) >= self._batch["max_batch_size"]:
                flush = self._pending
                self._pending = None
        if flush is not None:
            self._flush(flush)
        return fut

    def _start_flusher(self):
        wait_s = self._batch["batch_wait_timeout_s"]

        def run():
            time.sleep(wait_s)
            with self._lock:
                flush, self._pending = self._pending, None
            if flush is not None:
                self._flush(flush)

        threading.Thread(target=run, daemon=True).start()

    def _flush(self, batch: _PendingBatch):
        import ray_tpu

        idx = self._pick_replica()
        replica = self._replicas[idx]
        payload = [item for item, _ in batch.items]
        ref = replica.handle_batch.remote(self._method, payload)
        self._track(idx, ref)

        def resolve():
            try:
                results = ray_tpu.get(ref)
                for (_, fut), value in zip(batch.items, results):
                    fut._set_value(value)
            except BaseException as e:  # noqa: BLE001
                for _, fut in batch.items:
                    fut._set_error(e)

        threading.Thread(target=resolve, daemon=True).start()

    # ---- introspection -----------------------------------------------------

    def num_replicas(self) -> int:
        return len(self._replicas)

    def queue_depths(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._outstanding)
