"""DeploymentHandle + router.

Ref analogue: serve/handle.py DeploymentHandle → _private/router.py Router
(:893) with PowerOfTwoChoicesReplicaScheduler (:290): each request samples
two replicas and picks the one with fewer outstanding requests (queue
lengths tracked by the caller; the reference queries replicas — local
tracking is the single-process simplification of the same policy).

Routing state (replica set + queue depths) lives in one shared
``_RouterState`` per handle family: ``handle.options(method=...)`` clones
share it, so a scale/rolling-update seen by any of them is seen by all.
The state keeps itself fresh via a LONG-POLL to the controller (ref
analogue: long_poll.py LongPollClient): a daemon thread blocks in
``listen_for_route_change`` and swaps the routable set the moment the
controller scales or rolls a deployment. The same thread pushes the
handle's outstanding-request total to the controller, which is the input
to queue-depth autoscaling (ref: handle-side autoscaling metrics). The
thread holds only a WEAK reference to the state — dropping every handle
ends the poller instead of leaking it.

Requests that land on a replica retired mid-flight (rolling update,
downscale, worker crash) evict that replica locally and retry against the
refreshed set — this is what makes redeploys zero-downtime and replica
crashes invisible to the caller.

Dynamic batching lives here too (ref analogue: serve/batching.py
_BatchQueue:65): requests buffer until max_batch_size or batch_wait_timeout_s
and flush as ONE replica call — on TPU this is what keeps the MXU fed with
batched forward passes instead of single-row calls.

HOT PATH CONTRACT: replicas are plain actor handles, so every
``replica.handle_request.remote(...)`` + ``ray_tpu.get(...)`` pair rides
the direct actor-call plane (runtime._DirectChannel) once the replica's
channel engages — a steady-state request is submit -> framed channel ->
inline reply, with NO node-manager round-trip. Blocking NM calls
(``force_refresh``, ``call_sync``, KV ops, ...) are allowed ONLY inside
except-handler recovery blocks (dead replica, stale route); the
``make check-obs`` lint (tools/check_metric_names.py
validate_serve_hot_path) enforces this for the request-path functions.
"""

from __future__ import annotations

import os
import random
import threading
import time
import uuid
import weakref
from typing import Any, Dict, List, Optional, Tuple

MAX_DEATH_RETRIES = 3
# Per-item deadline for streaming responses (overridable via env);
# guards proxy/consumer threads against a wedged replica generator.
STREAM_ITEM_TIMEOUT_S = float(
    os.environ.get("RAY_TPU_SERVE_STREAM_ITEM_TIMEOUT_S", "120")
)
# How long an evicted replica key stays filtered out of snapshots (covers
# the gap until the controller's health check removes it server-side).
DEAD_REPLICA_TTL_S = 10.0


def _replica_key(replica) -> Any:
    return getattr(replica, "_actor_id", None) or id(replica)


class _RouterState:
    """Shared routing view for one deployment (all handle clones)."""

    def __init__(self, deployment_name: str, replicas: List[Any],
                 controller, route_version: int):
        self.deployment_name = deployment_name
        self.lock = threading.Lock()
        self.replicas = list(replicas)
        self.route_version = route_version
        self.outstanding: Dict[Any, int] = {}
        self.controller = controller
        self.handle_id = uuid.uuid4().hex[:12]
        self.closed = False
        # Keys of replicas we observed dead, with eviction time: filtered
        # out of controller snapshots until the health checker has had time
        # to remove them server-side (prevents re-routing to a corpse).
        self.dead: Dict[Any, float] = {}
        # Raw-HTTP (ASGI) deployment? Refreshed by every routing
        # snapshot so proxies follow protocol changes across redeploys.
        self.is_asgi: bool = False
        # multiplexed model id -> replica key that last served it.
        self.model_affinity: Dict[str, Any] = {}
        if controller is not None:
            t = threading.Thread(
                target=_refresh_loop, args=(weakref.ref(self),), daemon=True
            )
            t.start()

    # ---- replica selection (power of two choices) -------------------------

    MAX_TRACKED_MODELS = 256
    # A model spills onto another replica when its current holders are
    # this many requests deeper than the cluster's least-loaded replica.
    AFFINITY_SPILL_DEPTH = 2

    def pick(self, model_id: Optional[str] = None):
        """Power of two choices on local outstanding counts; multiplexed
        requests prefer replicas that already hold their model (cache
        affinity) but SPILL onto additional replicas when those are
        saturated — affinity must not defeat load balancing (ref:
        model-multiplex-aware request routing)."""
        with self.lock:
            reps = self.replicas
            n = len(reps)
            if n == 0:
                raise RuntimeError(
                    f"deployment {self.deployment_name!r} has no replicas"
                )

            def depth(r):
                return self.outstanding.get(_replica_key(r), 0)

            def p2c(cands):
                if len(cands) == 1:
                    return cands[0]
                a, b = random.sample(range(len(cands)), 2)
                return (cands[a] if depth(cands[a]) <= depth(cands[b])
                        else cands[b])

            if not model_id:
                return p2c(reps)
            live_keys = {_replica_key(r) for r in reps}
            holders = self.model_affinity.setdefault(model_id, [])
            holders[:] = [k for k in holders if k in live_keys]
            holding = [r for r in reps if _replica_key(r) in holders]
            min_depth = min((depth(r) for r in reps), default=0)
            if holding and (
                min(depth(r) for r in holding)
                <= min_depth + self.AFFINITY_SPILL_DEPTH
            ):
                return p2c(holding)
            # Saturated (or no holder yet): spread onto a new replica.
            chosen = p2c(reps)
            k = _replica_key(chosen)
            if k not in holders:
                holders.append(k)
            if len(self.model_affinity) > self.MAX_TRACKED_MODELS:
                self.model_affinity.pop(
                    next(iter(self.model_affinity))
                )
            return chosen

    def begin(self, replica) -> None:
        with self.lock:
            k = _replica_key(replica)
            self.outstanding[k] = self.outstanding.get(k, 0) + 1

    def end(self, replica) -> None:
        with self.lock:
            k = _replica_key(replica)
            n = self.outstanding.get(k, 0) - 1
            if n <= 0:
                self.outstanding.pop(k, None)
            else:
                self.outstanding[k] = n

    def evict(self, replica) -> None:
        """Drop a replica observed dead so retries don't re-pick it."""
        k = _replica_key(replica)
        with self.lock:
            self.dead[k] = time.monotonic()
            self.replicas = [
                r for r in self.replicas if _replica_key(r) != k
            ]

    def apply_snapshot(self, snap: Dict[str, Any]) -> None:
        now = time.monotonic()
        with self.lock:
            if "is_asgi" in snap:
                self.is_asgi = bool(snap["is_asgi"])
            for k, ts in list(self.dead.items()):
                if now - ts > DEAD_REPLICA_TTL_S:
                    del self.dead[k]
            self.route_version = snap["version"]
            self.replicas = [
                r for r in snap["replicas"]
                if _replica_key(r) not in self.dead
            ]

    def force_refresh(self) -> None:
        """Synchronous route refresh after observing a dead replica."""
        import ray_tpu

        if self.controller is None:
            return
        try:
            snap = ray_tpu.get(
                self.controller.get_routing.remote(self.deployment_name),
                timeout=5.0,
            )
            self.apply_snapshot(snap)
        except Exception:
            pass


def _refresh_loop(state_ref: "weakref.ref[_RouterState]") -> None:
    """Long-poll the controller for route changes and push metrics.

    Holds only a weakref: when the last handle sharing the state is
    garbage-collected, the loop exits — no immortal poller threads.
    """
    import ray_tpu

    while True:
        state = state_ref()
        if state is None or state.closed:
            return
        try:
            with state.lock:
                outstanding = dict(state.outstanding)
                known = state.route_version
            total = sum(outstanding.values())
            controller = state.controller
            name = state.deployment_name
            handle_id = state.handle_id
            # Gauges publish from HERE (~2Hz), not the per-request
            # begin/end hot path: in-flight/queue-depth need freshness,
            # not per-event registry traffic under the router lock.
            from . import _telemetry

            _telemetry.update_router_gauges(name, handle_id, outstanding)
            controller.record_handle_metrics.remote(name, handle_id, total)
            ref = controller.listen_for_route_change.remote(name, known, 0.5)
            del state  # don't pin the state across the blocking poll
            snap = ray_tpu.get(ref, timeout=10.0)
            state = state_ref()
            if state is None or state.closed:
                return
            if snap["version"] < 0:
                # Deployment deleted: back off instead of spinning on the
                # controller's immediate not-found replies (it may come
                # back on a future serve.run with the same name).
                del state
                time.sleep(0.5)
                continue
            if snap["version"] != known:
                state.apply_snapshot(snap)
            del state
        except Exception:
            time.sleep(0.2)


def _pick_with_refresh(state: _RouterState, model_id, attempt: int):
    """Shared pick step: on an empty replica set (stale snapshot /
    just-created handle) force-refresh and signal retry by returning
    None; raises only once retries are exhausted."""
    try:
        return state.pick(model_id)
    except RuntimeError:
        if attempt < MAX_DEATH_RETRIES:
            state.force_refresh()
            time.sleep(0.05 * (attempt + 1))
            return None
        raise


def _route_with_retry(state: _RouterState, submit, deliver, deliver_error,
                      model_id: Optional[str] = None):
    """Shared request path: pick a replica (p2c + model affinity),
    submit, deliver the result; on actor death evict + refresh + retry
    (bounded)."""
    import ray_tpu
    from ray_tpu.core.exceptions import ActorDiedError, WorkerCrashedError

    last_err: Optional[BaseException] = None
    for attempt in range(MAX_DEATH_RETRIES + 1):
        try:
            replica = _pick_with_refresh(state, model_id, attempt)
        except RuntimeError as e:
            deliver_error(last_err or e)
            return
        if replica is None:
            continue  # refreshed after an empty set; try again
        state.begin(replica)
        try:
            deliver(ray_tpu.get(submit(replica)))
            return
        except (ActorDiedError, WorkerCrashedError) as e:
            # Replica retired/crashed under us (rolling update, node
            # loss): evict it locally, refresh, retry elsewhere.
            last_err = e
            state.evict(replica)
            state.force_refresh()
        except BaseException as e:  # noqa: BLE001
            deliver_error(e)
            return
        finally:
            state.end(replica)
    deliver_error(last_err)


class _PendingBatch:
    def __init__(self):
        # [(payload, future, caller trace span | None), ...]
        self.items: List[Tuple[Any, "ServeFuture", Any]] = []
        self.created = time.monotonic()


class ServeFuture:
    """Resolves to the result of a routed request."""

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        self._ref = None

    def _set_ref(self, ref):
        self._ref = ref
        self._event.set()

    def _set_value(self, value):
        self._value = value
        self._event.set()

    def _set_error(self, err):
        self._error = err
        self._event.set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("serve request timed out")
        if self._error is not None:
            raise self._error
        if self._ref is not None:
            import ray_tpu

            return ray_tpu.get(self._ref, timeout=timeout)
        return self._value


class DeploymentHandle:
    def __init__(self, deployment_name: str, replicas: List[Any],
                 *, batch_config: Optional[Dict[str, Any]] = None,
                 method: str = "__call__", controller=None,
                 route_version: int = 0, _state: Optional[_RouterState] = None,
                 multiplexed_model_id: str = ""):
        self.deployment_name = deployment_name
        self._state = _state or _RouterState(
            deployment_name, replicas, controller, route_version
        )
        self._method = method
        self._model_id = multiplexed_model_id
        self._batch = batch_config
        self._batch_lock = threading.Lock()
        self._pending: Optional[_PendingBatch] = None

    def close(self):
        self._state.closed = True

    # ---- request path ------------------------------------------------------

    def options(self, method: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None
                ) -> "DeploymentHandle":
        """Clone bound to another method / multiplexed model id; shares
        routing + queue-depth state with the parent (one long-poller per
        handle family)."""
        return DeploymentHandle(
            self.deployment_name, [],
            batch_config=self._batch, method=method or self._method,
            _state=self._state,
            multiplexed_model_id=(self._model_id
                                  if multiplexed_model_id is None
                                  else multiplexed_model_id),
        )

    def remote(self, *args, **kwargs) -> ServeFuture:
        if self._batch:
            return self._remote_batched(args, kwargs)
        from ..core.timeline import current_span

        fut = ServeFuture()
        # The submit happens on a router thread: capture the CALLER's
        # span here so the replica task parents to the proxy/driver span
        # instead of rooting an orphan trace (ref: tracing context
        # stamped onto the task spec at submit).
        threading.Thread(
            target=self._run_with_retry,
            args=(fut, self._method, args, kwargs, current_span()),
            daemon=True,
        ).start()
        return fut

    def _run_with_retry(self, fut: ServeFuture, method, args, kwargs,
                        span=None):
        from ..core.timeline import enter_span, exit_span

        model_id = self._model_id
        prev = enter_span(*span) if span else None
        try:
            _route_with_retry(
                self._state,
                lambda replica: replica.handle_request.remote(
                    method, args, kwargs, model_id, time.time()
                ),
                fut._set_value,
                fut._set_error,
                model_id=model_id or None,
            )
        finally:
            if span:
                exit_span(prev)

    def stream(self, *args, **kwargs):
        """Streaming request: yields response items as the replica
        produces them (ref analogue: handle.options(stream=True) over the
        replica's generator path + RESPONSE_STREAMING in proxy.py:1097).
        Routing (p2c, model affinity, dead-replica retry) happens on the
        first item; once a replica has started yielding, a mid-stream
        death surfaces to the caller rather than silently replaying
        side effects."""
        import ray_tpu

        model_id = self._model_id
        state = self._state
        last_err = None
        for attempt in range(MAX_DEATH_RETRIES + 1):
            try:
                replica = _pick_with_refresh(
                    state, model_id or None, attempt
                )
            except RuntimeError as e:
                raise (last_err or e)
            if replica is None:
                continue  # refreshed after an empty set; try again
            state.begin(replica)
            started = False
            try:
                gen = replica.handle_request_streaming.options(
                    num_returns="streaming"
                ).remote(self._method, args, kwargs, model_id,
                         time.time())
                # Per-item production deadline: a wedged replica
                # generator surfaces a timeout instead of pinning the
                # consumer (e.g. a proxy SSE thread) forever.
                gen.item_timeout_s = STREAM_ITEM_TIMEOUT_S
                for ref in gen:
                    value = ray_tpu.get(ref, timeout=STREAM_ITEM_TIMEOUT_S)
                    started = True
                    yield value
                return
            except Exception as e:  # noqa: BLE001
                from ray_tpu.core.exceptions import (
                    ActorDiedError,
                    WorkerCrashedError,
                )

                if isinstance(e, (ActorDiedError, WorkerCrashedError)) \
                        and not started:
                    last_err = e
                    state.evict(replica)
                    state.force_refresh()
                    continue
                raise
            finally:
                state.end(replica)
        raise last_err

    # ---- dynamic batching --------------------------------------------------

    def _remote_batched(self, args, kwargs) -> ServeFuture:
        from ..core.timeline import current_span

        fut = ServeFuture()
        flush: Optional[_PendingBatch] = None
        with self._batch_lock:
            if self._pending is None:
                self._pending = _PendingBatch()
                self._start_flusher()
            self._pending.items.append(
                ((args, kwargs), fut, current_span())
            )
            if len(self._pending.items) >= self._batch["max_batch_size"]:
                flush = self._pending
                self._pending = None
        if flush is not None:
            self._flush(flush)
        return fut

    def _start_flusher(self):
        wait_s = self._batch["batch_wait_timeout_s"]

        def run():
            time.sleep(wait_s)
            with self._batch_lock:
                flush, self._pending = self._pending, None
            if flush is not None:
                self._flush(flush)

        threading.Thread(target=run, daemon=True).start()

    def _flush(self, batch: _PendingBatch):
        from ..core.timeline import enter_span, exit_span

        payload = [item for item, _fut, _span in batch.items]
        model_id = self._model_id
        # A flush carries many callers' requests in one replica call;
        # parent the batch task to the first item's span (the others
        # still share its trace through the ingress-side spans).
        span = next((s for _, _, s in batch.items if s), None)

        def deliver(results):
            for (_, fut, _s), value in zip(batch.items, results):
                fut._set_value(value)

        def deliver_error(err):
            for _, fut, _s in batch.items:
                fut._set_error(err)

        def run():
            prev = enter_span(*span) if span else None
            try:
                _route_with_retry(
                    self._state,
                    lambda replica: replica.handle_batch.remote(
                        self._method, payload, model_id, time.time()
                    ),
                    deliver,
                    deliver_error,
                    model_id=model_id or None,
                )
            finally:
                if span:
                    exit_span(prev)

        threading.Thread(target=run, daemon=True).start()

    # ---- introspection -----------------------------------------------------

    def num_replicas(self) -> int:
        with self._state.lock:
            return len(self._state.replicas)

    def queue_depths(self) -> Dict[Any, int]:
        with self._state.lock:
            return dict(self._state.outstanding)
