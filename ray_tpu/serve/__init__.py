"""ray_tpu.serve: online serving (Ray Serve equivalent, TPU-native:
dynamic batching keeps the MXU fed; continuous-batched LLM decode to come
on top of the same router)."""

from .api import (
    asgi,  # noqa: F401
    batch,
    delete,
    deployment,
    details,
    get_deployment_handle,
    get_multiplexed_model_id,
    multiplexed,
    run,
    scale,
    shutdown,
    status,
)
from .dag_driver import DAGDriver, json_request  # noqa: F401
from .deployment import AutoscalingConfig, Deployment  # noqa: F401
from .schema import deploy_config, parse_config  # noqa: F401
from .handle import DeploymentHandle, ServeFuture  # noqa: F401
from .grpc_ingress import (  # noqa: F401
    start_grpc_ingress,
    start_per_node_grpc_proxies,
    stop_grpc_ingress,
)

from ray_tpu.util import usage_stats as _usage
_usage.record_library_usage("serve")
