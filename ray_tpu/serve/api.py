"""Serve public API.

Ref analogue: python/ray/serve/api.py — serve.run (:449), serve.batch,
serve.delete, serve.shutdown, get_deployment_handle, serve.status.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import cloudpickle

from .controller import (
    CONTROLLER_MAX_CONCURRENCY,
    CONTROLLER_NAME,
    ServeControllerActor,
)
from .deployment import AutoscalingConfig, Deployment, deployment  # noqa: F401
from .handle import DeploymentHandle
from .multiplex import (  # noqa: F401
    get_multiplexed_model_id,
    multiplexed,
)
from . import http_proxy

_controller = None
# One router-state family per deployment: redeploys and repeated
# get_deployment_handle calls share the same long-poller instead of
# leaking one thread per handle.
_states: Dict[str, Any] = {}


def _make_handle(name: str, snap: Dict[str, Any],
                 batch_config=None) -> DeploymentHandle:
    state = _states.get(name)
    if state is not None and not state.closed:
        handle = DeploymentHandle(
            name, [], batch_config=batch_config, _state=state
        )
        state.force_refresh()
        state.is_asgi = bool(snap.get("is_asgi"))
        return handle
    handle = DeploymentHandle(
        name, snap["replicas"],
        batch_config=batch_config,
        controller=_get_controller(),
        route_version=snap["version"],
    )
    _states[name] = handle._state
    handle._state.is_asgi = bool(snap.get("is_asgi"))
    return handle


def _get_controller():
    global _controller
    if _controller is not None:
        return _controller
    import ray_tpu

    try:
        _controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        opts: Dict[str, Any] = dict(
            name=CONTROLLER_NAME,
            max_concurrency=CONTROLLER_MAX_CONCURRENCY,
        )
        # Pin the controller to the creating driver's node (normally
        # the head): the control plane must survive worker-node drains
        # and rolling restarts. soft=True keeps 0-CPU attach drivers
        # (`rtpu serve deploy`) working — placement falls back to the
        # default policy when this node is infeasible.
        try:
            from ray_tpu.core.runtime_context import current_runtime
            from ray_tpu.core.scheduling_strategies import (
                NodeAffinitySchedulingStrategy,
            )

            opts["scheduling_strategy"] = NodeAffinitySchedulingStrategy(
                current_runtime().node_id.hex(), soft=True
            )
        except Exception:
            pass
        _controller = ray_tpu.remote(ServeControllerActor).options(
            **opts
        ).remote()
        # Wait until the controller is live before first use.
        ray_tpu.get(_controller.list_deployments.remote())
    return _controller


def _deploy_children(controller, target: Deployment,
                     stack: tuple = ()) -> tuple:
    """Deployment-graph build: deploy every Deployment nested in the
    target's init args (post-order) and swap it for a picklable
    BoundDeployment the replica resolves to a live handle (ref:
    serve/_private/deployment_graph_build.py — ``Parent.bind(
    Child.bind())``)."""
    from .replica import BoundDeployment

    def resolve(v):
        if isinstance(v, Deployment):
            if v.name in stack:
                raise ValueError(
                    f"deployment graph cycle through {v.name!r}"
                )
            _deploy_one(controller, v.name, v,
                        stack=stack + (v.name,))
            return BoundDeployment(v.name)
        # Deployments may ride inside containers (DAGDriver's
        # {route: graph} dict is the canonical case).
        if isinstance(v, dict):
            return {k: resolve(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return type(v)(resolve(x) for x in v)
        return v

    args = tuple(resolve(a) for a in target._init_args)
    kwargs = {k: resolve(v) for k, v in target._init_kwargs.items()}
    return args, kwargs


def _deploy_one(controller, dep_name: str, target: Deployment, *,
                stack: tuple = ()):
    import ray_tpu

    init_args, init_kwargs = _deploy_children(controller, target, stack)
    blob = cloudpickle.dumps(target.func_or_class)
    batch_config = getattr(target.func_or_class, "_serve_batch_config",
                           None)
    autoscaling = (
        dataclasses.asdict(target.autoscaling_config)
        if target.autoscaling_config is not None else None
    )
    ray_tpu.get(
        controller.deploy.remote(
            dep_name,
            blob,
            init_args,
            init_kwargs,
            target.num_replicas,
            target.ray_actor_options,
            batch_config,
            autoscaling,
            is_asgi=getattr(target.func_or_class, "_rtpu_asgi", False),
            max_concurrent_queries=target.max_concurrent_queries,
            slo=target.slo,
        )
    )


def run(target: Deployment, *, name: Optional[str] = None,
        route_prefix: Optional[str] = None, http_port: int = 0,
        _blocking: bool = False) -> DeploymentHandle:
    """Deploy (or redeploy — rolling, zero-downtime) and return a handle
    (ref: serve.run). Nested ``.bind()`` deployments in the target's
    init args deploy first and arrive in the constructor as live
    handles (the deployment-graph build). Starts the HTTP proxy lazily
    on first use; ``http_port=0`` picks a free port."""
    import ray_tpu

    controller = _get_controller()
    dep_name = name or target.name
    init_args, init_kwargs = _deploy_children(
        controller, target, (dep_name,)
    )
    blob = cloudpickle.dumps(target.func_or_class)
    batch_config = getattr(target.func_or_class, "_serve_batch_config", None)
    autoscaling = (
        dataclasses.asdict(target.autoscaling_config)
        if target.autoscaling_config is not None else None
    )
    ray_tpu.get(
        controller.deploy.remote(
            dep_name,
            blob,
            init_args,
            init_kwargs,
            target.num_replicas,
            target.ray_actor_options,
            batch_config,
            autoscaling,
            is_asgi=getattr(target.func_or_class, "_rtpu_asgi", False),
            max_concurrent_queries=target.max_concurrent_queries,
            slo=target.slo,
        )
    )
    snap = ray_tpu.get(controller.get_routing.remote(dep_name))
    handle = _make_handle(dep_name, snap, batch_config)
    port = http_proxy.start_proxy(http_port)
    http_proxy.register_route(
        route_prefix or dep_name, handle,
        asgi=getattr(target.func_or_class, "_rtpu_asgi", False),
    )
    handle.http_port = port
    return handle


def asgi(app_or_factory, *, name: str = "asgi",
         num_replicas: int = 1,
         ray_actor_options: Optional[Dict[str, Any]] = None):
    """Wrap an ASGI-3 application (or zero-arg factory) as a deployment
    (ref analogue: @serve.ingress(app) with a FastAPI/starlette app —
    here any ASGI callable, no framework dependency). Route it with
    serve.run(...); the HTTP proxy forwards raw requests under
    /<route>/... and relays responses verbatim."""
    from .asgi_ingress import ASGIReplica

    dep = deployment(ASGIReplica).options(
        name=name, num_replicas=num_replicas,
        ray_actor_options={"max_concurrency": 8,
                           **(ray_actor_options or {})},
    )
    return dep.bind(app_or_factory)


def get_deployment_handle(name: str) -> DeploymentHandle:
    import ray_tpu

    controller = _get_controller()
    snap = ray_tpu.get(controller.get_routing.remote(name))
    return _make_handle(name, snap, snap["batch_config"])


def scale(name: str, num_replicas: int) -> DeploymentHandle:
    import ray_tpu

    controller = _get_controller()
    ray_tpu.get(controller.scale.remote(name, num_replicas))
    return get_deployment_handle(name)


def status() -> Dict[str, int]:
    import ray_tpu

    return ray_tpu.get(_get_controller().list_deployments.remote())


def details() -> Dict[str, Dict[str, Any]]:
    """Per-deployment state: replica count/target, version, autoscaling
    (ref: serve.status() ApplicationDetails)."""
    import ray_tpu

    return ray_tpu.get(_get_controller().describe.remote())


def delete(name: str):
    import ray_tpu

    state = _states.pop(name, None)
    if state is not None:
        state.closed = True
    ray_tpu.get(_get_controller().delete.remote(name))


def shutdown():
    global _controller
    import ray_tpu

    http_proxy.stop_proxy()
    for state in _states.values():
        state.closed = True
    _states.clear()
    if _controller is not None:
        try:
            ray_tpu.get(_controller.shutdown.remote())
            ray_tpu.kill(_controller)
        except Exception:
            pass
        _controller = None


def batch(_func=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """@serve.batch: mark a callable for dynamic batching (ref:
    serve/batching.py:65 _BatchQueue). The wrapped callable receives a LIST
    of requests and returns a list of responses; the router coalesces
    concurrent calls (continuous batching for model decode lives on top of
    this in serve/llm.py)."""

    def wrap(fn):
        fn._serve_batch_config = {
            "max_batch_size": max_batch_size,
            "batch_wait_timeout_s": batch_wait_timeout_s,
        }
        return fn

    if _func is not None:
        return wrap(_func)
    return wrap
