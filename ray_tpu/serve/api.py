"""Serve public API.

Ref analogue: python/ray/serve/api.py — serve.run (:449), serve.batch,
serve.delete, serve.shutdown, get_deployment_handle.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import cloudpickle

from .controller import CONTROLLER_NAME, ServeControllerActor
from .deployment import AutoscalingConfig, Deployment, deployment  # noqa: F401
from .handle import DeploymentHandle
from . import http_proxy

_controller = None


def _get_controller():
    global _controller
    if _controller is not None:
        return _controller
    import ray_tpu

    try:
        _controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        _controller = ray_tpu.remote(ServeControllerActor).options(
            name=CONTROLLER_NAME
        ).remote()
        # Wait until the controller is live before first use.
        ray_tpu.get(_controller.list_deployments.remote())
    return _controller


def run(target: Deployment, *, name: Optional[str] = None,
        route_prefix: Optional[str] = None, http_port: int = 0,
        _blocking: bool = False) -> DeploymentHandle:
    """Deploy and return a handle (ref: serve.run). Starts the HTTP proxy
    lazily on first use; ``http_port=0`` picks a free port."""
    import ray_tpu

    controller = _get_controller()
    dep_name = name or target.name
    blob = cloudpickle.dumps(target.func_or_class)
    batch_config = getattr(target.func_or_class, "_serve_batch_config", None)
    replicas = ray_tpu.get(
        controller.deploy.remote(
            dep_name,
            blob,
            target._init_args,
            target._init_kwargs,
            target.num_replicas,
            target.ray_actor_options,
            batch_config,
        )
    )
    handle = DeploymentHandle(dep_name, replicas, batch_config=batch_config)
    port = http_proxy.start_proxy(http_port)
    http_proxy.register_route(route_prefix or dep_name, handle)
    handle.http_port = port
    return handle


def get_deployment_handle(name: str) -> DeploymentHandle:
    import ray_tpu

    controller = _get_controller()
    replicas = ray_tpu.get(controller.get_replicas.remote(name))
    batch_config = ray_tpu.get(controller.get_batch_config.remote(name))
    return DeploymentHandle(name, replicas, batch_config=batch_config)


def scale(name: str, num_replicas: int) -> DeploymentHandle:
    import ray_tpu

    controller = _get_controller()
    replicas = ray_tpu.get(controller.scale.remote(name, num_replicas))
    batch_config = ray_tpu.get(controller.get_batch_config.remote(name))
    return DeploymentHandle(name, replicas, batch_config=batch_config)


def status() -> Dict[str, int]:
    import ray_tpu

    return ray_tpu.get(_get_controller().list_deployments.remote())


def delete(name: str):
    import ray_tpu

    ray_tpu.get(_get_controller().delete.remote(name))


def shutdown():
    global _controller
    import ray_tpu

    http_proxy.stop_proxy()
    if _controller is not None:
        try:
            ray_tpu.get(_controller.shutdown.remote())
            ray_tpu.kill(_controller)
        except Exception:
            pass
        _controller = None


def batch(_func=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """@serve.batch: mark a callable for dynamic batching (ref:
    serve/batching.py:65 _BatchQueue). The wrapped callable receives a LIST
    of requests and returns a list of responses; the router coalesces
    concurrent calls (continuous batching for model decode lives on top of
    this in serve/llm.py)."""

    def wrap(fn):
        fn._serve_batch_config = {
            "max_batch_size": max_batch_size,
            "batch_wait_timeout_s": batch_wait_timeout_s,
        }
        return fn

    if _func is not None:
        return wrap(_func)
    return wrap
