"""Serve data-path metrics (shared singletons).

Ref analogue: serve/_private/metrics_utils.py + the request metrics the
reference's proxy/replica record (ray_serve_*_request_latency_ms etc.).
One module owns the metric objects so the proxy, gRPC ingress, handle,
and replica all record into the SAME series through the util/metrics.py
KV pipeline — ``util/prometheus.render()`` then exposes them unchanged:

- ``ray_tpu_serve_request_latency_seconds{deployment,protocol}``
  end-to-end latency observed at the ingress (HTTP or gRPC);
- ``ray_tpu_serve_requests_total{deployment,protocol,code}``
  status/error accounting at the ingress;
- ``ray_tpu_serve_ongoing_requests{deployment}`` /
  ``ray_tpu_serve_queue_depth{deployment}`` router-side in-flight total
  and deepest per-replica queue (the autoscaler's input signals);
- ``ray_tpu_serve_queue_wait_seconds{deployment}`` submit-to-execution
  wait measured at the replica;
- ``ray_tpu_serve_replica_processing_seconds{deployment,method}`` user
  code execution time, and
  ``ray_tpu_serve_replica_ongoing_requests{deployment}``.
"""

from __future__ import annotations

import time
from typing import Optional

from ..util.metrics import Counter, Gauge, Histogram

# Prometheus' default latency buckets: sub-5ms cache hits through
# multi-second LLM generations land in distinct buckets.
LATENCY_BOUNDARIES = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
]

REQUEST_LATENCY = Histogram(
    "ray_tpu_serve_request_latency_seconds",
    "End-to-end request latency observed at the serve ingress.",
    boundaries=LATENCY_BOUNDARIES,
    tag_keys=("deployment", "protocol"),
)
REQUESTS_TOTAL = Counter(
    "ray_tpu_serve_requests_total",
    "Requests finished at the serve ingress, by status code.",
    tag_keys=("deployment", "protocol", "code"),
)
# Gauges carry an IDENTITY tag (handle/replica) beside the deployment:
# gauges merge last-writer-wins across processes in get_metrics_report,
# so two replicas sharing one tag set would clobber each other — sum
# over the identity tag at query time for the deployment total.
ONGOING_REQUESTS = Gauge(
    "ray_tpu_serve_ongoing_requests",
    "Requests currently in flight from this handle to replicas "
    "(sum over `handle` for the deployment total).",
    tag_keys=("deployment", "handle"),
)
QUEUE_DEPTH = Gauge(
    "ray_tpu_serve_queue_depth",
    "Deepest per-replica outstanding-request queue seen by this "
    "handle's router.",
    tag_keys=("deployment", "handle"),
)
QUEUE_WAIT = Histogram(
    "ray_tpu_serve_queue_wait_seconds",
    "Handle-submit to replica-execution wait time (wall clocks on both "
    "hosts: cross-machine readings include NTP skew).",
    boundaries=LATENCY_BOUNDARIES,
    tag_keys=("deployment",),
)
REPLICA_PROCESSING = Histogram(
    "ray_tpu_serve_replica_processing_seconds",
    "User-code execution time on the replica.",
    boundaries=LATENCY_BOUNDARIES,
    tag_keys=("deployment", "method"),
)
REPLICA_ONGOING = Gauge(
    "ray_tpu_serve_replica_ongoing_requests",
    "Requests currently executing on one replica (sum over `replica` "
    "for the deployment total).",
    tag_keys=("deployment", "replica"),
)
# --- overload-control plane (util/overload.py mechanisms) -----------------
SHED_TOTAL = Counter(
    "ray_tpu_serve_shed_total",
    "Requests shed by overload control before execution "
    "(scope: proxy=ingress admission gate, replica=adaptive "
    "concurrency limit, router=all replica breakers open, "
    "retry_budget=retry suppressed).",
    tag_keys=("deployment", "scope"),
)
DEADLINE_EXCEEDED_TOTAL = Counter(
    "ray_tpu_serve_deadline_exceeded_total",
    "Requests whose end-to-end deadline budget expired "
    "(where: replica=refused/cancelled on the replica, "
    "caller=timed out waiting, ingress=observed at the proxy).",
    tag_keys=("deployment", "where"),
)
BREAKER_STATE = Gauge(
    "ray_tpu_serve_breaker_state",
    "Per-replica circuit-breaker state as seen by one handle's router "
    "(0=closed, 1=half-open, 2=open; identity tags `handle`+`replica` — "
    "max over `handle` for a replica's worst view).",
    tag_keys=("deployment", "handle", "replica"),
)
RETRIES_TOTAL = Counter(
    "ray_tpu_serve_retries_total",
    "Handle-level request retries spent from the retry budget.",
    tag_keys=("deployment",),
)


def observe_ingress(deployment: str, protocol: str, code,
                    started: float, ended: Optional[float] = None,
                    trace_id: Optional[str] = None) -> None:
    """One finished ingress request: latency histogram + status counter.
    ``trace_id`` lands as the bucket's OpenMetrics exemplar, so
    `rtpu metrics` → offending trace is one hop."""
    ended = time.time() if ended is None else ended
    tags = {"deployment": deployment, "protocol": protocol}
    REQUEST_LATENCY.observe(max(0.0, ended - started), tags=tags,
                            exemplar=trace_id)
    REQUESTS_TOTAL.inc(1, tags={**tags, "code": str(code)})


def update_router_gauges(deployment: str, handle_id: str,
                         outstanding) -> None:
    """Refresh in-flight/queue-depth gauges from a router's per-replica
    outstanding map. Published from the router's long-poll loop (~every
    0.5s), NOT from the per-request begin/end hot path — gauges need
    freshness, not per-event precision."""
    tags = {"deployment": deployment, "handle": handle_id}
    ONGOING_REQUESTS.set(float(sum(outstanding.values())), tags=tags)
    QUEUE_DEPTH.set(
        float(max(outstanding.values(), default=0)), tags=tags
    )


def observe_shed(deployment: str, scope: str) -> None:
    """One request shed before execution (proxy gate, replica limiter,
    all-breakers-open router, or a suppressed retry). Inside an active
    request span the decision also lands as a zero-duration span event,
    so the shed shows up in the request's recorded waterfall."""
    from ..core.timeline import span_event

    SHED_TOTAL.inc(1, tags={"deployment": deployment or "anonymous",
                            "scope": scope})
    span_event(f"shed:{scope}:{deployment or 'anonymous'}")


def observe_deadline_exceeded(deployment: str, where: str) -> None:
    from ..core.timeline import span_event

    DEADLINE_EXCEEDED_TOTAL.inc(
        1, tags={"deployment": deployment or "anonymous", "where": where}
    )
    span_event(f"deadline:{where}:{deployment or 'anonymous'}")


def observe_retry(deployment: str) -> None:
    RETRIES_TOTAL.inc(1, tags={"deployment": deployment or "anonymous"})


def record_breaker_state(deployment: str, handle_id: str, replica: str,
                         state: str) -> None:
    """Published on breaker TRANSITIONS only (open/half-open/close are
    rare), not per request. A transition observed during a traced
    request additionally lands as a span event in its waterfall."""
    from ..core.timeline import span_event
    from ..util.overload import BREAKER_STATE_VALUES

    BREAKER_STATE.set(
        BREAKER_STATE_VALUES.get(state, 0.0),
        tags={"deployment": deployment or "anonymous",
              "handle": handle_id, "replica": replica},
    )
    span_event(f"breaker:{state}:{replica}")


def observe_replica_request(deployment: str, method: str,
                            submit_ts: float, started: float,
                            ended: float) -> None:
    """Queue-wait + execution time for one replica-side request.

    Queue wait subtracts the handle host's ``time.time()`` stamp from
    the replica host's — on one machine that is the true router+actor
    queue delay; across machines it includes clock skew (clamped at 0),
    the standard trade-off of cross-process wall-clock timing."""
    dep = deployment or "anonymous"
    if submit_ts:
        QUEUE_WAIT.observe(
            max(0.0, started - submit_ts), tags={"deployment": dep}
        )
    REPLICA_PROCESSING.observe(
        max(0.0, ended - started),
        tags={"deployment": dep, "method": method},
    )
