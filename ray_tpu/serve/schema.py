"""Declarative Serve config: YAML/dict schema + apply.

Ref analogue: python/ray/serve/schema.py (ServeDeploySchema /
ServeApplicationSchema pydantic models) + the `serve deploy` CLI and
REST flow (dashboard/modules/serve/). A config names applications by
``import_path`` ("module:attr" resolving to a bound Deployment),
optionally overrides per-deployment fields, and is applied with
``serve.deploy_config`` or `rtpu serve deploy config.yaml`:

    applications:
      - name: adder
        route_prefix: /add
        import_path: my_app:graph
        deployments:
          - name: Adder
            num_replicas: 3

Unknown keys fail validation loudly (the pydantic behavior) rather
than deploying something other than what the operator wrote.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Dict, List, Optional

_APP_KEYS = {"name", "route_prefix", "import_path", "deployments",
             "args"}
_DEP_KEYS = {"name", "num_replicas", "max_concurrent_queries",
             "ray_actor_options", "autoscaling_config", "slo"}


@dataclasses.dataclass
class DeploymentOverride:
    name: str
    num_replicas: Optional[int] = None
    max_concurrent_queries: Optional[int] = None
    ray_actor_options: Optional[Dict[str, Any]] = None
    autoscaling_config: Optional[Dict[str, Any]] = None
    slo: Optional[Dict[str, Any]] = None


@dataclasses.dataclass
class ApplicationConfig:
    name: str
    import_path: str
    route_prefix: Optional[str] = None
    deployments: List[DeploymentOverride] = dataclasses.field(
        default_factory=list
    )
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)


def _validate_keys(d: Dict[str, Any], allowed: set, where: str):
    unknown = set(d) - allowed
    if unknown:
        raise ValueError(
            f"unknown key(s) {sorted(unknown)} in {where} "
            f"(allowed: {sorted(allowed)})"
        )


def parse_config(config: Any) -> List[ApplicationConfig]:
    """dict (or YAML text) -> validated ApplicationConfigs."""
    if isinstance(config, str):
        import yaml

        config = yaml.safe_load(config)
    if not isinstance(config, dict) or "applications" not in config:
        raise ValueError("serve config must be a mapping with an "
                         "'applications' list")
    _validate_keys(config, {"applications"}, "serve config")
    apps = []
    for i, app in enumerate(config["applications"]):
        _validate_keys(app, _APP_KEYS, f"applications[{i}]")
        if "import_path" not in app:
            raise ValueError(f"applications[{i}]: import_path required")
        deps = []
        for j, dep in enumerate(app.get("deployments") or []):
            _validate_keys(dep, _DEP_KEYS,
                           f"applications[{i}].deployments[{j}]")
            if "name" not in dep:
                raise ValueError(
                    f"applications[{i}].deployments[{j}]: name required"
                )
            deps.append(DeploymentOverride(**dep))
        apps.append(ApplicationConfig(
            name=app.get("name") or app["import_path"],
            import_path=app["import_path"],
            route_prefix=app.get("route_prefix"),
            deployments=deps,
            args=app.get("args") or {},
        ))
    names = [a.name for a in apps]
    if len(names) != len(set(names)):
        raise ValueError(f"duplicate application names in {names}")
    return apps


def import_attr(import_path: str):
    """"pkg.module:attr" -> the attribute (ref:
    ray._private.utils.import_attr)."""
    if ":" not in import_path:
        raise ValueError(
            f"import_path {import_path!r} must look like "
            f"'module.sub:attr'"
        )
    module_path, attr = import_path.split(":", 1)
    module = importlib.import_module(module_path)
    target = module
    for part in attr.split("."):
        target = getattr(target, part)
    return target


def _apply_overrides(dep, overrides: List[DeploymentOverride]):
    """Returns dep with matching override fields applied (the nested
    graph is walked through init args)."""
    from .deployment import AutoscalingConfig, Deployment

    by_name = {o.name: o for o in overrides}

    def rebuild(d: Deployment) -> Deployment:
        o = by_name.get(d.name)
        out = d.options() if o is None else d.options(**{
            k: v for k, v in {
                "num_replicas": o.num_replicas,
                "max_concurrent_queries": o.max_concurrent_queries,
                "ray_actor_options": o.ray_actor_options,
                "autoscaling_config": (
                    AutoscalingConfig(**o.autoscaling_config)
                    if o.autoscaling_config is not None else None
                ),
                "slo": o.slo,
            }.items() if v is not None
        })
        out._init_args = tuple(
            rebuild(a) if isinstance(a, Deployment) else a
            for a in d._init_args
        )
        out._init_kwargs = {
            k: rebuild(v) if isinstance(v, Deployment) else v
            for k, v in d._init_kwargs.items()
        }
        return out

    return rebuild(dep)


def deploy_config(config: Any, *, http_port: int = 0) -> Dict[str, Any]:
    """Apply a declarative config: import each application's target,
    apply overrides, serve.run it under its route_prefix. Returns
    {app_name: route}."""
    from . import api
    from .deployment import Deployment

    routes: Dict[str, Any] = {}
    for app in parse_config(config):
        target = import_attr(app.import_path)
        if callable(target) and not isinstance(target, Deployment):
            target = target(**app.args)   # builder function
        if not isinstance(target, Deployment):
            raise TypeError(
                f"{app.import_path} resolved to "
                f"{type(target).__name__}, expected a Deployment"
            )
        target = _apply_overrides(target, app.deployments)
        handle = api.run(
            target, name=target.name,
            route_prefix=app.route_prefix or app.name,
            http_port=http_port,
        )
        routes[app.name] = {
            "route_prefix": app.route_prefix or app.name,
            "http_port": handle.http_port,
            "deployment": target.name,
        }
    return routes
