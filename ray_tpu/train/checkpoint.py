"""Checkpoints: directory-based, orbax-backed, crash-safe.

Ref analogue: python/ray/train/_checkpoint.py Checkpoint (:55 — a directory
plus a filesystem abstraction) and _internal/storage.py StorageContext. On
TPU the pytree payloads go through orbax (tensorstore) so sharded arrays
save/restore correctly across meshes.

Commit protocol (the crash-safety contract every consumer relies on):

1. ``from_pytree`` writes EVERYTHING — orbax payload, ``metadata.json``,
   and a ``COMMITTED`` manifest (step, world size, per-file sizes) —
   into a ``.tmp-`` sibling directory, fsyncs it, then atomically
   renames it into place and fsyncs the parent. A crash at ANY point
   leaves either the previous state or a ``.tmp-`` orphan that no
   restore path will ever pick up; it can never poison "latest".
2. ``is_committed`` verifies the manifest and every listed file's size,
   so a torn directory (partial copy, truncated tensorstore file) reads
   as uncommitted — corrupt and uncommitted are the same thing to
   restore.
3. ``latest_committed(storage_dir)`` scans newest-first and falls back
   past corrupt/uncommitted entries; :class:`CheckpointManager` prune
   never deletes the only committed entry and never deletes a
   checkpoint until a NEWER one has committed (a concurrently-resuming
   worker may still be restoring from it).

The ``checkpoint_io`` chaos point (util/faults.py) fires at the top of
both save and restore so the whole protocol is testable under injected
I/O failures.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ..util import faults

# Commit marker + manifest, written last inside the staging directory so
# the atomic rename is the single commit point.
COMMIT_MANIFEST = "COMMITTED"
_TMP_PREFIX = ".tmp-"


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without O_RDONLY dir opens — best effort
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _walk_files(root: str) -> List[Tuple[str, int]]:
    """(relpath, size) for every regular file under ``root``."""
    out: List[Tuple[str, int]] = []
    for dirpath, _dirs, files in os.walk(root):
        for fname in files:
            full = os.path.join(dirpath, fname)
            out.append((
                os.path.relpath(full, root).replace(os.sep, "/"),
                os.path.getsize(full),
            ))
    return sorted(out)


class Checkpoint:
    """An immutable directory of checkpoint data."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_pytree(cls, tree: Any, path: str, *,
                    metadata: Optional[Dict] = None,
                    step: Optional[int] = None,
                    world_size: Optional[int] = None) -> "Checkpoint":
        """Save a jax pytree (params/opt state/step...) with orbax,
        atomically: payload + metadata + COMMITTED manifest are staged
        in a ``.tmp-`` sibling and renamed into place in one step. A
        crash mid-save leaves no visible (and no half-committed)
        checkpoint at ``path``."""
        import orbax.checkpoint as ocp

        from ..core.timeline import record_span

        save_t0 = time.time()
        path = os.path.abspath(path)
        parent = os.path.dirname(path) or "."
        os.makedirs(parent, exist_ok=True)
        # Chaos: an injected checkpoint_io failure surfaces exactly like
        # a real storage fault at the start of the write window — the
        # staging dir is cleaned up and nothing commits.
        delay = faults.fire(faults.CHECKPOINT_IO, op="save", path=path)
        if delay:
            time.sleep(delay)
        tmp = os.path.join(
            parent,
            f"{_TMP_PREFIX}{os.path.basename(path)}-{uuid.uuid4().hex[:8]}",
        )
        try:
            ckptr = ocp.StandardCheckpointer()
            ckptr.save(os.path.join(tmp, "pytree"), tree, force=True)
            ckptr.wait_until_finished()
            # Metadata rides INSIDE the atomic commit: there is no
            # window where the payload exists but metadata() would
            # silently return {} (the pre-commit-protocol ordering bug).
            if metadata:
                with open(os.path.join(tmp, "metadata.json"), "w") as f:
                    json.dump(metadata, f)
            manifest = {
                "step": int(step) if step is not None else None,
                "world_size": int(world_size) if world_size else None,
                "ts": time.time(),
                "files": {rel: size for rel, size in _walk_files(tmp)},
            }
            with open(os.path.join(tmp, COMMIT_MANIFEST), "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            for rel, _size in _walk_files(tmp):
                if rel != COMMIT_MANIFEST:
                    _fsync_file(os.path.join(tmp, rel))
            for dirpath, _dirs, _files in os.walk(tmp):
                _fsync_dir(dirpath)
            # Re-saving over an existing checkpoint keeps the old one
            # until the replacement is fully staged. The aside name
            # does NOT carry the .tmp- prefix on purpose: the moved
            # directory is still a complete COMMITTED checkpoint, and a
            # crash between the two renames must leave it DISCOVERABLE
            # by latest_committed (same manifest step, slightly odd
            # name) — never lost. Success deletes it below.
            replaced = None
            if os.path.exists(path):
                replaced = (
                    f"{path}.replaced-{uuid.uuid4().hex[:8]}"
                )
                os.rename(path, replaced)
            try:
                os.rename(tmp, path)  # THE commit point
            except BaseException:
                if replaced:
                    os.rename(replaced, path)  # restore the original
                raise
            _fsync_dir(parent)
            if replaced:
                shutil.rmtree(replaced, ignore_errors=True)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        # Checkpoint-save span in the rank's waterfall (the gang's
        # restart analysis reads save/restore windows beside the steps).
        try:
            record_span(f"ckpt_save:{os.path.basename(path)}",
                        save_t0, time.time())
        # A lost span only blanks telemetry, never a commit.
        except Exception:  # rtlint: disable=swallowed-failure
            pass
        return cls(path)

    def as_pytree(self, target: Optional[Any] = None) -> Any:
        """Restore the pytree; ``target`` provides structure/shardings."""
        import orbax.checkpoint as ocp

        from ..core.timeline import record_span

        t0 = time.time()
        delay = faults.fire(faults.CHECKPOINT_IO, op="restore",
                            path=self.path)
        if delay:
            time.sleep(delay)
        ckptr = ocp.StandardCheckpointer()
        item = os.path.join(self.path, "pytree")
        try:
            if target is not None:
                return ckptr.restore(item, target)
            return ckptr.restore(item)
        finally:
            try:
                record_span(
                    f"ckpt_restore:{os.path.basename(self.path)}",
                    t0, time.time())
            # A lost span only blanks telemetry, never a restore.
            except Exception:  # rtlint: disable=swallowed-failure
                pass

    def metadata(self) -> Dict:
        p = os.path.join(self.path, "metadata.json")
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def manifest(self) -> Dict:
        """The COMMITTED manifest ({} when uncommitted/unreadable)."""
        p = os.path.join(self.path, COMMIT_MANIFEST)
        try:
            with open(p) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def is_committed(self) -> bool:
        """True when the COMMITTED manifest exists AND every file it
        lists is present with the recorded size — a torn directory
        (truncated tensorstore file, partial copy) reads as
        uncommitted, so restore falls back past it."""
        manifest = self.manifest()
        if not manifest:
            return False
        for rel, size in (manifest.get("files") or {}).items():
            if rel == COMMIT_MANIFEST:
                continue
            full = os.path.join(self.path, rel)
            try:
                if os.path.getsize(full) != int(size):
                    return False
            except OSError:
                return False
        return True

    @property
    def step(self) -> Optional[int]:
        return self.manifest().get("step")

    def to_directory(self, dest: str) -> str:
        shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def __repr__(self):
        return f"Checkpoint({self.path})"


def latest_committed(storage_dir: str) -> Optional[Checkpoint]:
    """Newest COMMITTED checkpoint under ``storage_dir`` (by manifest
    step, then mtime), scanning newest-first and falling back past
    corrupt, uncommitted, and ``.tmp-`` staging directories. The
    restart path's source of truth: a crash can strand torn state on
    disk, but never make this return it."""
    try:
        names = os.listdir(storage_dir)
    except OSError:
        return None
    candidates = []
    for name in names:
        if name.startswith(_TMP_PREFIX):
            continue  # an interrupted save's staging orphan
        path = os.path.join(storage_dir, name)
        if not os.path.isdir(path):
            continue
        ckpt = Checkpoint(path)
        manifest = ckpt.manifest()
        step = manifest.get("step")
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            mtime = 0.0
        candidates.append((
            step if isinstance(step, int) else -1, mtime, ckpt
        ))
    candidates.sort(key=lambda c: (c[0], c[1]), reverse=True)
    for _step, _mtime, ckpt in candidates:
        if ckpt.is_committed():
            return ckpt
    return None


class CheckpointManager:
    """Tracks reported checkpoints, retains top-k by score (ref:
    train/_internal/checkpoint_manager.py) under the commit-protocol
    safety rules: the only committed entry is never pruned, and no
    entry is deleted until a NEWER checkpoint has committed."""

    def __init__(self, storage_dir: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.storage_dir = storage_dir
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._entries = []  # (score, step, Checkpoint)
        os.makedirs(storage_dir, exist_ok=True)

    def register(self, checkpoint: Checkpoint, metrics: Dict,
                 step: int) -> Checkpoint:
        score = None
        if self.score_attribute and self.score_attribute in metrics:
            score = float(metrics[self.score_attribute])
        self._entries.append((score, step, checkpoint))
        self._prune()
        return checkpoint

    def _prune(self):
        if self.num_to_keep is None or len(self._entries) <= self.num_to_keep:
            return
        committed = [e for e in self._entries if e[2].is_committed()]
        committed_steps = [e[1] for e in committed]
        newest_committed = (max(committed, key=lambda e: e[1])
                            if committed else None)
        best = None
        scored = [e for e in self._entries if e[0] is not None]
        if scored:
            pick = max if self.score_order == "max" else min
            best = pick(scored, key=lambda e: e[0])

        def deletable(entry) -> bool:
            # Safety over budget, in order: (1) the newest committed
            # entry is what a concurrently-resuming worker restores
            # from — deletable only once an even newer checkpoint has
            # COMMITTED (an uncommitted "newer" save never justifies
            # deleting the committed fallback beneath it); (2) the
            # best-scored entry is the Result's checkpoint; (3) any
            # other entry needs a newer committed successor before its
            # directory can go. num_to_keep may be overshot while these
            # protections hold — the next commit rebalances.
            if entry is newest_committed or entry is best:
                return False
            return any(cs > entry[1] for cs in committed_steps)

        def sort_key(e):
            score, step, _ = e
            if score is None:
                return (step, step)  # fall back to recency
            ordered = score if self.score_order == "max" else -score
            # Ties (and score-free runs) evict oldest-step first.
            return (ordered, step)

        evictable = [e for e in sorted(self._entries, key=sort_key)
                     if deletable(e)]
        for entry in evictable:
            if len(self._entries) <= self.num_to_keep:
                break
            self._entries.remove(entry)
            shutil.rmtree(entry[2].path, ignore_errors=True)

    @property
    def latest(self) -> Optional[Checkpoint]:
        """Newest usable checkpoint: committed entries win; an
        uncommitted newest (its save failed or is still in flight)
        never shadows the committed one beneath it."""
        return self.latest_committed or self._newest_any

    @property
    def _newest_any(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        return max(self._entries, key=lambda e: e[1])[2]

    @property
    def latest_committed(self) -> Optional[Checkpoint]:
        committed = [e for e in self._entries if e[2].is_committed()]
        if not committed:
            return None
        return max(committed, key=lambda e: e[1])[2]

    @property
    def best(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        scored = [e for e in self._entries if e[0] is not None]
        if not scored:
            return self.latest
        pick = max if self.score_order == "max" else min
        return pick(scored, key=lambda e: e[0])[2]


def default_storage_path(name: Optional[str]) -> str:
    base = os.environ.get(
        "RAY_TPU_STORAGE_PATH",
        os.path.join(tempfile.gettempdir(), "ray_tpu_results"),
    )
    run = name or f"run-{int(time.time())}"
    return os.path.join(base, run)
