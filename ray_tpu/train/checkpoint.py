"""Checkpoints: directory-based with orbax-backed pytree save/restore.

Ref analogue: python/ray/train/_checkpoint.py Checkpoint (:55 — a directory
plus a filesystem abstraction) and _internal/storage.py StorageContext. On
TPU the pytree payloads go through orbax (tensorstore) so sharded arrays
save/restore correctly across meshes.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Any, Dict, Optional


class Checkpoint:
    """An immutable directory of checkpoint data."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_pytree(cls, tree: Any, path: str, *,
                    metadata: Optional[Dict] = None) -> "Checkpoint":
        """Save a jax pytree (params/opt state/step...) with orbax."""
        import orbax.checkpoint as ocp

        path = os.path.abspath(path)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(path, "pytree"), tree, force=True)
        ckptr.wait_until_finished()
        if metadata:
            with open(os.path.join(path, "metadata.json"), "w") as f:
                json.dump(metadata, f)
        return cls(path)

    def as_pytree(self, target: Optional[Any] = None) -> Any:
        """Restore the pytree; ``target`` provides structure/shardings."""
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        item = os.path.join(self.path, "pytree")
        if target is not None:
            return ckptr.restore(item, target)
        return ckptr.restore(item)

    def metadata(self) -> Dict:
        p = os.path.join(self.path, "metadata.json")
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def to_directory(self, dest: str) -> str:
        shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def __repr__(self):
        return f"Checkpoint({self.path})"


class CheckpointManager:
    """Tracks reported checkpoints, retains top-k by score (ref:
    train/_internal/checkpoint_manager.py)."""

    def __init__(self, storage_dir: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.storage_dir = storage_dir
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._entries = []  # (score, step, Checkpoint)
        os.makedirs(storage_dir, exist_ok=True)

    def register(self, checkpoint: Checkpoint, metrics: Dict,
                 step: int) -> Checkpoint:
        score = None
        if self.score_attribute and self.score_attribute in metrics:
            score = float(metrics[self.score_attribute])
        self._entries.append((score, step, checkpoint))
        self._prune()
        return checkpoint

    def _prune(self):
        if self.num_to_keep is None or len(self._entries) <= self.num_to_keep:
            return
        def sort_key(e):
            score, step, _ = e
            if score is None:
                return step  # fall back to recency
            return score if self.score_order == "max" else -score

        self._entries.sort(key=sort_key)
        while len(self._entries) > self.num_to_keep:
            _, _, ckpt = self._entries.pop(0)
            shutil.rmtree(ckpt.path, ignore_errors=True)

    @property
    def latest(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        return max(self._entries, key=lambda e: e[1])[2]

    @property
    def best(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        scored = [e for e in self._entries if e[0] is not None]
        if not scored:
            return self.latest
        pick = max if self.score_order == "max" else min
        return pick(scored, key=lambda e: e[0])[2]


def default_storage_path(name: Optional[str]) -> str:
    base = os.environ.get(
        "RAY_TPU_STORAGE_PATH",
        os.path.join(tempfile.gettempdir(), "ray_tpu_results"),
    )
    run = name or f"run-{int(time.time())}"
    return os.path.join(base, run)
