"""Gradient-boosted tree trainers.

Ref analogue: python/ray/train/xgboost/xgboost_trainer.py +
lightgbm_trainer.py (the AIR GBDT family). The boosting engine here is
sklearn's histogram GBDT (xgboost isn't in the TPU image); the framework
contract is identical: datasets flow in as ray_tpu Datasets, training
runs in a remote worker, the fitted model ships back as a checkpoint
usable by ``GBDTPredictor`` / ``BatchPredictor``.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional

from .checkpoint import Checkpoint, default_storage_path
from .config import Result, RunConfig, ScalingConfig

MODEL_FILE = "model.pkl"


def _fit_gbdt(columns: Dict[str, Any], label_column: str, params: Dict,
              objective: str, storage_dir: str) -> Dict[str, Any]:
    """Runs in a remote worker: assemble the matrix, fit, checkpoint."""
    import numpy as np

    y = np.asarray(columns.pop(label_column))
    feature_names = sorted(columns)
    X = np.column_stack([np.asarray(columns[c]) for c in feature_names])
    if objective == "classification":
        from sklearn.ensemble import HistGradientBoostingClassifier

        model = HistGradientBoostingClassifier(**params)
    else:
        from sklearn.ensemble import HistGradientBoostingRegressor

        model = HistGradientBoostingRegressor(**params)
    model.fit(X, y)
    score = float(model.score(X, y))
    os.makedirs(storage_dir, exist_ok=True)
    ckpt_dir = os.path.join(storage_dir, "gbdt_checkpoint")
    os.makedirs(ckpt_dir, exist_ok=True)
    with open(os.path.join(ckpt_dir, MODEL_FILE), "wb") as f:
        pickle.dump(
            {"model": model, "features": feature_names,
             "label": label_column}, f
        )
    return {"train_score": score, "checkpoint_dir": ckpt_dir,
            "num_rows": int(len(y))}


class GBDTTrainer:
    """Fit a boosted-tree model on a Dataset (ref: XGBoostTrainer API)."""

    def __init__(self, *, datasets: Dict[str, Any], label_column: str,
                 params: Optional[Dict[str, Any]] = None,
                 objective: str = "classification",
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None):
        if "train" not in datasets:
            raise ValueError("datasets must include a 'train' Dataset")
        self._datasets = datasets
        self.label_column = label_column
        self.params = dict(params or {})
        self.objective = objective
        self.run_config = run_config or RunConfig()

    def fit(self) -> Result:
        import ray_tpu

        storage = self.run_config.storage_path or default_storage_path(
            self.run_config.name
        )
        columns = self._datasets["train"].to_numpy()
        fit_remote = ray_tpu.remote(_fit_gbdt)
        metrics = ray_tpu.get(
            fit_remote.remote(
                columns, self.label_column, self.params, self.objective,
                storage,
            )
        )
        ckpt = Checkpoint(metrics.pop("checkpoint_dir"))
        return Result(metrics=metrics, checkpoint=ckpt,
                      metrics_history=[metrics])
