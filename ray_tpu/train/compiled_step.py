"""One fused, compiled training step that survives real model depth.

The bench's previous hot path composed the step in the train loop (a
``jax.value_and_grad`` + optax update jitted ad hoc per caller); the
full-depth scan schedule OOM'd at 16.4 GB with 43-46% allocator
fragmentation (PERF_r05 ab_matrix) because the stacked ``[L, ...]`` scan
residuals plus host-staged init buffers shattered the HBM arena. This
module is the single train-step authority (ROADMAP item 3):

- **One XLA program** per step: forward (chunked-scan schedule,
  models/llama.py), backward, optimizer update and — under a mesh — the
  GSPMD-inserted grad all-reduces, compiled together via pjit (jax.jit
  with shardings) so XLA schedules collectives against compute.
- **In-place buffer donation**: params + optimizer state donate their
  buffers into the step (``donate_argnums=(0, 1)``) — the update aliases
  the old arena instead of doubling it.
- **Donation-friendly init**: :meth:`init` materializes params AND
  optimizer state in one compiled program, sharding-constrained in-graph
  (parallel/sharding.py), so every persistent buffer is allocated by the
  same allocator pass with its final layout — no host-staged arrays
  fragmenting the arena before training starts.
- **Compile + HBM telemetry**: jits through
  ``util/device_metrics.instrumented_jit(sample_memory=True)`` (the
  serve/llm.py wiring), so ``rtpu metrics`` shows train compile cache
  hits and the per-device peak/fragmentation gauges.

Ref analogue: the reference delegates all of this to the user's torch
loop; a TPU-native framework owns the compiled step the way it owns the
serving decode loop.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ..models.llama import (
    LlamaConfig,
    causal_lm_loss,
    init_params,
    num_params,
    param_logical_axes,
    scan_chunks,
)
from ..parallel.sharding import (
    DEFAULT_RULES,
    constrain_pytree,
    named_sharding,
    tree_shardings,
)
from ..util import device_metrics


def _constrain_opt_state(tx, opt_state, mesh, axes_tree, rules):
    """Pin the optimizer state's param-shaped leaves (adam m/v) to the
    same shardings as their parameters; scalars (step count) pass
    through untouched."""
    shardings = tree_shardings(mesh, axes_tree, rules)
    return optax.tree_map_params(
        tx,
        lambda s, sh: jax.lax.with_sharding_constraint(s, sh),
        opt_state,
        shardings,
        transform_non_params=lambda s: s,
    )


class CompiledTrainStep:
    """Fused train step for the Llama family: loss + grad + optimizer +
    collectives in one donated XLA program.

    >>> step = CompiledTrainStep(cfg, mesh=mesh)
    >>> params, opt_state = step.init(jax.random.PRNGKey(0))
    >>> params, opt_state, loss = step(params, opt_state, tokens)

    ``mesh=None`` compiles for the local device set with no explicit
    shardings (single chip / CPU tests); a mesh routes params through
    the logical-axis rules (parallel/sharding.py) and batches over
    dp+fsdp.
    """

    def __init__(
        self,
        cfg: LlamaConfig,
        *,
        mesh=None,
        optimizer: Optional[optax.GradientTransformation] = None,
        learning_rate: float = 1e-3,
        rules=DEFAULT_RULES,
        aux_weight: float = 0.01,
        donate: bool = True,
    ):
        scan_chunks(cfg)  # validate the chunk schedule up front
        self.cfg = cfg
        self.mesh = mesh
        self.rules = rules
        self.tx = optimizer or optax.adamw(learning_rate)
        self.donate = donate
        self._axes = param_logical_axes(cfg)

        def train_step(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(
                lambda p: causal_lm_loss(
                    p, tokens, cfg, mesh, aux_weight=aux_weight
                )
            )(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        jit_kwargs: Dict[str, Any] = {}
        if donate:
            jit_kwargs["donate_argnums"] = (0, 1)
        self._step = device_metrics.instrumented_jit(
            train_step, sample_memory=True, **jit_kwargs
        )

        def _init(key):
            params = init_params(cfg, key)
            opt_state = self.tx.init(params)
            if mesh is not None:
                params = constrain_pytree(params, mesh, self._axes, rules)
                opt_state = _constrain_opt_state(
                    self.tx, opt_state, mesh, self._axes, rules
                )
            return params, opt_state

        self._init = jax.jit(_init)

    # ------------------------------------------------------------ state

    def init(self, key: jax.Array) -> Tuple[Any, Any]:
        """Materialize (params, opt_state) in ONE compiled program with
        their final shardings — the donation-friendly arena layout (every
        persistent buffer placed by one allocator pass, nothing staged
        through host arrays).

        Traced under ``jax.threefry_partitionable``: the legacy threefry
        lowering generates DIFFERENT values when XLA partitions the RNG
        op to satisfy a sharded output, so the same seed would produce
        different params on different meshes (and differ from the
        single-device init). The partitionable lowering is
        sharding-invariant by construction — one seed, one model,
        regardless of mesh shape."""
        with jax.threefry_partitionable(True):
            return self._init(key)

    def token_sharding(self):
        """Sharding for the [B, S] token batch under the mesh (batch
        over dp+fsdp), or None off-mesh — hand this to the input
        pipeline so device_put lands batches pre-sharded."""
        if self.mesh is None:
            return None
        return named_sharding(self.mesh, ("batch", "seq"), self.rules)

    # ------------------------------------------------------------- step

    def __call__(self, params, opt_state, tokens):
        """One fused step: returns (params, opt_state, loss). The input
        params/opt_state buffers are DONATED — dead after the call.
        Each step records a ``train_step`` span under the rank's active
        trace (no-op outside one), so a gang's waterfall shows step
        cadence beside checkpoint save/restore windows."""
        import time as _time

        from ..core.timeline import record_span

        t0 = _time.time()
        try:
            return self._step(params, opt_state, tokens)
        finally:
            try:
                record_span("train_step", t0, _time.time())
            # A lost span only blanks telemetry, never a step.
            except Exception:  # rtlint: disable=swallowed-failure
                pass

    # ------------------------------------------------------ diagnostics

    def num_params(self, params) -> int:
        return num_params(params)

    def compile_stats(self) -> Dict[str, Any]:
        """Executable-cache telemetry for this step (also published as
        ray_tpu_device_jit_* series through the KV metrics pipeline)."""
        jitted = getattr(self._step, "__wrapped_jit__", None)
        cache_size = getattr(jitted, "_cache_size", None)
        out: Dict[str, Any] = {"fn": "train_step"}
        if cache_size is not None:
            try:
                out["executables"] = int(cache_size())
            except Exception:
                out["executables"] = None
        return out

    def memory_snapshot(self) -> Dict[str, Any]:
        """The HBM/allocator probe for the step's device: live + peak +
        reserved bytes and the fragmentation ratio (bench ab_matrix rows
        record exactly this dict)."""
        return device_metrics.hbm_snapshot()
