"""Predictors + distributed batch inference.

Ref analogue: python/ray/train/predictor.py Predictor +
batch_predictor.py BatchPredictor (retired upstream into
Dataset.map_batches — both surfaces exist here). A Predictor restores a
model from a Checkpoint and scores numpy batches; BatchPredictor fans it
out over a Dataset through the actor-pool map operator, so model loading
happens once per pool member, not per block.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Dict, Optional

import numpy as np

from .checkpoint import Checkpoint


class Predictor:
    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kw) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        raise NotImplementedError


class GBDTPredictor(Predictor):
    """Scores with a GBDTTrainer checkpoint (ref: XGBoostPredictor)."""

    def __init__(self, model, features, label):
        self._model = model
        self._features = features
        self._label = label

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        **kw) -> "GBDTPredictor":
        from .gbdt import MODEL_FILE

        with open(os.path.join(checkpoint.path, MODEL_FILE), "rb") as f:
            payload = pickle.load(f)
        return cls(payload["model"], payload["features"],
                   payload["label"])

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        X = np.column_stack(
            [np.asarray(batch[c]) for c in self._features]
        )
        return {"predictions": self._model.predict(X)}


class JaxPredictor(Predictor):
    """Scores with a jax apply fn + params pytree restored from an orbax
    checkpoint (ref: TorchPredictor with the framework swapped)."""

    def __init__(self, params, apply_fn: Callable,
                 input_column: str = "x"):
        self._params = params
        self._apply = apply_fn
        self._col = input_column

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        apply_fn: Callable, example_tree: Any = None,
                        input_column: str = "x") -> "JaxPredictor":
        params = checkpoint.as_pytree(example_tree)
        return cls(params, apply_fn, input_column)

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        import jax.numpy as jnp

        out = self._apply(self._params, jnp.asarray(batch[self._col]))
        return {"predictions": np.asarray(out)}


class _PredictorWorker:
    """Actor-pool member: one restored predictor per process."""

    def __init__(self, predictor_cls_blob: bytes, checkpoint_path: str,
                 from_ckpt_kwargs: Dict[str, Any]):
        import cloudpickle

        predictor_cls = cloudpickle.loads(predictor_cls_blob)
        self._predictor = predictor_cls.from_checkpoint(
            Checkpoint(checkpoint_path), **from_ckpt_kwargs
        )

    def __call__(self, batch):
        return self._predictor.predict(batch)


class BatchPredictor:
    """Distributed inference over a Dataset (ref: batch_predictor.py)."""

    def __init__(self, checkpoint: Checkpoint, predictor_cls,
                 **from_ckpt_kwargs):
        self._checkpoint = checkpoint
        self._predictor_cls = predictor_cls
        self._kwargs = from_ckpt_kwargs

    def predict(self, dataset, *, concurrency: int = 2,
                batch_size: Optional[int] = None):
        import cloudpickle

        blob = cloudpickle.dumps(self._predictor_cls)
        return dataset.map_batches(
            _PredictorWorker,
            concurrency=concurrency,
            batch_size=batch_size,
            fn_constructor_args=(blob, self._checkpoint.path,
                                 self._kwargs),
        )
