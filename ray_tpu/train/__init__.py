"""ray_tpu.train: distributed training (Ray Train equivalent, TPU-native).

Public surface mirrors ray.train (SURVEY.md §2.3): JaxTrainer ~ TorchTrainer,
session functions report/get_checkpoint/get_dataset_shard/get_world_rank,
Checkpoint, ScalingConfig/RunConfig/CheckpointConfig/FailureConfig, Result.
"""

from .checkpoint import (  # noqa: F401
    Checkpoint,
    CheckpointManager,
    latest_committed,
)
from .gbdt import GBDTTrainer  # noqa: F401
from .predictor import (  # noqa: F401
    BatchPredictor,
    GBDTPredictor,
    JaxPredictor,
    Predictor,
)
from .config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from .session import (  # noqa: F401
    PreemptionSignal,
    get_checkpoint,
    get_dataset_shard,
    get_session,
    get_world_rank,
    get_world_size,
    preemption_requested,
    report,
)
from .trainer import JaxTrainer, TrainWorkerGroupError  # noqa: F401
from .torch import TorchTrainer  # noqa: F401


def __getattr__(name):
    # CompiledTrainStep lives behind a lazy hook: compiled_step.py
    # imports jax/optax at module scope, and `import ray_tpu.train` must
    # stay backend-free (session plumbing runs in every train worker,
    # including cpu-only ones that never touch the accelerator).
    if name == "CompiledTrainStep":
        from .compiled_step import CompiledTrainStep

        return CompiledTrainStep
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

from ray_tpu.util import usage_stats as _usage
_usage.record_library_usage("train")
