"""TorchTrainer: data-parallel torch training on the cluster.

Ref analogue: python/ray/train/torch/ — TorchTrainer
(torch_trainer.py:14) over the gloo/nccl process group set up in
TorchConfig (config.py:62 _setup_torch_process_group) plus the
train-loop utilities (train_loop_utils.py: prepare_model:74 wraps DDP,
prepare_data_loader:116 adds a DistributedSampler). On this framework
torch runs CPU-side (the accelerator path is jax — JaxTrainer); the
trainer exists so torch-based reference workloads port unchanged:
same WorkerGroup machinery, same session.report/checkpoint flow, with
the rendezvous swapped from jax.distributed to a torch gloo group.
"""

from __future__ import annotations

from .trainer import JaxTrainer


class TorchTrainer(JaxTrainer):
    """Same fit/failure/checkpoint machinery as JaxTrainer; workers
    rendezvous into a torch.distributed gloo group instead of
    jax.distributed."""

    _collective_backend = "torch"


def get_device():
    """The device this worker should use (ref:
    train/torch/train_loop_utils.py get_device) — CPU here; TPU work
    goes through jax."""
    import torch

    return torch.device("cpu")


def prepare_model(model):
    """Wrap the model for distributed training (ref: prepare_model,
    train_loop_utils.py:74,330 — DDP wrap keyed on world size)."""
    import torch.distributed as dist

    if dist.is_available() and dist.is_initialized() and \
            dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model


def prepare_data_loader(data_loader):
    """Shard a DataLoader across workers with a DistributedSampler
    (ref: prepare_data_loader, train_loop_utils.py:116)."""
    import torch.distributed as dist

    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return data_loader
    from torch.utils.data import DataLoader
    from torch.utils.data.distributed import DistributedSampler

    if isinstance(data_loader.sampler, DistributedSampler):
        return data_loader
    return DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=DistributedSampler(data_loader.dataset),
        num_workers=data_loader.num_workers,
        collate_fn=data_loader.collate_fn,
        pin_memory=data_loader.pin_memory,
        drop_last=data_loader.drop_last,
    )
