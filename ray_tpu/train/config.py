"""Train/run configuration dataclasses.

Ref analogue: python/ray/air/config.py — ScalingConfig, RunConfig,
CheckpointConfig, FailureConfig (SURVEY.md §2.3 AIR common).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class ScalingConfig:
    """How many workers and what each needs (ref: air/config.py
    ScalingConfig). ``use_tpu`` workers are scheduled into accelerator-
    enabled worker processes (core worker_type="tpu")."""

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        if self.use_tpu:
            res.setdefault("TPU", 1)
        else:
            res.setdefault("CPU", 1)
        return res


@dataclasses.dataclass
class CheckpointConfig:
    """Ref: air/config.py CheckpointConfig — top-k retention."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclasses.dataclass
class FailureConfig:
    """Ref: air/config.py FailureConfig — whole-group restart-from-
    checkpoint on worker failure (SURVEY.md §2.5 elastic row)."""

    max_failures: int = 0


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig
    )
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig
    )
    # Tune surface (ref: air RunConfig callbacks/stop): lifecycle hooks
    # (tune/callback.py — loggers are callbacks) and a declarative stop
    # condition (tune/stoppers.py — a Stopper, a callable, or a
    # {metric: threshold} dict).
    callbacks: Optional[list] = None
    stop: Any = None


@dataclasses.dataclass
class Result:
    """Ref analogue: python/ray/air/result.py Result."""

    metrics: Dict[str, Any]
    checkpoint: Optional["Checkpoint"]  # noqa: F821
    error: Optional[BaseException] = None
    metrics_history: Optional[list] = None

    @property
    def best_checkpoint(self):
        return self.checkpoint
