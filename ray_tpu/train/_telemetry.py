"""Train gang lifecycle metrics (declared at import time so the
observability lint validates the surface; published through the
util/metrics KV pipeline like every other plane).

- ``ray_tpu_train_restarts_total``: whole-gang restarts the supervisor
  executed (tag ``reason``: error | hang | preempt).
- ``ray_tpu_train_gang_aborts_total``: prompt gang aborts — a rank died
  or its heartbeat went stale past ``train_rank_timeout_s`` and the
  surviving ranks were killed out of their collectives.
- ``ray_tpu_train_recovery_seconds``: failure detection → the restarted
  gang's first successful report (the paper's gang-restart latency).
- ``ray_tpu_train_preemptions_total``: cooperative drain preemptions
  (the gang checkpointed and surrendered a draining node).
"""

from __future__ import annotations

from ..util.metrics import Counter, Gauge, Histogram

TRAIN_RESTARTS = Counter(
    "ray_tpu_train_restarts_total",
    "Whole-gang restarts executed by the train supervisor",
    tag_keys=("reason",),
)

TRAIN_GANG_ABORTS = Counter(
    "ray_tpu_train_gang_aborts_total",
    "Prompt gang aborts (dead/hung rank detected; survivors killed)",
    tag_keys=("reason",),
)

TRAIN_RECOVERY_SECONDS = Histogram(
    "ray_tpu_train_recovery_seconds",
    "Failure detection to the restarted gang's first report",
    boundaries=[0.5, 1, 2, 5, 10, 30, 60, 120, 300],
)

TRAIN_PREEMPTIONS = Counter(
    "ray_tpu_train_preemptions_total",
    "Cooperative drain preemptions (gang checkpointed and moved)",
)

TRAIN_GANG_SIZE = Gauge(
    "ray_tpu_train_gang_size",
    "World size of the currently-running train gang",
)
