"""In-worker training session.

Ref analogue: python/ray/train/_internal/session.py _TrainSession (:109) —
``report(metrics, checkpoint)`` (:393,653), ``get_checkpoint`` (:711), rank
accessors. Reports stream to the driver through the control-plane KV store
(sequence-numbered keys) instead of the reference's in-actor queue, so the
trainer can poll while the worker's actor method is still running.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import cloudpickle

from .checkpoint import Checkpoint

_session: Optional["TrainSession"] = None


class TrainSession:
    def __init__(
        self,
        run_id: str,
        world_rank: int,
        world_size: int,
        storage_dir: str,
        start_checkpoint: Optional[Checkpoint],
        dataset_shards: Optional[Dict[str, Any]] = None,
        trial_info: Optional[Dict[str, Any]] = None,
    ):
        self.run_id = run_id
        self.world_rank = world_rank
        self.world_size = world_size
        self.storage_dir = storage_dir
        self.start_checkpoint = start_checkpoint
        self.dataset_shards = dataset_shards or {}
        self.trial_info = trial_info or {}
        self._seq = 0

    def _kv(self):
        from ..core.runtime_context import current_runtime

        return current_runtime()

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None):
        payload = {
            "metrics": dict(metrics),
            "checkpoint_path": checkpoint.path if checkpoint else None,
            "rank": self.world_rank,
            "seq": self._seq,
        }
        self._kv().kv_put(
            f"__train__/{self.run_id}/{self.world_rank}/{self._seq}",
            cloudpickle.dumps(payload),
        )
        self._seq += 1

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.start_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        return self.dataset_shards.get(name)

    def checkpoint_dir(self, step: int) -> str:
        return os.path.join(
            self.storage_dir, f"checkpoint_{step:06d}_rank{self.world_rank}"
        )


# ---- public session API (module functions, like ray.train.*) ----

def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No training session active; these APIs only work inside "
            "train_loop_per_worker."
        )
    return _session


def set_session(session: Optional[TrainSession]):
    global _session
    _session = session


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    return get_session().get_dataset_shard(name)


def get_world_rank() -> int:
    return get_session().world_rank


def get_world_size() -> int:
    return get_session().world_size


def get_trial_name() -> str:
    return get_session().trial_info.get("name", get_session().run_id)
