"""In-worker training session.

Ref analogue: python/ray/train/_internal/session.py _TrainSession (:109) —
``report(metrics, checkpoint)`` (:393,653), ``get_checkpoint`` (:711), rank
accessors. Reports stream to the driver through the control-plane KV store
(sequence-numbered keys) instead of the reference's in-actor queue, so the
trainer can poll while the worker's actor method is still running.

Elastic-gang surface (PR 11):

- Every rank publishes a heartbeat + step counter to GCS KV
  (``__train__/<run>/<rank>/hb``) from a background thread; the driver-
  side gang supervisor declares a rank dead/hung when its heartbeat goes
  stale past ``train_rank_timeout_s`` and aborts the whole gang.
- :func:`preemption_requested` / ``TrainSession.preemption`` surface a
  :class:`PreemptionSignal` when the gang must checkpoint and surrender
  a draining node: the local signal arrives as a ``node_draining`` frame
  (core/preemption.py), and the first rank to see it raises a gang-wide
  KV flag so every rank winds down at the SAME step boundary (a lone
  rank exiting mid-collective would hang the survivors).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Dict, Optional

import cloudpickle

from .checkpoint import Checkpoint

_session: Optional["TrainSession"] = None


@dataclasses.dataclass
class PreemptionSignal:
    """The gang is being preempted: checkpoint at the next step boundary
    and return from the train loop — the supervisor restarts the run
    from the last committed checkpoint on surviving/replacement nodes
    WITHOUT consuming a FailureConfig.max_failures budget slot."""

    node_id: str          # the draining node (hex; "?" when unknown)
    since: float          # when the drain was first observed
    rank: int             # rank that first raised the gang-wide flag


class TrainSession:
    def __init__(
        self,
        run_id: str,
        world_rank: int,
        world_size: int,
        storage_dir: str,
        start_checkpoint: Optional[Checkpoint],
        dataset_shards: Optional[Dict[str, Any]] = None,
        trial_info: Optional[Dict[str, Any]] = None,
    ):
        self.run_id = run_id
        self.world_rank = world_rank
        self.world_size = world_size
        self.storage_dir = storage_dir
        self.start_checkpoint = start_checkpoint
        self.dataset_shards = dataset_shards or {}
        self.trial_info = trial_info or {}
        self._seq = 0
        # Step counter the heartbeat thread ships: report() advances it
        # (preferring an explicit metrics["step"]), so the supervisor
        # sees both liveness AND progress per rank.
        self.step = 0
        self._preempt: Optional[PreemptionSignal] = None
        self._preempt_local = False  # this rank raised the gang flag
        self._preempt_checked = 0.0
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None

    def _kv(self):
        from ..core.runtime_context import current_runtime

        return current_runtime()

    # ------------------------------------------------------------- report

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None):
        from ..util import faults

        # Chaos: a rank "killed mid-step" — the injected ConnectionError
        # escapes the train loop exactly like a real crash, the error
        # key publishes, and the supervisor aborts + restarts the gang.
        delay = faults.fire(faults.TRAIN_WORKER, rank=str(self.world_rank),
                            run=self.run_id)
        if delay:
            time.sleep(delay)
        payload = {
            "metrics": dict(metrics),
            "checkpoint_path": checkpoint.path if checkpoint else None,
            "rank": self.world_rank,
            "seq": self._seq,
        }
        self._kv().kv_put(
            f"__train__/{self.run_id}/{self.world_rank}/{self._seq}",
            cloudpickle.dumps(payload),
        )
        self._seq += 1
        step = metrics.get("step")
        self.step = int(step) if isinstance(step, (int, float)) \
            else self.step + 1

    # --------------------------------------------------------- heartbeats

    def heartbeat_key(self) -> str:
        return f"__train__/{self.run_id}/{self.world_rank}/hb"

    def publish_heartbeat(self) -> None:
        self._kv().kv_put(
            self.heartbeat_key(),
            cloudpickle.dumps({"ts": time.time(), "step": self.step,
                               "rank": self.world_rank}),
        )

    def start_heartbeats(self, interval_s: float) -> None:
        """Background per-rank heartbeat through GCS KV. Connection is
        thread-safe (protocol.Connection send lock), so this rides the
        same node socket as report()."""
        if self._hb_thread is not None:
            return

        def loop():
            warned = False
            while not self._hb_stop.wait(interval_s):
                try:
                    self.publish_heartbeat()
                    warned = False
                except Exception as e:  # noqa: BLE001
                    # Keep beating through transient control-plane
                    # blips (GCS failover window, reconnect): a
                    # permanently-exited heartbeat thread would get a
                    # HEALTHY rank declared dead train_rank_timeout_s
                    # later. If the failure persists that long, the
                    # rank really is unreachable and the supervisor's
                    # verdict is correct.
                    if not warned:
                        warned = True
                        import sys

                        print(
                            f"[ray_tpu.train] rank {self.world_rank}: "
                            f"heartbeat publish failed ({e!r}); "
                            f"retrying every {interval_s}s",
                            file=sys.stderr,
                        )

        try:
            self.publish_heartbeat()
        except Exception:
            pass  # first beat best-effort; the thread keeps trying
        self._hb_thread = threading.Thread(
            target=loop, name="ray_tpu-train-hb", daemon=True
        )
        self._hb_thread.start()

    def stop_heartbeats(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
            self._hb_thread = None
        # Final beat with the FINAL step counter: a rank that finishes
        # faster than one heartbeat interval would otherwise leave a
        # step-0 beat behind, hiding the gang's real progress from the
        # supervisor's divergence (hung-rank) detector.
        try:
            self.publish_heartbeat()
        except Exception:
            pass  # socket already down; the rank is done either way

    # --------------------------------------------------------- preemption

    def _preempt_flag_key(self) -> str:
        return f"__train__/{self.run_id}/preempt"

    @property
    def preemption(self) -> Optional[PreemptionSignal]:
        """The gang-wide preemption signal, or None. Poll at step
        boundaries: when set, save a checkpoint, report it, and return
        from the train loop. Sources, in order: (1) this worker's node
        began draining (node_draining frame -> core/preemption.py) —
        the first rank to see it raises the gang-wide KV flag; (2) the
        KV flag raised by another rank (or the supervisor). An aborted
        drain RETRACTS the signal: node_undrain clears the local flag,
        the raising rank deletes the gang flag, and every rank's next
        poll sees the retraction — a rolled-back drain costs at most
        one step-boundary wobble, not a whole-gang restart."""
        from ..core import preemption as _local

        local = _local.local_drain()
        if local is not None:
            if not self._preempt_local:
                sig = PreemptionSignal(node_id=local["node_id"],
                                       since=local["since"],
                                       rank=self.world_rank)
                try:
                    self._kv().kv_put(
                        self._preempt_flag_key(),
                        cloudpickle.dumps(dataclasses.asdict(sig)),
                        overwrite=False,
                    )
                except Exception:
                    pass  # advisory; the drain timeout still bounds us
                self._preempt = sig
                self._preempt_local = True
            return self._preempt
        if self._preempt_local:
            # We raised the gang flag for a drain that has since been
            # aborted (node_undrain): retract it for the whole gang.
            try:
                self._kv().kv_del(self._preempt_flag_key())
            except Exception:
                pass  # stale flag worst-case costs one gang restart
            self._preempt = None
            self._preempt_local = False
        # Gang-wide flag: throttled KV poll (discovery AND
        # retraction-tracking) so a tight step loop doesn't hammer the
        # control plane.
        now = time.monotonic()
        if now - self._preempt_checked < 0.2:
            return self._preempt
        self._preempt_checked = now
        try:
            blob = self._kv().kv_get(self._preempt_flag_key())
        except Exception:
            return self._preempt
        if blob is None:
            self._preempt = None
        elif self._preempt is None:
            try:
                self._preempt = PreemptionSignal(**cloudpickle.loads(blob))
            except Exception:
                self._preempt = PreemptionSignal(
                    node_id="?", since=time.time(), rank=-1)
        return self._preempt

    def preemption_requested(self) -> bool:
        return self.preemption is not None

    # ------------------------------------------------------------- misc

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.start_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        return self.dataset_shards.get(name)

    def checkpoint_dir(self, step: int) -> str:
        return os.path.join(
            self.storage_dir, f"checkpoint_{step:06d}_rank{self.world_rank}"
        )


# ---- public session API (module functions, like ray.train.*) ----

def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No training session active; these APIs only work inside "
            "train_loop_per_worker."
        )
    return _session


def set_session(session: Optional[TrainSession]):
    global _session
    _session = session


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    return get_session().get_dataset_shard(name)


def get_world_rank() -> int:
    return get_session().world_rank


def get_world_size() -> int:
    return get_session().world_size


def preemption_requested() -> bool:
    """True when the gang must checkpoint and surrender its node(s) —
    check at step boundaries; see TrainSession.preemption."""
    return get_session().preemption_requested()


def get_trial_name() -> str:
    return get_session().trial_info.get("name", get_session().run_id)
