"""JaxTrainer: distributed data-parallel training over worker actors.

Ref analogue: the TorchTrainer path (SURVEY.md §3.4) — BaseTrainer.fit
(train/base_trainer.py:579) → BackendExecutor (start:124, start_training:438)
→ WorkerGroup of actors (_internal/worker_group.py:102), with
_setup_torch_process_group replaced by the TPU-native recipe: each worker is
one jax process on one host of the slice; rank 0 publishes the coordinator
address through the control-plane KV and every worker calls
jax.distributed.initialize, after which the train loop is a single SPMD
program over the slice's mesh (collectives on ICI via XLA, no NCCL).

Failure handling follows SURVEY.md §2.5: whole-group restart from the last
checkpoint, bounded by FailureConfig.max_failures.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

from .checkpoint import Checkpoint, CheckpointManager, default_storage_path
from .config import FailureConfig, Result, RunConfig, ScalingConfig
from .session import TrainSession, set_session


class TrainWorkerGroupError(RuntimeError):
    pass


def _train_worker_entry(
    fn_blob: bytes,
    config: Optional[Dict[str, Any]],
    run_id: str,
    rank: int,
    world_size: int,
    storage_dir: str,
    start_checkpoint_path: Optional[str],
    dataset_shards: Dict[str, Any],
    coordinator: Optional[str],
    use_tpu: bool,
):
    """Runs inside a worker actor process."""
    if coordinator is not None and world_size > 1 and use_tpu:
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=world_size,
            process_id=rank,
        )
    fn = cloudpickle.loads(fn_blob)
    start_ckpt = (
        Checkpoint(start_checkpoint_path) if start_checkpoint_path else None
    )
    session = TrainSession(
        run_id=run_id,
        world_rank=rank,
        world_size=world_size,
        storage_dir=storage_dir,
        start_checkpoint=start_ckpt,
        dataset_shards=dataset_shards,
    )
    set_session(session)
    try:
        if config is not None:
            fn(config)
        else:
            fn()
    finally:
        set_session(None)
    return "done"


class JaxTrainer:
    """Data-parallel trainer (ref analogue: DataParallelTrainer /
    TorchTrainer, train/data_parallel_trainer.py:432)."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self._fn = train_loop_per_worker
        self._config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self._datasets = datasets or {}
        self._resume = resume_from_checkpoint

    # ------------------------------------------------------------------ fit

    def fit(self) -> Result:
        import ray_tpu

        storage = self.run_config.storage_path or default_storage_path(
            self.run_config.name
        )
        ckpt_cfg = self.run_config.checkpoint_config
        manager = CheckpointManager(
            storage,
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order,
        )
        failures_left = self.run_config.failure_config.max_failures
        start_ckpt = self._resume
        history: List[Dict[str, Any]] = []
        while True:
            try:
                metrics = self._run_attempt(manager, start_ckpt, history)
                return Result(
                    metrics=metrics,
                    checkpoint=manager.best,
                    metrics_history=history,
                )
            except TrainWorkerGroupError as e:
                if failures_left == 0:
                    return Result(
                        metrics=history[-1] if history else {},
                        checkpoint=manager.best,
                        error=e,
                        metrics_history=history,
                    )
                failures_left -= 1
                start_ckpt = manager.latest or start_ckpt

    def _shard_datasets(self, world_size: int) -> List[Dict[str, Any]]:
        """Per-worker dataset shards; ray_tpu.data Datasets use
        streaming_split, other values pass through whole."""
        shards: List[Dict[str, Any]] = [dict() for _ in range(world_size)]
        for name, ds in self._datasets.items():
            split = None
            if hasattr(ds, "streaming_split"):
                split = ds.streaming_split(world_size)
            for rank in range(world_size):
                shards[rank][name] = split[rank] if split else ds
        return shards

    def _run_attempt(
        self,
        manager: CheckpointManager,
        start_ckpt: Optional[Checkpoint],
        history: List[Dict[str, Any]],
    ) -> Dict[str, Any]:
        import ray_tpu
        from ..core.runtime_context import current_runtime

        sc = self.scaling_config
        world = sc.num_workers
        run_id = uuid.uuid4().hex[:12]
        rt = current_runtime()

        fn_blob = cloudpickle.dumps(self._fn)
        storage = manager.storage_dir
        shards = self._shard_datasets(world)

        res = sc.worker_resources()
        worker_cls = ray_tpu.remote(
            num_cpus=res.get("CPU", 0),
            resources={k: v for k, v in res.items() if k != "CPU"},
        )(_RemoteTrainWorker)

        coordinator = None
        if world > 1 and sc.use_tpu:
            # Rank 0's host:port; workers resolve it before jax.distributed.
            import socket

            host = socket.gethostbyname(socket.gethostname())
            coordinator = f"{host}:{29400 + (hash(run_id) % 1000)}"

        actors = [worker_cls.remote() for _ in range(world)]
        refs = [
            a.run.remote(
                fn_blob,
                self._config,
                run_id,
                rank,
                world,
                storage,
                start_ckpt.path if start_ckpt else None,
                shards[rank],
                coordinator,
                sc.use_tpu,
            )
            for rank, a in enumerate(actors)
        ]

        next_seq = [0] * world
        last_metrics: Dict[str, Any] = {}
        error: Optional[BaseException] = None
        try:
            pending = list(refs)
            while pending:
                _, pending = ray_tpu.wait(
                    pending, num_returns=len(pending), timeout=0.25
                )
                last_metrics, error = self._drain_reports(
                    rt, run_id, world, next_seq, manager, history, last_metrics
                )
                if error:
                    raise TrainWorkerGroupError(str(error)) from error
            # Final drain + surface worker exceptions.
            for ref in refs:
                ray_tpu.get(ref)
            last_metrics, _ = self._drain_reports(
                rt, run_id, world, next_seq, manager, history, last_metrics
            )
            return last_metrics
        except TrainWorkerGroupError:
            raise
        except Exception as e:
            raise TrainWorkerGroupError(f"train worker failed: {e}") from e
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass

    def _drain_reports(self, rt, run_id, world, next_seq, manager, history,
                       last_metrics):
        for rank in range(world):
            while True:
                key = f"__train__/{run_id}/{rank}/{next_seq[rank]}"
                blob = rt.kv_get(key)
                if blob is None:
                    break
                next_seq[rank] += 1
                payload = cloudpickle.loads(blob)
                if rank == 0:
                    metrics = payload["metrics"]
                    history.append(metrics)
                    last_metrics = metrics
                    if payload.get("checkpoint_path"):
                        ckpt = Checkpoint(payload["checkpoint_path"])
                        manager.register(
                            ckpt, metrics, metrics.get("step", len(history))
                        )
        return last_metrics, None


class _RemoteTrainWorker:
    """Actor wrapper so the worker body runs in a dedicated process."""

    def run(self, *args):
        return _train_worker_entry(*args)
