"""JaxTrainer: distributed data-parallel training over an SPMD actor group.

Ref analogue: the TorchTrainer path (SURVEY.md §3.4) — BaseTrainer.fit
(train/base_trainer.py:579) → BackendExecutor (start:124, start_training:438)
→ WorkerGroup of actors (_internal/worker_group.py:102), with
_setup_torch_process_group replaced by the TPU-native recipe: the worker
gang is a :class:`ray_tpu.SpmdActorGroup` (gang-scheduled, one host-actor
per placement-group bundle — on a TPU pod, one per slice host via
``tpu.tpu_slice()``), rank 0 reserves a coordinator port on *its own* host
and the address is published through the control-plane KV, then every worker
calls ``jax.distributed.initialize`` and the train loop is a single SPMD
program over the slice's mesh (collectives on ICI via XLA, no NCCL).

Failure handling follows SURVEY.md §2.5 — whole-group restart from the
last COMMITTED checkpoint, bounded by FailureConfig.max_failures — and is
driven by a **gang supervisor** in the fit loop:

- every rank publishes a heartbeat + step counter through GCS KV
  (``__train__/<run>/<rank>/hb``, TrainSession.start_heartbeats);
- the supervisor declares a rank DEAD when its heartbeat goes stale past
  ``train_rank_timeout_s``, and HUNG when the gang's step counters
  diverge (another rank moved on) while the lagging rank's counter has
  not advanced within the same window;
- either verdict aborts the WHOLE gang promptly — surviving ranks stuck
  in a collective are killed rather than waiting out the collective
  timeout — emitting WARNING TRAIN cluster events and the
  ``ray_tpu_train_{gang_aborts,restarts}_total`` /
  ``ray_tpu_train_recovery_seconds`` metrics;
- a drain-preempted gang (TrainSession.preemption) checkpoints at the
  next step boundary and exits cleanly; the supervisor restarts it on
  surviving/replacement nodes WITHOUT consuming a max_failures slot.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ._telemetry import (
    TRAIN_GANG_ABORTS,
    TRAIN_GANG_SIZE,
    TRAIN_PREEMPTIONS,
    TRAIN_RECOVERY_SECONDS,
    TRAIN_RESTARTS,
)
from .checkpoint import (
    Checkpoint,
    CheckpointManager,
    default_storage_path,
    latest_committed,
)
from .config import FailureConfig, Result, RunConfig, ScalingConfig
from .session import TrainSession, set_session


class TrainWorkerGroupError(RuntimeError):
    pass


class GangPreempted(Exception):
    """Internal: the attempt ended because the gang cooperatively
    surrendered a draining node (not a failure)."""


def _train_worker_entry(
    fn_blob: bytes,
    config: Optional[Dict[str, Any]],
    run_id: str,
    rank: int,
    world_size: int,
    storage_dir: str,
    start_checkpoint_path: Optional[str],
    dataset_shards: Dict[str, Any],
    coordinator: Optional[str],
    backend: Optional[str],
    heartbeat_interval_s: float = 2.0,
):
    """Runs inside a worker actor process. ``backend`` selects the
    collective rendezvous: "jax" = jax.distributed over the slice,
    "torch" = torch.distributed gloo process group (the TorchTrainer
    path, ref: train/torch/config.py _setup_torch_process_group:62),
    None = no collectives."""
    from ..core.runtime_context import current_runtime

    start_ckpt = (
        Checkpoint(start_checkpoint_path) if start_checkpoint_path else None
    )
    session = TrainSession(
        run_id=run_id,
        world_rank=rank,
        world_size=world_size,
        storage_dir=storage_dir,
        start_checkpoint=start_ckpt,
        dataset_shards=dataset_shards,
    )
    set_session(session)
    # Heartbeats start BEFORE the rendezvous: a hung
    # jax.distributed.initialize (dead peer, half-open coordinator) is
    # a live process, and the supervisor needs the beat flowing to tell
    # "slow rendezvous" from "dead rank".
    session.start_heartbeats(heartbeat_interval_s)
    torch_group = False
    try:
        if coordinator is not None and world_size > 1:
            if backend == "jax":
                import jax

                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=world_size,
                    process_id=rank,
                )
            elif backend == "torch":
                import torch.distributed as dist

                dist.init_process_group(
                    "gloo",
                    init_method=f"tcp://{coordinator}",
                    rank=rank,
                    world_size=world_size,
                )
                torch_group = True
        fn = cloudpickle.loads(fn_blob)
        if config is not None:
            fn(config)
        else:
            fn()
    except BaseException as e:  # noqa: BLE001 — surfaced via KV + re-raise
        try:
            current_runtime().kv_put(
                f"__train__/{run_id}/{rank}/error",
                cloudpickle.dumps(
                    {"rank": rank, "error": repr(e)}
                ),
            )
        except Exception as kv_err:
            # The task error itself re-raises below; what is lost here
            # is only the PROMPT surfacing through the KV error key —
            # the driver then learns of the failure at join time. Note
            # the delay on the worker's stderr (shipped to worker logs).
            import sys

            print(
                f"[ray_tpu.train] WARNING: rank {rank} could not "
                f"publish its error key ({kv_err!r}); failure will "
                f"surface at gang join instead",
                file=sys.stderr,
            )
        raise
    finally:
        session.stop_heartbeats()
        set_session(None)
        if torch_group:
            import torch.distributed as dist

            # Teardown of a rendezvous that may already be half-dead
            # (peer ranks crashed): the run's outcome is decided by now;
            # a destroy failure changes nothing for the caller.
            try:
                dist.destroy_process_group()
            except Exception:  # rtlint: disable=swallowed-failure
                pass
    return "done"


class _RemoteTrainWorker:
    """Actor wrapper so the worker body runs in a dedicated process."""

    def reserve_coordinator(self) -> str:
        """Bind a free port on THIS worker's host and return host:port —
        the jax.distributed rendezvous address. Fixes the driver-host bug:
        rank 0 may not share a machine with the driver in cluster mode."""
        import socket

        host = socket.gethostbyname(socket.gethostname())
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return f"{host}:{port}"

    def run(self, *args):
        return _train_worker_entry(*args)


class _RankState:
    """Supervisor-side liveness record for one rank."""

    __slots__ = ("last_beat", "last_blob", "step", "step_changed")

    def __init__(self, now: float):
        self.last_beat = now
        self.last_blob: Optional[bytes] = None
        self.step = -1
        self.step_changed = now


class JaxTrainer:
    """Data-parallel trainer (ref analogue: DataParallelTrainer /
    TorchTrainer, train/data_parallel_trainer.py:432)."""

    # Collective rendezvous flavor for multi-worker runs; the
    # TorchTrainer subclass (train/torch.py) swaps this for "torch".
    _collective_backend = "jax"

    def __init__(
        self,
        train_loop_per_worker,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self._fn = train_loop_per_worker
        self._config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self._datasets = datasets or {}
        self._resume = resume_from_checkpoint

    # ------------------------------------------------------------------ fit

    def fit(self) -> Result:
        from ..util import events

        storage = self.run_config.storage_path or default_storage_path(
            self.run_config.name
        )
        ckpt_cfg = self.run_config.checkpoint_config
        manager = CheckpointManager(
            storage,
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order,
        )
        failures_left = self.run_config.failure_config.max_failures
        start_ckpt = self._resume
        history: List[Dict[str, Any]] = []
        recovery_started: Optional[float] = None
        try:
            while True:
                try:
                    metrics = self._run_attempt(
                        manager, start_ckpt, history, recovery_started
                    )
                    return Result(
                        metrics=metrics,
                        checkpoint=manager.best,
                        metrics_history=history,
                    )
                except GangPreempted:
                    # Cooperative drain surrender: restart on surviving/
                    # replacement nodes from the last committed
                    # checkpoint. NOT a failure — no budget consumed.
                    TRAIN_PREEMPTIONS.inc()
                    TRAIN_RESTARTS.inc(tags={"reason": "preempt"})
                    recovery_started = time.monotonic()
                    start_ckpt = self._restart_checkpoint(
                        manager, storage, start_ckpt
                    )
                    events.emit(
                        events.WARNING, events.TRAIN,
                        "train gang preempted by node drain; restarting "
                        "from "
                        + (start_ckpt.path if start_ckpt else "scratch"),
                        custom_fields={"restart_from": getattr(
                            start_ckpt, "path", None)},
                    )
                except TrainWorkerGroupError as e:
                    if failures_left == 0:
                        return Result(
                            metrics=history[-1] if history else {},
                            checkpoint=manager.best,
                            error=e,
                            metrics_history=history,
                        )
                    failures_left -= 1
                    TRAIN_RESTARTS.inc(tags={"reason": "error"})
                    recovery_started = time.monotonic()
                    start_ckpt = self._restart_checkpoint(
                        manager, storage, start_ckpt
                    )
                    events.emit(
                        events.WARNING, events.TRAIN,
                        f"train gang restarting after failure ({e}); "
                        f"{failures_left} restart(s) left, resuming from "
                        + (start_ckpt.path if start_ckpt else "scratch"),
                        custom_fields={
                            "failures_left": failures_left,
                            "restart_from": getattr(start_ckpt, "path",
                                                    None),
                        },
                    )
        finally:
            TRAIN_GANG_SIZE.set(0)

    @staticmethod
    def _restart_checkpoint(manager: CheckpointManager, storage: str,
                            fallback: Optional[Checkpoint]
                            ) -> Optional[Checkpoint]:
        """The restart source of truth: the newest COMMITTED checkpoint
        — from the manager's registry, else a storage-dir scan (covers
        checkpoints a crashed save never registered past), else the
        original resume point. An uncommitted/corrupt 'latest' is never
        restarted from."""
        ckpt = manager.latest_committed
        if ckpt is None:
            ckpt = latest_committed(storage)
        return ckpt or fallback

    def _shard_datasets(self, world_size: int) -> List[Dict[str, Any]]:
        """Per-worker dataset shards; ray_tpu.data Datasets use
        streaming_split, other values pass through whole."""
        shards: List[Dict[str, Any]] = [dict() for _ in range(world_size)]
        for name, ds in self._datasets.items():
            split = None
            if hasattr(ds, "streaming_split"):
                split = ds.streaming_split(world_size)
            for rank in range(world_size):
                shards[rank][name] = split[rank] if split else ds
        return shards

    def _make_worker_group(self):
        """Gang-schedule the workers. On a cluster with registered TPU
        slices and use_tpu, the gang is the hosts of one slice
        (tpu.tpu_slice()); otherwise a SPREAD placement group sized by
        ScalingConfig."""
        import ray_tpu
        from ..core.spmd import SpmdActorGroup
        from ..core import tpu as tpu_mod

        sc = self.scaling_config
        pg = None
        if sc.use_tpu:
            try:
                rt_nodes = ray_tpu.nodes()
                slices = tpu_mod.list_slices(
                    [
                        {
                            "state": "alive" if n.get("Alive", True) else "dead",
                            "labels": n.get("Labels", {}),
                            "resources_total": n.get("Resources", {}),
                        }
                        for n in rt_nodes
                    ]
                )
                eligible = {
                    name: hosts
                    for name, hosts in slices.items()
                    if len(hosts) >= sc.num_workers
                }
                if eligible:
                    name = sorted(eligible)[0]
                    pg = tpu_mod.tpu_slice(
                        name, num_hosts=sc.num_workers
                    )
            except Exception as e:
                # Only the no-slices case is a silent fallback; anything
                # else (selector mismatch, reservation timeout) degrades to
                # non-topology placement and must be visible.
                import sys

                print(
                    f"[ray_tpu.train] WARNING: tpu slice placement failed "
                    f"({type(e).__name__}: {e}); falling back to plain "
                    f"SPREAD gang (no ICI-topology affinity)",
                    file=sys.stderr,
                )
                pg = None
        res = sc.worker_resources()
        return SpmdActorGroup(
            _RemoteTrainWorker,
            num_workers=sc.num_workers,
            resources_per_worker=res,
            placement_group=pg,
            strategy="SPREAD",
            name="jax-train",
            # The slice PG is created here for this run; the group must tear
            # it down with the gang or the slice reservation leaks forever.
            owns_placement_group=True,
        )

    # ------------------------------------------------------------ attempt

    def _run_attempt(
        self,
        manager: CheckpointManager,
        start_ckpt: Optional[Checkpoint],
        history: List[Dict[str, Any]],
        recovery_started: Optional[float] = None,
    ) -> Dict[str, Any]:
        import ray_tpu
        from ..core.config import get_config
        from ..util import events

        sc = self.scaling_config
        world = sc.num_workers
        run_id = uuid.uuid4().hex[:12]
        cfg = get_config()
        rank_timeout = float(cfg.train_rank_timeout_s)
        hb_interval = float(cfg.train_heartbeat_interval_s)

        fn_blob = cloudpickle.dumps(self._fn)
        storage = manager.storage_dir
        shards = self._shard_datasets(world)

        group = self._make_worker_group()
        attempt = _AttemptState(run_id, world, rank_timeout,
                                recovery_started, manager, history)
        TRAIN_GANG_SIZE.set(world)
        try:
            group.wait_ready(timeout=120.0)
            coordinator = None
            backend = None
            if world > 1 and (sc.use_tpu
                              or self._collective_backend != "jax"):
                # Rank 0 reserves the rendezvous port on its own host; the
                # address is published through the control-plane KV
                # (docstring contract; also consumed by state tooling).
                backend = self._collective_backend
                coordinator = ray_tpu.get(
                    group.actors[0].reserve_coordinator.remote()
                )
                attempt.rt.kv_put(
                    f"__train__/{run_id}/coordinator",
                    coordinator.encode(),
                )

            def rank_args(rank: int):
                return (
                    (
                        fn_blob,
                        self._config,
                        run_id,
                        rank,
                        world,
                        storage,
                        start_ckpt.path if start_ckpt else None,
                        shards[rank],
                        coordinator,
                        backend,
                        hb_interval,
                    ),
                    {},
                )

            refs = group.submit("run", per_rank_args=rank_args)
            attempt.mark_submitted()

            rank_of = {ref: rank for rank, ref in enumerate(refs)}
            pending = list(refs)
            while pending:
                ready, pending = ray_tpu.wait(
                    pending, num_returns=len(pending), timeout=0.25
                )
                # Eager join: a rank whose actor died errors its ref
                # long before the final join — surface it NOW so the
                # survivors (possibly blocked in a collective) are
                # killed promptly. A clean return just retires the rank
                # from the liveness sweep (it stopped heartbeating).
                for ref in ready:
                    rank = rank_of[ref]
                    try:
                        ray_tpu.get(ref)
                    except Exception as e:  # noqa: BLE001
                        attempt.drain_reports()
                        raise _GangAbort(
                            "dead",
                            f"rank {rank} worker failed: {e}",
                        ) from e
                    attempt.mark_rank_done(rank)
                attempt.drain_reports()
                attempt.check_liveness()
            attempt.drain_reports()
            if attempt.preempted:
                raise GangPreempted()
            if attempt.error:
                raise TrainWorkerGroupError(str(attempt.error))
            return attempt.last_metrics
        except (TrainWorkerGroupError, GangPreempted):
            raise
        except _GangAbort as e:
            # Prompt whole-gang abort: kill every rank NOW — survivors
            # blocked in a collective would otherwise sit out the
            # collective timeout — then surface as a restartable failure.
            TRAIN_GANG_ABORTS.inc(tags={"reason": e.reason})
            events.emit(
                events.WARNING, events.TRAIN,
                f"train gang {run_id} aborted: {e} — killing all "
                f"{world} rank(s)",
                custom_fields={"run_id": run_id, "reason": e.reason},
            )
            if attempt.preempted:
                raise GangPreempted() from e
            raise TrainWorkerGroupError(str(e)) from e
        except Exception as e:
            if attempt.preempted:
                # The drain beat the supervisor to the node: worker
                # death during a signalled preemption is the preemption,
                # not a budgeted failure.
                raise GangPreempted() from e
            raise TrainWorkerGroupError(f"train worker failed: {e}") from e
        finally:
            group.shutdown()


class _GangAbort(RuntimeError):
    """Supervisor verdict: a rank is dead or hung; the gang cannot
    continue and must be killed promptly."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


class _AttemptState:
    """Driver-side supervisor state for one gang attempt: KV report
    draining, per-rank heartbeat/step tracking, preemption flag."""

    def __init__(self, run_id: str, world: int, rank_timeout: float,
                 recovery_started: Optional[float],
                 manager: CheckpointManager,
                 history: List[Dict[str, Any]]):
        from ..core.runtime_context import current_runtime

        self.rt = current_runtime()
        self.run_id = run_id
        self.world = world
        self.rank_timeout = rank_timeout
        self.recovery_started = recovery_started
        self.manager = manager
        self.history = history
        self.next_seq = [0] * world
        self.last_metrics: Dict[str, Any] = {}
        self.error: Optional[str] = None
        self.preempted = False
        self.done: set = set()
        now = time.monotonic()
        # Grace until the first beat: actor entry starts beating almost
        # immediately after submit, but a loaded box deserves slack.
        self.ranks = [_RankState(now + 10.0) for _ in range(world)]
        # Gang step cadence, for the adaptive hang threshold: a step
        # that legitimately includes slow rank-local work (rank 0's
        # orbax save of a big model) must not read as a hang.
        self.gang_step = -1
        self.gang_step_changed = now
        self.step_interval = 0.0

    def mark_submitted(self):
        now = time.monotonic()
        for r in self.ranks:
            r.last_beat = now + 10.0
            r.step_changed = now

    def mark_rank_done(self, rank: int):
        self.done.add(rank)

    # -------------------------------------------------------- KV draining

    def drain_reports(self):
        rt = self.rt
        for rank in range(self.world):
            blob = rt.kv_get(f"__train__/{self.run_id}/{rank}/error")
            if blob is not None and self.error is None:
                payload = cloudpickle.loads(blob)
                self.error = f"rank {payload['rank']}: {payload['error']}"
            while True:
                key = f"__train__/{self.run_id}/{rank}/{self.next_seq[rank]}"
                blob = rt.kv_get(key)
                if blob is None:
                    break
                self.next_seq[rank] += 1
                payload = cloudpickle.loads(blob)
                if rank == 0:
                    metrics = payload["metrics"]
                    self.history.append(metrics)
                    self.last_metrics = metrics
                    self._note_recovered()
                    if payload.get("checkpoint_path"):
                        ckpt = Checkpoint(payload["checkpoint_path"])
                        self.manager.register(
                            ckpt, metrics,
                            metrics.get("step", len(self.history))
                        )
        # Non-latching: an aborted drain retracts the gang flag
        # (session.preemption deletes the key), and the supervisor must
        # follow — otherwise the rolled-back drain still costs a
        # whole-gang restart.
        self.preempted = rt.kv_get(
            f"__train__/{self.run_id}/preempt") is not None
        if self.error is not None and not self.preempted:
            raise _GangAbort("error", self.error)

    def _note_recovered(self):
        if self.recovery_started is None:
            return
        elapsed = time.monotonic() - self.recovery_started
        self.recovery_started = None
        TRAIN_RECOVERY_SECONDS.observe(elapsed)
        from ..util import events

        events.emit(
            events.INFO, events.TRAIN,
            f"train gang {self.run_id} recovered: first report "
            f"{elapsed:.2f}s after failure detection",
            custom_fields={"run_id": self.run_id,
                           "recovery_seconds": elapsed},
        )

    # ---------------------------------------------------------- liveness

    def check_liveness(self):
        """Heartbeat sweep: a rank with no beat inside
        ``train_rank_timeout_s`` is DEAD; a rank whose step counter
        froze while another rank moved past it is HUNG (lock-step SPMD:
        healthy gangs advance together — divergence means someone is
        stuck between collectives). Either verdict aborts the gang.

        Staleness is measured by when the heartbeat BLOB last changed,
        in the driver's own monotonic frame — worker wall clocks never
        enter the comparison, so cross-host clock offset cannot fake
        (or mask) a dead rank. The hang threshold adapts to the gang's
        own step cadence (4× the slowest observed inter-step gap, floor
        ``train_rank_timeout_s``): a step that legitimately spends
        minutes in rank-local work — rank 0's orbax save — already
        stretched the cadence in earlier steps, so it does not read as
        a hang."""
        rt = self.rt
        now = time.monotonic()
        max_step = -1
        for rank in range(self.world):
            state = self.ranks[rank]
            blob = rt.kv_get(f"__train__/{self.run_id}/{rank}/hb")
            if blob is not None and blob != state.last_blob:
                state.last_blob = blob
                state.last_beat = now
                try:
                    hb = cloudpickle.loads(blob)
                # An unreadable beat still proves the process lives;
                # the step counter just doesn't advance from it.
                except Exception:  # rtlint: disable=swallowed-failure
                    hb = None
                if hb:
                    step = int(hb.get("step", -1))
                    if step != state.step:
                        state.step = step
                        state.step_changed = now
            # Gang progress floor: drained report count also witnesses
            # progress (covers a rank whose final beat was lost).
            max_step = max(max_step, state.step, self.next_seq[rank] - 1)
        if max_step > self.gang_step:
            if self.gang_step >= 0:
                self.step_interval = max(
                    self.step_interval, now - self.gang_step_changed)
            self.gang_step = max_step
            self.gang_step_changed = now
        if self.preempted:
            return  # winding down cooperatively; drain timeout bounds us
        hang_timeout = max(self.rank_timeout, 4.0 * self.step_interval)
        for rank in range(self.world):
            if rank in self.done:
                continue  # returned cleanly; it stopped beating by design
            state = self.ranks[rank]
            if now - state.last_beat > self.rank_timeout:
                raise _GangAbort(
                    "dead",
                    f"rank {rank} heartbeat stale for "
                    f"{now - state.last_beat:.1f}s "
                    f"(> train_rank_timeout_s={self.rank_timeout}) — "
                    f"declaring it dead",
                )
            if (state.step < max_step
                    and now - state.step_changed > hang_timeout):
                raise _GangAbort(
                    "hang",
                    f"rank {rank} stuck at step {state.step} while the "
                    f"gang reached {max_step} "
                    f"(no progress for {now - state.step_changed:.1f}s > "
                    f"{hang_timeout:.1f}s = max(train_rank_timeout_s, "
                    f"4x gang step cadence)) — declaring it hung",
                )
