"""JaxTrainer: distributed data-parallel training over an SPMD actor group.

Ref analogue: the TorchTrainer path (SURVEY.md §3.4) — BaseTrainer.fit
(train/base_trainer.py:579) → BackendExecutor (start:124, start_training:438)
→ WorkerGroup of actors (_internal/worker_group.py:102), with
_setup_torch_process_group replaced by the TPU-native recipe: the worker
gang is a :class:`ray_tpu.SpmdActorGroup` (gang-scheduled, one host-actor
per placement-group bundle — on a TPU pod, one per slice host via
``tpu.tpu_slice()``), rank 0 reserves a coordinator port on *its own* host
and the address is published through the control-plane KV, then every worker
calls ``jax.distributed.initialize`` and the train loop is a single SPMD
program over the slice's mesh (collectives on ICI via XLA, no NCCL).

Failure handling follows SURVEY.md §2.5: whole-group restart from the last
checkpoint, bounded by FailureConfig.max_failures. Workers surface errors
promptly through KV error keys (not only at join), so a hung 40-hour run
does not hide a rank-3 crash.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

from .checkpoint import Checkpoint, CheckpointManager, default_storage_path
from .config import FailureConfig, Result, RunConfig, ScalingConfig
from .session import TrainSession, set_session


class TrainWorkerGroupError(RuntimeError):
    pass


def _train_worker_entry(
    fn_blob: bytes,
    config: Optional[Dict[str, Any]],
    run_id: str,
    rank: int,
    world_size: int,
    storage_dir: str,
    start_checkpoint_path: Optional[str],
    dataset_shards: Dict[str, Any],
    coordinator: Optional[str],
    backend: Optional[str],
):
    """Runs inside a worker actor process. ``backend`` selects the
    collective rendezvous: "jax" = jax.distributed over the slice,
    "torch" = torch.distributed gloo process group (the TorchTrainer
    path, ref: train/torch/config.py _setup_torch_process_group:62),
    None = no collectives."""
    from ..core.runtime_context import current_runtime

    torch_group = False
    if coordinator is not None and world_size > 1:
        if backend == "jax":
            import jax

            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=world_size,
                process_id=rank,
            )
        elif backend == "torch":
            import torch.distributed as dist

            dist.init_process_group(
                "gloo",
                init_method=f"tcp://{coordinator}",
                rank=rank,
                world_size=world_size,
            )
            torch_group = True
    fn = cloudpickle.loads(fn_blob)
    start_ckpt = (
        Checkpoint(start_checkpoint_path) if start_checkpoint_path else None
    )
    session = TrainSession(
        run_id=run_id,
        world_rank=rank,
        world_size=world_size,
        storage_dir=storage_dir,
        start_checkpoint=start_ckpt,
        dataset_shards=dataset_shards,
    )
    set_session(session)
    try:
        if config is not None:
            fn(config)
        else:
            fn()
    except BaseException as e:  # noqa: BLE001 — surfaced via KV + re-raise
        try:
            current_runtime().kv_put(
                f"__train__/{run_id}/{rank}/error",
                cloudpickle.dumps(
                    {"rank": rank, "error": repr(e)}
                ),
            )
        except Exception as kv_err:
            # The task error itself re-raises below; what is lost here
            # is only the PROMPT surfacing through the KV error key —
            # the driver then learns of the failure at join time. Note
            # the delay on the worker's stderr (shipped to worker logs).
            import sys

            print(
                f"[ray_tpu.train] WARNING: rank {rank} could not "
                f"publish its error key ({kv_err!r}); failure will "
                f"surface at gang join instead",
                file=sys.stderr,
            )
        raise
    finally:
        set_session(None)
        if torch_group:
            import torch.distributed as dist

            # Teardown of a rendezvous that may already be half-dead
            # (peer ranks crashed): the run's outcome is decided by now;
            # a destroy failure changes nothing for the caller.
            try:
                dist.destroy_process_group()
            except Exception:  # rtlint: disable=swallowed-failure
                pass
    return "done"


class _RemoteTrainWorker:
    """Actor wrapper so the worker body runs in a dedicated process."""

    def reserve_coordinator(self) -> str:
        """Bind a free port on THIS worker's host and return host:port —
        the jax.distributed rendezvous address. Fixes the driver-host bug:
        rank 0 may not share a machine with the driver in cluster mode."""
        import socket

        host = socket.gethostbyname(socket.gethostname())
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return f"{host}:{port}"

    def run(self, *args):
        return _train_worker_entry(*args)


class JaxTrainer:
    """Data-parallel trainer (ref analogue: DataParallelTrainer /
    TorchTrainer, train/data_parallel_trainer.py:432)."""

    # Collective rendezvous flavor for multi-worker runs; the
    # TorchTrainer subclass (train/torch.py) swaps this for "torch".
    _collective_backend = "jax"

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self._fn = train_loop_per_worker
        self._config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self._datasets = datasets or {}
        self._resume = resume_from_checkpoint

    # ------------------------------------------------------------------ fit

    def fit(self) -> Result:
        storage = self.run_config.storage_path or default_storage_path(
            self.run_config.name
        )
        ckpt_cfg = self.run_config.checkpoint_config
        manager = CheckpointManager(
            storage,
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order,
        )
        failures_left = self.run_config.failure_config.max_failures
        start_ckpt = self._resume
        history: List[Dict[str, Any]] = []
        while True:
            try:
                metrics = self._run_attempt(manager, start_ckpt, history)
                return Result(
                    metrics=metrics,
                    checkpoint=manager.best,
                    metrics_history=history,
                )
            except TrainWorkerGroupError as e:
                if failures_left == 0:
                    return Result(
                        metrics=history[-1] if history else {},
                        checkpoint=manager.best,
                        error=e,
                        metrics_history=history,
                    )
                failures_left -= 1
                start_ckpt = manager.latest or start_ckpt

    def _shard_datasets(self, world_size: int) -> List[Dict[str, Any]]:
        """Per-worker dataset shards; ray_tpu.data Datasets use
        streaming_split, other values pass through whole."""
        shards: List[Dict[str, Any]] = [dict() for _ in range(world_size)]
        for name, ds in self._datasets.items():
            split = None
            if hasattr(ds, "streaming_split"):
                split = ds.streaming_split(world_size)
            for rank in range(world_size):
                shards[rank][name] = split[rank] if split else ds
        return shards

    def _make_worker_group(self):
        """Gang-schedule the workers. On a cluster with registered TPU
        slices and use_tpu, the gang is the hosts of one slice
        (tpu.tpu_slice()); otherwise a SPREAD placement group sized by
        ScalingConfig."""
        import ray_tpu
        from ..core.spmd import SpmdActorGroup
        from ..core import tpu as tpu_mod

        sc = self.scaling_config
        pg = None
        if sc.use_tpu:
            try:
                rt_nodes = ray_tpu.nodes()
                slices = tpu_mod.list_slices(
                    [
                        {
                            "state": "alive" if n.get("Alive", True) else "dead",
                            "labels": n.get("Labels", {}),
                            "resources_total": n.get("Resources", {}),
                        }
                        for n in rt_nodes
                    ]
                )
                eligible = {
                    name: hosts
                    for name, hosts in slices.items()
                    if len(hosts) >= sc.num_workers
                }
                if eligible:
                    name = sorted(eligible)[0]
                    pg = tpu_mod.tpu_slice(
                        name, num_hosts=sc.num_workers
                    )
            except Exception as e:
                # Only the no-slices case is a silent fallback; anything
                # else (selector mismatch, reservation timeout) degrades to
                # non-topology placement and must be visible.
                import sys

                print(
                    f"[ray_tpu.train] WARNING: tpu slice placement failed "
                    f"({type(e).__name__}: {e}); falling back to plain "
                    f"SPREAD gang (no ICI-topology affinity)",
                    file=sys.stderr,
                )
                pg = None
        res = sc.worker_resources()
        return SpmdActorGroup(
            _RemoteTrainWorker,
            num_workers=sc.num_workers,
            resources_per_worker=res,
            placement_group=pg,
            strategy="SPREAD",
            name="jax-train",
            # The slice PG is created here for this run; the group must tear
            # it down with the gang or the slice reservation leaks forever.
            owns_placement_group=True,
        )

    def _run_attempt(
        self,
        manager: CheckpointManager,
        start_ckpt: Optional[Checkpoint],
        history: List[Dict[str, Any]],
    ) -> Dict[str, Any]:
        import ray_tpu
        from ..core.runtime_context import current_runtime

        sc = self.scaling_config
        world = sc.num_workers
        run_id = uuid.uuid4().hex[:12]
        rt = current_runtime()

        fn_blob = cloudpickle.dumps(self._fn)
        storage = manager.storage_dir
        shards = self._shard_datasets(world)

        group = self._make_worker_group()
        try:
            group.wait_ready(timeout=120.0)
            coordinator = None
            backend = None
            if world > 1 and (sc.use_tpu
                              or self._collective_backend != "jax"):
                # Rank 0 reserves the rendezvous port on its own host; the
                # address is published through the control-plane KV
                # (docstring contract; also consumed by state tooling).
                backend = self._collective_backend
                coordinator = ray_tpu.get(
                    group.actors[0].reserve_coordinator.remote()
                )
                rt.kv_put(
                    f"__train__/{run_id}/coordinator",
                    coordinator.encode(),
                )

            def rank_args(rank: int):
                return (
                    (
                        fn_blob,
                        self._config,
                        run_id,
                        rank,
                        world,
                        storage,
                        start_ckpt.path if start_ckpt else None,
                        shards[rank],
                        coordinator,
                        backend,
                    ),
                    {},
                )

            refs = group.submit("run", per_rank_args=rank_args)

            next_seq = [0] * world
            last_metrics: Dict[str, Any] = {}
            pending = list(refs)
            while pending:
                _, pending = ray_tpu.wait(
                    pending, num_returns=len(pending), timeout=0.25
                )
                last_metrics, error = self._drain_reports(
                    rt, run_id, world, next_seq, manager, history, last_metrics
                )
                if error:
                    raise TrainWorkerGroupError(str(error))
            # Final join surfaces worker exceptions not seen via KV.
            for ref in refs:
                ray_tpu.get(ref)
            last_metrics, error = self._drain_reports(
                rt, run_id, world, next_seq, manager, history, last_metrics
            )
            if error:
                raise TrainWorkerGroupError(str(error))
            return last_metrics
        except TrainWorkerGroupError:
            raise
        except Exception as e:
            raise TrainWorkerGroupError(f"train worker failed: {e}") from e
        finally:
            group.shutdown()

    def _drain_reports(self, rt, run_id, world, next_seq, manager, history,
                       last_metrics):
        error = None
        for rank in range(world):
            blob = rt.kv_get(f"__train__/{run_id}/{rank}/error")
            if blob is not None and error is None:
                payload = cloudpickle.loads(blob)
                error = f"rank {payload['rank']}: {payload['error']}"
            while True:
                key = f"__train__/{run_id}/{rank}/{next_seq[rank]}"
                blob = rt.kv_get(key)
                if blob is None:
                    break
                next_seq[rank] += 1
                payload = cloudpickle.loads(blob)
                if rank == 0:
                    metrics = payload["metrics"]
                    history.append(metrics)
                    last_metrics = metrics
                    if payload.get("checkpoint_path"):
                        ckpt = Checkpoint(payload["checkpoint_path"])
                        manager.register(
                            ckpt, metrics, metrics.get("step", len(history))
                        )
        return last_metrics, error
