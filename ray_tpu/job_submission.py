"""Job submission.

Ref analogue: dashboard/modules/job/sdk.py JobSubmissionClient (:39) +
job_manager.py JobSupervisor: a submitted job runs its shell entrypoint
inside a supervisor ACTOR on the cluster (so the job lands where the
scheduler puts it, not in the client process), with stdout/stderr captured
to the GCS KV for `rtpu logs` streaming and a status record
(PENDING → RUNNING → SUCCEEDED/FAILED/STOPPED) the client polls.

The supervisor exports RAY_TPU_ADDRESS into the child so a script that
calls ``ray_tpu.init()`` attaches to the SAME cluster as its own driver.
"""

from __future__ import annotations

import enum
import json
import os
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_STATUS_KEY = "job:{}:status"
_LOGS_KEY = "job:{}:logs"
_LIST_KEY = "jobs:index"
MAX_LOG_BYTES = 1 << 20  # KV log tail cap per job


class JobStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    @classmethod
    def terminal(cls, s: "JobStatus") -> bool:
        return s in (cls.SUCCEEDED, cls.FAILED, cls.STOPPED)


class _JobSupervisor:
    """Actor hosting one job's entrypoint subprocess (ref:
    job_manager.py JobSupervisor)."""

    def __init__(self, job_id: str, entrypoint: str,
                 env: Optional[Dict[str, str]], working_dir: Optional[str]):
        self._job_id = job_id
        self._entrypoint = entrypoint
        self._env = env or {}
        self._working_dir = working_dir
        self._proc: Optional[subprocess.Popen] = None
        self._log_buf = bytearray()
        self._lock = threading.Lock()
        self._status = JobStatus.PENDING
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- KV helpers (run inside the worker: kv goes through the runtime) --

    def _kv_put(self, key: str, value: bytes):
        import ray_tpu

        ray_tpu.kv_put(key, value)

    def _set_status(self, status: JobStatus, message: str = ""):
        self._status = status
        self._kv_put(
            _STATUS_KEY.format(self._job_id),
            json.dumps({
                "status": status.value,
                "message": message,
                "entrypoint": self._entrypoint,
                "timestamp": time.time(),
            }).encode(),
        )

    def _flush_logs(self):
        with self._lock:
            data = bytes(self._log_buf[-MAX_LOG_BYTES:])
        self._kv_put(_LOGS_KEY.format(self._job_id), data)

    def _run(self):
        try:
            env = dict(os.environ)
            env.update(self._env)
            # The job's own ray_tpu.init() must attach to this cluster.
            addr = env.get("RAY_TPU_ADDRESS") or _gcs_address_of_runtime()
            if addr:
                env["RAY_TPU_ADDRESS"] = addr
            env["RAY_TPU_JOB_ID"] = self._job_id
            self._set_status(JobStatus.RUNNING)
            self._proc = subprocess.Popen(
                self._entrypoint,
                shell=True,
                cwd=self._working_dir or None,
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
            last_flush = 0.0
            for line in iter(self._proc.stdout.readline, b""):
                with self._lock:
                    self._log_buf += line
                now = time.monotonic()
                if now - last_flush > 0.25:
                    self._flush_logs()
                    last_flush = now
            code = self._proc.wait()
            self._flush_logs()
            if self._status == JobStatus.STOPPED:
                return
            if code == 0:
                self._set_status(JobStatus.SUCCEEDED)
            else:
                self._set_status(JobStatus.FAILED, f"exit code {code}")
        except Exception as e:  # noqa: BLE001
            try:
                self._flush_logs()
                self._set_status(JobStatus.FAILED, repr(e))
            except Exception:
                pass

    # -- actor methods --

    def status(self) -> str:
        return self._status.value

    def stop(self) -> str:
        self._set_status(JobStatus.STOPPED, "stopped by user")
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        self._flush_logs()
        return JobStatus.STOPPED.value

    def ping(self) -> str:
        return "ok"


def _gcs_address_of_runtime() -> Optional[str]:
    """The GCS address of the cluster this process is attached to."""
    try:
        from .core import runtime_context

        rt = runtime_context.current_runtime_or_none()
        nm = getattr(rt, "_nm", None)
        if nm is not None and nm.gcs_service is not None:
            host, port = nm.gcs_service.address
            return f"{host}:{port}"
        if nm is not None and nm.gcs_address is not None:
            host, port = nm.gcs_address
            return f"{host}:{port}"
    except Exception:
        pass
    return os.environ.get("RAY_TPU_ADDRESS")


class JobSubmissionClient:
    """Submit/inspect/stop jobs on the connected cluster (ref:
    JobSubmissionClient; address handling is implicit — the client uses
    the runtime this process is already attached to)."""

    def __init__(self, address: Optional[str] = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)

    def submit_job(self, *, entrypoint: str,
                   env: Optional[Dict[str, str]] = None,
                   working_dir: Optional[str] = None,
                   job_id: Optional[str] = None) -> str:
        import ray_tpu

        job_id = job_id or f"job-{uuid.uuid4().hex[:10]}"
        supervisor = ray_tpu.remote(_JobSupervisor).options(
            name=f"__job_supervisor_{job_id}__"
        ).remote(job_id, entrypoint, env, working_dir)
        ray_tpu.get(supervisor.ping.remote())
        index = self.list_jobs()
        index.append(job_id)
        ray_tpu.kv_put(_LIST_KEY, json.dumps(index).encode())
        # Pin the supervisor under its job id for stop()/status().
        self._supervisors = getattr(self, "_supervisors", {})
        self._supervisors[job_id] = supervisor
        return job_id

    def _supervisor(self, job_id: str):
        import ray_tpu

        sup = getattr(self, "_supervisors", {}).get(job_id)
        if sup is not None:
            return sup
        return ray_tpu.get_actor(f"__job_supervisor_{job_id}__")

    def get_job_status(self, job_id: str) -> JobStatus:
        import ray_tpu

        raw = ray_tpu.kv_get(_STATUS_KEY.format(job_id))
        if raw is None:
            return JobStatus.PENDING
        return JobStatus(json.loads(raw)["status"])

    def get_job_info(self, job_id: str) -> Dict[str, Any]:
        import ray_tpu

        raw = ray_tpu.kv_get(_STATUS_KEY.format(job_id))
        return json.loads(raw) if raw else {"status": "PENDING"}

    def get_job_logs(self, job_id: str) -> str:
        import ray_tpu

        raw = ray_tpu.kv_get(_LOGS_KEY.format(job_id))
        return (raw or b"").decode("utf-8", "replace")

    def tail_job_logs(self, job_id: str, *, poll_interval_s: float = 0.25):
        """Generator of new log chunks until the job reaches a terminal
        state (ref: tail_job_logs)."""
        seen = 0
        while True:
            logs = self.get_job_logs(job_id)
            if len(logs) > seen:
                yield logs[seen:]
                seen = len(logs)
            status = self.get_job_status(job_id)
            if JobStatus.terminal(status):
                logs = self.get_job_logs(job_id)
                if len(logs) > seen:
                    yield logs[seen:]
                return
            time.sleep(poll_interval_s)

    def stop_job(self, job_id: str) -> bool:
        import ray_tpu

        try:
            sup = self._supervisor(job_id)
            ray_tpu.get(sup.stop.remote(), timeout=10.0)
            return True
        except Exception:
            return False

    def list_jobs(self) -> List[str]:
        import ray_tpu

        raw = ray_tpu.kv_get(_LIST_KEY)
        return json.loads(raw) if raw else []

    def wait_until_finish(self, job_id: str, timeout: float = 300.0
                          ) -> JobStatus:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            s = self.get_job_status(job_id)
            if JobStatus.terminal(s):
                return s
            time.sleep(0.25)
        raise TimeoutError(f"job {job_id} still running after {timeout}s")
