"""Dashboard single-page UI (served at /).

Ref analogue: dashboard/client/src/ — the reference ships a 19.5k-LoC
React app built with npm; this is the no-build-step equivalent: one
vanilla-JS page with the same information architecture (overview tiles,
nodes, tasks/actors/objects/workers tables with filtering, user
metrics, on-demand profiling) over the same ``/api/*`` surface, auto-
refreshing. No external assets — it works inside an airgapped cluster.
"""

PAGE = r"""<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
:root { --bg:#0e1117; --panel:#161b24; --line:#242b38; --txt:#dce3ee;
        --dim:#8b97a8; --acc:#5aa2ff; --ok:#39c07b; --warn:#e6b450;
        --err:#e5604c; }
* { box-sizing:border-box; }
body { margin:0; background:var(--bg); color:var(--txt);
       font:13px/1.5 system-ui,-apple-system,'Segoe UI',sans-serif; }
header { display:flex; align-items:center; gap:16px;
         padding:10px 20px; background:var(--panel);
         border-bottom:1px solid var(--line); }
header h1 { font-size:15px; margin:0; font-weight:600; }
header .sub { color:var(--dim); font-size:12px; }
nav { display:flex; gap:2px; padding:0 12px; background:var(--panel);
      border-bottom:1px solid var(--line); }
nav button { background:none; border:none; color:var(--dim);
             padding:9px 14px; cursor:pointer; font:inherit;
             border-bottom:2px solid transparent; }
nav button.on { color:var(--txt); border-bottom-color:var(--acc); }
main { padding:16px 20px; max-width:1280px; margin:0 auto; }
.tiles { display:grid; grid-template-columns:repeat(auto-fill,
         minmax(170px,1fr)); gap:10px; margin-bottom:16px; }
.tile { background:var(--panel); border:1px solid var(--line);
        border-radius:8px; padding:12px 14px; }
.tile .v { font-size:22px; font-weight:650; }
.tile .k { color:var(--dim); font-size:11px;
           text-transform:uppercase; letter-spacing:.05em; }
table { border-collapse:collapse; width:100%; background:var(--panel);
        border:1px solid var(--line); border-radius:8px;
        overflow:hidden; }
th,td { text-align:left; padding:6px 10px;
        border-bottom:1px solid var(--line); white-space:nowrap; }
th { color:var(--dim); font-size:11px; text-transform:uppercase;
     letter-spacing:.05em; position:sticky; top:0;
     background:var(--panel); }
tr:last-child td { border-bottom:none; }
td.num { font-variant-numeric:tabular-nums; }
.pill { display:inline-block; padding:1px 8px; border-radius:999px;
        font-size:11px; }
.pill.ok { background:rgba(57,192,123,.15); color:var(--ok); }
.pill.warn { background:rgba(230,180,80,.15); color:var(--warn); }
.pill.err { background:rgba(229,96,76,.15); color:var(--err); }
.pill.dim { background:rgba(139,151,168,.15); color:var(--dim); }
.bar { height:6px; background:var(--line); border-radius:3px;
       min-width:80px; }
.bar i { display:block; height:100%; border-radius:3px;
         background:var(--acc); }
.controls { display:flex; gap:10px; margin-bottom:10px;
            align-items:center; }
input,select { background:var(--panel); color:var(--txt);
               border:1px solid var(--line); border-radius:6px;
               padding:5px 9px; font:inherit; }
button.act { background:var(--acc); color:#fff; border:none;
             border-radius:6px; padding:6px 12px; cursor:pointer; }
pre { background:var(--panel); border:1px solid var(--line);
      border-radius:8px; padding:12px; overflow:auto; }
.muted { color:var(--dim); }
#err { color:var(--err); padding:4px 0; }
</style></head><body>
<header><h1>ray_tpu</h1><span class="sub" id="clock"></span>
  <span style="flex:1"></span>
  <label class="sub"><input type="checkbox" id="auto" checked>
    auto-refresh</label>
  <button class="act" onclick="refresh()">refresh</button></header>
<nav id="nav"></nav>
<main><div id="err"></div><div id="view"></div></main>
<script>
const TABS = ["overview","tasks","actors","objects","workers",
              "metrics","profile"];
let tab = location.hash.slice(1) || "overview";
let D = {nodes:[],tasks:[],actors:[],objects:[],workers:[],
         tsum:{},asum:{},osum:{},metrics:{}};
let filter = "";

function h(s){return String(s==null?"":s).replace(/[&<>"]/g,
  c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));}
function short(s){s=String(s||"");return s.length>12?s.slice(0,12):s;}
function mb(b){b=+b||0;return b>1<<30?(b/(1<<30)).toFixed(2)+" GiB":
  b>1<<20?(b/(1<<20)).toFixed(1)+" MiB":
  b>1024?(b/1024).toFixed(1)+" KiB":b+" B";}
function pill(s){const m={alive:"ok",running:"ok",finished:"dim",
  done:"dim",idle:"dim",pending:"warn",waiting:"warn",queued:"warn",
  dead:"err",failed:"err",error:"err"};
  return `<span class="pill ${m[String(s).toLowerCase()]||"dim"}">`+
         `${h(s)}</span>`;}

async function j(u){const r=await fetch(u);return r.json();}
async function load(){
  try{
    const [nodes,tsum,asum,osum]=await Promise.all([
      j("/api/nodes"),j("/api/summary/tasks"),
      j("/api/summary/actors"),j("/api/summary/objects")]);
    D.nodes=nodes;D.tsum=tsum;D.asum=asum;D.osum=osum;
    if(tab=="tasks")D.tasks=await j("/api/tasks");
    if(tab=="actors")D.actors=await j("/api/actors");
    if(tab=="objects")D.objects=await j("/api/objects");
    if(tab=="workers")D.workers=await j("/api/workers");
    if(tab=="metrics")D.metrics=await j("/api/metrics");
    document.getElementById("err").textContent="";
  }catch(e){document.getElementById("err").textContent=
    "fetch failed: "+e;}
  render();
}

function table(rows,cols){
  if(!rows.length)return '<p class="muted">none</p>';
  const f=filter.toLowerCase();
  const vis=f?rows.filter(r=>JSON.stringify(r).toLowerCase()
    .includes(f)):rows;
  let out="<table><tr>"+cols.map(c=>`<th>${h(c[0])}</th>`).join("")+
    "</tr>";
  for(const r of vis.slice(0,500))
    out+="<tr>"+cols.map(c=>`<td class="${c[2]||""}">${c[1](r)}</td>`)
      .join("")+"</tr>";
  out+="</table>";
  if(vis.length>500)out+=`<p class="muted">showing 500 of `+
    `${vis.length}</p>`;
  return out;
}
function controls(){return `<div class="controls">
  <input placeholder="filter…" value="${h(filter)}"
    oninput="filter=this.value;render()"></div>`;}

function viewOverview(){
  const alive=D.nodes.filter(n=>n.Alive).length;
  const res={};const avail={};
  for(const n of D.nodes){if(!n.Alive)continue;
    for(const[k,v]of Object.entries(n.Resources||{}))
      res[k]=(res[k]||0)+v;
    for(const[k,v]of Object.entries(n.Available||n.ResourcesAvailable
      ||{}))avail[k]=(avail[k]||0)+v;}
  const ts=D.tsum.by_state||D.tsum;  // summarize_tasks nests states
  const running=ts.running||0,
        pending=(ts.pending||0)+(ts.queued||0)+(ts.waiting||0),
        failed=D.tsum.failed||ts.failed||0;
  let t=`<div class="tiles">
    <div class="tile"><div class="v">${alive}</div>
      <div class="k">alive nodes</div></div>
    <div class="tile"><div class="v">${running}</div>
      <div class="k">running tasks</div></div>
    <div class="tile"><div class="v">${pending}</div>
      <div class="k">pending tasks</div></div>
    <div class="tile"><div class="v">${failed}</div>
      <div class="k">failed tasks</div></div>
    <div class="tile"><div class="v">${D.asum.alive||0}</div>
      <div class="k">alive actors</div></div>
    <div class="tile"><div class="v">${D.osum.total_objects||0}</div>
      <div class="k">objects</div></div>
    <div class="tile"><div class="v">`+
      `${mb(D.osum.total_size_bytes||0)}</div>
      <div class="k">object bytes</div></div></div>`;
  t+="<h3>resources</h3><table><tr><th>resource</th><th>used</th>"+
     "<th>total</th><th></th></tr>";
  for(const k of Object.keys(res).sort()){
    const total=res[k],free=avail[k]??total,used=total-free;
    const pct=total?Math.round(100*used/total):0;
    t+=`<tr><td>${h(k)}</td><td class="num">${used.toFixed(1)}</td>
      <td class="num">${total.toFixed(1)}</td>
      <td><div class="bar"><i style="width:${pct}%"></i></div></td>
      </tr>`;}
  const epoch=Math.max(0,...D.nodes.map(n=>n.Epoch||0));
  t+=`</table><h3>nodes (membership epoch ${epoch})</h3>`+table(D.nodes,[
    ["id",n=>short(n.NodeID)],["state",n=>pill(n.State||
      (n.Alive?"alive":"dead"))],
    ["inc",n=>h(n.Incarnation||1)],
    ["host",n=>h(n.NodeManagerAddress||n.Host||"")],
    ["head",n=>n.IsHead?"head":""],
    ["resources",n=>h(Object.entries(n.Resources||{})
      .map(([k,v])=>`${k}:${v}`).join(" "))],
  ]);
  return t;
}
function viewTasks(){return controls()+table(D.tasks,[
  ["task",t=>h(t.name||t.func_or_class_name||"")],
  ["id",t=>short(t.task_id)],["state",t=>pill(t.state)],
  ["node",t=>short(t.node_id)],
  ["type",t=>h(t.type||"")]]);}
function viewActors(){return controls()+table(D.actors,[
  ["class",a=>h(a.class_name||"")],["id",a=>short(a.actor_id)],
  ["state",a=>pill(a.state)],["name",a=>h(a.name||"")],
  ["node",a=>short(a.node_id)],["pid",a=>h(a.pid||"")]]);}
function viewObjects(){return controls()+table(D.objects,[
  ["object",o=>short(o.object_id)],
  ["size",o=>mb(o.size_bytes),"num"],
  ["state",o=>h(o.state||o.where||"")],
  ["owner",o=>h(o.owner||"")],
  ["refs",o=>h(o.refcount==null?"":o.refcount),"num"],
  ["age(s)",o=>h(o.age_s==null?"":o.age_s),"num"],
  ["node",o=>short(o.node_id)]]);}
function viewWorkers(){return controls()+table(D.workers,[
  ["worker",w=>short(w.worker_id)],["state",w=>pill(w.state)],
  ["type",w=>h(w.worker_type||"")],["pid",w=>h(w.pid||"")],
  ["node",w=>short(w.node_id)]]);}
function viewMetrics(){
  let t=`<p class="muted">Prometheus exposition at
    <a href="/metrics" style="color:var(--acc)">/metrics</a></p>`;
  const names=Object.keys(D.metrics);
  if(!names.length)return t+'<p class="muted">no user metrics</p>';
  for(const name of names.sort()){
    const m=D.metrics[name];
    t+=`<h3>${h(name)} <span class="muted">(${h(m.type)})</span></h3>`+
      "<table><tr><th>labels</th><th>value</th></tr>";
    for(const[k,v]of Object.entries(m.series))
      t+=`<tr><td>${h(k)}</td><td class="num">`+
         `${typeof v=="number"?v.toFixed(3):h(JSON.stringify(v))}`+
         `</td></tr>`;
    t+="</table>";}
  return t;
}
function viewProfile(){
  return `<div class="controls">
    <label>seconds <input id="psec" value="2" size="3"></label>
    <button class="act" onclick="profile()">sample stacks</button>
    </div><div id="prof" class="muted">On-demand wall-clock stack
    sampling of the whole cluster — every node manager and worker
    (collapsed-stack format — paste into any flamegraph
    renderer).</div>`;
}
async function profile(){
  const el=document.getElementById("prof");
  el.textContent="sampling…";
  const s=document.getElementById("psec").value||"2";
  const d=await j("/api/profile?seconds="+s);
  const rows=Object.entries(d.counts||{}).sort((a,b)=>b[1]-a[1]);
  let t=`<p>${rows.length} distinct stacks, `+
    `${d.samples||""} samples across `+
    `${(d.nodes||[]).length} node(s)</p>`;
  const errs=Object.entries(d.errors||{});
  if(errs.length)t+=`<p class="muted">partial: `+
    errs.map(([n,e])=>`${n.slice(0,8)}: ${h(e)}`).join(", ")+`</p>`;
  t+="<pre>";
  for(const[st,n]of rows.slice(0,40))t+=`${n}\t${h(st)}\n`;
  el.innerHTML=t+"</pre>";
}

const VIEWS={overview:viewOverview,tasks:viewTasks,actors:viewActors,
  objects:viewObjects,workers:viewWorkers,metrics:viewMetrics,
  profile:viewProfile};
function render(){
  document.getElementById("nav").innerHTML=TABS.map(t=>
    `<button class="${t==tab?"on":""}"
      onclick="go('${t}')">${t}</button>`).join("");
  document.getElementById("view").innerHTML=VIEWS[tab]();
  document.getElementById("clock").textContent=
    new Date().toLocaleTimeString();
}
function go(t){tab=t;location.hash=t;load();}
function refresh(){load();}
setInterval(()=>{if(document.getElementById("auto").checked &&
  tab!="profile")load();},2000);
load();
</script></body></html>"""
