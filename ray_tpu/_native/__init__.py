"""Loader for ray_tpu's native (C++) components.

The CPython extension ``_rtstore`` (shared-memory object store, see
src/store/) is built in-place by the repo Makefile. On first import, if the
.so is missing and a toolchain is available, we build it on demand; callers
fall back to the pure-Python store when the native module is unavailable, so
the framework works (slower) on machines without g++.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading

_lock = threading.Lock()
_rtstore_mod = None
_build_attempted = False

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(os.path.dirname(_PKG_DIR))


def _try_import():
    try:
        from . import _rtstore  # type: ignore

        return _rtstore
    except ImportError:
        return None


def _try_build() -> bool:
    makefile = os.path.join(_REPO_ROOT, "Makefile")
    if not os.path.exists(makefile):
        return False
    try:
        proc = subprocess.run(
            ["make", "-C", _REPO_ROOT, "native", f"PY={sys.executable}"],
            capture_output=True,
            timeout=120,
        )
        return proc.returncode == 0
    except Exception:
        return False


def load_rtstore():
    """Return the _rtstore extension module, building it if needed, or None."""
    global _rtstore_mod, _build_attempted
    with _lock:
        if _rtstore_mod is not None:
            return _rtstore_mod
        _rtstore_mod = _try_import()
        if _rtstore_mod is None and not _build_attempted:
            _build_attempted = True
            if os.environ.get("RAY_TPU_NO_NATIVE_BUILD") != "1" and _try_build():
                _rtstore_mod = _try_import()
        return _rtstore_mod


def native_store_available() -> bool:
    return load_rtstore() is not None
