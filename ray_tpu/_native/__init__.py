"""Loader for ray_tpu's native (C++) components.

Two CPython extensions are built in-place by the repo Makefile:

* ``_rtstore`` — shared-memory object store (src/store/)
* ``_rtpump``  — direct-plane frame pump: framed-channel I/O, call-frame
  codec, per-channel seq dispatch (src/pump/)

On first import, if a .so is missing and a toolchain is available, we build
on demand; callers fall back to the pure-Python implementations when a
native module is unavailable, so the framework works (slower) on machines
without g++. ``RAY_TPU_NO_NATIVE_BUILD=1`` suppresses the on-demand build;
``RTPU_NO_NATIVE=1`` makes the frame-pump callers ignore the extension even
when present (see core/frame_pump.py).
"""

from __future__ import annotations

import importlib
import os
import subprocess
import sys
import threading

_lock = threading.Lock()
_mods: dict = {}
_build_attempted = False

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(os.path.dirname(_PKG_DIR))


def _try_import(name: str):
    try:
        return importlib.import_module(f".{name}", __name__)
    except ImportError:
        return None


def _try_build() -> bool:
    makefile = os.path.join(_REPO_ROOT, "Makefile")
    if not os.path.exists(makefile):
        return False
    try:
        proc = subprocess.run(
            ["make", "-C", _REPO_ROOT, "native", f"PY={sys.executable}"],
            capture_output=True,
            timeout=120,
        )
        return proc.returncode == 0
    except Exception:
        return False


def _load(name: str):
    """Return the named extension module, building once if needed."""
    global _build_attempted
    with _lock:
        mod = _mods.get(name)
        if mod is not None:
            return mod
        mod = _try_import(name)
        if mod is None and not _build_attempted:
            _build_attempted = True
            if os.environ.get("RAY_TPU_NO_NATIVE_BUILD") != "1" and _try_build():
                mod = _try_import(name)
        if mod is not None:
            _mods[name] = mod
        return mod


def load_rtstore():
    """The _rtstore extension module, building it if needed, or None."""
    return _load("_rtstore")


def load_rtpump():
    """The _rtpump extension module, building it if needed, or None."""
    return _load("_rtpump")


def native_store_available() -> bool:
    return load_rtstore() is not None
