"""DataContext: execution knobs (ref: python/ray/data/context.py
DataContext singleton)."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    # Streaming backpressure: max concurrently in-flight block tasks per
    # operator chain (ref analogue: ConcurrencyCapBackpressurePolicy in
    # _internal/execution/backpressure_policy/).
    max_in_flight_tasks: int = 8
    # Resource-aware backpressure (ref analogue: the output-size /
    # object-store-usage policies): stages stop SUBMITTING new block
    # tasks while the local object store is fuller than this fraction —
    # a slow consumer therefore bounds producer memory instead of
    # filling the store / forcing spills. <= 0 disables.
    store_usage_cap_fraction: float = 0.8
    # Prefetch depth for iter_batches / device feed.
    prefetch_batches: int = 2
    use_remote_tasks: bool = True
    # Shuffle plan: None = auto (push-based merge stage at >=16 input
    # blocks — ref: _internal/push_based_shuffle.py), True/False forces.
    push_based_shuffle: "bool | None" = None

    _instance = None

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance
