"""Random-access view over a Dataset.

Ref analogue: python/ray/data/random_access_dataset.py
(RandomAccessDataset) — the dataset is partitioned on a key across a
pool of actors, each holding its partition in memory with a hash index;
``get_async``/``multiget`` route keys to the owning actor. The reference
range-partitions via a global sort; here partitioning is by stable key
HASH, which serves the same point-lookup API without a distributed sort
and keeps construction fully remote: one task per input block splits
rows into per-partition buckets, and each serving actor materializes
only ITS buckets (the driver handles refs, never rows)."""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional

import ray_tpu


def _hash_key(key) -> int:
    """Deterministic across processes (unlike builtin hash for str).
    Numpy scalars normalize to native python first — repr(np.int64(42))
    differs from repr(42) and would route to the wrong partition."""
    if hasattr(key, "item"):
        key = key.item()
    return int(
        hashlib.md5(repr(key).encode()).hexdigest()[:8], 16
    )


def _split_block(block, key: str, n: int):
    """Remote task: bucket one block's rows by key hash (num_returns=n)."""
    from .block import BlockAccessor

    buckets: List[List[Dict[str, Any]]] = [[] for _ in range(n)]
    for row in BlockAccessor(block).iter_rows():
        row = dict(row)
        buckets[_hash_key(row[key]) % n].append(row)
    return tuple(buckets) if n > 1 else buckets[0]


class _PartitionServer:
    """One hash partition, indexed by key (actor). ``bucket_lists``
    arrive as resolved task outputs — the rows travel store-to-actor."""

    def __init__(self, key: str, *bucket_lists):
        self._index = {}
        for rows in bucket_lists:
            for r in rows:
                self._index[r[key]] = r

    def get(self, key):
        return self._index.get(key)

    def multiget(self, keys: List[Any]):
        return [self._index.get(k) for k in keys]

    def stats(self) -> Dict[str, int]:
        return {"rows": len(self._index)}


class RandomAccessDataset:
    """Built via :meth:`Dataset.to_random_access`."""

    def __init__(self, dataset, key: str, *, num_workers: int = 2):
        n = max(1, int(num_workers))
        self._key = key
        self._n = n
        splitter = ray_tpu.remote(num_returns=n)(_split_block)
        from .streaming_executor import execute_refs

        # Block REFS go straight into the splitter tasks — the rows
        # travel store-to-worker, never through the driver.
        bucket_refs: List[List[Any]] = []  # [block][partition]
        for item in execute_refs(dataset._sources, dataset._stages):
            out = splitter.remote(item, key, n)
            bucket_refs.append([out] if n == 1 else list(out))
        server = ray_tpu.remote(_PartitionServer)
        self._actors = [
            server.remote(key, *[row_refs[p] for row_refs in bucket_refs])
            for p in range(n)
        ]
        # Readiness gate: constructors hold the rows.
        ray_tpu.get([a.stats.remote() for a in self._actors])

    def _owner(self, key) -> int:
        return _hash_key(key) % self._n

    def get_async(self, key):
        """ObjectRef resolving to the row (or None)."""
        return self._actors[self._owner(key)].get.remote(key)

    def get(self, key, timeout: Optional[float] = 30.0):
        return ray_tpu.get(self.get_async(key), timeout=timeout)

    def multiget(self, keys: List[Any],
                 timeout: Optional[float] = 60.0) -> List[Any]:
        """Batched lookup: one actor call per owning partition, results
        re-assembled in input order (ref: multiget batching)."""
        by_owner: Dict[int, List[int]] = {}
        for pos, k in enumerate(keys):
            by_owner.setdefault(self._owner(k), []).append(pos)
        refs = {
            owner: self._actors[owner].multiget.remote(
                [keys[p] for p in positions]
            )
            for owner, positions in by_owner.items()
        }
        out: List[Any] = [None] * len(keys)
        for owner, positions in by_owner.items():
            vals = ray_tpu.get(refs[owner], timeout=timeout)
            for p, v in zip(positions, vals):
                out[p] = v
        return out

    def stats(self) -> Dict[str, Any]:
        per = ray_tpu.get([a.stats.remote() for a in self._actors])
        return {
            "num_partitions": len(self._actors),
            "total_rows": sum(s["rows"] for s in per),
            "partition_rows": [s["rows"] for s in per],
        }

    def destroy(self):
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
