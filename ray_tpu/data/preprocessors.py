"""Dataset preprocessors.

Ref analogue: python/ray/data/preprocessor.py Preprocessor (fit/transform
statefulness) + data/preprocessors/{scaler,encoder,concatenator,chain}.py.
``fit`` computes statistics WITH the dataset's own distributed aggregates
(blocks stream through remote tasks; only the per-column stats come back
to the driver); ``transform`` appends a fused per-batch op to the plan.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Preprocessor:
    """Base: fit() learns state from a Dataset, transform() applies it
    lazily as a map_batches stage."""

    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(
                f"{type(self).__name__} must be fit before transform"
            )
        return ds.map_batches(self._transform_numpy)

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform_batch(self, batch: Dict[str, np.ndarray]):
        """Apply to one in-memory batch (serving-time path; ref:
        preprocessor.py transform_batch)."""
        return self._transform_numpy(dict(batch))

    # -- subclass hooks --

    def _needs_fit(self) -> bool:
        return True

    def _fit(self, ds) -> None:
        raise NotImplementedError

    def _transform_numpy(self, batch: Dict[str, np.ndarray]):
        raise NotImplementedError


def _col_stats(ds, columns: List[str]) -> Dict[str, Dict[str, float]]:
    """One streaming pass computing per-column aggregates
    (sum/sumsq/min/max/count — one fused pass covers every scaler)."""

    def per_block(batch: Dict[str, np.ndarray]):
        out = {}
        for c in columns:
            v = batch[c].astype(np.float64)
            out[f"{c}/sum"] = np.asarray([v.sum()])
            out[f"{c}/sumsq"] = np.asarray([(v * v).sum()])
            out[f"{c}/min"] = np.asarray(
                [v.min() if v.size else np.inf]
            )
            out[f"{c}/max"] = np.asarray(
                [v.max() if v.size else -np.inf]
            )
            out[f"{c}/count"] = np.asarray([float(v.size)])
        return out

    parts = ds.map_batches(per_block, batch_size=None).to_numpy()
    stats: Dict[str, Dict[str, float]] = {}
    for c in columns:
        stats[c] = {
            "sum": float(parts[f"{c}/sum"].sum()),
            "sumsq": float(parts[f"{c}/sumsq"].sum()),
            "min": float(parts[f"{c}/min"].min()),
            "max": float(parts[f"{c}/max"].max()),
            "count": float(parts[f"{c}/count"].sum()),
        }
    return stats


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (ref: preprocessors/scaler.py)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        stats = _col_stats(ds, self.columns)
        for c, s in stats.items():
            mean = s["sum"] / max(s["count"], 1.0)
            var = s["sumsq"] / max(s["count"], 1.0) - mean * mean
            self.stats_[c] = (mean, float(np.sqrt(max(var, 0.0))))

    def _transform_numpy(self, batch):
        for c, (mean, std) in self.stats_.items():
            if c in batch:
                batch[c] = (batch[c] - mean) / (std or 1.0)
        return batch


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        stats = _col_stats(ds, self.columns)
        for c, s in stats.items():
            self.stats_[c] = (s["min"], s["max"])

    def _transform_numpy(self, batch):
        for c, (lo, hi) in self.stats_.items():
            if c in batch:
                span = (hi - lo) or 1.0
                batch[c] = (batch[c] - lo) / span
        return batch


class LabelEncoder(Preprocessor):
    """Categorical column -> dense int codes (ref: encoder.py)."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: Optional[np.ndarray] = None

    def _fit(self, ds) -> None:
        col = self.label_column

        def uniques(batch):
            return {"u": np.unique(batch[col])}

        parts = ds.map_batches(uniques, batch_size=None).to_numpy()
        self.classes_ = np.unique(parts["u"])

    def _transform_numpy(self, batch):
        c = self.label_column
        batch[c] = np.searchsorted(self.classes_, batch[c])
        return batch


class OneHotEncoder(Preprocessor):
    """Categorical columns -> one-hot float matrices."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.classes_: Dict[str, np.ndarray] = {}

    def _fit(self, ds) -> None:
        for c in self.columns:
            def uniques(batch, c=c):
                return {"u": np.unique(batch[c])}

            parts = ds.map_batches(uniques, batch_size=None).to_numpy()
            self.classes_[c] = np.unique(parts["u"])

    def _transform_numpy(self, batch):
        for c, classes in self.classes_.items():
            codes = np.searchsorted(classes, batch[c])
            eye = np.eye(len(classes), dtype=np.float32)
            batch[c] = eye[codes]
        return batch


class Concatenator(Preprocessor):
    """Concatenate feature columns into one 2-D matrix column (ref:
    preprocessors/concatenator.py — the trainer-ingest adapter)."""

    def __init__(self, columns: List[str], *, output_column_name: str =
                 "concat_out", dtype=np.float32):
        self.columns = list(columns)
        self.output_column_name = output_column_name
        self.dtype = dtype

    def _needs_fit(self) -> bool:
        return False

    def _fit(self, ds) -> None:
        pass

    def _transform_numpy(self, batch):
        mats = []
        for c in self.columns:
            v = np.asarray(batch.pop(c))
            if v.ndim == 1:
                v = v[:, None]
            mats.append(v.astype(self.dtype))
        batch[self.output_column_name] = np.concatenate(mats, axis=1)
        return batch


class Chain(Preprocessor):
    """Sequential composition of preprocessors (ref: chain.py)."""

    def __init__(self, *preprocessors: Preprocessor):
        self.preprocessors = list(preprocessors)

    def fit(self, ds) -> "Chain":
        # Each stage fits on the data as transformed by the previous ones.
        cur = ds
        for p in self.preprocessors:
            p.fit(cur)
            cur = p.transform(cur)
        self._fitted = True
        return self

    def _fit(self, ds) -> None:  # pragma: no cover - fit() overridden
        pass

    def transform(self, ds):
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds

    def _transform_numpy(self, batch):
        for p in self.preprocessors:
            batch = p._transform_numpy(batch)
        return batch
