"""Blocks: the unit of distributed data.

Ref analogue: python/ray/data/block.py — Block (Arrow table) +
BlockAccessor (:192) + BlockMetadata. Canonical block format is a
pyarrow.Table; accessors convert to/from numpy-dict and row-dict views.
Tensor columns (ndim > 1) are stored as FixedSizeList columns and restored
to numpy with shape metadata.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa

Block = pa.Table
_SHAPE_META = b"ray_tpu:shape"


def from_numpy_dict(data: Dict[str, np.ndarray]) -> Block:
    """Build a block from named numpy arrays (tensor columns allowed)."""
    arrays, fields = [], []
    n = None
    for name, arr in data.items():
        arr = np.asarray(arr)
        n = len(arr) if n is None else n
        if len(arr) != n:
            raise ValueError("column length mismatch")
        if arr.ndim == 1:
            pa_arr = pa.array(arr)
            field = pa.field(name, pa_arr.type)
        else:
            inner = int(np.prod(arr.shape[1:]))
            flat = np.ascontiguousarray(arr).reshape(n * inner)
            values = pa.array(flat)
            pa_arr = pa.FixedSizeListArray.from_arrays(values, inner)
            field = pa.field(
                name, pa_arr.type,
                metadata={_SHAPE_META: repr(arr.shape[1:]).encode()},
            )
        arrays.append(pa_arr)
        fields.append(field)
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def from_rows(rows: List[Dict[str, Any]]) -> Block:
    if not rows:
        return pa.table({})
    # Column set = UNION of all rows' keys (rows[0] alone silently drops
    # fields absent from the first row); absent values become nulls.
    names: List[str] = []
    for r in rows:
        for k in r:
            if k not in names:
                names.append(k)
    cols: Dict[str, list] = {k: [] for k in names}
    for r in rows:
        for k in names:
            cols[k].append(r.get(k))
    arrays: Dict[str, Any] = {}
    np_cols = {}
    for k, v in cols.items():
        if any(isinstance(x, (bytes, bytearray)) for x in v):
            # Keep bytes as arrow binary: numpy's |S coercion strips
            # trailing NUL bytes (silent payload corruption). Mixed
            # str values encode (utf-8) rather than crashing.
            arrays[k] = pa.array(
                [None if x is None
                 else x.encode() if isinstance(x, str) else bytes(x)
                 for x in v],
                type=pa.binary(),
            )
            continue
        try:
            np_cols[k] = np.asarray(v)
            if np_cols[k].dtype == object:
                raise TypeError("object dtype: let arrow try")
        except Exception:
            np_cols.pop(k, None)
            arrays[k] = _build_column(v)
    if not arrays:
        return from_numpy_dict(np_cols)
    table = from_numpy_dict(np_cols) if np_cols else pa.table({})
    for k, arr in arrays.items():
        table = table.append_column(k, arr)
    # Preserve the caller's column order.
    return table.select([n for n in names if n in table.schema.names])


def _build_column(values: list) -> "pa.Array":
    """Robust arrow column: native inference first, then JSON text for
    nested python values arrow cannot type uniformly, then repr as the
    last resort — ingest degrades, it never crashes."""
    import json as _json

    try:
        return pa.array(values)
    except (pa.ArrowInvalid, pa.ArrowTypeError, TypeError):
        pass
    try:
        return pa.array(
            [None if v is None else _json.dumps(v, default=str)
             for v in values]
        )
    except Exception:
        return pa.array(
            [None if v is None else repr(v) for v in values]
        )


class BlockAccessor:
    """Read-side view over a block (ref: data/block.py BlockAccessor)."""

    def __init__(self, block: Block):
        self.block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        return self.block.num_rows

    def size_bytes(self) -> int:
        return self.block.nbytes

    def schema(self):
        return self.block.schema

    @staticmethod
    def _one_chunk(col):
        """ChunkedArray -> Array without copying when single-chunk (the
        common case for store-read blocks; combine_chunks copies even
        then)."""
        if isinstance(col, pa.ChunkedArray):
            return col.chunk(0) if col.num_chunks == 1 \
                else col.combine_chunks()
        return col

    @staticmethod
    def _arrow_to_numpy(arr) -> np.ndarray:
        """Arrow array -> numpy, ZERO-COPY when the buffers allow it
        (primitive dtype, no nulls): the numpy array then views the
        arrow buffer, which views the shared-memory mapping — the whole
        read path stays copy-free (SURVEY.md §5.8). Falls back to a
        copying conversion for nullable/non-primitive columns."""
        try:
            return arr.to_numpy(zero_copy_only=True)
        except Exception:
            return arr.to_numpy(zero_copy_only=False)

    def to_numpy(self) -> Dict[str, np.ndarray]:
        out = {}
        for i, name in enumerate(self.block.schema.names):
            col = self._one_chunk(self.block.column(i))
            field = self.block.schema.field(i)
            meta = field.metadata or {}
            if _SHAPE_META in meta:
                shape = eval(meta[_SHAPE_META].decode())  # noqa: S307 (own metadata)
                if isinstance(col, pa.FixedSizeListArray):
                    # .values is a zero-copy view — but it spans the
                    # WHOLE backing buffer, so apply the array's
                    # offset/length window (sliced blocks); the window
                    # slice stays zero-copy. .flatten() would copy.
                    lsize = col.type.list_size
                    flat = col.values[
                        col.offset * lsize:
                        (col.offset + len(col)) * lsize
                    ]
                else:
                    flat = col.flatten()
                arr = self._arrow_to_numpy(flat).reshape(
                    (self.block.num_rows,) + tuple(shape)
                )
            else:
                arr = self._arrow_to_numpy(col)
            out[name] = arr
        return out

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        cols = self.to_numpy()
        names = list(cols)
        for i in range(self.num_rows()):
            yield {k: cols[k][i] for k in names}

    def slice(self, start: int, end: int) -> Block:
        return self.block.slice(start, end - start)

    def take_indices(self, idx: np.ndarray) -> Block:
        return self.block.take(pa.array(idx))


def concat_blocks(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if b.num_rows > 0] or blocks[:1]
    if len(blocks) == 1:
        return blocks[0]
    # unify_schemas fails on metadata mismatch; use first schema.
    return pa.concat_tables(
        [b.cast(blocks[0].schema) for b in blocks]
    ).combine_chunks()


def normalize_to_block(data: Any) -> Block:
    """Accept a block in any supported user format."""
    if isinstance(data, pa.Table):
        return data
    if isinstance(data, dict):
        return from_numpy_dict(data)
    if isinstance(data, np.ndarray):
        return from_numpy_dict({"data": data})
    if isinstance(data, list):
        return from_rows(
            [r if isinstance(r, dict) else {"item": r} for r in data]
        )
    try:
        import pandas as pd

        if isinstance(data, pd.DataFrame):
            return pa.Table.from_pandas(data, preserve_index=False)
    except ImportError:
        pass
    raise TypeError(f"cannot convert {type(data)} to a Block")


def batch_to_format(block: Block, batch_format: str):
    acc = BlockAccessor(block)
    if batch_format in ("numpy", "default"):
        return acc.to_numpy()
    if batch_format == "pandas":
        return block.to_pandas()
    if batch_format in ("pyarrow", "arrow"):
        return block
    raise ValueError(f"unknown batch_format {batch_format!r}")
