"""File write sinks.

Ref analogue: python/ray/data/dataset.py write_parquet (:2823) /
write_csv / write_json over _internal/datasource/*_datasink.py. Each block
is written by its own remote task directly from wherever it lives (the
write is distributed — data never funnels through the driver), producing
one ``part-NNNNN.<ext>`` file per block, the reference's file layout.
"""

from __future__ import annotations

import os
from typing import Any, Callable, List


def _write_block(block, path: str, fmt: str, index: int,
                 write_kwargs: dict) -> str:
    import pyarrow as pa

    os.makedirs(path, exist_ok=True)
    fname = os.path.join(path, f"part-{index:05d}.{fmt}")
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(block, fname, **write_kwargs)
    elif fmt == "csv":
        import pyarrow.csv as pacsv

        pacsv.write_csv(block, fname, **write_kwargs)
    elif fmt == "json":
        # Newline-delimited JSON (the reference's JSON sink format).
        import json

        from .block import BlockAccessor

        with open(fname, "w") as f:
            for row in BlockAccessor(block).iter_rows():
                f.write(json.dumps(_jsonable(row)) + "\n")
    elif fmt == "npy":
        import numpy as np

        from .block import BlockAccessor

        cols = BlockAccessor(block).to_numpy()
        if len(cols) == 1:
            np.save(fname, next(iter(cols.values())))
        else:
            np.savez(fname, **cols)
    elif fmt == "webdataset":
        from .block import BlockAccessor
        from .webdataset import write_shard

        fname = fname[:-len(".webdataset")] + ".tar"
        write_shard(
            fname, (dict(r) for r in BlockAccessor(block).iter_rows())
        )
    elif fmt == "tfrecords":
        from .block import BlockAccessor
        from .tfrecords import write_example_file

        fname = fname[:-len(".tfrecords")] + ".tfrecord"
        write_example_file(
            fname, [dict(r) for r in BlockAccessor(block).iter_rows()]
        )
    elif fmt == "avro":
        from .avro import write_avro_file
        from .block import BlockAccessor

        write_avro_file(
            fname,
            [_jsonable(r) for r in BlockAccessor(block).iter_rows()],
            **write_kwargs,
        )
    else:
        raise ValueError(f"unknown sink format {fmt!r}")
    return fname


def _jsonable(row):
    import numpy as np

    out = {}
    for k, v in row.items():
        if isinstance(v, np.generic):
            v = v.item()
        elif isinstance(v, np.ndarray):
            v = v.tolist()
        out[k] = v
    return out


def write_blocks(dataset, path: str, fmt: str, **write_kwargs) -> List[str]:
    """Stream the dataset's blocks through per-block write tasks; returns
    the written file paths."""
    from ..core import runtime_context
    from .context import DataContext
    from .streaming_executor import ExecStats, execute_refs, _is_ref

    ctx = DataContext.get_current()
    use_remote = ctx.use_remote_tasks and runtime_context.is_initialized()
    path = os.path.abspath(path)
    stats = ExecStats()
    dataset._last_stats = stats

    if not use_remote:
        return [
            _write_block(b, path, fmt, i, write_kwargs)
            for i, b in enumerate(
                execute_refs(dataset._sources, dataset._stages, stats)
            )
        ]

    import ray_tpu

    writer = ray_tpu.remote(_write_block)
    out_refs = []
    for i, item in enumerate(execute_refs(dataset._sources,
                                          dataset._stages, stats)):
        out_refs.append(writer.remote(item, path, fmt, i, write_kwargs))
    return ray_tpu.get(out_refs)
