"""GroupedData aggregates (ref analogue: python/ray/data/grouped_data.py +
data/aggregate/_aggregate.py — count/sum/min/max/mean/std + map_groups).

Aggregates run DISTRIBUTED as a combiner tree: each block reduces to a
tiny per-key partial table inside its own task (one streaming pass, all
five moments at once), and only those partials merge on the driver —
the input never materializes centrally. ``map_groups`` (which needs whole
groups) hash-shuffles rows by key across tasks first (shuffle.py), then
applies the UDF per group within each partition.
"""

from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

from .block import BlockAccessor, from_rows


def _partial_agg(batch: Dict[str, np.ndarray], key: str, on: str):
    """Per-block combiner: per-key (count, sum, sumsq, min, max).

    Integer columns accumulate sums in int64 (no float precision loss);
    non-numeric columns (strings) support min/max/count only — their
    sum/sumsq partials are None."""
    keys = batch[key]
    raw = (np.asarray(batch[on]) if on is not None
           else np.zeros(len(keys)))
    uniq, inv = np.unique(keys, return_inverse=True)
    n = len(uniq)
    numeric = raw.dtype.kind in "biuf"
    out: Dict = {
        key: uniq,
        "_count": np.bincount(inv, minlength=n).astype(np.int64),
    }
    if numeric:
        if raw.dtype.kind in "biu":
            vals = raw.astype(np.int64)
            sums = np.zeros(n, dtype=np.int64)
            np.add.at(sums, inv, vals)
        else:
            vals = raw.astype(np.float64)
            sums = np.bincount(inv, weights=vals, minlength=n)
        out["_sum"] = sums
        out["_sumsq"] = np.bincount(
            inv, weights=raw.astype(np.float64) ** 2, minlength=n
        )
        mins = np.full(n, np.inf)
        maxs = np.full(n, -np.inf)
        np.minimum.at(mins, inv, raw.astype(np.float64))
        np.maximum.at(maxs, inv, raw.astype(np.float64))
        if raw.dtype.kind in "biu":
            mins = mins.astype(np.int64)
            maxs = maxs.astype(np.int64)
        out["_min"] = mins
        out["_max"] = maxs
    else:
        # Lexicographic min/max per group; sums undefined.
        out["_sum"] = np.asarray([None] * n, dtype=object)
        out["_sumsq"] = np.asarray([None] * n, dtype=object)
        out["_min"] = np.asarray(
            [raw[inv == g].min() for g in range(n)], dtype=object
        )
        out["_max"] = np.asarray(
            [raw[inv == g].max() for g in range(n)], dtype=object
        )
    return out


class GroupedData:
    def __init__(self, dataset, key: str):
        self._dataset = dataset
        self._key = key

    # ---- distributed combiner-tree aggregates ----

    def _partials(self, on):
        key = self._key

        def per_block(batch):
            return _partial_agg(batch, key, on)

        # batch_size=None: one combiner pass per block.
        rows = self._dataset.map_batches(
            per_block, batch_size=None
        ).take_all()
        merged: Dict = {}
        for r in rows:
            k = r[key]
            k = k.item() if hasattr(k, "item") else k
            m = merged.setdefault(
                k, {"count": 0, "sum": None, "sumsq": 0.0,
                    "min": None, "max": None}
            )
            m["count"] += int(r["_count"])
            if r["_sum"] is not None:
                m["sum"] = (r["_sum"] if m["sum"] is None
                            else m["sum"] + r["_sum"])
                m["sumsq"] += float(r["_sumsq"])
            m["min"] = (r["_min"] if m["min"] is None
                        else min(m["min"], r["_min"]))
            m["max"] = (r["_max"] if m["max"] is None
                        else max(m["max"], r["_max"]))
        return merged

    def _finalize(self, on, name, fn):
        from .dataset import Dataset

        rows = [
            {self._key: k, f"{name}({on})": fn(m)}
            for k, m in sorted(self._partials(on).items())
        ]
        return Dataset.from_blocks([from_rows(rows)])

    def count(self):
        from .dataset import Dataset

        rows = [
            {self._key: k, "count()": int(m["count"])}
            for k, m in sorted(self._partials(None).items())
        ]
        return Dataset.from_blocks([from_rows(rows)])

    def sum(self, on: str):
        def _sum(m):
            if m["sum"] is None:
                raise TypeError(f"sum() on non-numeric column {on!r}")
            return m["sum"]

        return self._finalize(on, "sum", _sum)

    def min(self, on: str):
        return self._finalize(on, "min", lambda m: m["min"])

    def max(self, on: str):
        return self._finalize(on, "max", lambda m: m["max"])

    def mean(self, on: str):
        def _mean(m):
            if m["sum"] is None:
                raise TypeError(f"mean() on non-numeric column {on!r}")
            return float(m["sum"]) / max(m["count"], 1)

        return self._finalize(on, "mean", _mean)

    def std(self, on: str):
        def _std(m):
            if m["sum"] is None:
                raise TypeError(f"std() on non-numeric column {on!r}")
            mean = float(m["sum"]) / max(m["count"], 1)
            var = m["sumsq"] / max(m["count"], 1) - mean * mean
            return float(np.sqrt(max(var, 0.0)))

        return self._finalize(on, "std", _std)

    # ---- whole-group UDFs (hash shuffle) ----

    def map_groups(self, fn: Callable):
        from .block import concat_blocks, normalize_to_block
        from .dataset import Dataset

        key = self._key

        class _ApplyGroups:
            """Runs inside the shuffle's reduce step: every row of a key
            lives in exactly one hash partition, so per-partition grouping
            is globally correct."""

            def __init__(self, fn, key):
                self.fn = fn
                self.key = key

            def __call__(self, block):
                cols = BlockAccessor(block).to_numpy()
                keys = cols[self.key]
                out = []
                for k in np.unique(keys):
                    idx = np.nonzero(keys == k)[0]
                    group = {c: v[idx] for c, v in cols.items()}
                    out.append(normalize_to_block(self.fn(group)))
                if not out:
                    return block
                return concat_blocks(out)

        ds = self._dataset
        if ds._use_remote():
            num = max(1, ds.num_blocks())
            return ds._shuffled(
                num, "hash", key, postprocess=_ApplyGroups(fn, key)
            )
        # Local fallback: group over the materialized table.
        table = ds._materialize_table()
        return Dataset.from_blocks(
            [_ApplyGroups(fn, key)(table)]
        )
