"""GroupedData aggregates (ref analogue: python/ray/data/grouped_data.py +
data/aggregate/_aggregate.py — count/sum/min/max/mean/std + map_groups)."""

from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

from .block import BlockAccessor, from_rows


class GroupedData:
    def __init__(self, dataset, key: str):
        self._dataset = dataset
        self._key = key

    def _groups(self) -> Dict:
        table = self._dataset._materialize_table()
        cols = BlockAccessor(table).to_numpy()
        keys = cols[self._key]
        order = np.argsort(keys, kind="stable")
        groups: Dict = {}
        for i in order:
            groups.setdefault(keys[i].item() if hasattr(keys[i], "item")
                              else keys[i], []).append(int(i))
        return {k: (cols, idx) for k, (idx) in
                ((k, v) for k, v in groups.items())}

    def _agg(self, on: str, fn: Callable, name: str):
        rows: List[Dict] = []
        for k, (cols, idx) in self._groups().items():
            rows.append({self._key: k, f"{name}({on})": fn(cols[on][idx])})
        from .dataset import Dataset

        return Dataset.from_blocks([from_rows(rows)])

    def count(self):
        rows = [
            {self._key: k, "count()": len(idx)}
            for k, (cols, idx) in self._groups().items()
        ]
        from .dataset import Dataset

        return Dataset.from_blocks([from_rows(rows)])

    def sum(self, on: str):
        return self._agg(on, np.sum, "sum")

    def min(self, on: str):
        return self._agg(on, np.min, "min")

    def max(self, on: str):
        return self._agg(on, np.max, "max")

    def mean(self, on: str):
        return self._agg(on, np.mean, "mean")

    def std(self, on: str):
        return self._agg(on, np.std, "std")

    def map_groups(self, fn: Callable):
        from .dataset import Dataset
        from .block import concat_blocks, normalize_to_block

        out = []
        for k, (cols, idx) in self._groups().items():
            group = {c: v[idx] for c, v in cols.items()}
            out.append(normalize_to_block(fn(group)))
        return Dataset.from_blocks([concat_blocks(out)])
