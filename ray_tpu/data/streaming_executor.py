"""Multi-operator streaming execution.

Ref analogue: python/ray/data/_internal/execution/streaming_executor.py
(:242 scheduling loop) + operators/map_operator.py +
operators/actor_pool_map_operator.py. The plan is a list of STAGES:

- ``TaskStage``: a fused chain of per-block ops, one remote task per block
  (the reference's fused MapOperator). The first TaskStage fuses with the
  read: source thunk + ops run inside one task.
- ``ActorStage``: a pool of stateful actors each holding one instance of a
  user callable class (the reference's ActorPoolMapOperator — the operator
  for model-loading transforms where per-task construction would dominate).

Execution is a chain of pull-based generators, one per stage, each with its
own bounded in-flight window — per-operator backpressure: a slow stage
stops pulling, which stops its upstream from submitting. Blocks stream
between stages as ObjectRefs (never gathered on the driver).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

from .context import DataContext


class TaskStage:
    def __init__(self, ops: Optional[List[Any]] = None):
        self.ops = list(ops or [])

    def with_op(self, op) -> "TaskStage":
        return TaskStage(self.ops + [op])


class ActorStage:
    """Stateful map_batches through a pool of actors."""

    def __init__(self, fn_cls: type, fn_constructor_args: tuple,
                 fn_constructor_kwargs: dict, pool_size: int,
                 batch_format: str, batch_size: Optional[int],
                 ray_remote_args: Optional[dict] = None):
        self.fn_cls = fn_cls
        self.fn_constructor_args = fn_constructor_args
        self.fn_constructor_kwargs = fn_constructor_kwargs
        self.pool_size = pool_size
        self.batch_format = batch_format
        self.batch_size = batch_size
        self.ray_remote_args = ray_remote_args or {}


# ---- plan DAG nodes (non-linear inputs) ----------------------------------
#
# A Dataset's ``sources`` is either a flat list of read thunks (the leaf
# case) or one of these nodes — making the physical plan an operator DAG
# rather than a chain (ref analogue: the operator graph in
# _internal/execution/streaming_executor_state.py, where zip/union are
# physical operators with multiple input edges).

class UnionSource:
    """Concatenation of several upstream datasets' block streams, in
    order (ref: Dataset.union)."""

    def __init__(self, datasets: List[Any]):
        self.datasets = list(datasets)


class ZipSource:
    """Pairwise block zip of two upstream datasets (ref: Dataset.zip —
    both sides must have the same number of blocks and row counts per
    block; a mismatch raises inside the zip task)."""

    def __init__(self, left: Any, right: Any):
        self.left = left
        self.right = right


def _zip_blocks(left, right):
    """Column-merge two row-aligned blocks; right-side name collisions
    get a ``_1`` suffix (matches the reference's zip semantics)."""
    from .block import BlockAccessor

    la = BlockAccessor(left).to_numpy()
    ra = BlockAccessor(right).to_numpy()
    ln = BlockAccessor(left).num_rows()
    rn = BlockAccessor(right).num_rows()
    if ln != rn:
        raise ValueError(
            f"zip requires row-aligned blocks; got {ln} vs {rn} rows "
            "(repartition both datasets identically first)"
        )
    out = dict(la)
    for k, v in ra.items():
        name = k
        suffix = 1
        while name in out:  # first free suffix: never clobber a column
            name = f"{k}_{suffix}"
            suffix += 1
        out[name] = v
    from .block import from_numpy_dict

    return from_numpy_dict(out)


# ---- backpressure policies (ref: backpressure_policy/) -------------------

class BackpressurePolicy:
    """Submission gate consulted by every stage before launching a new
    block task. ``can_submit`` may return False only while the stage has
    work in flight (progress is always possible)."""

    def can_submit(self, num_inflight: int) -> bool:
        raise NotImplementedError


class ConcurrencyCapPolicy(BackpressurePolicy):
    """Bound concurrent block tasks per stage (ref:
    concurrency_cap_backpressure_policy.py)."""

    def __init__(self, cap: int):
        self.cap = max(1, cap)

    def can_submit(self, num_inflight: int) -> bool:
        return num_inflight < self.cap


class StoreUsagePolicy(BackpressurePolicy):
    """Resource-aware: stop submitting while the local object store sits
    above ``cap_fraction`` of capacity, so a slow consumer bounds
    producer memory (ref: the reference's output-size/resource
    backpressure). Always allows the first in-flight task."""

    def __init__(self, cap_fraction: float):
        self.cap_fraction = cap_fraction

    def _usage(self) -> float:
        from ..core import runtime_context

        rt = runtime_context.current_runtime_or_none()
        nm = getattr(rt, "_nm", None)
        if nm is None:
            return 0.0
        d = nm.directory
        if d.capacity_bytes <= 0:
            return 0.0
        return d.used_bytes / d.capacity_bytes

    def can_submit(self, num_inflight: int) -> bool:
        if num_inflight == 0:
            return True  # progress guarantee
        return self._usage() < self.cap_fraction


def _default_policies(ctx) -> List[BackpressurePolicy]:
    out: List[BackpressurePolicy] = [
        ConcurrencyCapPolicy(ctx.max_in_flight_tasks)
    ]
    if ctx.store_usage_cap_fraction > 0:
        out.append(StoreUsagePolicy(ctx.store_usage_cap_fraction))
    return out


# ---- execution stats (per-operator; ref: data/_internal/stats.py) --------

class ExecStats:
    """Per-stage / per-operator accounting for ONE execution: each fused
    task measures its ops' wall time and its output block's rows/bytes
    in the worker, returning them as a second (tiny) task output; the
    driver aggregates lazily when Dataset.stats() is called."""

    def __init__(self):
        self.stage_names: List[str] = []
        self.stats_refs: List[List[Any]] = []   # per stage: refs/dicts
        self.blocks: List[int] = []
        self.wall_s: float = 0.0

    def add_stage(self, name: str) -> int:
        self.stage_names.append(name)
        self.stats_refs.append([])
        self.blocks.append(0)
        return len(self.stage_names) - 1

    def summary(self) -> str:
        import ray_tpu
        from ..core import runtime_context

        lines = []
        for i, name in enumerate(self.stage_names):
            raw = self.stats_refs[i]
            resolved = []
            for item in raw:
                if isinstance(item, dict):
                    resolved.append(item)
                elif runtime_context.is_initialized():
                    try:
                        resolved.append(ray_tpu.get(item, timeout=60))
                    except Exception:
                        pass
            rows = sum(st.get("rows", 0) for st in resolved)
            nbytes = sum(st.get("bytes", 0) for st in resolved)
            per_op: Dict[str, float] = {}
            for st in resolved:
                for op_name, dur in st.get("ops", []):
                    per_op[op_name] = per_op.get(op_name, 0.0) + dur
            lines.append(
                f"Stage {i} {name}: {self.blocks[i]} blocks, "
                f"{rows} rows, {nbytes} bytes"
            )
            for op_name, dur in per_op.items():
                lines.append(f"  * {op_name}: {dur * 1e3:.1f}ms")
        lines.append(f"Total wall: {self.wall_s * 1e3:.1f}ms")
        return "\n".join(lines)


def _block_stats(block, per_op):
    from .block import BlockAccessor

    acc = BlockAccessor(block)
    try:
        rows = acc.num_rows()
        nbytes = acc.size_bytes()
    except Exception:
        rows, nbytes = 0, 0
    return {"ops": per_op, "rows": rows, "bytes": nbytes}


# ---- task bodies (top-level: picklable by function table) ----------------

def _run_chain_from_source(src: Callable[[], Any], ops: List[Any]):
    block = src()
    for op in ops:
        block = op.apply(block)
    return block


def _run_chain_on_block(block, ops: List[Any]):
    for op in ops:
        block = op.apply(block)
    return block


def _run_chain_from_source_stats(src: Callable[[], Any], ops: List[Any]):
    import time as _t

    t0 = _t.perf_counter()
    block = src()
    per_op = [("read", _t.perf_counter() - t0)]
    for op in ops:
        t0 = _t.perf_counter()
        block = op.apply(block)
        per_op.append(
            (type(op).__name__.lstrip("_"), _t.perf_counter() - t0)
        )
    return block, _block_stats(block, per_op)


def _run_chain_on_block_stats(block, ops: List[Any]):
    import time as _t

    per_op = []
    for op in ops:
        t0 = _t.perf_counter()
        block = op.apply(block)
        per_op.append(
            (type(op).__name__.lstrip("_"), _t.perf_counter() - t0)
        )
    return block, _block_stats(block, per_op)


class _ActorMapWorker:
    """Pool member: holds one instance of the user's callable class."""

    def __init__(self, blob: bytes, batch_format: str,
                 batch_size: Optional[int]):
        import cloudpickle

        cls, args, kwargs = cloudpickle.loads(blob)
        self._fn = cls(*args, **kwargs)
        self._batch_format = batch_format
        self._batch_size = batch_size

    def apply(self, block):
        from .dataset import _MapBatches

        op = _MapBatches(self._fn, self._batch_format, self._batch_size)
        return op.apply(block)


# ---- local (no-runtime) execution ---------------------------------------

def _execute_local(sources: Sequence[Callable[[], Any]],
                   stages: Sequence[Any],
                   stats: Optional["ExecStats"] = None) -> Iterator[Any]:
    from .dataset import _MapBatches

    sidx = -1
    if stats is not None:
        sidx = stats.add_stage("LocalPipeline")
    # Instantiate each actor stage's callable once (pool of one).
    insts = {}
    for i, st in enumerate(stages):
        if isinstance(st, ActorStage):
            insts[i] = st.fn_cls(*st.fn_constructor_args,
                                 **st.fn_constructor_kwargs)
    for src in sources:
        import time as _t

        t0 = _t.perf_counter()
        block = src()
        per_op = [("read", _t.perf_counter() - t0)]
        for i, st in enumerate(stages):
            if isinstance(st, TaskStage):
                for op in st.ops:
                    t0 = _t.perf_counter()
                    block = op.apply(block)
                    per_op.append((type(op).__name__.lstrip("_"),
                                   _t.perf_counter() - t0))
            else:
                op = _MapBatches(insts[i], st.batch_format, st.batch_size)
                t0 = _t.perf_counter()
                block = op.apply(block)
                per_op.append(("MapBatches", _t.perf_counter() - t0))
        if stats is not None:
            stats.blocks[sidx] += 1
            stats.stats_refs[sidx].append(_block_stats(block, per_op))
        yield block


# ---- distributed execution ----------------------------------------------

def _task_stage_gen(upstream: Iterator[Any], stage: TaskStage,
                    policies: List[BackpressurePolicy], first: bool,
                    stats: Optional[ExecStats] = None,
                    stage_idx: int = -1) -> Iterator[Any]:
    """Submit one fused task per upstream item; yield result refs in
    order, gating every submission on the backpressure policies
    (concurrency cap + store usage). With ``stats``, the task returns a
    second tiny output carrying per-op wall + block rows/bytes."""
    import ray_tpu

    if stats is not None:
        fn = ray_tpu.remote(num_returns=2)(
            _run_chain_from_source_stats if first
            else _run_chain_on_block_stats
        )
    else:
        fn = ray_tpu.remote(
            _run_chain_from_source if first else _run_chain_on_block
        )
    inflight: List[Any] = []
    up = iter(upstream)
    done = False
    while inflight or not done:
        while not done and all(
            p.can_submit(len(inflight)) for p in policies
        ):
            item = next(up, None)
            if item is None:
                done = True
                break
            if stats is not None:
                block_ref, stats_ref = fn.remote(item, stage.ops)
                stats.stats_refs[stage_idx].append(stats_ref)
                stats.blocks[stage_idx] += 1
                inflight.append(block_ref)
            else:
                inflight.append(fn.remote(item, stage.ops))
        if inflight:
            yield inflight.pop(0)


def _actor_stage_gen(upstream: Iterator[Any],
                     stage: ActorStage,
                     stats: Optional[ExecStats] = None,
                     stage_idx: int = -1) -> Iterator[Any]:
    """Round-robin blocks over the actor pool; yield in submission order
    (per-actor queueing keeps each member busy without head-of-line
    blocking the whole pool)."""
    import cloudpickle

    import ray_tpu

    blob = cloudpickle.dumps(
        (stage.fn_cls, stage.fn_constructor_args,
         stage.fn_constructor_kwargs)
    )
    opts = dict(stage.ray_remote_args)
    actor_cls = (ray_tpu.remote(**opts)(_ActorMapWorker) if opts
                 else ray_tpu.remote(_ActorMapWorker))
    pool = [
        actor_cls.remote(blob, stage.batch_format, stage.batch_size)
        for _ in range(stage.pool_size)
    ]
    try:
        window = stage.pool_size * 2
        inflight: List[Any] = []
        up = iter(upstream)
        done = False
        i = 0
        while inflight or not done:
            while not done and len(inflight) < window:
                item = next(up, None)
                if item is None:
                    done = True
                    break
                member = pool[i % len(pool)]
                i += 1
                inflight.append(member.apply.remote(item))
            if inflight:
                ref = inflight.pop(0)
                # Seal before yielding: the pool is killed when this
                # generator closes, and a killed actor can't seal a result
                # that downstream hasn't consumed yet.
                ray_tpu.wait([ref], num_returns=1, timeout=None)
                if stats is not None:
                    stats.blocks[stage_idx] += 1
                yield ref
    finally:
        for a in pool:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


def execute(sources: Sequence[Callable[[], Any]],
            stages: Sequence[Any],
            stats: Optional[ExecStats] = None) -> Iterator[Any]:
    """Run the stage pipeline; yields materialized blocks on the driver.
    (Use :func:`execute_refs` to keep results remote.)"""
    import ray_tpu

    for item in execute_refs(sources, stages, stats):
        yield ray_tpu.get(item) if _is_ref(item) else item


def _is_ref(x) -> bool:
    from ..core.reference import ObjectRef

    return isinstance(x, ObjectRef)


def _node_ref_stream(node, stats: Optional[ExecStats]) -> Iterator[Any]:
    """Ref stream for a DAG input node: recursively executes upstream
    plans and combines their block streams (union = ordered concat, zip
    = pairwise zip tasks). Upstream datasets run their OWN stage chains
    — the combined stream then feeds this dataset's stages with
    first=False (blocks arrive as refs, not source thunks)."""
    import ray_tpu

    if isinstance(node, UnionSource):
        idx = -1
        if stats is not None:
            idx = stats.add_stage(f"Union(x{len(node.datasets)})")
        for ds in node.datasets:
            for ref in execute_refs(ds._sources, ds._stages, None):
                if stats is not None:
                    stats.blocks[idx] += 1
                yield ref if _is_ref(ref) else ray_tpu.put(ref)
        return
    if isinstance(node, ZipSource):
        idx = -1
        if stats is not None:
            idx = stats.add_stage("Zip")
        zipper = ray_tpu.remote(_zip_blocks)
        left = execute_refs(node.left._sources, node.left._stages, None)
        right = execute_refs(node.right._sources, node.right._stages, None)
        while True:
            l = next(left, None)
            r = next(right, None)
            if l is None and r is None:
                return
            if l is None or r is None:
                raise ValueError(
                    "zip requires datasets with the same number of "
                    "blocks (repartition them identically first)"
                )
            if stats is not None:
                stats.blocks[idx] += 1
            yield zipper.remote(l, r)
        return
    raise TypeError(f"unknown plan node {type(node).__name__}")


def _node_local_blocks(node, stats):
    """Local (no-runtime) evaluation of a DAG input node."""
    if isinstance(node, UnionSource):
        for ds in node.datasets:
            yield from execute(ds._sources, ds._stages, None)
        return
    if isinstance(node, ZipSource):
        left = list(execute(node.left._sources, node.left._stages, None))
        right = list(execute(node.right._sources, node.right._stages, None))
        if len(left) != len(right):
            raise ValueError(
                "zip requires datasets with the same number of blocks"
            )
        for l, r in zip(left, right):
            yield _zip_blocks(l, r)
        return
    raise TypeError(f"unknown plan node {type(node).__name__}")


def execute_refs(sources: Any,
                 stages: Sequence[Any],
                 stats: Optional[ExecStats] = None) -> Iterator[Any]:
    """Yield per-block results as ObjectRefs (driver never holds data),
    falling back to local inline execution without a runtime. Pass an
    ``ExecStats`` to collect per-stage / per-operator accounting.
    ``sources`` is either a list of read thunks or a plan DAG node
    (UnionSource/ZipSource) whose upstream datasets execute as their own
    streaming chains."""
    import time as _t

    ctx = DataContext.get_current()
    from ..core import runtime_context

    t_start = _t.perf_counter()
    is_node = isinstance(sources, (UnionSource, ZipSource))
    if not (ctx.use_remote_tasks and runtime_context.is_initialized()):
        if is_node:
            # Local mode: upstream blocks materialize inline, then this
            # plan's stages run over them like pulled blocks.
            blocks = _node_local_blocks(sources, stats)
            srcs = [(lambda b=b: b) for b in blocks]
            yield from _execute_local(srcs, stages, stats)
        else:
            yield from _execute_local(sources, stages, stats)
        if stats is not None:
            stats.wall_s = _t.perf_counter() - t_start
        return

    policies = _default_policies(ctx)
    stages = list(stages) or [TaskStage([])]
    if is_node:
        gen: Iterator[Any] = _node_ref_stream(sources, stats)
        first = False  # upstream yields block refs, not source thunks
    else:
        gen = iter(sources)
        first = True
    for i, st in enumerate(stages):
        if isinstance(st, TaskStage):
            if not st.ops and not first:
                continue  # identity over an already-ref stream: no hop
            idx = -1
            if stats is not None:
                names = [type(o).__name__.lstrip("_") for o in st.ops]
                label = "Read->" if first else ""
                idx = stats.add_stage(
                    f"TaskStage({label}{'->'.join(names) or 'identity'})"
                )
            gen = _task_stage_gen(gen, st, policies, first, stats, idx)
        else:
            if first:
                idx = -1
                if stats is not None:
                    idx = stats.add_stage("TaskStage(Read)")
                gen = _task_stage_gen(
                    gen, TaskStage([]), policies, True, stats, idx,
                )
            aidx = -1
            if stats is not None:
                aidx = stats.add_stage(
                    f"ActorStage({st.fn_cls.__name__} x{st.pool_size})"
                )
            gen = _actor_stage_gen(gen, st, stats, aidx)
        first = False
    for item in gen:
        yield item
    if stats is not None:
        stats.wall_s = _t.perf_counter() - t_start
