"""Multi-operator streaming execution.

Ref analogue: python/ray/data/_internal/execution/streaming_executor.py
(:242 scheduling loop) + operators/map_operator.py +
operators/actor_pool_map_operator.py. The plan is a list of STAGES:

- ``TaskStage``: a fused chain of per-block ops, one remote task per block
  (the reference's fused MapOperator). The first TaskStage fuses with the
  read: source thunk + ops run inside one task.
- ``ActorStage``: a pool of stateful actors each holding one instance of a
  user callable class (the reference's ActorPoolMapOperator — the operator
  for model-loading transforms where per-task construction would dominate).

Execution is a chain of pull-based generators, one per stage, each with its
own bounded in-flight window — per-operator backpressure: a slow stage
stops pulling, which stops its upstream from submitting. Blocks stream
between stages as ObjectRefs (never gathered on the driver).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional, Sequence

from .context import DataContext


class TaskStage:
    def __init__(self, ops: Optional[List[Any]] = None):
        self.ops = list(ops or [])

    def with_op(self, op) -> "TaskStage":
        return TaskStage(self.ops + [op])


class ActorStage:
    """Stateful map_batches through a pool of actors."""

    def __init__(self, fn_cls: type, fn_constructor_args: tuple,
                 fn_constructor_kwargs: dict, pool_size: int,
                 batch_format: str, batch_size: Optional[int],
                 ray_remote_args: Optional[dict] = None):
        self.fn_cls = fn_cls
        self.fn_constructor_args = fn_constructor_args
        self.fn_constructor_kwargs = fn_constructor_kwargs
        self.pool_size = pool_size
        self.batch_format = batch_format
        self.batch_size = batch_size
        self.ray_remote_args = ray_remote_args or {}


# ---- task bodies (top-level: picklable by function table) ----------------

def _run_chain_from_source(src: Callable[[], Any], ops: List[Any]):
    block = src()
    for op in ops:
        block = op.apply(block)
    return block


def _run_chain_on_block(block, ops: List[Any]):
    for op in ops:
        block = op.apply(block)
    return block


class _ActorMapWorker:
    """Pool member: holds one instance of the user's callable class."""

    def __init__(self, blob: bytes, batch_format: str,
                 batch_size: Optional[int]):
        import cloudpickle

        cls, args, kwargs = cloudpickle.loads(blob)
        self._fn = cls(*args, **kwargs)
        self._batch_format = batch_format
        self._batch_size = batch_size

    def apply(self, block):
        from .dataset import _MapBatches

        op = _MapBatches(self._fn, self._batch_format, self._batch_size)
        return op.apply(block)


# ---- local (no-runtime) execution ---------------------------------------

def _execute_local(sources: Sequence[Callable[[], Any]],
                   stages: Sequence[Any]) -> Iterator[Any]:
    from .dataset import _MapBatches

    # Instantiate each actor stage's callable once (pool of one).
    insts = {}
    for i, st in enumerate(stages):
        if isinstance(st, ActorStage):
            insts[i] = st.fn_cls(*st.fn_constructor_args,
                                 **st.fn_constructor_kwargs)
    for src in sources:
        block = src()
        for i, st in enumerate(stages):
            if isinstance(st, TaskStage):
                for op in st.ops:
                    block = op.apply(block)
            else:
                op = _MapBatches(insts[i], st.batch_format, st.batch_size)
                block = op.apply(block)
        yield block


# ---- distributed execution ----------------------------------------------

def _task_stage_gen(upstream: Iterator[Any], stage: TaskStage,
                    window: int, first: bool) -> Iterator[Any]:
    """Submit one fused task per upstream item; yield result refs in order
    with at most ``window`` in flight."""
    import ray_tpu

    fn = ray_tpu.remote(
        _run_chain_from_source if first else _run_chain_on_block
    )
    inflight: List[Any] = []
    up = iter(upstream)
    done = False
    while inflight or not done:
        while not done and len(inflight) < window:
            item = next(up, None)
            if item is None:
                done = True
                break
            inflight.append(fn.remote(item, stage.ops))
        if inflight:
            yield inflight.pop(0)


def _actor_stage_gen(upstream: Iterator[Any],
                     stage: ActorStage) -> Iterator[Any]:
    """Round-robin blocks over the actor pool; yield in submission order
    (per-actor queueing keeps each member busy without head-of-line
    blocking the whole pool)."""
    import cloudpickle

    import ray_tpu

    blob = cloudpickle.dumps(
        (stage.fn_cls, stage.fn_constructor_args,
         stage.fn_constructor_kwargs)
    )
    opts = dict(stage.ray_remote_args)
    actor_cls = (ray_tpu.remote(**opts)(_ActorMapWorker) if opts
                 else ray_tpu.remote(_ActorMapWorker))
    pool = [
        actor_cls.remote(blob, stage.batch_format, stage.batch_size)
        for _ in range(stage.pool_size)
    ]
    try:
        window = stage.pool_size * 2
        inflight: List[Any] = []
        up = iter(upstream)
        done = False
        i = 0
        while inflight or not done:
            while not done and len(inflight) < window:
                item = next(up, None)
                if item is None:
                    done = True
                    break
                member = pool[i % len(pool)]
                i += 1
                inflight.append(member.apply.remote(item))
            if inflight:
                ref = inflight.pop(0)
                # Seal before yielding: the pool is killed when this
                # generator closes, and a killed actor can't seal a result
                # that downstream hasn't consumed yet.
                ray_tpu.wait([ref], num_returns=1, timeout=None)
                yield ref
    finally:
        for a in pool:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


def execute(sources: Sequence[Callable[[], Any]],
            stages: Sequence[Any]) -> Iterator[Any]:
    """Run the stage pipeline; yields materialized blocks on the driver.
    (Use :func:`execute_refs` to keep results remote.)"""
    import ray_tpu

    for item in execute_refs(sources, stages):
        yield ray_tpu.get(item) if _is_ref(item) else item


def _is_ref(x) -> bool:
    from ..core.reference import ObjectRef

    return isinstance(x, ObjectRef)


def execute_refs(sources: Sequence[Callable[[], Any]],
                 stages: Sequence[Any]) -> Iterator[Any]:
    """Yield per-block results as ObjectRefs (driver never holds data),
    falling back to local inline execution without a runtime."""
    ctx = DataContext.get_current()
    from ..core import runtime_context

    if not (ctx.use_remote_tasks and runtime_context.is_initialized()):
        yield from _execute_local(sources, stages)
        return

    stages = list(stages) or [TaskStage([])]
    gen: Iterator[Any] = iter(sources)
    first = True
    for i, st in enumerate(stages):
        if isinstance(st, TaskStage):
            gen = _task_stage_gen(gen, st, ctx.max_in_flight_tasks, first)
        else:
            if first:
                # Materialize sources into blocks before an actor stage.
                gen = _task_stage_gen(
                    gen, TaskStage([]), ctx.max_in_flight_tasks, True
                )
            gen = _actor_stage_gen(gen, st)
        first = False
    yield from gen
