"""WebDataset-format source/sink (tar shards of grouped samples).

Ref analogue: python/ray/data/datasource webdataset reader/writer. A
WebDataset shard is a plain tar archive where files sharing a basename
form one sample: ``0001.jpg`` + ``0001.cls`` + ``0001.json`` decode to
one row ``{"__key__": "0001", "jpg": ..., "cls": ..., "json": ...}``.
Implemented on stdlib ``tarfile`` — no webdataset dependency. Decoding:
``.json`` parses, ``.cls``/``.txt`` decode to str (cls to int when
numeric), ``.npy`` loads an array, everything else stays raw bytes
(images are passed through — pair with map_batches for pixel decode,
matching the reference's decode=None mode).
"""

from __future__ import annotations

import io
import json
import os
import tarfile
from typing import Any, Dict, Iterator, List


def _decode(ext: str, data: bytes):
    if ext == "json":
        return json.loads(data)
    if ext in ("txt", "text"):
        return data.decode()
    if ext == "cls":
        text = data.decode().strip()
        return int(text) if text.lstrip("-").isdigit() else text
    if ext == "npy":
        import numpy as np

        return np.load(io.BytesIO(data), allow_pickle=False)
    return data  # images & unknown extensions stay raw bytes


def _encode(ext: str, value) -> bytes:
    if isinstance(value, bytes):
        return value
    if ext == "json":
        return json.dumps(value).encode()
    if ext == "npy":
        import numpy as np

        buf = io.BytesIO()
        np.save(buf, value, allow_pickle=False)
        return buf.getvalue()
    return str(value).encode()


def read_shard(path: str) -> List[Dict[str, Any]]:
    """All samples of one tar shard in tar order (webdataset semantics:
    members of a sample are adjacent, keyed by the FULL member path up
    to the first dot — directories distinguish samples, exactly like the
    reference reader)."""
    rows: List[Dict[str, Any]] = []
    current: Dict[str, Any] = {}
    current_key = None
    with tarfile.open(path, "r:*") as tf:
        for member in tf:
            if not member.isfile():
                continue
            name = member.name
            base = os.path.basename(name)
            if "." not in base:
                continue
            dot = name.index(".", len(name) - len(base))
            key, ext = name[:dot], name[dot + 1:].lower()
            if key != current_key:
                if current:
                    rows.append(current)
                current = {"__key__": key}
                current_key = key
            data = tf.extractfile(member).read()
            current[ext] = _decode(ext, data)
    if current:
        rows.append(current)
    return rows


def rows_to_table(rows: List[Dict[str, Any]]):
    """Arrow table preserving webdataset payloads (delegates to the
    block layer's from_rows: union of keys, binary-typed bytes columns,
    JSON-text fallback for values arrow cannot type uniformly)."""
    from .block import from_rows

    return from_rows(rows)


def write_shard(path: str, rows: Iterator[Dict[str, Any]]) -> int:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    n = 0
    with tarfile.open(path, "w") as tf:
        for i, row in enumerate(rows):
            key = str(row.get("__key__", f"{i:06d}"))
            base = os.path.basename(key)
            if "." in base:
                raise ValueError(
                    f"webdataset __key__ {key!r} must not contain '.' in "
                    f"its basename — the reader splits at the first dot "
                    f"(directories in the key are fine)"
                )
            for ext, value in row.items():
                if ext == "__key__":
                    continue
                data = _encode(ext, value)
                info = tarfile.TarInfo(name=f"{key}.{ext}")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
            n += 1
    return n
