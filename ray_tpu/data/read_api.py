"""Data sources.

Ref analogue: python/ray/data/read_api.py (read_parquet:552, read_csv,
read_json, read_images, read_binary_files, from_items, range, from_numpy,
from_pandas, from_arrow). Each file becomes one read task (a source thunk);
reads execute lazily inside the fused block task.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Dict, List, Optional

import numpy as np
import pyarrow as pa

import builtins

from .block import from_numpy_dict, from_rows, normalize_to_block
from .dataset import Dataset

# This module defines its own `range` (the Dataset source, matching the
# reference API name) — internal loops use the builtin via this alias.
_range = builtins.range


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(
                sorted(
                    os.path.join(p, f)
                    for f in os.listdir(p)
                    if not f.startswith(".")
                )
            )
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def from_items(items: List[Any], *, override_num_blocks: int = 8) -> Dataset:
    n = min(override_num_blocks, max(1, len(items)))
    chunks = [items[i::n] for i in _range(n)]
    return Dataset(
        [
            (lambda c=c: from_rows(
                [r if isinstance(r, dict) else {"item": r} for r in c]
            ))
            for c in chunks if c
        ]
    )


def range(n: int, *, override_num_blocks: int = 8) -> Dataset:  # noqa: A001
    nb = min(override_num_blocks, max(1, n))
    bounds = np.linspace(0, n, nb + 1, dtype=np.int64)
    return Dataset(
        [
            (lambda lo=lo, hi=hi: from_numpy_dict(
                {"id": np.arange(lo, hi, dtype=np.int64)}
            ))
            for lo, hi in zip(bounds[:-1], bounds[1:])
            if hi > lo
        ]
    )


def from_numpy(arr: np.ndarray, *, column: str = "data",
               override_num_blocks: int = 8) -> Dataset:
    nb = min(override_num_blocks, max(1, len(arr)))
    chunks = np.array_split(arr, nb)
    return Dataset(
        [(lambda c=c: from_numpy_dict({column: c})) for c in chunks
         if len(c)]
    )


def from_pandas(df) -> Dataset:
    return Dataset([lambda: pa.Table.from_pandas(df, preserve_index=False)])


def from_arrow(table: pa.Table) -> Dataset:
    return Dataset([lambda: table])


def read_parquet(paths, **kw) -> Dataset:
    files = _expand_paths(paths)

    def make(path):
        def read():
            import pyarrow.parquet as pq

            return pq.read_table(path)

        return read

    return Dataset([make(p) for p in files])


def read_csv(paths, **kw) -> Dataset:
    files = _expand_paths(paths)

    def make(path):
        def read():
            from pyarrow import csv as pacsv

            return pacsv.read_csv(path)

        return read

    return Dataset([make(p) for p in files])


def read_json(paths, **kw) -> Dataset:
    files = _expand_paths(paths)

    def make(path):
        def read():
            from pyarrow import json as pajson

            return pajson.read_json(path)

        return read

    return Dataset([make(p) for p in files])


def read_tfrecords(paths, **kw) -> Dataset:
    """TFRecord files of tf.train.Example protos, one block per file
    (ref analogue: ray.data.read_tfrecords; parsing is the dependency-
    free codec in data/tfrecords.py)."""
    files = _expand_paths(paths)

    def make(path):
        def read():
            import pyarrow as pa

            from .tfrecords import read_example_file

            rows = read_example_file(path)
            cols = {}
            for row in rows:
                for k in row:
                    cols.setdefault(k, [])
            for row in rows:
                for k in cols:
                    cols[k].append(row.get(k))
            return pa.table(cols)

        return read

    return Dataset([make(p) for p in files])


def read_sql(sql: str, connection_factory, *,
             override_num_blocks: int = 1, **kw) -> Dataset:
    """Run a SQL query through a DBAPI connection factory (ref analogue:
    ray.data.read_sql — e.g. ``lambda: sqlite3.connect(path)``). With
    ``override_num_blocks`` > 1 each shard runs the SAME query and keeps
    every n-th row (portable across DBAPI drivers — no dialect-specific
    OFFSET syntax; rows must be stably ordered for deterministic
    sharding, and each shard transfers the full result set — same
    parallelize-the-transform-not-the-scan tradeoff as the reference's
    read_sql)."""

    def make(shard, nshards):
        def read():
            import pyarrow as pa

            conn = connection_factory()
            try:
                cur = conn.cursor()
                cur.execute(sql)
                names = [d[0] for d in cur.description]
                rows = cur.fetchall()
                if nshards > 1:
                    rows = rows[shard::nshards]
                cols = {n: [r[i] for r in rows]
                        for i, n in enumerate(names)}
                return pa.table(cols)
            finally:
                conn.close()

        return read

    n = max(1, int(override_num_blocks))
    # builtins.range: this module's ``range`` is the Dataset factory.
    import builtins

    return Dataset([make(i, n) for i in builtins.range(n)])


def read_webdataset(paths, **kw) -> Dataset:
    """WebDataset tar shards, one block per shard; samples are rows of
    {"__key__", <ext>: decoded value} (ref analogue:
    ray.data.read_webdataset; stdlib-tar codec in data/webdataset.py).
    Blocks use binary-typed arrow columns and the union of all samples'
    keys, so ragged payloads and optional fields survive intact."""
    files = _expand_paths(paths)

    def make(path):
        def read():
            from .webdataset import read_shard, rows_to_table

            return rows_to_table(read_shard(path))

        return read

    return Dataset([make(p) for p in files])


def from_torch(torch_dataset, *, override_num_blocks: int = 8
               ) -> Dataset:
    """Materialize a torch map-style Dataset (ref:
    ray.data.from_torch). Rows become {"item": value} with tensors
    converted to numpy."""
    import numpy as np

    def to_row(x):
        if hasattr(x, "numpy"):
            x = x.numpy()
        elif isinstance(x, (tuple, list)):
            x = type(x)(
                v.numpy() if hasattr(v, "numpy") else v for v in x
            )
        return {"item": np.asarray(x) if not isinstance(x, (tuple,
                                                            list))
                else x}

    import builtins

    # NOTE: this module shadows builtins.range with the dataset
    # constructor.
    rows = [to_row(torch_dataset[i])
            for i in builtins.range(len(torch_dataset))]
    return from_items(rows, override_num_blocks=override_num_blocks)


def from_tf(tf_dataset, *, override_num_blocks: int = 8) -> Dataset:
    """Materialize a tf.data.Dataset (ref: ray.data.from_tf);
    requires tensorflow. Elements become rows: dict elements keep
    their keys, others land in "item"."""
    rows = []
    for elem in tf_dataset:
        if isinstance(elem, dict):
            rows.append({k: v.numpy() for k, v in elem.items()})
        elif isinstance(elem, (tuple, list)):
            rows.append({"item": [v.numpy() for v in elem]})
        else:
            rows.append({"item": elem.numpy()})
    return from_items(rows, override_num_blocks=override_num_blocks)


def read_avro(paths, **kw) -> Dataset:
    """Avro Object Container Files, one block per file (ref analogue:
    ray.data.read_avro over datasource/avro_datasource.py; the
    dependency-free codec lives in data/avro.py)."""
    import pyarrow as pa

    files = _expand_paths(paths)

    def make(path):
        def read():
            from .avro import read_avro_file

            rows = read_avro_file(path)
            return pa.Table.from_pylist(rows)

        return read

    return Dataset([make(p) for p in files])


def read_lance(uri: str, *, columns=None, **kw) -> Dataset:
    """Lance datasets via the `lance` package (ref analogue:
    ray.data.read_lance over datasource/lance_datasource.py, which
    carries the same dependency)."""
    try:
        import lance  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "read_lance requires the `lance` package, which is not "
            "installed in this environment"
        ) from e

    def read():
        import lance

        ds = lance.dataset(uri)
        return ds.to_table(columns=columns)

    return Dataset([read])


def read_numpy(paths, **kw) -> Dataset:
    files = _expand_paths(paths)

    def make(path):
        def read():
            arr = np.load(path)
            return from_numpy_dict({"data": arr})

        return read

    return Dataset([make(p) for p in files])


def read_binary_files(paths, *, include_paths: bool = False) -> Dataset:
    files = _expand_paths(paths)

    def make(path):
        def read():
            with open(path, "rb") as f:
                data = f.read()
            row: Dict[str, Any] = {"bytes": data}
            if include_paths:
                row["path"] = path
            return from_rows([row])

        return read

    return Dataset([make(p) for p in files])


def read_images(paths, *, size: Optional[tuple] = None,
                include_paths: bool = False) -> Dataset:
    """Decode images into an 'image' tensor column (uint8 HWC). Uses PIL if
    available; raw decode of .npy otherwise."""
    files = _expand_paths(paths)

    def make(path):
        def read():
            try:
                from PIL import Image

                img = Image.open(path).convert("RGB")
                if size is not None:
                    img = img.resize(size)
                arr = np.asarray(img, dtype=np.uint8)
            except ImportError:
                arr = np.load(path)
            cols: Dict[str, Any] = {"image": arr[None]}
            if include_paths:
                cols["path"] = np.asarray([path])
            return from_numpy_dict(cols)

        return read

    return Dataset([make(p) for p in files])


def from_huggingface(hf_dataset, *, override_num_blocks: int = 8
                     ) -> Dataset:
    """A HuggingFace ``datasets.Dataset`` (or DatasetDict split) as a
    Dataset (ref analogue: ray.data.from_huggingface /
    huggingface_datasource.py). HF datasets are arrow-backed, so blocks
    are zero-copy slices of the underlying table."""
    import datasets as hf

    if isinstance(hf_dataset, hf.DatasetDict):
        raise ValueError(
            "pass one split, e.g. from_huggingface(ds['train']) "
            f"(got DatasetDict with splits {list(hf_dataset)})"
        )
    table = hf_dataset.data.table if hasattr(
        hf_dataset.data, "table") else hf_dataset.data
    table = table.combine_chunks()
    n = len(table)
    nb = min(max(1, override_num_blocks), max(1, n))
    bounds = [n * i // nb for i in builtins.range(nb + 1)]
    return Dataset([
        (lambda lo=lo, hi=hi: table.slice(lo, hi - lo))
        for lo, hi in zip(bounds[:-1], bounds[1:]) if hi > lo
    ])


def read_bigquery(query: str = None, *, project_id: str = None,
                  dataset: str = None,
                  queries: Optional[List[str]] = None,
                  client_factory=None) -> Dataset:
    """Read BigQuery into a Dataset (ref analogue: ray.data.read_bigquery
    / bigquery_datasource.py). ``client_factory`` defaults to
    ``google.cloud.bigquery.Client(project=project_id)``; inject a fake
    for tests/offline use. Results arrive as arrow via to_arrow().

    One ``query`` (or ``dataset`` table) = one block. For PARALLEL reads
    pass ``queries=[...]`` — explicit disjoint shard queries (e.g.
    partition-date predicates), one block each. Row-offset slicing of a
    repeated query is deliberately NOT offered: BigQuery result order is
    unspecified without ORDER BY, so offset shards of independent query
    jobs can silently overlap or drop rows (and bill N times)."""
    specs = list(queries or [])
    if query is not None:
        specs.insert(0, query)
    if dataset is not None:
        specs.insert(0, f"SELECT * FROM `{dataset}`")
    if not specs:
        raise ValueError("read_bigquery needs query=, dataset= or queries=")

    def make(sql):
        def read():
            if client_factory is not None:
                client = client_factory()
            else:
                from google.cloud import bigquery

                client = bigquery.Client(project=project_id)
            return client.query(sql).to_arrow()

        return read

    return Dataset([make(s) for s in specs])


def read_mongo(uri: str = None, *, database: str, collection: str,
               query: Optional[Dict[str, Any]] = None,
               client_factory=None,
               override_num_blocks: int = 1) -> Dataset:
    """Read a MongoDB collection into a Dataset (ref analogue:
    ray.data.read_mongo / mongo_datasource.py). ``client_factory``
    defaults to ``pymongo.MongoClient(uri)`` (pymongo is an optional
    dependency); inject a fake for tests/offline use. Shards split by
    server-side skip/limit over the stably _id-ordered cursor — each
    shard transfers only ITS contiguous window, and the count query runs
    once per shard (cheap; index-only)."""

    def make(shard, nshards):
        def read():
            if client_factory is not None:
                client = client_factory()
            else:
                try:
                    import pymongo
                except ImportError as e:
                    raise ImportError(
                        "read_mongo requires the 'pymongo' package "
                        "(or pass client_factory=)"
                    ) from e
                client = pymongo.MongoClient(uri)
            coll = client[database][collection]
            q = query or {}
            cursor = coll.find(q).sort("_id", 1)
            if nshards > 1:
                total = coll.count_documents(q)
                lo = total * shard // nshards
                hi = total * (shard + 1) // nshards
                cursor = cursor.skip(lo).limit(hi - lo)
            docs = list(cursor)
            for d in docs:
                d.pop("_id", None)  # ObjectId is not arrow-able
            return from_rows(docs)

        return read

    n = max(1, int(override_num_blocks))
    return Dataset([make(i, n) for i in builtins.range(n)])
