"""TFRecord datasource/sink without a tensorflow dependency.

Ref analogue: python/ray/data/datasource tfrecords reader/writer (the
reference parses tf.train.Example via TF). Here both halves are
self-contained:

- Container framing: ``[len:u64le][masked_crc32c(len):u32le][payload]
  [masked_crc32c(payload):u32le]`` — the standard TFRecord layout, with
  a table-driven pure-python CRC32C (Castagnoli) and the TF mask so
  files interoperate with TensorFlow readers.
- Payloads are tf.train.Example protos; a minimal hand-rolled protobuf
  codec covers the Example schema (features -> feature map ->
  bytes_list/float_list/int64_list), which is all the reference's
  reader handles either.
"""

from __future__ import annotations

import os
import struct
from typing import Any, Dict, Iterator, List

# ---------------------------------------------------------------- crc32c

_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78  # Castagnoli, reflected
        table = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------------- container

def read_records(path: str, *, verify: bool = True) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise ValueError(f"truncated TFRecord header in {path}")
            (length,), (lcrc,) = (struct.unpack("<Q", header[:8]),
                                  struct.unpack("<I", header[8:]))
            if verify and _masked_crc(header[:8]) != lcrc:
                raise ValueError(f"corrupt TFRecord length crc in {path}")
            payload = f.read(length)
            (pcrc,) = struct.unpack("<I", f.read(4))
            if verify and _masked_crc(payload) != pcrc:
                raise ValueError(f"corrupt TFRecord payload crc in {path}")
            yield payload


def write_records(path: str, payloads: Iterator[bytes]) -> int:
    n = 0
    with open(path, "wb") as f:
        for payload in payloads:
            header = struct.pack("<Q", len(payload))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(payload)
            f.write(struct.pack("<I", _masked_crc(payload)))
            n += 1
    return n


# ------------------------------------------- minimal tf.train.Example codec
#
# Wire schema (all fields are submessages with inner field 1):
#   Example.features (field 1) -> Features.feature map<string, Feature>
#   (field 1); each map entry: key (field 1, string), value (field 2,
#   Feature); Feature is a oneof: bytes_list=1, float_list=2,
#   int64_list=3; each list's values live in its field 1 (floats fixed32,
#   int64 varint — packed or repeated).

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: memoryview, pos: int):
    shift = 0
    val = 0
    while True:
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7


def _len_delim(field: int, payload: bytes) -> bytes:
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def encode_example(features: Dict[str, Any]) -> bytes:
    """Build a tf.train.Example proto from {name: value(s)}: bytes/str ->
    bytes_list, float -> float_list, int -> int64_list (scalars or
    lists)."""
    entries = b""
    for name, value in features.items():
        vals = list(value) if isinstance(value, (list, tuple)) else [value]
        # Numpy scalars (arrow/pandas rows) -> native python types.
        vals = [v.item() if hasattr(v, "item") else v for v in vals]
        if all(isinstance(v, (bytes, str)) for v in vals):
            items = b"".join(
                _len_delim(1, v.encode() if isinstance(v, str) else v)
                for v in vals
            )
            feature = _len_delim(1, items)          # bytes_list
        elif all(isinstance(v, (bool, int)) for v in vals):
            # field 1 varint: tag byte 0x08 per value
            items = b"".join(b"\x08" + _varint(int(v) & ((1 << 64) - 1))
                             for v in vals)
            feature = _len_delim(3, items)          # int64_list
        elif all(isinstance(v, (int, float)) for v in vals):
            items = b"".join(b"\x0d" + struct.pack("<f", float(v))
                             for v in vals)          # field 1 fixed32
            feature = _len_delim(2, items)          # float_list
        else:
            raise TypeError(f"unsupported feature type for {name!r}")
        entry = _len_delim(1, name.encode()) + _len_delim(2, feature)
        entries += _len_delim(1, entry)
    features_msg = entries
    return _len_delim(1, features_msg)


def _parse_fields(buf: memoryview) -> Iterator[Any]:
    pos = 0
    end = len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 2:
            length, pos = _read_varint(buf, pos)
            yield field, buf[pos:pos + length]
            pos += length
        elif wire == 0:
            val, pos = _read_varint(buf, pos)
            yield field, val
        elif wire == 5:
            yield field, buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            yield field, buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")


def _parse_feature(buf: memoryview):
    for field, payload in _parse_fields(buf):
        if field == 1:      # bytes_list
            return [bytes(v) for f, v in _parse_fields(payload) if f == 1]
        if field == 2:      # float_list (packed or repeated fixed32)
            vals: List[float] = []
            for f, v in _parse_fields(payload):
                if f != 1:
                    continue
                if isinstance(v, memoryview) and len(v) == 4:
                    vals.append(struct.unpack("<f", v)[0])
                elif isinstance(v, memoryview):  # packed
                    vals.extend(
                        struct.unpack(f"<{len(v) // 4}f", v)
                    )
            return vals
        if field == 3:      # int64_list (packed or repeated varint)
            ints: List[int] = []
            for f, v in _parse_fields(payload):
                if f != 1:
                    continue
                if isinstance(v, int):
                    ints.append(v if v < (1 << 63) else v - (1 << 64))
                else:  # packed varints
                    pos = 0
                    while pos < len(v):
                        val, pos = _read_varint(v, pos)
                        ints.append(val if val < (1 << 63)
                                    else val - (1 << 64))
            return ints
    return []


def decode_example(payload: bytes) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for field, features_msg in _parse_fields(memoryview(payload)):
        if field != 1:
            continue
        for f2, entry in _parse_fields(features_msg):
            if f2 != 1:
                continue
            name = None
            feature = None
            for f3, v in _parse_fields(entry):
                if f3 == 1:
                    name = bytes(v).decode()
                elif f3 == 2:
                    feature = _parse_feature(v)
            if name is not None:
                vals = feature or []
                out[name] = vals[0] if len(vals) == 1 else vals
    return out


# --------------------------------------------------------------- dataset IO

def read_example_file(path: str) -> List[Dict[str, Any]]:
    return [decode_example(rec) for rec in read_records(path)]


def write_example_file(path: str, rows: List[Dict[str, Any]]) -> str:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    write_records(path, (encode_example(r) for r in rows))
    return path
