"""Avro Object Container File codec — dependency-free.

Ref analogue: ray.data.read_avro
(python/ray/data/datasource/avro_datasource.py, which delegates to the
`fastavro` package). This image ships no avro library, so the codec is
implemented here against the Avro 1.11 spec: OCF layout
(magic ``Obj\\x01`` | metadata map with ``avro.schema``/``avro.codec``
| 16-byte sync marker | blocks of ``count, byte-size, records`` each
followed by the sync marker), binary encoding (zigzag-varint
longs, little-endian float/double, length-prefixed bytes/strings),
``null`` and ``deflate`` codecs, and the schema types the tabular
layer produces: primitives, records, enums, fixed, arrays, maps and
unions.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, List, Tuple

MAGIC = b"Obj\x01"


# ------------------------------------------------------------ binary layer


def _read_long(buf: io.BytesIO) -> int:
    """Zigzag varint (the avro long/int wire format)."""
    shift = 0
    accum = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated avro varint")
        byte = b[0]
        accum |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    return (accum >> 1) ^ -(accum & 1)


def _write_long(out: io.BytesIO, n: int):
    n = (n << 1) ^ (n >> 63)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise EOFError("truncated avro bytes")
    return data


def _write_bytes(out: io.BytesIO, data: bytes):
    _write_long(out, len(data))
    out.write(data)


def _read_datum(buf: io.BytesIO, schema: Any) -> Any:
    if isinstance(schema, list):                      # union
        idx = _read_long(buf)
        return _read_datum(buf, schema[idx])
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return {
                f["name"]: _read_datum(buf, f["type"])
                for f in schema["fields"]
            }
        if t == "array":
            out: List[Any] = []
            while True:
                count = _read_long(buf)
                if count == 0:
                    return out
                if count < 0:
                    _read_long(buf)  # block byte size, unused
                    count = -count
                out.extend(
                    _read_datum(buf, schema["items"])
                    for _ in range(count)
                )
        if t == "map":
            m: Dict[str, Any] = {}
            while True:
                count = _read_long(buf)
                if count == 0:
                    return m
                if count < 0:
                    _read_long(buf)
                    count = -count
                for _ in range(count):
                    k = _read_bytes(buf).decode()
                    m[k] = _read_datum(buf, schema["values"])
        if t == "enum":
            return schema["symbols"][_read_long(buf)]
        if t == "fixed":
            return buf.read(schema["size"])
        schema = t                                    # {"type": "long"}
    if schema == "null":
        return None
    if schema == "boolean":
        return buf.read(1) == b"\x01"
    if schema in ("int", "long"):
        return _read_long(buf)
    if schema == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if schema == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if schema == "bytes":
        return _read_bytes(buf)
    if schema == "string":
        return _read_bytes(buf).decode()
    raise ValueError(f"unsupported avro type {schema!r}")


def _write_datum(out: io.BytesIO, schema: Any, value: Any):
    if isinstance(schema, list):                      # union
        for i, branch in enumerate(schema):
            if _matches(branch, value):
                _write_long(out, i)
                _write_datum(out, branch, value)
                return
        raise ValueError(f"value {value!r} matches no union branch")
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            for f in schema["fields"]:
                _write_datum(out, f["type"], value[f["name"]])
            return
        if t == "array":
            if value:
                _write_long(out, len(value))
                for item in value:
                    _write_datum(out, schema["items"], item)
            _write_long(out, 0)
            return
        if t == "map":
            if value:
                _write_long(out, len(value))
                for k, v in value.items():
                    _write_bytes(out, str(k).encode())
                    _write_datum(out, schema["values"], v)
            _write_long(out, 0)
            return
        if t == "enum":
            _write_long(out, schema["symbols"].index(value))
            return
        if t == "fixed":
            out.write(value)
            return
        schema = t
    if schema == "null":
        return
    if schema == "boolean":
        out.write(b"\x01" if value else b"\x00")
    elif schema in ("int", "long"):
        _write_long(out, int(value))
    elif schema == "float":
        out.write(struct.pack("<f", float(value)))
    elif schema == "double":
        out.write(struct.pack("<d", float(value)))
    elif schema == "bytes":
        _write_bytes(out, bytes(value))
    elif schema == "string":
        _write_bytes(out, str(value).encode())
    else:
        raise ValueError(f"unsupported avro type {schema!r}")


def _matches(schema: Any, value: Any) -> bool:
    t = schema["type"] if isinstance(schema, dict) else schema
    if t == "null":
        return value is None
    if value is None:
        return False
    if t == "boolean":
        return isinstance(value, bool)
    if t in ("int", "long"):
        return isinstance(value, int) and not isinstance(value, bool)
    if t in ("float", "double"):
        return isinstance(value, (int, float))
    if t == "string":
        return isinstance(value, str)
    if t == "bytes":
        return isinstance(value, (bytes, bytearray))
    if t == "array":
        return isinstance(value, list)
    if t == "map":
        return isinstance(value, dict)
    if t == "record":
        return isinstance(value, dict)
    return True


# --------------------------------------------------------------- container


def read_avro_file(path: str) -> List[Dict[str, Any]]:
    """All records of one OCF file as python dicts."""
    with open(path, "rb") as f:
        data = f.read()
    buf = io.BytesIO(data)
    if buf.read(4) != MAGIC:
        raise ValueError(f"{path}: not an avro object container file")
    meta: Dict[str, bytes] = {}
    while True:
        count = _read_long(buf)
        if count == 0:
            break
        if count < 0:
            _read_long(buf)
            count = -count
        for _ in range(count):
            k = _read_bytes(buf).decode()
            meta[k] = _read_bytes(buf)
    schema = json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    sync = buf.read(16)
    records: List[Dict[str, Any]] = []
    while buf.tell() < len(data):
        count = _read_long(buf)
        size = _read_long(buf)
        payload = buf.read(size)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        rbuf = io.BytesIO(payload)
        records.extend(_read_datum(rbuf, schema) for _ in range(count))
        if buf.read(16) != sync:
            raise ValueError(f"{path}: sync marker mismatch")
    return records


def infer_schema(rows: List[Dict[str, Any]],
                 name: str = "Row") -> Dict[str, Any]:
    """Record schema from sampled rows; fields seen as None anywhere
    become ["null", T] unions."""
    types: Dict[str, set] = {}
    for row in rows:
        for k, v in row.items():
            types.setdefault(k, set()).add(_py_avro_type(v))
    fields = []
    for k in sorted(types):
        ts = types[k]
        nullable = "null" in ts
        ts.discard("null")
        if len(ts) > 1:
            # int+float widen to double; else fall back to a union
            if ts <= {"long", "double"}:
                ts = {"double"}
        t: Any = sorted(ts)[0] if len(ts) == 1 else sorted(ts)
        if nullable:
            t = ["null", t] if not isinstance(t, list) else \
                ["null"] + t
        fields.append({"name": k, "type": t})
    return {"type": "record", "name": name, "fields": fields}


def _py_avro_type(v: Any) -> Any:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, int):
        return "long"
    if isinstance(v, float):
        return "double"
    if isinstance(v, (bytes, bytearray)):
        return "bytes"
    if isinstance(v, str):
        return "string"
    raise ValueError(
        f"cannot infer avro type for {type(v).__name__} "
        f"(convert arrays/objects to lists/dicts with an explicit "
        f"schema)"
    )


def write_avro_file(path: str, rows: List[Dict[str, Any]],
                    schema: Dict[str, Any] = None,
                    codec: str = "deflate"):
    """One OCF file with a single block."""
    if schema is None:
        schema = infer_schema(rows)
    body = io.BytesIO()
    for row in rows:
        _write_datum(body, schema, row)
    payload = body.getvalue()
    if codec == "deflate":
        comp = zlib.compressobj(wbits=-15)
        payload = comp.compress(payload) + comp.flush()
    elif codec != "null":
        raise ValueError(f"unsupported avro codec {codec!r}")
    sync = os.urandom(16)
    out = io.BytesIO()
    out.write(MAGIC)
    meta: List[Tuple[str, bytes]] = [
        ("avro.schema", json.dumps(schema).encode()),
        ("avro.codec", codec.encode()),
    ]
    _write_long(out, len(meta))
    for k, v in meta:
        _write_bytes(out, k.encode())
        _write_bytes(out, v)
    _write_long(out, 0)
    out.write(sync)
    _write_long(out, len(rows))
    _write_long(out, len(payload))
    out.write(payload)
    out.write(sync)
    with open(path, "wb") as f:
        f.write(out.getvalue())
