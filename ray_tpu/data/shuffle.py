"""Distributed two-stage shuffle.

Ref analogue: python/ray/data/_internal/push_based_shuffle.py +
planner/exchange/ (ShuffleTaskSpec, sort/repartition/random-shuffle task
schedulers). Design (tpu-repo original): map tasks partition each input
block and ``put`` every partition into the object store (so partitions
live distributed, never on the driver); reduce tasks fetch their
partition refs — cross-node pulls ride the object transfer protocol —
and assemble the output block. The driver only moves ObjectRefs.

partition assignment is a top-level function + args (picklable), one of:
- random:   seeded per-block permutation → round-robin split (shuffle)
- contiguous: row ranges (repartition)
- range:    searchsorted against sampled boundaries (sort)
- hash:     stable hash of key column mod R (groupby)
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from .block import BlockAccessor, concat_blocks


# ---- partition assigners (return list of index arrays, one per reducer) --

def _assign_random(block, num: int, seed) -> List[np.ndarray]:
    n = BlockAccessor(block).num_rows()
    idx = np.random.RandomState(seed).permutation(n)
    return [idx[r::num] for r in range(num)]


def _assign_contiguous(block, num: int, _arg) -> List[np.ndarray]:
    n = BlockAccessor(block).num_rows()
    bounds = np.linspace(0, n, num + 1).astype(np.int64)
    all_idx = np.arange(n)
    return [all_idx[bounds[r]:bounds[r + 1]] for r in range(num)]


def _assign_range(block, num: int, arg) -> List[np.ndarray]:
    key, boundaries, descending = arg
    col = BlockAccessor(block).to_numpy()[key]
    part = np.searchsorted(np.asarray(boundaries), col, side="right")
    if descending:
        part = (num - 1) - part
    return [np.nonzero(part == r)[0] for r in range(num)]


def _assign_hash(block, num: int, key) -> List[np.ndarray]:
    col = BlockAccessor(block).to_numpy()[key]
    if col.dtype.kind in "OUS":  # strings/objects: stable per-value hash
        import zlib

        part = np.asarray(
            [zlib.crc32(str(v).encode()) % num for v in col],
            dtype=np.int64,
        )
    else:
        part = np.asarray(col).view(np.ndarray).astype(np.int64) % num
    return [np.nonzero(part == r)[0] for r in range(num)]


_ASSIGNERS = {
    "random": _assign_random,
    "contiguous": _assign_contiguous,
    "range": _assign_range,
    "hash": _assign_hash,
}


# ---- task bodies ---------------------------------------------------------

def _shuffle_map(src: Callable[[], Any], ops: List[Any], assigner: str,
                 num_reducers: int, arg) -> tuple:
    """Run the fused upstream chain on one source block and split it into
    ``num_reducers`` partitions, one per RETURN SLOT (``num_returns=R``,
    the reference's shuffle_map signature — shuffle_op.py): return-slot
    objects are owned/held by the submitting driver, so partitions stay
    alive in the distributed store until every reducer consumed them."""
    block = src()
    for op in ops:
        block = op.apply(block)
    acc = BlockAccessor(block)
    parts = _ASSIGNERS[assigner](block, num_reducers, arg)
    out = tuple(acc.take_indices(idx) for idx in parts)
    return out if num_reducers > 1 else out[0]


def _shuffle_reduce(postprocess, *blocks) -> Any:
    """Assemble one reducer's output from its partitions (passed as
    top-level ref args: the runtime pulls cross-node copies as needed)."""
    block = concat_blocks(list(blocks))
    if postprocess is not None:
        block = postprocess(block)
    return block


def _shuffle_merge(width: int, *round_parts) -> tuple:
    """Push-based shuffle's MERGE stage (ref:
    data/_internal/push_based_shuffle.py): combine one round's map
    partials for a SLICE of ``width`` reducers into one merged block per
    reducer. ``round_parts`` arrives flattened as width-sized groups,
    one group per map task in the round."""
    merged = []
    for r in range(width):
        merged.append(concat_blocks(
            [round_parts[m * width + r]
             for m in range(len(round_parts) // width)]
        ))
    out = tuple(merged)
    return out if width > 1 else out[0]


def _sample_block(src: Callable[[], Any], ops: List[Any], key: str,
                  max_samples: int) -> np.ndarray:
    block = src()
    for op in ops:
        block = op.apply(block)
    col = BlockAccessor(block).to_numpy()[key]
    if len(col) > max_samples:
        sel = np.random.RandomState(0).choice(
            len(col), max_samples, replace=False
        )
        col = col[sel]
    return np.asarray(col)


class _SortBlock:
    def __init__(self, key: str, descending: bool):
        self.key = key
        self.descending = descending

    def __call__(self, block):
        acc = BlockAccessor(block)
        col = acc.to_numpy()[self.key]
        idx = np.argsort(col, kind="stable")
        if self.descending:
            idx = idx[::-1]
        return acc.take_indices(idx)


# ---- driver-side orchestration ------------------------------------------

def shuffle(sources: Sequence[Callable[[], Any]], ops: List[Any],
            num_reducers: int, assigner: str, arg=None,
            postprocess=None,
            push_based: Optional[bool] = None
            ) -> Tuple[List[Any], List[Any]]:
    """Distributed shuffle. Returns (reduce_refs, pin) — ``pin`` holds
    the intermediate refs and must stay referenced until the reduce
    outputs are consumed.

    Two execution plans (ref: simple_shuffle vs the reference's
    push_based_shuffle.py / Exoshuffle):

    - SIMPLE (small M): M map tasks x R return slots feed R reduce
      tasks directly; every reducer fans in M refs and all M x R
      partials stay live until the last reducer ran.
    - PUSH-BASED (default at M >= 16, or DataContext.push_based_shuffle
      / the ``push_based`` arg): maps run in rounds of ~sqrt(M); each
      round's partials MERGE immediately into per-reducer blocks (merge
      tasks sliced over the reducer range, pipelining with the next
      round's maps), so reducer fan-in drops from M to the round count
      and a round's M x R map partials can be collected as soon as its
      merges finish instead of living for the whole shuffle.
    """
    import math

    import ray_tpu

    M = len(sources)
    if push_based is None:
        from .context import DataContext

        ctx_flag = DataContext.get_current().push_based_shuffle
        push_based = (M >= 16) if ctx_flag is None else ctx_flag

    map_task = ray_tpu.remote(_shuffle_map).options(
        num_returns=num_reducers
    )
    reduce_task = ray_tpu.remote(_shuffle_reduce)

    def run_maps(idx_src):
        i, src = idx_src
        refs = map_task.remote(
            src, ops, assigner, num_reducers,
            (arg ^ i if assigner == "random" else arg),
        )
        return refs if isinstance(refs, list) else [refs]

    if not push_based or M < 2:
        part_lists = [run_maps(x) for x in enumerate(sources)]
        reduce_refs = [
            reduce_task.remote(postprocess, *[pl[r] for pl in part_lists])
            for r in range(num_reducers)
        ]
        return reduce_refs, part_lists

    R = num_reducers
    round_size = max(2, int(math.ceil(math.sqrt(M))))
    # Slice reducers among merge tasks so one merge's fan-in stays at
    # round_size x slice_width refs.
    slice_width = min(R, 8)
    slices = [(lo, min(lo + slice_width, R))
              for lo in range(0, R, slice_width)]
    # merged[round][r] = merged block ref for reducer r in that round.
    merged_rounds: List[List[Any]] = []
    pin: List[Any] = []
    for lo_m in range(0, M, round_size):
        if len(merged_rounds) >= 2:
            # THROTTLE: at most two rounds in flight (one merging while
            # the next maps — the pipeline overlap) before submitting
            # more, so peak live map partials stay ~2 rounds' worth
            # instead of all M x R (the plan's whole point; ref: the
            # reference gates rounds on merge completion too).
            prev = merged_rounds[-2]
            ray_tpu.wait(prev, num_returns=len(prev), timeout=None)
        round_parts = [
            run_maps((i, sources[i]))
            for i in range(lo_m, min(lo_m + round_size, M))
        ]
        round_merged: List[Any] = [None] * R
        for lo, hi in slices:
            width = hi - lo
            merge = ray_tpu.remote(_shuffle_merge).options(
                num_returns=width
            )
            flat = [pl[r] for pl in round_parts
                    for r in range(lo, hi)]
            out = merge.remote(width, *flat)
            out = out if isinstance(out, list) else [out]
            for k, r in enumerate(range(lo, hi)):
                round_merged[r] = out[k]
        # The map partials are consumed by the merges; dropping our refs
        # here lets each round's M x R partials be collected as soon as
        # its merges finish (the merge task specs pin them until then).
        merged_rounds.append(round_merged)
        pin.extend(round_merged)
    reduce_refs = [
        reduce_task.remote(
            postprocess, *[rnd[r] for rnd in merged_rounds]
        )
        for r in range(R)
    ]
    return reduce_refs, pin


def sample_sort_boundaries(sources: Sequence[Callable[[], Any]],
                           ops: List[Any], key: str, num: int,
                           max_samples_per_block: int = 128) -> np.ndarray:
    """Stage 0 of distributed sort: sample each block's key column and cut
    the sampled distribution into ``num`` quantile ranges (ref:
    planner/exchange/sort_task_spec.py SortTaskSpec.sample_boundaries)."""
    import ray_tpu

    sampler = ray_tpu.remote(_sample_block)
    samples = ray_tpu.get([
        sampler.remote(src, ops, key, max_samples_per_block)
        for src in sources
    ])
    allv = np.sort(np.concatenate([s for s in samples if len(s)]))
    if len(allv) == 0:
        return np.asarray([])
    cuts = [
        allv[int(len(allv) * (r + 1) / num) - 1] for r in range(num - 1)
    ]
    return np.asarray(cuts)
