"""ray_tpu.data: distributed datasets (Ray Data equivalent, TPU-native
ingest: streaming block execution + HBM prefetch via iter_jax_batches)."""

from .block import Block, BlockAccessor  # noqa: F401
from .context import DataContext  # noqa: F401
from .dataset import Dataset  # noqa: F401
from .iterator import DataIterator  # noqa: F401
from . import preprocessors  # noqa: F401
from .read_api import (  # noqa: F401
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    from_tf,
    from_torch,
    range,
    read_avro,
    read_bigquery,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_lance,
    read_mongo,
    read_numpy,
    read_parquet,
    read_sql,
    read_tfrecords,
    read_webdataset,
)

from ray_tpu.util import usage_stats as _usage
_usage.record_library_usage("data")
