"""DataIterator: a per-worker shard view of a Dataset.

Ref analogue: python/ray/data/iterator.py DataIterator
(iter_batches:98, iter_torch_batches:242 → here iter_jax_batches). Picklable
(carries the lazy plan) so trainers ship it to workers; blocks execute
where the iterator is consumed.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional


class DataIterator:
    def __init__(self, dataset, shard_index: int, num_shards: int):
        self._dataset = dataset
        self.shard_index = shard_index
        self.num_shards = num_shards

    def _shard(self):
        from .dataset import Dataset

        ds = self._dataset
        return Dataset(
            ds._sources[self.shard_index :: self.num_shards],
            list(ds._stages), _pin=ds._pin,
        )

    def iter_batches(self, **kw) -> Iterator[Any]:
        return self._shard().iter_batches(**kw)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        return self._shard().iter_rows()

    def iter_jax_batches(self, **kw) -> Iterator[Any]:
        return self._shard().iter_jax_batches(**kw)

    def count(self) -> int:
        return self._shard().count()

    def materialize(self):
        return self._shard().materialize()

    def __repr__(self):
        return (f"DataIterator(shard={self.shard_index}/"
                f"{self.num_shards})")
