"""DataIterator: a per-worker shard view of a Dataset.

Ref analogue: python/ray/data/iterator.py DataIterator
(iter_batches:98, iter_torch_batches:242 → here iter_jax_batches). Picklable
(carries the lazy plan) so trainers ship it to workers; blocks execute
where the iterator is consumed. Flat plans shard by source stride; DAG
plans (union/zip) stream through ONE shared ``_SplitCoordinator`` actor
that executes the plan once and deals blocks round-robin (ref analogue:
the OutputSplitter behind Dataset.streaming_split) — nothing
materializes up front, and a full shard buffer stalls the upstream pull
so backpressure propagates through the split.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional


class _SplitCoordinator:
    """Actor: executes a (DAG) plan's block stream once and serves
    shards round-robin with small bounded buffers. The puller thread
    blocks while its next target's buffer is full, so a slow shard
    backpressures the whole stream instead of buffering it."""

    def __init__(self, ds_blob: bytes, num_shards: int, maxbuf: int = 4):
        import collections
        import threading

        import cloudpickle

        self._ds = cloudpickle.loads(ds_blob)
        self._n = num_shards
        self._maxbuf = maxbuf
        self._bufs = [collections.deque() for _ in range(num_shards)]
        self._cv = threading.Condition()
        self._done = False
        self._error = None
        self._puller = threading.Thread(target=self._pull, daemon=True)
        self._puller.start()

    def _pull(self):
        try:
            target = 0
            for ref in self._ds.iter_blocks_refs():
                with self._cv:
                    while len(self._bufs[target]) >= self._maxbuf:
                        self._cv.wait(timeout=1.0)
                    self._bufs[target].append(ref)
                    self._cv.notify_all()
                target = (target + 1) % self._n
        except Exception as e:  # surfaced to every shard
            self._error = e
        finally:
            with self._cv:
                self._done = True
                self._cv.notify_all()

    def next_for(self, shard: int):
        """Next block ref for ``shard`` (None = exhausted)."""
        with self._cv:
            while not self._bufs[shard] and not self._done:
                self._cv.wait(timeout=1.0)
            if self._error is not None:
                raise self._error
            if self._bufs[shard]:
                return self._bufs[shard].popleft()
            return None


class _CoordinatorShard:
    """Dataset-shaped adapter over a coordinator shard: provides the
    block iteration surface Dataset's batching helpers consume."""

    def __init__(self, coord, shard_index: int):
        self._coord = coord
        self._shard_index = shard_index

    def _iter_blocks(self):
        import ray_tpu

        while True:
            ref = ray_tpu.get(
                self._coord.next_for.remote(self._shard_index),
                timeout=600,
            )
            if ref is None:
                return
            yield ray_tpu.get(ref)


class DataIterator:
    def __init__(self, dataset, shard_index: int, num_shards: int,
                 coordinator=None):
        self._dataset = dataset
        self.shard_index = shard_index
        self.num_shards = num_shards
        self._coordinator = coordinator

    def _shard(self):
        from .dataset import Dataset

        ds = self._dataset
        if self._coordinator is not None:
            # Stream through the shared coordinator: reuse Dataset's
            # batching by wrapping the pulled blocks as a one-source
            # plan whose single "read" drains this shard.
            shard = _CoordinatorShard(self._coordinator, self.shard_index)
            out = Dataset([], _pin=ds._pin)
            out._iter_blocks = shard._iter_blocks  # type: ignore
            return out
        return Dataset(
            ds._sources[self.shard_index :: self.num_shards],
            list(ds._stages), _pin=ds._pin,
        )

    def iter_batches(self, **kw) -> Iterator[Any]:
        return self._shard().iter_batches(**kw)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        return self._shard().iter_rows()

    def iter_jax_batches(self, **kw) -> Iterator[Any]:
        return self._shard().iter_jax_batches(**kw)

    def iter_torch_batches(self, **kw) -> Iterator[Any]:
        return self._shard().iter_torch_batches(**kw)

    def count(self) -> int:
        return self._shard().count()

    def materialize(self):
        if self._coordinator is not None:
            from .dataset import Dataset

            return Dataset.from_blocks(list(self._shard()._iter_blocks()))
        return self._shard().materialize()

    def __repr__(self):
        return (f"DataIterator(shard={self.shard_index}/"
                f"{self.num_shards})")
