"""Dataset: lazy, streaming, distributed data over blocks.

Ref analogue: python/ray/data/dataset.py Dataset (:158) with the logical
plan + streaming execution model of _internal/execution/ (SURVEY.md §2.3):
transforms build a lazy STAGE pipeline (streaming_executor.py) — fused
per-block task chains plus actor-pool stages for stateful transforms —
executed with per-stage bounded in-flight windows (backpressure). Global
ops (shuffle/sort/repartition) run as distributed two-stage shuffles
(shuffle.py) whose intermediate partitions never touch the driver.
"""

from __future__ import annotations

import inspect
import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from .block import (
    Block,
    BlockAccessor,
    batch_to_format,
    concat_blocks,
    from_numpy_dict,
    normalize_to_block,
)
from .context import DataContext
from .streaming_executor import (
    ActorStage,
    TaskStage,
    UnionSource,
    ZipSource,
    execute,
    execute_refs,
)


# ----------------------------------------------------------- logical plan

class _Op:
    """A per-block transform (fusable)."""

    def apply(self, block: Block) -> Block:
        raise NotImplementedError


class _MapBatches(_Op):
    def __init__(self, fn, batch_format: str, batch_size: Optional[int]):
        self.fn = fn
        self.batch_format = batch_format
        self.batch_size = batch_size

    def apply(self, block: Block) -> Block:
        acc = BlockAccessor(block)
        n = acc.num_rows()
        bs = self.batch_size or max(n, 1)
        out = []
        for start in range(0, max(n, 1), bs):
            sub = acc.slice(start, min(start + bs, n)) if n else block
            batch = batch_to_format(sub, self.batch_format)
            res = self.fn(batch)
            out.append(normalize_to_block(res))
            if n == 0:
                break
        return concat_blocks(out) if out else block


class _MapRows(_Op):
    def __init__(self, fn):
        self.fn = fn

    def apply(self, block: Block) -> Block:
        from .block import from_rows

        rows = [self.fn(dict(r)) for r in BlockAccessor(block).iter_rows()]
        return from_rows(rows)


class _FlatMapRows(_Op):
    def __init__(self, fn):
        self.fn = fn

    def apply(self, block: Block) -> Block:
        from .block import from_rows

        rows = []
        for r in BlockAccessor(block).iter_rows():
            rows.extend(self.fn(dict(r)))
        return from_rows(rows)


class _FilterRows(_Op):
    def __init__(self, fn):
        self.fn = fn

    def apply(self, block: Block) -> Block:
        acc = BlockAccessor(block)
        keep = np.asarray(
            [bool(self.fn(dict(r))) for r in acc.iter_rows()], dtype=bool
        )
        return acc.take_indices(np.nonzero(keep)[0])


def _apply_chain(source: Callable[[], Block], ops: Sequence[_Op]) -> Block:
    block = source()
    for op in ops:
        block = op.apply(block)
    return block


# ---------------------------------------------------------- dlpack export

def _dlpack_alias(arr: np.ndarray) -> np.ndarray:
    """Writable-FLAGGED alias of a store-backed array for DLPack export
    (SURVEY.md §5.8 zero-copy hand-off). The store's sealed views are
    readonly, and numpy refuses to export readonly arrays through
    DLPack (the protocol cannot signal readonly); jax arrays are
    immutable, so letting jax alias the immutable store page is sound —
    the flag flip exists ONLY to satisfy the export check. Never write
    through the returned array. The alias carries a reference chain
    (jax capsule -> alias -> ctypes buffer -> original array -> store
    mapping) so the shm pages outlive every consumer."""
    if arr.flags.writeable:
        return arr
    if not arr.flags.c_contiguous:
        raise ValueError("dlpack export needs a contiguous array")
    import ctypes

    buf = (ctypes.c_char * arr.nbytes).from_address(
        arr.ctypes.data
    )
    buf._rtpu_pin = arr  # keeps the readonly view (and its mapping) alive
    return np.frombuffer(buf, dtype=arr.dtype).reshape(arr.shape)


# -------------------------------------------------------------- the API

class Dataset:
    def __init__(self, sources: List[Callable[[], Block]],
                 ops: Optional[List[Any]] = None, *, _pin: Any = None):
        # sources: zero-arg callables producing the input blocks (read tasks
        # or in-memory closures); ops: stage pipeline — a legacy flat op
        # list is wrapped into one fused TaskStage. _pin keeps upstream
        # shuffle partitions alive while this dataset's refs are consumed.
        self._sources = sources
        if ops and not isinstance(ops[0], (TaskStage, ActorStage)):
            ops = [TaskStage(ops)]
        self._stages: List[Any] = list(ops) if ops else [TaskStage([])]
        self._pin = _pin

    @property
    def _ops(self) -> List[_Op]:
        """Flat fused op chain (only valid for single-task-stage plans)."""
        assert len(self._stages) == 1 and isinstance(
            self._stages[0], TaskStage
        ), "plan has actor stages; use _stages"
        return self._stages[0].ops

    # ---- construction helpers (used by read_api) ----

    @classmethod
    def from_blocks(cls, blocks: List[Block], *, _pin: Any = None
                    ) -> "Dataset":
        return cls([(lambda b=b: b) for b in blocks], _pin=_pin)

    @classmethod
    def _from_refs(cls, refs: List[Any], *, _pin: Any = None) -> "Dataset":
        """Blocks already in the object store (e.g. shuffle output): each
        source pulls its ref where it executes — never via the driver."""

        def make(ref):
            def pull():
                import ray_tpu

                return ray_tpu.get(ref)

            return pull

        ds = cls([make(r) for r in refs], _pin=(_pin, refs))
        return ds

    # ---- lazy transforms (per-block: fused) ----

    def _with_op(self, op: _Op) -> "Dataset":
        last = self._stages[-1]
        if isinstance(last, TaskStage):
            stages = self._stages[:-1] + [last.with_op(op)]
        else:
            stages = self._stages + [TaskStage([op])]
        return Dataset(self._sources, stages, _pin=self._pin)

    def map_batches(self, fn, *, batch_format: str = "numpy",
                    batch_size: Optional[int] = None,
                    concurrency: Optional[int] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None,
                    ray_remote_args: Optional[dict] = None) -> "Dataset":
        """Per-batch transform. A CLASS argument becomes a stateful
        actor-pool stage of ``concurrency`` members, each constructing the
        class once (ref: actor_pool_map_operator.py — the operator for
        model-loading transforms)."""
        if inspect.isclass(fn):
            stage = ActorStage(
                fn, fn_constructor_args, fn_constructor_kwargs or {},
                concurrency or 2, batch_format, batch_size,
                ray_remote_args,
            )
            return Dataset(
                self._sources, self._stages + [stage], _pin=self._pin
            )
        return self._with_op(_MapBatches(fn, batch_format, batch_size))

    def map(self, fn) -> "Dataset":
        return self._with_op(_MapRows(fn))

    def flat_map(self, fn) -> "Dataset":
        return self._with_op(_FlatMapRows(fn))

    def filter(self, fn) -> "Dataset":
        return self._with_op(_FilterRows(fn))

    def add_column(self, name: str, fn) -> "Dataset":
        def add(batch: Dict[str, np.ndarray]):
            batch[name] = np.asarray(fn(batch))
            return batch

        return self.map_batches(add)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(
            lambda b: {k: v for k, v in b.items() if k not in cols}
        )

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(
            lambda b: {k: v for k, v in b.items() if k in cols}
        )

    # ---- global ops (distributed two-stage shuffles) ----

    def _use_remote(self) -> bool:
        from ..core import runtime_context

        ctx = DataContext.get_current()
        return ctx.use_remote_tasks and runtime_context.is_initialized()

    def _shuffle_plan(self, *, materialize: bool = False):
        """(sources, fusable ops, hold) for a shuffle's map stage: the
        fused op chain when the plan is one task stage, else the
        pre-executed block refs (actor stages must run before
        partitioning; sort also materializes so boundary sampling doesn't
        execute the chain twice). ``hold`` must stay pinned until the
        shuffle output is consumed — it keeps the intermediate refs alive
        past this driver frame."""
        single_task = (
            not self._is_node_plan()
            and len(self._stages) == 1
            and isinstance(self._stages[0], TaskStage)
        )
        if single_task and not materialize:
            return self._sources, self._stages[0].ops, None
        refs = list(execute_refs(self._sources, self._stages))

        def make(ref):
            def pull():
                import ray_tpu

                return ray_tpu.get(ref)

            return pull

        return [make(r) for r in refs], [], refs

    def _shuffled(self, num: int, assigner: str, arg=None,
                  postprocess=None) -> "Dataset":
        from . import shuffle as _shuffle

        srcs, ops, hold = self._shuffle_plan()
        reduce_refs, pin = _shuffle.shuffle(
            srcs, ops, num, assigner, arg, postprocess
        )
        return Dataset._from_refs(
            reduce_refs, _pin=(self._pin, pin, hold)
        )

    def repartition(self, num_blocks: int) -> "Dataset":
        if self._use_remote():
            return self._shuffled(num_blocks, "contiguous")
        full = self._materialize_table()
        n = full.num_rows
        sizes = [n // num_blocks + (1 if i < n % num_blocks else 0)
                 for i in range(num_blocks)]
        blocks, start = [], 0
        for s in sizes:
            blocks.append(full.slice(start, s))
            start += s
        return Dataset.from_blocks(blocks)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        if self._use_remote():
            import random as _random

            num = max(1, self.num_blocks())
            return self._shuffled(
                num, "random",
                seed if seed is not None else _random.randrange(2 ** 31),
            )
        full = self._materialize_table()
        idx = np.random.RandomState(seed).permutation(full.num_rows)
        shuffled = BlockAccessor(full).take_indices(idx)
        num = max(1, self.num_blocks())
        return Dataset.from_blocks([shuffled]).repartition(num)

    def sort(self, key: str, *, descending: bool = False) -> "Dataset":
        if self._use_remote():
            from . import shuffle as _shuffle

            # Materialize once: boundary sampling + shuffle both read the
            # stored blocks instead of re-running the upstream chain.
            srcs, ops, hold = self._shuffle_plan(materialize=True)
            num = max(1, len(srcs))
            bounds = _shuffle.sample_sort_boundaries(srcs, ops, key, num)
            reduce_refs, pin = _shuffle.shuffle(
                srcs, ops, num, "range", (key, bounds, descending),
                _shuffle._SortBlock(key, descending),
            )
            return Dataset._from_refs(
                reduce_refs, _pin=(self._pin, pin, hold)
            )
        full = self._materialize_table()
        col = BlockAccessor(full).to_numpy()[key]
        idx = np.argsort(col, kind="stable")
        if descending:
            idx = idx[::-1]
        return Dataset.from_blocks([BlockAccessor(full).take_indices(idx)])

    def union(self, *others: "Dataset") -> "Dataset":
        """Streaming concatenation: upstream datasets execute their own
        chains and their block streams concatenate in order — an
        operator-DAG fan-in, nothing materializes on the driver (ref:
        Dataset.union over the executor's operator graph)."""
        inputs = [self, *others]
        return Dataset(UnionSource(inputs),
                       _pin=tuple(d._pin for d in inputs))

    def zip(self, other: "Dataset") -> "Dataset":
        """Pairwise block zip: block i of ``self`` merges columns with
        block i of ``other`` (right-side name collisions get a ``_1``
        suffix). Both datasets must be identically blocked — same block
        count and per-block row counts (ref: Dataset.zip)."""
        return Dataset(ZipSource(self, other),
                       _pin=(self._pin, other._pin))

    def _is_node_plan(self) -> bool:
        return isinstance(self._sources, (UnionSource, ZipSource))

    def _ensure_flat(self) -> "Dataset":
        """A dataset whose sources are a flat thunk list — node-sourced
        plans (union/zip) materialize their blocks first (needed by the
        source-indexed paths: split, streaming_split, shuffles)."""
        return self.materialize() if self._is_node_plan() else self

    def limit(self, n: int) -> "Dataset":
        out, taken = [], 0
        for block in self._iter_blocks():
            if taken >= n:
                break
            take = min(n - taken, block.num_rows)
            out.append(block.slice(0, take))
            taken += take
        return Dataset.from_blocks(out or [from_numpy_dict({})])

    def groupby(self, key: str):
        from .grouped_data import GroupedData

        return GroupedData(self, key)

    # ---- execution ----

    def _iter_blocks(self) -> Iterator[Block]:
        """Streaming execution through the stage pipeline (per-stage
        bounded windows = per-operator backpressure; see
        streaming_executor.py)."""
        from .streaming_executor import ExecStats

        self._last_stats = ExecStats()
        yield from execute(self._sources, self._stages, self._last_stats)

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
    ) -> Iterator[Any]:
        """NOTE: numpy batches may be READ-ONLY views over the shared
        object store (the zero-copy read path); copy before mutating in
        place (``batch["x"] = batch["x"] * s``, not ``*=``)."""
        leftover: Optional[Block] = None
        for block in self._iter_blocks():
            if leftover is not None and leftover.num_rows:
                block = concat_blocks([leftover, block])
                leftover = None
            if batch_size is None:
                yield batch_to_format(block, batch_format)
                continue
            acc = BlockAccessor(block)
            n = acc.num_rows()
            start = 0
            while n - start >= batch_size:
                yield batch_to_format(
                    acc.slice(start, start + batch_size), batch_format
                )
                start += batch_size
            if start < n:
                leftover = acc.slice(start, n)
        if leftover is not None and leftover.num_rows and not drop_last:
            yield batch_to_format(leftover, batch_format)

    def iter_blocks_refs(self) -> Iterator[Any]:
        """Streaming execution yielding per-block ObjectRefs (the blocks
        stay in the object store; nothing materializes on the driver) —
        the consumption surface backpressure acts through."""
        from .streaming_executor import ExecStats

        self._last_stats = ExecStats()
        yield from execute_refs(self._sources, self._stages,
                                self._last_stats)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self._iter_blocks():
            yield from BlockAccessor(block).iter_rows()

    def iter_torch_batches(self, *, batch_size: int = 256,
                           dtypes=None, device=None,
                           drop_last: bool = False) -> Iterator[Any]:
        """Batches as {column: torch.Tensor} dicts (ref:
        iterator.py iter_torch_batches:242). Tensors wrap the numpy
        batch buffers without copy where torch allows (the store's
        read-only views are cloned first — torch cannot alias
        non-writable memory without a warning)."""
        import numpy as np
        import torch

        for batch in self.iter_batches(
            batch_size=batch_size, batch_format="numpy",
            drop_last=drop_last,
        ):
            out = {}
            for k, v in batch.items():
                arr = np.ascontiguousarray(v) if not (
                    isinstance(v, np.ndarray) and v.flags["C_CONTIGUOUS"]
                ) else v
                if isinstance(arr, np.ndarray) and \
                        not arr.flags.writeable:
                    arr = arr.copy()
                if arr.dtype == object:
                    out[k] = list(arr)  # strings/ragged pass through
                    continue
                t = torch.from_numpy(arr)
                if dtypes is not None:
                    want = (dtypes.get(k) if isinstance(dtypes, dict)
                            else dtypes)
                    if want is not None:
                        t = t.to(want)
                if device is not None:
                    t = t.to(device)
                out[k] = t
            yield out

    def iter_jax_batches(self, *, batch_size: int = 256, device=None,
                         drop_last: bool = True,
                         zero_copy: Optional[bool] = None
                         ) -> Iterator[Any]:
        """Batches as jax arrays with one-batch device prefetch (the HBM
        double-buffering path — SURVEY.md §7 phase 8).

        The batch arrays are numpy VIEWS over the shared-memory object
        store (the store's 64-byte-aligned layout exists for this;
        SURVEY.md §5.8's zero-copy hand-off). ``zero_copy=True`` imports
        them into jax via dlpack — NO copy at all on the CPU backend
        (the jax array aliases the store pages); on accelerators the
        view feeds ``device_put``'s DMA directly, skipping the
        staging copy ``jnp.asarray`` of a non-owned buffer can make.
        Default: dlpack on the CPU backend, device_put elsewhere.
        NOTE (dlpack aliasing): jax must not be handed writable aliases
        of live store pages lightly — the store is immutable by
        contract, so read-only aliasing is sound here."""
        import jax
        import jax.numpy as jnp

        if zero_copy is None:
            zero_copy = jax.default_backend() == "cpu" and device is None
        # dlpack aliasing only lands on HOST memory: with a non-CPU
        # target (explicit device, or an accelerator default backend)
        # the data must move — fall through to device_put/asarray so
        # zero_copy=True cannot silently pin batches to CPU.
        if zero_copy and (
            (device is not None
             and getattr(device, "platform", "cpu") != "cpu")
            or (device is None and jax.default_backend() != "cpu")
        ):
            zero_copy = False

        def convert(v):
            if zero_copy:
                try:
                    # copy=False: alias or raise (never silently copy —
                    # jax's copying dlpack import is SLOWER than
                    # asarray, so only the true zero-copy path is worth
                    # taking). Store buffers are 64-byte aligned by the
                    # serialization layout precisely for this.
                    return jnp.from_dlpack(_dlpack_alias(v), copy=False)
                except Exception:
                    pass  # non-contiguous/unaligned/exotic: fall through
            if device is not None:
                return jax.device_put(v, device)
            return jnp.asarray(v)

        def put(batch):
            return {k: convert(v) for k, v in batch.items()}

        it = self.iter_batches(batch_size=batch_size, drop_last=drop_last)
        prev = None
        for batch in it:
            nxt = put(batch)  # enqueue transfer before yielding previous
            if prev is not None:
                yield prev
            prev = nxt
        if prev is not None:
            yield prev

    # ---- consumption ----

    def _materialize_table(self) -> Block:
        return concat_blocks(list(self._iter_blocks()))

    def materialize(self) -> "Dataset":
        if self._use_remote():
            from .streaming_executor import ExecStats

            self._last_stats = ExecStats()
            refs = list(execute_refs(self._sources, self._stages,
                                     self._last_stats))
            out = Dataset._from_refs(refs, _pin=self._pin)
            out._last_stats = self._last_stats
            return out
        return Dataset.from_blocks(list(self._iter_blocks()))

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        return list(itertools.islice(self.iter_rows(), n))

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(b.num_rows for b in self._iter_blocks())

    def schema(self):
        for block in self._iter_blocks():
            return block.schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s else []

    def num_blocks(self) -> int:
        if isinstance(self._sources, UnionSource):
            return sum(d.num_blocks() for d in self._sources.datasets)
        if isinstance(self._sources, ZipSource):
            return self._sources.left.num_blocks()
        return len(self._sources)

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def to_numpy(self) -> Dict[str, np.ndarray]:
        return BlockAccessor(self._materialize_table()).to_numpy()

    def to_pandas(self):
        return self._materialize_table().to_pandas()

    def stats(self) -> str:
        """Per-stage / per-operator execution stats of the LAST executed
        pipeline on this dataset (wall per op, rows, bytes, blocks — ref
        analogue: data/_internal/stats.py ds.stats()); falls back to the
        static plan description before any execution."""
        last = getattr(self, "_last_stats", None)
        if last is not None and last.stage_names:
            return last.summary()
        nops = sum(
            len(s.ops) if isinstance(s, TaskStage) else 1
            for s in self._stages
        )
        return (f"Dataset(blocks={self.num_blocks()}, "
                f"stages={len(self._stages)}, ops={nops})")

    # ---- write sinks (distributed per-block writes) ----

    def write_parquet(self, path: str, **kw) -> List[str]:
        """One parquet file per block, written by remote tasks (ref:
        dataset.py write_parquet:2823)."""
        from .datasink import write_blocks

        return write_blocks(self, path, "parquet", **kw)

    def write_csv(self, path: str, **kw) -> List[str]:
        from .datasink import write_blocks

        return write_blocks(self, path, "csv", **kw)

    def write_json(self, path: str, **kw) -> List[str]:
        from .datasink import write_blocks

        return write_blocks(self, path, "json", **kw)

    def write_tfrecords(self, path: str, **kw) -> List[str]:
        """One TFRecord file of tf.train.Example protos per block (ref:
        dataset write_tfrecords; codec in data/tfrecords.py)."""
        from .datasink import write_blocks

        return write_blocks(self, path, "tfrecords", **kw)

    def write_avro(self, path: str, **kw) -> List[str]:
        """One Avro Object Container File per block (ref:
        write_avro; codec in data/avro.py)."""
        from .datasink import write_blocks

        return write_blocks(self, path, "avro", **kw)

    def write_webdataset(self, path: str, **kw) -> List[str]:
        """One WebDataset tar shard per block (ref: write_webdataset)."""
        from .datasink import write_blocks

        return write_blocks(self, path, "webdataset", **kw)

    def write_numpy(self, path: str, *, column: str = "data") -> List[str]:
        from .datasink import write_blocks

        return write_blocks(
            self.select_columns([column]), path, "npy"
        )

    def to_random_access(self, key: str, *, num_workers: int = 2):
        """Materialize into a range-partitioned actor pool supporting
        O(1) point lookups by ``key`` (ref analogue:
        Dataset.to_random_access_dataset / random_access_dataset.py)."""
        from .random_access import RandomAccessDataset

        return RandomAccessDataset(self, key, num_workers=num_workers)

    # ---- splitting for train ingest ----

    def streaming_split(self, n: int, *, equal: bool = True
                        ) -> List["DataIterator"]:
        """Per-worker shard iterators (ref: dataset.py:1269
        streaming_split). Shard i consumes source blocks i, i+n, ..."""
        from .iterator import DataIterator

        if self._is_node_plan() and self._use_remote():
            # DAG plans stream through ONE shared coordinator actor
            # (executes the plan once, deals blocks round-robin with
            # bounded buffers) — splitting must not materialize the
            # upstream (ref: OutputSplitter behind streaming_split).
            import cloudpickle

            import ray_tpu
            from .iterator import _SplitCoordinator

            coord = ray_tpu.remote(max_concurrency=n + 1)(
                _SplitCoordinator
            ).remote(cloudpickle.dumps(self), n)
            return [DataIterator(self, shard_index=i, num_shards=n,
                                 coordinator=coord)
                    for i in range(n)]
        flat = self._ensure_flat()
        return [DataIterator(flat, shard_index=i, num_shards=n)
                for i in range(n)]

    def split(self, n: int) -> List["Dataset"]:
        flat = self._ensure_flat()
        return [
            Dataset(flat._sources[i::n], list(flat._stages),
                    _pin=flat._pin)
            for i in range(n)
        ]

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        """Split at global row offsets (ref: dataset.split_at_indices);
        materializes block boundaries."""
        bounds = list(indices) + [None]
        out: List[List[Block]] = [[] for _ in bounds]
        row = 0
        part = 0
        for block in self._iter_blocks():
            off = 0
            while off < block.num_rows:
                end = bounds[part]
                if end is None:
                    out[part].append(block.slice(
                        off, block.num_rows - off
                    ))
                    off = block.num_rows
                    continue
                take = min(block.num_rows - off, end - row)
                if take > 0:
                    out[part].append(block.slice(off, take))
                    off += take
                    row += take
                if row >= end:
                    part += 1
            # blocks exhausted; advance parts with zero-length bounds
            while bounds[part] is not None and row >= bounds[part]:
                part += 1
        from .block import from_numpy_dict

        return [
            Dataset.from_blocks(blocks or [from_numpy_dict({})],
                                _pin=self._pin)
            for blocks in out
        ]

    def split_proportionately(self, proportions: List[float]
                              ) -> List["Dataset"]:
        """Split by fractions; the remainder forms the final split
        (ref: dataset.split_proportionately)."""
        if not proportions or sum(proportions) >= 1.0 or \
                any(p <= 0 for p in proportions):
            raise ValueError(
                "proportions must be positive and sum to < 1"
            )
        n = self.count()
        indices, acc = [], 0
        for p in proportions:
            acc += int(n * p)
            indices.append(acc)
        return self.split_at_indices(indices)

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> List["Dataset"]:
        """(train, test) split (ref: dataset.train_test_split)."""
        if not 0 < test_size < 1:
            raise ValueError("test_size must be in (0, 1)")
        ds = self.random_shuffle(seed=seed) if shuffle else self
        train, test = ds.split_proportionately([1.0 - test_size])
        return [train, test]

    def random_sample(self, fraction: float, *,
                      seed: Optional[int] = None) -> "Dataset":
        """Bernoulli row sample (ref: dataset.random_sample); lazy."""
        if not 0 <= fraction <= 1:
            raise ValueError("fraction must be in [0, 1]")

        def sample(batch):
            import zlib

            import numpy as _np

            n = len(next(iter(batch.values()), []))
            if seed is not None:
                # Derive a per-batch stream by mixing the seed with the
                # batch CONTENT — the same closure runs in every block's
                # worker, so reusing `seed` directly would draw the same
                # mask offsets in every block (position-correlated, not
                # i.i.d.).
                first = _np.ascontiguousarray(
                    next(iter(batch.values()))
                )
                salt = zlib.crc32(first.tobytes())
                rng = _np.random.default_rng((seed, salt))
            else:
                rng = _np.random.default_rng()
            mask = rng.random(n) < fraction
            return {k: _np.asarray(v)[mask] for k, v in batch.items()}

        return self.map_batches(sample, batch_format="numpy")

    def unique(self, column: str) -> List[Any]:
        """Distinct values of one column (ref: dataset.unique)."""
        seen = {}
        for batch in self.select_columns([column]).iter_batches(
            batch_format="numpy"
        ):
            for v in batch[column]:
                key = v.item() if hasattr(v, "item") else v
                seen.setdefault(key, None)
        return list(seen)

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        """Rename columns lazily (ref: dataset.rename_columns)."""

        def rename(batch):
            return {mapping.get(k, k): v for k, v in batch.items()}

        return self.map_batches(rename, batch_format="numpy")

    # -- column aggregates (ref: dataset.sum/min/max/mean/std) --

    def _agg_column(self, on: str):
        import numpy as _np

        parts = [
            _np.asarray(b[on])
            for b in self.select_columns([on]).iter_batches(
                batch_format="numpy"
            )
            if len(b[on])
        ]
        return _np.concatenate(parts) if parts else _np.asarray([])

    def sum(self, on: str):
        vals = self._agg_column(on)
        return vals.sum().item() if vals.size else None

    def min(self, on: str):
        vals = self._agg_column(on)
        return vals.min().item() if vals.size else None

    def max(self, on: str):
        vals = self._agg_column(on)
        return vals.max().item() if vals.size else None

    def mean(self, on: str):
        vals = self._agg_column(on)
        return vals.mean().item() if vals.size else None

    def std(self, on: str, ddof: int = 1):
        vals = self._agg_column(on)
        return (vals.std(ddof=ddof).item()
                if vals.size > ddof else None)

    def __repr__(self):
        return self.stats()
