"""ray_tpu.autoscaler: demand-driven cluster scaling (ref analogue:
python/ray/autoscaler/)."""

from .autoscaler import Autoscaler, AutoscalerConfig  # noqa: F401
from .node_provider import LocalNodeProvider, NodeProvider  # noqa: F401
