"""Node providers: how the autoscaler obtains and releases hosts.

Ref analogue: python/ray/autoscaler/node_provider.py NodeProvider (the
cloud-agnostic interface) and _private/fake_multi_node/node_provider.py
(nodes as local subprocesses — the testing provider). A TPU-pod provider
implements the same three calls against the GCE TPU API.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Any, Dict, List, Optional


# Label every provider-launched node carries so the autoscaler can match
# cluster views back to provider node ids (ref analogue: the
# ray-node-name / instance-id tags cloud providers stamp on instances).
PROVIDER_NODE_LABEL = "rtpu-provider-node-id"


class NodeProvider:
    """Minimal provider surface (ref: NodeProvider.create_node /
    terminate_node / non_terminated_nodes). Implementations MUST stamp
    ``PROVIDER_NODE_LABEL: <returned id>`` into the node's labels."""

    def create_node(self, resources: Dict[str, float],
                    labels: Optional[Dict[str, str]] = None) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Launches worker nodes as ``node_main`` subprocesses on this machine
    (the reference's fake_multi_node pattern — also exactly what a
    single-host TPU VM needs)."""

    def __init__(self, gcs_address: str):
        self.gcs_address = gcs_address
        self._procs: Dict[str, subprocess.Popen] = {}

    def create_node(self, resources: Dict[str, float],
                    labels: Optional[Dict[str, str]] = None) -> str:
        node_id = f"local-{uuid.uuid4().hex[:8]}"
        session_dir = os.path.join(
            tempfile.gettempdir(), "ray_tpu",
            f"autoscaled-{int(time.time())}-{node_id}",
        )
        os.makedirs(session_dir, exist_ok=True)
        labels = dict(labels or {})
        labels[PROVIDER_NODE_LABEL] = node_id
        env = dict(os.environ)
        env["RAY_TPU_GCS_ADDRESS"] = self.gcs_address
        env["RAY_TPU_SESSION_DIR"] = session_dir
        env["RAY_TPU_RESOURCES"] = json.dumps(resources)
        env["RAY_TPU_NODE_LABELS"] = json.dumps(labels)
        from ray_tpu.core.config import get_config as _get_config

        if _get_config().session_token:
            env["RAY_TPU_SESSION_TOKEN"] = _get_config().session_token
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        pp = env.get("PYTHONPATH", "")
        if pkg_root not in pp.split(os.pathsep):
            env["PYTHONPATH"] = pkg_root + (
                os.pathsep + pp if pp else ""
            )
        log = open(os.path.join(session_dir, "node.log"), "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.node_main"],
            env=env, stdout=log, stderr=subprocess.STDOUT,
        )
        self._procs[node_id] = proc
        return node_id

    def terminate_node(self, provider_node_id: str) -> None:
        proc = self._procs.pop(provider_node_id, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    def non_terminated_nodes(self) -> List[str]:
        return [
            nid for nid, p in self._procs.items() if p.poll() is None
        ]

    def shutdown(self) -> None:
        for nid in list(self._procs):
            self.terminate_node(nid)
