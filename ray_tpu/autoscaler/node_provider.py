"""Node providers: how the autoscaler obtains and releases hosts.

Ref analogue: python/ray/autoscaler/node_provider.py NodeProvider (the
cloud-agnostic interface) and _private/fake_multi_node/node_provider.py
(nodes as local subprocesses — the testing provider). A TPU-pod provider
implements the same three calls against the GCE TPU API.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Any, Dict, List, Optional


# Label every provider-launched node carries so the autoscaler can match
# cluster views back to provider node ids (ref analogue: the
# ray-node-name / instance-id tags cloud providers stamp on instances).
PROVIDER_NODE_LABEL = "rtpu-provider-node-id"


class NodeProvider:
    """Minimal provider surface (ref: NodeProvider.create_node /
    terminate_node / non_terminated_nodes). Implementations MUST stamp
    ``PROVIDER_NODE_LABEL: <returned id>`` into the node's labels."""

    def create_node(self, resources: Dict[str, float],
                    labels: Optional[Dict[str, str]] = None) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class _SubprocessProvider(NodeProvider):
    """Shared Popen lifecycle (terminate/reap/shutdown) for providers
    whose nodes are child processes; subclasses implement create_node."""

    def __init__(self):
        self._procs: Dict[str, subprocess.Popen] = {}

    def _reap(self, provider_node_id: str) -> None:
        """Forget a node whose process is gone (subclass hook for
        releasing per-node resources like ssh IPs)."""
        self._procs.pop(provider_node_id, None)

    def terminate_node(self, provider_node_id: str) -> None:
        proc = self._procs.get(provider_node_id)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        self._reap(provider_node_id)

    def non_terminated_nodes(self) -> List[str]:
        alive = []
        for nid, p in list(self._procs.items()):
            if p.poll() is None:
                alive.append(nid)
            else:
                # Reap dead children so their resources (e.g. an ssh
                # worker IP) free up instead of leaking forever.
                self._reap(nid)
        return alive

    def shutdown(self) -> None:
        for nid in list(self._procs):
            self.terminate_node(nid)


class LocalNodeProvider(_SubprocessProvider):
    """Launches worker nodes as ``node_main`` subprocesses on this machine
    (the reference's fake_multi_node pattern — also exactly what a
    single-host TPU VM needs)."""

    def __init__(self, gcs_address: str):
        super().__init__()
        self.gcs_address = gcs_address

    def create_node(self, resources: Dict[str, float],
                    labels: Optional[Dict[str, str]] = None) -> str:
        node_id = f"local-{uuid.uuid4().hex[:8]}"
        session_dir = os.path.join(
            tempfile.gettempdir(), "ray_tpu",
            f"autoscaled-{int(time.time())}-{node_id}",
        )
        os.makedirs(session_dir, exist_ok=True)
        labels = dict(labels or {})
        labels[PROVIDER_NODE_LABEL] = node_id
        env = dict(os.environ)
        env["RAY_TPU_GCS_ADDRESS"] = self.gcs_address
        env["RAY_TPU_SESSION_DIR"] = session_dir
        env["RAY_TPU_RESOURCES"] = json.dumps(resources)
        env["RAY_TPU_NODE_LABELS"] = json.dumps(labels)
        from ray_tpu.core.config import get_config as _get_config

        if _get_config().session_token:
            env["RAY_TPU_SESSION_TOKEN"] = _get_config().session_token
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        pp = env.get("PYTHONPATH", "")
        if pkg_root not in pp.split(os.pathsep):
            env["PYTHONPATH"] = pkg_root + (
                os.pathsep + pp if pp else ""
            )
        log = open(os.path.join(session_dir, "node.log"), "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.node_main"],
            env=env, stdout=log, stderr=subprocess.STDOUT,
        )
        self._procs[node_id] = proc
        return node_id


class GCPTpuNodeProvider(NodeProvider):
    """TPU pod slices on GCE via the Cloud TPU API (ref analogue:
    autoscaler/_private/gcp/node_provider.py — the TPU-VM path). One
    provider "node" = ONE pod slice: create_node POSTs a TPU node of
    the type's ``accelerator_type``; every HOST of the slice runs the
    same startup script and joins the cluster as a gang, each stamping
    the shared provider-node id into its labels, so the autoscaler
    reasons about the slice as a unit (idle only when every host is
    idle; sized as hosts_per_node bins of per-host resources).

    The HTTP layer is injectable (``http=``) so the whole flow is
    testable against a fake TPU API; production auth uses the GCE
    metadata server's default service-account token.
    """

    def __init__(self, gcs_address: str, *, project: str, zone: str,
                 cluster_name: str = "rtpu",
                 api_base: str = "https://tpu.googleapis.com/v2",
                 network: str = "",
                 http=None, auth_token_fn=None,
                 setup_commands: Optional[List[str]] = None):
        import re

        self.gcs_address = gcs_address
        self.project = project
        self.zone = zone
        # GCP label values must be lowercase [a-z0-9-_]; normalize so an
        # arbitrary cluster_name cannot 400 every create (the autoscaler
        # loop would swallow the error and the cluster would silently
        # never scale).
        self.cluster_name = re.sub(
            r"[^a-z0-9-]", "-", cluster_name.lower()
        )[:40] or "rtpu"
        self.api_base = api_base.rstrip("/")
        self.network = network
        self.setup_commands = list(setup_commands or [])
        self._http = http or _UrllibHttp(
            auth_token_fn or _gce_metadata_token
        )
        # provider node id -> node-type metadata (accelerator etc.)
        self._nodes: Dict[str, Dict[str, Any]] = {}
        # id -> creation time: the list API is eventually consistent, so
        # a just-created node missing from a listing must not be pruned
        # (pruning would leak the paid slice at shutdown and relaunch a
        # duplicate).
        self._created_at: Dict[str, float] = {}
        self._list_grace_s = 120.0
        # Per-launch type config handed in through create_node's labels
        # channel (the autoscaler passes the node-type name; the YAML
        # loader registers the full type configs here).
        self.node_type_configs: Dict[str, Dict[str, Any]] = {}

    # -- REST plumbing ---------------------------------------------------

    def _parent(self) -> str:
        return (f"{self.api_base}/projects/{self.project}"
                f"/locations/{self.zone}")

    def _startup_script(self, node_id: str, resources: Dict[str, float],
                        labels: Dict[str, str]) -> str:
        """Runs on EVERY host of the slice: join the cluster as one
        node of the gang. NOTE: the session token (when set) travels
        through the node's startup-script metadata, which is visible to
        any principal with TPU viewer permission on the project — scope
        the project's IAM accordingly, or leave the token unset and rely
        on network isolation / mTLS (core/tls.py) instead."""
        import shlex

        from ray_tpu.core.config import get_config

        env = (
            f"RAY_TPU_GCS_ADDRESS={shlex.quote(self.gcs_address)} "
            f"RAY_TPU_SESSION_DIR=/tmp/ray_tpu/{node_id} "
            f"RAY_TPU_RESOURCES={shlex.quote(json.dumps(resources))} "
            f"RAY_TPU_NODE_LABELS={shlex.quote(json.dumps(labels))}"
        )
        token = get_config().session_token
        if token:
            env += f" RAY_TPU_SESSION_TOKEN={shlex.quote(token)}"
        lines = ["#!/bin/bash", "set -e"]
        lines += self.setup_commands
        lines += [
            f"mkdir -p /tmp/ray_tpu/{node_id}",
            f"{env} python3 -m ray_tpu.core.node_main "
            f">> /tmp/ray_tpu/{node_id}/node.log 2>&1 &",
        ]
        return "\n".join(lines)

    # -- NodeProvider surface --------------------------------------------

    def create_node(self, resources: Dict[str, float],
                    labels: Optional[Dict[str, str]] = None) -> str:
        labels = dict(labels or {})
        type_name = labels.get("rtpu-node-type", "")
        tcfg = self.node_type_configs.get(type_name)
        if tcfg is None:
            # Launching unknown (billed!) hardware on a silent fallback
            # would also desynchronize the autoscaler's hosts_per_node
            # accounting — fail fast instead.
            raise ValueError(
                f"gcp_tpu: no node_type_configs entry for node type "
                f"{type_name!r} (have {sorted(self.node_type_configs)})"
            )
        accel = tcfg.get("accelerator_type", "v5litepod-4")
        runtime = tcfg.get("runtime_version", "tpu-ubuntu2204-base")
        node_id = f"tpu-{self.cluster_name}-{uuid.uuid4().hex[:8]}"
        labels[PROVIDER_NODE_LABEL] = node_id
        labels["rtpu-slice"] = node_id
        body = {
            "acceleratorType": accel,
            "runtimeVersion": runtime,
            "labels": {
                "rtpu-cluster": self.cluster_name,
                "rtpu-provider-node-id": node_id,
            },
            "metadata": {
                "startup-script": self._startup_script(
                    node_id, resources, labels
                ),
            },
        }
        if self.network:
            body["networkConfig"] = {"network": self.network}
        self._http.request(
            "POST", f"{self._parent()}/nodes?nodeId={node_id}", body
        )
        self._nodes[node_id] = {"type": type_name, "accel": accel}
        self._created_at[node_id] = time.monotonic()
        return node_id

    def terminate_node(self, provider_node_id: str) -> None:
        # Local tracking is dropped only on a SUCCESSFUL delete: a
        # transient API error must leave the node tracked so shutdown()
        # (or the next reconcile) retries instead of leaking a billed
        # slice.
        self._http.request(
            "DELETE", f"{self._parent()}/nodes/{provider_node_id}"
        )
        self._nodes.pop(provider_node_id, None)
        self._created_at.pop(provider_node_id, None)

    def non_terminated_nodes(self) -> List[str]:
        try:
            resp = self._http.request("GET", f"{self._parent()}/nodes")
        except Exception as e:
            # API blip: report the locally-tracked set rather than
            # pretending every slice vanished (which would relaunch) —
            # but a provider API outage must be visible while it lasts.
            sys.stderr.write(
                f"[node_provider] WARNING: TPU API list failed "
                f"({type(e).__name__}: {e}); serving cached node set\n"
            )
            return list(self._nodes)
        now = time.monotonic()
        out = []
        for node in (resp or {}).get("nodes", []):
            nlabels = node.get("labels") or {}
            if nlabels.get("rtpu-cluster") != self.cluster_name:
                continue
            if node.get("state") in ("DELETING", "TERMINATED"):
                continue
            nid = nlabels.get("rtpu-provider-node-id") or (
                node.get("name", "").rsplit("/", 1)[-1]
            )
            out.append(nid)
            self._nodes.setdefault(nid, {})
        # Drop local records the API no longer reports — EXCEPT nodes
        # created within the list-consistency grace window (the listing
        # may simply not surface them yet).
        listed = set(out)
        for nid in list(self._nodes):
            if nid in listed:
                continue
            created = self._created_at.get(nid)
            if created is not None and now - created < self._list_grace_s:
                out.append(nid)  # still ours; listing just lags
                continue
            self._nodes.pop(nid, None)
            self._created_at.pop(nid, None)
        return out

    def shutdown(self) -> None:
        for nid in list(self._nodes):
            try:
                self.terminate_node(nid)
            except Exception as e:
                sys.stderr.write(
                    f"[node_provider] WARNING: terminate of {nid} at "
                    f"shutdown failed ({e!r}); instance may be leaked\n"
                )


class _UrllibHttp:
    """Minimal JSON-over-HTTP client for the TPU REST API (stdlib only;
    swap out in tests via GCPTpuNodeProvider(http=...)). The auth token
    is cached with an expiry — the reconcile loop calls the API every
    tick, and GCE metadata tokens are valid ~1h."""

    _TOKEN_TTL_S = 600.0

    def __init__(self, token_fn=None):
        self._token_fn = token_fn
        self._token = ""
        self._token_expiry = 0.0

    def _auth(self) -> str:
        if self._token_fn is None:
            return ""
        now = time.monotonic()
        if now >= self._token_expiry:
            self._token = self._token_fn() or ""
            # Failed fetches (empty) retry sooner than good tokens.
            self._token_expiry = now + (
                self._TOKEN_TTL_S if self._token else 30.0
            )
        return self._token

    def request(self, method: str, url: str, body=None):
        import urllib.request

        data = None
        headers = {"Content-Type": "application/json"}
        tok = self._auth()
        if tok:
            headers["Authorization"] = f"Bearer {tok}"
        if body is not None:
            data = json.dumps(body).encode()
        req = urllib.request.Request(
            url, data=data, method=method, headers=headers
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            payload = resp.read()
        return json.loads(payload) if payload else {}


def _gce_metadata_token() -> str:
    """Default service-account token from the GCE metadata server
    (empty off-GCE — requests then go unauthenticated, which only a
    test/fake endpoint accepts)."""
    import urllib.request

    try:
        req = urllib.request.Request(
            "http://metadata.google.internal/computeMetadata/v1/"
            "instance/service-accounts/default/token",
            headers={"Metadata-Flavor": "Google"},
        )
        with urllib.request.urlopen(req, timeout=2) as resp:
            return json.loads(resp.read()).get("access_token", "")
    # Off-GCE (dev boxes, CI) the metadata server does not exist and an
    # empty token is the designed answer; logging here would fire on
    # every reconcile tick of every non-GCE run.
    except Exception:  # rtlint: disable=swallowed-failure
        return ""


class SSHNodeProvider(_SubprocessProvider):
    """Launches worker nodes on remote hosts over ssh (ref analogue: the
    on-prem/"local" provider's ssh command_runner.py — one node process
    per configured worker IP; no cloud API, the machines already exist).
    Each create_node takes the next free IP from ``worker_ips``."""

    def __init__(self, gcs_address: str, *, worker_ips: List[str],
                 ssh_user: str = "", ssh_key: str = "",
                 python: str = "python3"):
        super().__init__()
        self.gcs_address = gcs_address
        self.worker_ips = list(worker_ips)
        self.ssh_user = ssh_user
        self.ssh_key = ssh_key
        self.python = python
        self._ip_of: Dict[str, str] = {}

    def _reap(self, provider_node_id: str) -> None:
        super()._reap(provider_node_id)
        self._ip_of.pop(provider_node_id, None)  # free the IP

    def _free_ip(self) -> Optional[str]:
        used = set(self._ip_of.values())
        for ip in self.worker_ips:
            if ip not in used:
                return ip
        return None

    def ssh_command(self, ip: str, node_id: str,
                    resources: Dict[str, float],
                    labels: Dict[str, str],
                    with_token: bool = False) -> List[str]:
        """The exact argv used to start a node on ``ip`` (separated out
        for tests: the sandbox has no reachable ssh hosts). Creates the
        remote session dir. JSON values are shell-quoted (a resource or
        label containing a quote must not break the command). When
        ``with_token``, the remote command reads the session token from
        its STDIN (``read``) rather than the command line, where
        `ps`/audit logs on the remote host would expose it — the caller
        must then write exactly one token line to the child's stdin."""
        import shlex

        target = f"{self.ssh_user}@{ip}" if self.ssh_user else ip
        session_dir = f"/tmp/ray_tpu/{node_id}"
        env = (
            f"RAY_TPU_GCS_ADDRESS={shlex.quote(self.gcs_address)} "
            f"RAY_TPU_SESSION_DIR={shlex.quote(session_dir)} "
            f"RAY_TPU_RESOURCES={shlex.quote(json.dumps(resources))} "
            f"RAY_TPU_NODE_LABELS={shlex.quote(json.dumps(labels))}"
        )
        launch = (f"mkdir -p {shlex.quote(session_dir)} && "
                  f"{env} {self.python} -m ray_tpu.core.node_main")
        if with_token:
            launch = ('IFS= read -r RAY_TPU_SESSION_TOKEN && '
                      'export RAY_TPU_SESSION_TOKEN && ' + launch)
        cmd = ["ssh", "-o", "StrictHostKeyChecking=accept-new"]
        if self.ssh_key:
            cmd += ["-i", os.path.expanduser(self.ssh_key)]
        cmd += [target, launch]
        return cmd

    def create_node(self, resources: Dict[str, float],
                    labels: Optional[Dict[str, str]] = None) -> str:
        ip = self._free_ip()
        if ip is None:
            raise RuntimeError(
                f"ssh provider exhausted: all {len(self.worker_ips)} "
                f"worker_ips in use"
            )
        node_id = f"ssh-{ip}-{uuid.uuid4().hex[:6]}"
        labels = dict(labels or {})
        labels[PROVIDER_NODE_LABEL] = node_id
        from ray_tpu.core.config import get_config

        token = get_config().session_token
        proc = subprocess.Popen(
            self.ssh_command(ip, node_id, resources, labels,
                             with_token=bool(token)),
            stdin=subprocess.PIPE if token else subprocess.DEVNULL,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        if token:
            try:  # the remote `read` consumes exactly this one line
                proc.stdin.write(token.encode() + b"\n")
                proc.stdin.close()
            except (BrokenPipeError, OSError):
                pass
        self._procs[node_id] = proc
        self._ip_of[node_id] = ip
        return node_id
