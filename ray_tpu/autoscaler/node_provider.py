"""Node providers: how the autoscaler obtains and releases hosts.

Ref analogue: python/ray/autoscaler/node_provider.py NodeProvider (the
cloud-agnostic interface) and _private/fake_multi_node/node_provider.py
(nodes as local subprocesses — the testing provider). A TPU-pod provider
implements the same three calls against the GCE TPU API.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Any, Dict, List, Optional


# Label every provider-launched node carries so the autoscaler can match
# cluster views back to provider node ids (ref analogue: the
# ray-node-name / instance-id tags cloud providers stamp on instances).
PROVIDER_NODE_LABEL = "rtpu-provider-node-id"


class NodeProvider:
    """Minimal provider surface (ref: NodeProvider.create_node /
    terminate_node / non_terminated_nodes). Implementations MUST stamp
    ``PROVIDER_NODE_LABEL: <returned id>`` into the node's labels."""

    def create_node(self, resources: Dict[str, float],
                    labels: Optional[Dict[str, str]] = None) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class _SubprocessProvider(NodeProvider):
    """Shared Popen lifecycle (terminate/reap/shutdown) for providers
    whose nodes are child processes; subclasses implement create_node."""

    def __init__(self):
        self._procs: Dict[str, subprocess.Popen] = {}

    def _reap(self, provider_node_id: str) -> None:
        """Forget a node whose process is gone (subclass hook for
        releasing per-node resources like ssh IPs)."""
        self._procs.pop(provider_node_id, None)

    def terminate_node(self, provider_node_id: str) -> None:
        proc = self._procs.get(provider_node_id)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        self._reap(provider_node_id)

    def non_terminated_nodes(self) -> List[str]:
        alive = []
        for nid, p in list(self._procs.items()):
            if p.poll() is None:
                alive.append(nid)
            else:
                # Reap dead children so their resources (e.g. an ssh
                # worker IP) free up instead of leaking forever.
                self._reap(nid)
        return alive

    def shutdown(self) -> None:
        for nid in list(self._procs):
            self.terminate_node(nid)


class LocalNodeProvider(_SubprocessProvider):
    """Launches worker nodes as ``node_main`` subprocesses on this machine
    (the reference's fake_multi_node pattern — also exactly what a
    single-host TPU VM needs)."""

    def __init__(self, gcs_address: str):
        super().__init__()
        self.gcs_address = gcs_address

    def create_node(self, resources: Dict[str, float],
                    labels: Optional[Dict[str, str]] = None) -> str:
        node_id = f"local-{uuid.uuid4().hex[:8]}"
        session_dir = os.path.join(
            tempfile.gettempdir(), "ray_tpu",
            f"autoscaled-{int(time.time())}-{node_id}",
        )
        os.makedirs(session_dir, exist_ok=True)
        labels = dict(labels or {})
        labels[PROVIDER_NODE_LABEL] = node_id
        env = dict(os.environ)
        env["RAY_TPU_GCS_ADDRESS"] = self.gcs_address
        env["RAY_TPU_SESSION_DIR"] = session_dir
        env["RAY_TPU_RESOURCES"] = json.dumps(resources)
        env["RAY_TPU_NODE_LABELS"] = json.dumps(labels)
        from ray_tpu.core.config import get_config as _get_config

        if _get_config().session_token:
            env["RAY_TPU_SESSION_TOKEN"] = _get_config().session_token
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        pp = env.get("PYTHONPATH", "")
        if pkg_root not in pp.split(os.pathsep):
            env["PYTHONPATH"] = pkg_root + (
                os.pathsep + pp if pp else ""
            )
        log = open(os.path.join(session_dir, "node.log"), "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.node_main"],
            env=env, stdout=log, stderr=subprocess.STDOUT,
        )
        self._procs[node_id] = proc
        return node_id


class SSHNodeProvider(_SubprocessProvider):
    """Launches worker nodes on remote hosts over ssh (ref analogue: the
    on-prem/"local" provider's ssh command_runner.py — one node process
    per configured worker IP; no cloud API, the machines already exist).
    Each create_node takes the next free IP from ``worker_ips``."""

    def __init__(self, gcs_address: str, *, worker_ips: List[str],
                 ssh_user: str = "", ssh_key: str = "",
                 python: str = "python3"):
        super().__init__()
        self.gcs_address = gcs_address
        self.worker_ips = list(worker_ips)
        self.ssh_user = ssh_user
        self.ssh_key = ssh_key
        self.python = python
        self._ip_of: Dict[str, str] = {}

    def _reap(self, provider_node_id: str) -> None:
        super()._reap(provider_node_id)
        self._ip_of.pop(provider_node_id, None)  # free the IP

    def _free_ip(self) -> Optional[str]:
        used = set(self._ip_of.values())
        for ip in self.worker_ips:
            if ip not in used:
                return ip
        return None

    def ssh_command(self, ip: str, node_id: str,
                    resources: Dict[str, float],
                    labels: Dict[str, str],
                    with_token: bool = False) -> List[str]:
        """The exact argv used to start a node on ``ip`` (separated out
        for tests: the sandbox has no reachable ssh hosts). Creates the
        remote session dir. JSON values are shell-quoted (a resource or
        label containing a quote must not break the command). When
        ``with_token``, the remote command reads the session token from
        its STDIN (``read``) rather than the command line, where
        `ps`/audit logs on the remote host would expose it — the caller
        must then write exactly one token line to the child's stdin."""
        import shlex

        target = f"{self.ssh_user}@{ip}" if self.ssh_user else ip
        session_dir = f"/tmp/ray_tpu/{node_id}"
        env = (
            f"RAY_TPU_GCS_ADDRESS={shlex.quote(self.gcs_address)} "
            f"RAY_TPU_SESSION_DIR={shlex.quote(session_dir)} "
            f"RAY_TPU_RESOURCES={shlex.quote(json.dumps(resources))} "
            f"RAY_TPU_NODE_LABELS={shlex.quote(json.dumps(labels))}"
        )
        launch = (f"mkdir -p {shlex.quote(session_dir)} && "
                  f"{env} {self.python} -m ray_tpu.core.node_main")
        if with_token:
            launch = ('IFS= read -r RAY_TPU_SESSION_TOKEN && '
                      'export RAY_TPU_SESSION_TOKEN && ' + launch)
        cmd = ["ssh", "-o", "StrictHostKeyChecking=accept-new"]
        if self.ssh_key:
            cmd += ["-i", os.path.expanduser(self.ssh_key)]
        cmd += [target, launch]
        return cmd

    def create_node(self, resources: Dict[str, float],
                    labels: Optional[Dict[str, str]] = None) -> str:
        ip = self._free_ip()
        if ip is None:
            raise RuntimeError(
                f"ssh provider exhausted: all {len(self.worker_ips)} "
                f"worker_ips in use"
            )
        node_id = f"ssh-{ip}-{uuid.uuid4().hex[:6]}"
        labels = dict(labels or {})
        labels[PROVIDER_NODE_LABEL] = node_id
        from ray_tpu.core.config import get_config

        token = get_config().session_token
        proc = subprocess.Popen(
            self.ssh_command(ip, node_id, resources, labels,
                             with_token=bool(token)),
            stdin=subprocess.PIPE if token else subprocess.DEVNULL,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        if token:
            try:  # the remote `read` consumes exactly this one line
                proc.stdin.write(token.encode() + b"\n")
                proc.stdin.close()
            except (BrokenPipeError, OSError):
                pass
        self._procs[node_id] = proc
        self._ip_of[node_id] = ip
        return node_id
