"""Demand-driven cluster autoscaler.

Ref analogue: python/ray/autoscaler/_private/autoscaler.py
StandardAutoscaler (:169 update loop) +
_private/resource_demand_scheduler.py: scale UP by the resource *shapes*
of queued work (bin-packed against free capacity, then against candidate
node types), scale DOWN worker nodes idle longer than ``idle_timeout_s``.
Demand is read from the GCS load reports every node already sends
(pending task shapes + available resources); nodes come and go through a
pluggable NodeProvider. Each provider node stamps its id into the node's
labels (``rtpu-provider-node-id``) so idleness is judged per-node, not
cluster-wide.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .node_provider import PROVIDER_NODE_LABEL, LocalNodeProvider, NodeProvider
from ..util import events as cluster_events


def _fits(shape: Dict[str, float], avail: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= float(q) for k, q in shape.items()
               if float(q) > 0)


def _deduct(shape: Dict[str, float], avail: Dict[str, float]) -> None:
    for k, q in shape.items():
        avail[k] = avail.get(k, 0.0) - float(q)


class AutoscalerConfig:
    """``node_types`` maps a type name to ``{"resources": {...},
    "labels": {...}}`` (ref: available_node_types in the cluster YAML).
    ``worker_resources`` is shorthand for a single ``"worker"`` type."""

    def __init__(self, *, min_workers: int = 0, max_workers: int = 4,
                 worker_resources: Optional[Dict[str, float]] = None,
                 node_types: Optional[Dict[str, Dict[str, Any]]] = None,
                 upscale_delay_s: float = 1.0,
                 idle_timeout_s: float = 10.0,
                 interval_s: float = 0.5,
                 boot_timeout_s: float = 60.0):
        self.min_workers = min_workers
        self.max_workers = max_workers
        if node_types is None:
            node_types = {
                "worker": {"resources": worker_resources or {"CPU": 1}},
            }
        self.node_types = node_types
        self.upscale_delay_s = upscale_delay_s
        self.idle_timeout_s = idle_timeout_s
        self.interval_s = interval_s
        # How long a launched node may stay view-less before its phantom
        # capacity stops masking demand (hung boot → replacement can come).
        self.boot_timeout_s = boot_timeout_s

    @property
    def worker_resources(self) -> Dict[str, float]:
        return next(iter(self.node_types.values()))["resources"]


class Autoscaler:
    """Drive a NodeProvider from cluster demand. Runs in the head/driver
    process (``start()`` spawns the reconcile thread)."""

    def __init__(self, config: Optional[AutoscalerConfig] = None,
                 provider: Optional[NodeProvider] = None,
                 *, nodes_fn=None):
        self.config = config or AutoscalerConfig()
        if nodes_fn is None or provider is None:
            # Default to the in-process driver runtime (a CLI head
            # passes nodes_fn + provider explicitly — it has a
            # NodeManager but no driver runtime).
            from ..core.runtime_context import current_runtime

            rt = current_runtime()
            if nodes_fn is None:
                nodes_fn = rt.nodes
            if provider is None:
                nm = rt._nm
                if nm.gcs_service is None:
                    raise RuntimeError(
                        "autoscaler must run on the head node"
                    )
                host, port = nm.gcs_service.address
                provider = LocalNodeProvider(f"{host}:{port}")
        self.provider = provider
        self._nodes_fn = nodes_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pending_since: Optional[float] = None
        # provider node id -> time its own view became idle
        self._idle_since: Dict[str, float] = {}
        # provider node id -> (node-type name, boot deadline), for nodes we
        # launched that have not registered a cluster view yet. Their
        # capacity counts against demand (or every tick would launch a
        # duplicate), but only until boot_timeout_s — a hung boot must not
        # mask demand forever. A multi-host SLICE stays booting until
        # EVERY host has registered (partially-registered slices still
        # contribute their missing hosts as phantom capacity).
        self._booting: Dict[str, Tuple[str, float]] = {}
        # provider node id -> node-type name for every node THIS process
        # launched (outlives _booting: idleness needs the host count).
        self._type_of: Dict[str, str] = {}

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def shutdown(self, *, terminate_nodes: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if terminate_nodes and hasattr(self.provider, "shutdown"):
            self.provider.shutdown()

    def num_workers(self) -> int:
        return len(self.provider.non_terminated_nodes())

    # -- rolling replacement -------------------------------------------------

    def rolling_restart(self, *, drain_timeout: Optional[float] = None,
                        drain_fn=None,
                        register_timeout: Optional[float] = None
                        ) -> List[Tuple[str, str]]:
        """Zero-downtime rolling replacement of every provider node (ref
        analogue: kuberay's rolling upgrade — drain, then delete): for
        each node, launch a same-type replacement, wait for all its
        hosts to register, drain every host of the old node
        (``ray_tpu.drain_node`` unless ``drain_fn(node_hex, timeout)``
        is supplied — a CLI head without a driver runtime passes its
        own), then terminate the old provider node. Returns
        ``[(old_provider_id, new_provider_id), ...]``."""
        import sys

        if drain_fn is None:
            from ..core.api import drain_node as _api_drain

            def drain_fn(node_hex, timeout=None):
                return _api_drain(node_hex, timeout=timeout)

        if register_timeout is None:
            register_timeout = self.config.boot_timeout_s
        replaced: List[Tuple[str, str]] = []
        for nid in list(self.provider.non_terminated_nodes()):
            tname = self._type_of.get(nid) or self._default_type()
            new_id = self._launch(tname)
            deadline = time.monotonic() + register_timeout
            hosts = max(1, int(
                self.config.node_types.get(tname, {})
                .get("hosts_per_node", 1)
            ))
            views: List[Dict[str, Any]] = []
            while time.monotonic() < deadline:
                views = [v for v in self._nodes_fn()
                         if v.get("state") == "alive"
                         and (v.get("labels") or {})
                         .get(PROVIDER_NODE_LABEL) == new_id]
                if len(views) >= hosts:
                    break
                time.sleep(0.25)
            if len(views) < hosts:
                # The replacement never (fully) registered — draining
                # the old node now would shrink capacity one node per
                # iteration, the opposite of zero-downtime. Reap the
                # failed replacement and abort the roll.
                try:
                    self.provider.terminate_node(new_id)
                except Exception as e:
                    sys.stderr.write(
                        f"[autoscaler] terminate of failed replacement "
                        f"{new_id} also failed ({e!r}); instance may be "
                        f"leaked\n"
                    )
                cluster_events.emit(
                    cluster_events.WARNING, cluster_events.AUTOSCALER,
                    f"rolling restart aborted: replacement {new_id} for "
                    f"{nid} registered {len(views)}/{hosts} host(s) "
                    f"within {register_timeout}s",
                    custom_fields={"old": nid, "new": new_id,
                                   "node_type": tname},
                )
                raise RuntimeError(
                    f"rolling restart aborted at node {nid}: replacement "
                    f"{new_id} registered {len(views)}/{hosts} host(s) "
                    f"within {register_timeout}s "
                    f"({len(replaced)} node(s) already replaced)"
                )
            # Drain every host the old provider node registered.
            for v in self._nodes_fn():
                if (v.get("labels") or {}).get(PROVIDER_NODE_LABEL) \
                        != nid or v.get("state") != "alive":
                    continue
                try:
                    drain_fn(v["node_id"], timeout=drain_timeout)
                except Exception as e:  # noqa: BLE001
                    from ..core.api import DrainRefusedError

                    if isinstance(e, DrainRefusedError):
                        # Refused by policy (the node hosts the serve
                        # controller): it is healthy — terminating it
                        # anyway would behead serve, the exact outcome
                        # the refusal guards against. Reap the spare
                        # replacement and abort the roll.
                        try:
                            self.provider.terminate_node(new_id)
                        except Exception as te:
                            sys.stderr.write(
                                f"[autoscaler] terminate of spare "
                                f"replacement {new_id} failed ({te!r}); "
                                f"instance may be leaked\n"
                            )
                        cluster_events.emit(
                            cluster_events.WARNING,
                            cluster_events.AUTOSCALER,
                            f"rolling restart aborted at {nid}: {e}",
                            custom_fields={"old": nid, "new": new_id},
                        )
                        raise
                    # A wedged/dead node must still be replaceable:
                    # keep rolling and terminate it undrained.
                    sys.stderr.write(
                        f"[autoscaler] drain of {v['node_id'][:8]} "
                        f"failed ({e!r}); terminating anyway\n"
                    )
            try:
                self.provider.terminate_node(nid)
            except Exception as e:
                sys.stderr.write(
                    f"[autoscaler] terminate of drained node {nid} "
                    f"failed ({e!r}); instance may be leaked\n"
                )
            self._type_of.pop(nid, None)
            self._booting.pop(nid, None)
            self._idle_since.pop(nid, None)
            cluster_events.emit(
                cluster_events.INFO, cluster_events.AUTOSCALER,
                f"rolling restart: node {nid} drained and replaced by "
                f"{new_id} (type {tname})",
                custom_fields={"old": nid, "new": new_id,
                               "node_type": tname},
            )
            replaced.append((nid, new_id))
        return replaced

    # -- demand -------------------------------------------------------------

    def _unmet_shapes(self, alive: List[Dict[str, Any]],
                      extra_capacity: Optional[List[Dict]] = None
                      ) -> List[Dict]:
        """Pending task shapes that do NOT fit anywhere in the cluster's
        current free capacity (ref: resource_demand_scheduler
        get_bin_pack_residual). ``extra_capacity``: full node shapes of
        launched-but-unregistered nodes, counted as free."""
        units: List[Dict[str, float]] = []
        for v in alive:
            shapes = v.get("pending_shapes")
            if shapes:
                for shape, n in shapes:
                    units.extend([shape] * int(n))
            elif v.get("pending_tasks", 0):
                # Node predates shape reporting: assume 1-CPU units.
                units.extend([{"CPU": 1.0}] * int(v["pending_tasks"]))
        if not units:
            return []
        avail = [dict(v.get("resources_available") or {}) for v in alive]
        avail.extend(dict(c) for c in (extra_capacity or []))
        unmet = []
        for shape in units:
            for a in avail:
                if _fits(shape, a):
                    _deduct(shape, a)
                    break
            else:
                unmet.append(shape)
        return unmet

    def _plan_nodes(self, unmet: List[Dict]) -> List[str]:
        """Greedy-pack unmet shapes into fresh nodes of fitting types;
        returns the node-type names to launch. Shapes no type can hold are
        skipped (they are infeasible, not a scaling problem). A type with
        ``hosts_per_node`` > 1 is a POD SLICE: one launch opens that many
        per-host bins (ref analogue: the gcp provider's TPU-slice node
        types, where one instance is a multi-host gang)."""
        plan: List[str] = []
        open_nodes: List[Tuple[str, Dict[str, float]]] = []
        for shape in unmet:
            placed = False
            for _, rem in open_nodes:
                if _fits(shape, rem):
                    _deduct(shape, rem)
                    placed = True
                    break
            if placed:
                continue
            for tname, tcfg in self.config.node_types.items():
                total = tcfg.get("resources") or {}
                if _fits(shape, dict(total)):
                    hosts = max(1, int(tcfg.get("hosts_per_node", 1)))
                    rem = dict(total)
                    _deduct(shape, rem)
                    open_nodes.append((tname, rem))
                    # The slice's other hosts are fresh bins too.
                    for _ in range(hosts - 1):
                        open_nodes.append((tname, dict(total)))
                    plan.append(tname)
                    break
        return plan

    def _launch(self, type_name: str) -> str:
        tcfg = self.config.node_types[type_name]
        labels = dict(tcfg.get("labels") or {})
        labels.setdefault("rtpu-node-type", type_name)
        nid = self.provider.create_node(
            dict(tcfg["resources"]), labels=labels)
        self._booting[nid] = (
            type_name, time.monotonic() + self.config.boot_timeout_s
        )
        self._type_of[nid] = type_name
        cluster_events.emit(
            cluster_events.INFO, cluster_events.AUTOSCALER,
            f"scale up: launching node {nid} (type {type_name}, "
            f"resources {dict(tcfg['resources'])})",
            custom_fields={"provider_node_id": nid,
                           "node_type": type_name},
        )
        return nid

    def _hosts_of(self, nid: str, host_views=None) -> int:
        """Expected host count of a provider node (1 unless it is a
        multi-host slice). Falls back to the 'rtpu-node-type' label the
        launch stamped into every host's view — so a RESTARTED head,
        whose process-local _type_of is empty, still sizes adopted
        slices correctly instead of tearing them down as 1-host nodes."""
        tname = self._type_of.get(nid)
        if tname is None and host_views:
            for v in host_views:
                tname = (v.get("labels") or {}).get("rtpu-node-type")
                if tname:
                    break
        tcfg = self.config.node_types.get(tname) if tname else None
        if tcfg is None:
            return 1
        return max(1, int(tcfg.get("hosts_per_node", 1)))

    def _default_type(self) -> str:
        return next(iter(self.config.node_types))

    def _slo_burn_active(self) -> bool:
        """True while ANY deployment's SLO burn alert fires (read from
        the head engine's `__slo_status__` KV blob). Scale-down is held
        during a burn: removing capacity mid-incident deepens the very
        alert the serve controller is scaling up to clear."""
        from ..core.runtime_context import current_runtime_or_none
        from ..util import slo

        rt = current_runtime_or_none()
        if rt is None:
            return False
        try:
            status = slo.read_status(rt.kv_get)
        except Exception:  # rtlint: disable=swallowed-failure
            return False  # no SLO plane (older head): no hold
        return any(
            v for dep in status.values() if isinstance(dep, dict)
            for k, v in dep.items() if k.endswith("_burn_active")
        )

    # -- reconcile ----------------------------------------------------------

    def _loop(self) -> None:
        cfg = self.config
        while not self._stop.is_set():
            try:
                self._reconcile_once()
            except Exception as e:
                # A provider/API error silently stalling scale-up was an
                # rtlint swallowed-failure finding: every failed
                # reconcile now leaves a cluster event before retrying.
                cluster_events.emit(
                    cluster_events.WARNING, cluster_events.AUTOSCALER,
                    f"autoscaler reconcile failed: {e!r}",
                    custom_fields={"error_type": type(e).__name__},
                )
            self._stop.wait(cfg.interval_s)

    def _reconcile_once(self) -> None:
        cfg = self.config
        now = time.monotonic()
        live = self.provider.non_terminated_nodes()

        # Floor.
        while len(live) < cfg.min_workers:
            live.append(self._launch(self._default_type()))

        views = self._nodes_fn()
        alive = [v for v in views if v.get("state") == "alive"]
        # One provider node may be a multi-host slice: EVERY host's view
        # maps back to the same provider id (slice-aware accounting).
        by_provider: Dict[str, List[Dict[str, Any]]] = {}
        for v in alive:
            pid = (v.get("labels") or {}).get(PROVIDER_NODE_LABEL)
            if pid:
                by_provider.setdefault(pid, []).append(v)

        # Booting bookkeeping: a node is no longer booting once ALL its
        # hosts registered (a slice's hosts boot staggered — popping on
        # the first would drop the rest's phantom capacity and launch a
        # duplicate slice) or the provider lost it. A node that blows
        # its boot deadline is TERMINATED, not just forgotten — a hung
        # instance would otherwise leak cost and pin a max_workers slot.
        live_set = set(live)
        # Maintain the node count locally: with a REST-backed provider
        # every non_terminated_nodes() is a network round trip, and the
        # loops below would otherwise issue O(plan + idle nodes) of them
        # per tick.
        live_count = len(live)
        for nid in [n for n in self._type_of if n not in live_set]:
            self._type_of.pop(nid, None)  # vanished externally: prune
        for nid, (_t, deadline) in list(self._booting.items()):
            registered = len(by_provider.get(nid, ()))
            if (registered >= self._hosts_of(nid, by_provider.get(nid))
                    or nid not in live_set):
                self._booting.pop(nid, None)
            elif now > deadline:
                cluster_events.emit(
                    cluster_events.WARNING, cluster_events.AUTOSCALER,
                    f"terminating node {nid}: boot deadline blown "
                    f"(hung instance would leak cost and pin a "
                    f"max_workers slot)",
                    custom_fields={"provider_node_id": nid,
                                   "reason": "boot_timeout"},
                )
                try:
                    self.provider.terminate_node(nid)
                    live_count -= 1
                except Exception as e:
                    # Transient provider failure: keep the entry with a
                    # short extension so termination retries, and say so —
                    # silently dropping it would leak the instance.
                    import sys

                    sys.stderr.write(
                        f"[autoscaler] terminate of hung node {nid} "
                        f"failed ({e!r}); will retry\n"
                    )
                    self._booting[nid] = (_t, now + 5.0)
                else:
                    self._booting.pop(nid, None)
                    self._type_of.pop(nid, None)
        booting_capacity = []
        for nid, (t, _deadline) in self._booting.items():
            tcfg = self.config.node_types.get(t)
            if tcfg is None:
                continue
            hosts = max(1, int(tcfg.get("hosts_per_node", 1)))
            # Only the hosts that have NOT registered yet are phantom;
            # registered ones already report real capacity.
            missing = hosts - len(by_provider.get(nid, ()))
            booting_capacity.extend(
                dict(tcfg["resources"]) for _ in range(max(0, missing))
            )

        # Upscale by shape: launch node types that fit the unmet demand,
        # sustained past upscale_delay_s.
        unmet = self._unmet_shapes(alive, booting_capacity)
        if unmet and live_count < cfg.max_workers:
            if self._pending_since is None:
                self._pending_since = now
            elif now - self._pending_since >= cfg.upscale_delay_s:
                for tname in self._plan_nodes(unmet):
                    if live_count >= cfg.max_workers:
                        break
                    self._launch(tname)
                    live_count += 1
                self._pending_since = None
        else:
            self._pending_since = None

        # Downscale: terminate a worker only when ITS OWN view has been
        # idle past the timeout (never below min_workers). Nodes whose
        # hosts have not ALL registered yet are still booting — treat as
        # busy (a slice with one idle registered host must not be torn
        # down while its other hosts are mid-boot). For a registered
        # slice, idle means EVERY host is idle. While any deployment's
        # SLO error budget is burning, idle nodes are kept warm — the
        # idle timer keeps running, so capacity releases the moment the
        # burn clears.
        slo_hold = self._slo_burn_active()
        for nid in list(live):
            hosts_views = by_provider.get(nid) or []
            idle = len(hosts_views) >= self._hosts_of(
                nid, hosts_views
            ) and all(
                v.get("pending_tasks", 0) == 0
                and v.get("resources_available", {})
                == v.get("resources_total", {})
                for v in hosts_views
            )
            if not idle:
                self._idle_since.pop(nid, None)
                continue
            since = self._idle_since.get(nid)
            if since is None:
                self._idle_since[nid] = now
            elif now - since >= cfg.idle_timeout_s:
                if slo_hold:
                    continue
                if live_count > cfg.min_workers:
                    cluster_events.emit(
                        cluster_events.INFO, cluster_events.AUTOSCALER,
                        f"scale down: terminating node {nid} "
                        f"(idle {now - since:.1f}s)",
                        custom_fields={"provider_node_id": nid,
                                       "idle_s": round(now - since, 1),
                                       "reason": "idle_timeout"},
                    )
                    self.provider.terminate_node(nid)
                    live_count -= 1
                    self._idle_since.pop(nid, None)
                    self._type_of.pop(nid, None)
