"""Demand-driven cluster autoscaler.

Ref analogue: python/ray/autoscaler/_private/autoscaler.py
StandardAutoscaler (:169 update loop) + resource_demand_scheduler: scale
UP while tasks are queued beyond the cluster's free capacity (sustained
past ``upscale_delay_s``), scale DOWN worker nodes idle longer than
``idle_timeout_s``. Demand is read from the GCS load reports every node
already sends (pending task counts + available resources); nodes come and
go through a pluggable NodeProvider.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from .node_provider import LocalNodeProvider, NodeProvider


class AutoscalerConfig:
    def __init__(self, *, min_workers: int = 0, max_workers: int = 4,
                 worker_resources: Optional[Dict[str, float]] = None,
                 upscale_delay_s: float = 1.0,
                 idle_timeout_s: float = 10.0,
                 interval_s: float = 0.5):
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.worker_resources = worker_resources or {"CPU": 1}
        self.upscale_delay_s = upscale_delay_s
        self.idle_timeout_s = idle_timeout_s
        self.interval_s = interval_s


class Autoscaler:
    """Drive a NodeProvider from cluster demand. Runs in the head/driver
    process (``start()`` spawns the reconcile thread)."""

    def __init__(self, config: Optional[AutoscalerConfig] = None,
                 provider: Optional[NodeProvider] = None):
        from ..core.runtime_context import current_runtime

        self.config = config or AutoscalerConfig()
        rt = current_runtime()
        if provider is None:
            nm = rt._nm
            if nm.gcs_service is None:
                raise RuntimeError("autoscaler must run on the head node")
            host, port = nm.gcs_service.address
            provider = LocalNodeProvider(f"{host}:{port}")
        self.provider = provider
        self._rt = rt
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pending_since: Optional[float] = None
        # provider node id -> time it became idle (None = busy)
        self._idle_since: Dict[str, float] = {}
        self._launched: List[str] = []

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def shutdown(self, *, terminate_nodes: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if terminate_nodes and hasattr(self.provider, "shutdown"):
            self.provider.shutdown()

    def num_workers(self) -> int:
        return len(self.provider.non_terminated_nodes())

    # -- reconcile ----------------------------------------------------------

    def _demand(self) -> Dict[str, Any]:
        """Cluster pressure from the node views the GCS gossips."""
        views = self._rt.nodes()
        pending = sum(v.get("pending_tasks", 0) for v in views)
        free_cpu = sum(
            v.get("resources_available", {}).get("CPU", 0.0)
            for v in views if v.get("state") == "alive"
        )
        return {"pending_tasks": pending, "free_cpu": free_cpu}

    def _loop(self) -> None:
        cfg = self.config
        while not self._stop.is_set():
            try:
                self._reconcile_once()
            except Exception:
                pass
            self._stop.wait(cfg.interval_s)

    def _reconcile_once(self) -> None:
        cfg = self.config
        now = time.monotonic()
        live = self.provider.non_terminated_nodes()

        # Floor.
        while len(live) < cfg.min_workers:
            live.append(
                self.provider.create_node(dict(cfg.worker_resources))
            )

        d = self._demand()
        starved = d["pending_tasks"] > 0 and d["free_cpu"] <= 0.0
        if starved and len(live) < cfg.max_workers:
            if self._pending_since is None:
                self._pending_since = now
            elif now - self._pending_since >= cfg.upscale_delay_s:
                self.provider.create_node(dict(cfg.worker_resources))
                self._pending_since = None
        else:
            self._pending_since = None

        # Downscale: terminate workers idle past the timeout (never below
        # min_workers). A node is idle when it reports full availability
        # and no pending tasks.
        views = {
            v["node_id"]: v for v in self._rt.nodes()
        }
        # Map provider ids to cluster nodes by resource fingerprinting is
        # fragile; LocalNodeProvider nodes are the only non-head nodes it
        # launched, so count-based reconciliation is exact for it.
        idle_workers = [
            v for v in views.values()
            if not v.get("is_head") and v.get("state") == "alive"
            and v.get("pending_tasks", 0) == 0
            and v.get("resources_available", {}) ==
            v.get("resources_total", {})
        ]
        busy = len(live) - len(idle_workers)
        for nid in list(live):
            if len(self.provider.non_terminated_nodes()) <= max(
                    cfg.min_workers, busy):
                break
            since = self._idle_since.get(nid)
            if len(idle_workers) == 0:
                self._idle_since.pop(nid, None)
                continue
            if since is None:
                self._idle_since[nid] = time.monotonic()
            elif time.monotonic() - since >= cfg.idle_timeout_s:
                self.provider.terminate_node(nid)
                self._idle_since.pop(nid, None)
                idle_workers.pop()
