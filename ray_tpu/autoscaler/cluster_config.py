"""Cluster YAML config: load, validate, and build the autoscaler.

Ref analogue: the reference's cluster YAML + ray-schema.json consumed by
`ray up` (autoscaler/_private/commands.py). Schema (all keys except
``provider`` optional):

.. code-block:: yaml

    cluster_name: demo
    max_workers: 4          # global cap
    min_workers: 0
    idle_timeout_s: 60
    upscale_delay_s: 1.0
    provider:
      type: local           # local | ssh | gcp_tpu
      # ssh only:
      # worker_ips: [10.0.0.2, 10.0.0.3]
      # ssh_user: ubuntu
      # ssh_key: ~/.ssh/id_rsa
      # python: python3
      # gcp_tpu only:
      # project: my-project
      # zone: us-central2-b
      # api_base: https://tpu.googleapis.com/v2   # test override
      # network: default
      # setup_commands: ["pip install ray-tpu"]
    head:
      port: 7777
      num_cpus: 4
      resources: {TPU: 1}
    available_node_types:
      cpu_worker:
        resources: {CPU: 2}
        labels: {pool: general}
      tpu_v5e_16:                       # gcp_tpu: one node = one SLICE
        resources: {TPU: 4, CPU: 8}    # PER HOST of the slice
        hosts_per_node: 4              # v5litepod-16 = 4 hosts
        accelerator_type: v5litepod-16
        runtime_version: v2-alpha-tpuv5-lite
"""

from __future__ import annotations

import os
from typing import Any, Dict

from .autoscaler import Autoscaler, AutoscalerConfig
from .node_provider import (
    GCPTpuNodeProvider,
    LocalNodeProvider,
    SSHNodeProvider,
)

_ALLOWED_TOP = {
    "cluster_name", "max_workers", "min_workers", "idle_timeout_s",
    "upscale_delay_s", "boot_timeout_s", "infeasible_grace_s",
    "provider", "head", "available_node_types",
}
_ALLOWED_PROVIDER = {
    "type", "worker_ips", "ssh_user", "ssh_key", "python",
    "project", "zone", "api_base", "network", "setup_commands",
}
_ALLOWED_HEAD = {"port", "num_cpus", "resources", "node_ip"}


def load_cluster_config(path: str) -> Dict[str, Any]:
    import yaml

    with open(os.path.expanduser(path)) as f:
        cfg = yaml.safe_load(f) or {}
    if not isinstance(cfg, dict):
        raise ValueError(f"cluster config {path} must be a mapping")
    unknown = set(cfg) - _ALLOWED_TOP
    if unknown:
        raise ValueError(
            f"unknown cluster config keys: {sorted(unknown)} "
            f"(allowed: {sorted(_ALLOWED_TOP)})"
        )
    provider = cfg.setdefault("provider", {"type": "local"})
    unknown = set(provider) - _ALLOWED_PROVIDER
    if unknown:
        raise ValueError(f"unknown provider keys: {sorted(unknown)}")
    ptype = provider.setdefault("type", "local")
    if ptype not in ("local", "ssh", "gcp_tpu"):
        raise ValueError(
            f"provider.type must be local|ssh|gcp_tpu, got {ptype!r}"
        )
    if ptype == "ssh" and not provider.get("worker_ips"):
        raise ValueError("provider.type=ssh requires provider.worker_ips")
    if ptype == "gcp_tpu":
        for req in ("project", "zone"):
            if not provider.get(req):
                raise ValueError(
                    f"provider.type=gcp_tpu requires provider.{req}"
                )
    head = cfg.get("head") or {}
    unknown = set(head) - _ALLOWED_HEAD
    if unknown:
        raise ValueError(
            f"unknown head keys: {sorted(unknown)} "
            f"(allowed: {sorted(_ALLOWED_HEAD)})"
        )
    cfg.setdefault("cluster_name", "default")
    cfg.setdefault("max_workers", 4)
    cfg.setdefault("min_workers", 0)
    cfg.setdefault("head", {})
    for name, nt in (cfg.get("available_node_types") or {}).items():
        if "resources" not in nt:
            raise ValueError(f"node type {name!r} needs a resources map")
    return cfg


def build_autoscaler(cfg: Dict[str, Any], gcs_address: str,
                     *, nodes_fn=None) -> Autoscaler:
    """Construct (not start) an Autoscaler from a loaded cluster config."""
    node_types = cfg.get("available_node_types") or None
    as_cfg = AutoscalerConfig(
        min_workers=int(cfg.get("min_workers", 0)),
        max_workers=int(cfg.get("max_workers", 4)),
        node_types=node_types,
        idle_timeout_s=float(cfg.get("idle_timeout_s", 10.0)),
        upscale_delay_s=float(cfg.get("upscale_delay_s", 1.0)),
        boot_timeout_s=float(cfg.get("boot_timeout_s", 60.0)),
    )
    p = cfg["provider"]
    if p["type"] == "ssh":
        provider = SSHNodeProvider(
            gcs_address,
            worker_ips=list(p["worker_ips"]),
            ssh_user=p.get("ssh_user", ""),
            ssh_key=p.get("ssh_key", ""),
            python=p.get("python", "python3"),
        )
    elif p["type"] == "gcp_tpu":
        provider = GCPTpuNodeProvider(
            gcs_address,
            project=p["project"],
            zone=p["zone"],
            cluster_name=cfg.get("cluster_name", "rtpu"),
            api_base=p.get("api_base", "https://tpu.googleapis.com/v2"),
            network=p.get("network", ""),
            setup_commands=p.get("setup_commands"),
        )
        # The provider needs each type's accelerator/runtime to build
        # the TPU create request for a launch of that type.
        provider.node_type_configs = dict(node_types or {})
    else:
        provider = LocalNodeProvider(gcs_address)
    return Autoscaler(as_cfg, provider, nodes_fn=nodes_fn)
