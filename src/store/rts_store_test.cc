// Assert-based unit test for the native store (run via `make native-test`).
#include "rts_store.h"

#include <assert.h>
#include <stdio.h>
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

static void mkid(uint8_t* id, int n) {
  memset(id, 0, RTS_ID_SIZE);
  memcpy(id, &n, sizeof(n));
  id[RTS_ID_SIZE - 1] = 0xAB;  // non-zero tail so hash != 0 for n == 0
}

int main() {
  char name[64];
  snprintf(name, sizeof(name), "/rts-test-%d", (int)getpid());
  char err[256];
  rts_store* s = rts_create(name, 1 << 20, 1024, err);
  assert(s && "create failed");

  uint8_t id1[RTS_ID_SIZE], id2[RTS_ID_SIZE], id3[RTS_ID_SIZE];
  mkid(id1, 1);
  mkid(id2, 2);
  mkid(id3, 3);
  int32_t pid = (int32_t)getpid();

  // Alloc + write + seal + get round trip.
  uint64_t off = 0, size = 0;
  assert(rts_alloc_pin(s, id1, 1000, pid, &off) == RTS_OK);
  memset(rts_base(s) + off, 0x5A, 1000);
  assert(rts_get_pin(s, id1, pid, &off, &size) == RTS_BAD_STATE);  // unsealed
  assert(rts_seal(s, id1) == RTS_OK);
  assert(rts_unpin(s, id1, pid) == RTS_OK);  // drop creator pin
  assert(rts_get_pin(s, id1, pid, &off, &size) == RTS_OK);
  assert(size == 1000);
  assert(rts_base(s)[off] == 0x5A && rts_base(s)[off + 999] == 0x5A);
  assert((off % 64) == 0 && "payload must be 64B aligned");

  // Duplicate alloc rejected.
  uint64_t off2;
  assert(rts_alloc_pin(s, id1, 10, pid, &off2) == RTS_EXISTS);

  // Delete defers while pinned, frees after unpin.
  assert(rts_delete(s, id1) == RTS_OK);
  assert(rts_count(s) == 1);  // still pending
  assert(rts_unpin(s, id1, pid) == RTS_OK);
  assert(rts_count(s) == 0);
  uint64_t used_after_free = rts_used(s);
  assert(used_after_free == 0);

  // Fill / coalesce: allocate three, free middle, then re-alloc bigger than
  // a single fragment to force coalescing correctness.
  assert(rts_alloc_pin(s, id1, 4096, pid, &off) == RTS_OK);
  assert(rts_alloc_pin(s, id2, 4096, pid, &off) == RTS_OK);
  assert(rts_alloc_pin(s, id3, 4096, pid, &off) == RTS_OK);
  rts_seal(s, id1);
  rts_seal(s, id2);
  rts_seal(s, id3);
  rts_unpin(s, id1, pid);
  rts_unpin(s, id2, pid);
  rts_unpin(s, id3, pid);
  assert(rts_delete(s, id2) == RTS_OK);
  assert(rts_delete(s, id1) == RTS_OK);  // coalesce with freed id2 block
  uint8_t id4[RTS_ID_SIZE];
  mkid(id4, 4);
  assert(rts_alloc_pin(s, id4, 8192, pid, &off) == RTS_OK);  // fits coalesced
  rts_seal(s, id4);
  rts_unpin(s, id4, pid);

  // Eviction: free everything via LRU eviction.
  uint8_t evicted[RTS_ID_SIZE * 16];
  int n = rts_evict(s, 1 << 20, evicted, 16);
  assert(n == 2);  // id3 then id4 (id3 older)
  assert(memcmp(evicted, id3, RTS_ID_SIZE) == 0);
  assert(rts_count(s) == 0);

  // Cross-process: child attaches, writes an object; parent reads it.
  uint8_t idx[RTS_ID_SIZE];
  mkid(idx, 99);
  pid_t child = fork();
  if (child == 0) {
    rts_store* c = rts_attach(name, err);
    if (!c) _exit(1);
    uint64_t o;
    if (rts_alloc_pin(c, idx, 64, (int32_t)getpid(), &o) != RTS_OK) _exit(2);
    memset(rts_base(c) + o, 0x77, 64);
    if (rts_seal(c, idx) != RTS_OK) _exit(3);
    // Exit WITHOUT unpinning: parent must reclaim the dead pid's pin.
    _exit(0);
  }
  int status = 0;
  waitpid(child, &status, 0);
  assert(WIFEXITED(status) && WEXITSTATUS(status) == 0);
  assert(rts_get_pin(s, idx, pid, &off, &size) == RTS_OK);
  assert(size == 64 && rts_base(s)[off] == 0x77);
  rts_unpin(s, idx, pid);
  // The dead child's creator pin blocks delete until purged.
  rts_delete(s, idx);
  rts_purge_dead_pins(s);
  assert(rts_count(s) == 0);

  rts_close(s);
  rts_unlink(name);
  printf("rts_store_test: OK\n");
  return 0;
}
