// CPython binding for the native shared-memory object store (rts_store.h).
//
// Exposes two types:
//   Store — a created/attached arena; alloc/seal/get/delete/evict/stats.
//   View  — a buffer-protocol window over one object's payload. A View holds
//           a pin on the object (and a reference on the Store); deserialized
//           numpy arrays keep the View alive through the memoryview chain, so
//           the block cannot be reused under a live zero-copy reader.
//
// pybind11 is not available in this environment; the plain CPython C API is.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <string.h>
#include <unistd.h>

#include "rts_store.h"

namespace {

struct StoreObject {
  PyObject_HEAD
  rts_store* handle;
  int live_views;
  int want_close;
  char name[128];
};

struct ViewObject {
  PyObject_HEAD
  StoreObject* store;  // owned reference
  uint8_t id[RTS_ID_SIZE];
  uint8_t* ptr;
  Py_ssize_t size;
  int readonly;
  int released;
};

extern PyTypeObject StoreType;
extern PyTypeObject ViewType;

void store_do_close(StoreObject* self) {
  if (self->handle) {
    rts_close(self->handle);
    self->handle = nullptr;
  }
}

// ---- View ------------------------------------------------------------------

void View_release_pin(ViewObject* v) {
  if (!v->released) {
    v->released = 1;
    if (v->store && v->store->handle) {
      rts_unpin(v->store->handle, v->id, (int32_t)getpid());
    }
    if (v->store) {
      v->store->live_views -= 1;
      if (v->store->want_close && v->store->live_views == 0) {
        store_do_close(v->store);
      }
    }
  }
}

void View_dealloc(ViewObject* v) {
  View_release_pin(v);
  Py_XDECREF((PyObject*)v->store);
  Py_TYPE(v)->tp_free((PyObject*)v);
}

int View_getbuffer(ViewObject* v, Py_buffer* view, int flags) {
  if (v->released || !v->store || !v->store->handle) {
    PyErr_SetString(PyExc_ValueError, "view released or store closed");
    return -1;
  }
  return PyBuffer_FillInfo(view, (PyObject*)v, v->ptr, v->size, v->readonly,
                           flags);
}

PyBufferProcs View_as_buffer = {
    (getbufferproc)View_getbuffer,
    nullptr,
};

PyObject* View_size(ViewObject* v, void*) { return PyLong_FromSsize_t(v->size); }

PyObject* View_releasemeth(ViewObject* v, PyObject*) {
  View_release_pin(v);
  Py_RETURN_NONE;
}

PyMethodDef View_methods[] = {
    {"release", (PyCFunction)View_releasemeth, METH_NOARGS,
     "Drop the pin early (the buffer must no longer be accessed)."},
    {nullptr, nullptr, 0, nullptr},
};

PyGetSetDef View_getset[] = {
    {"nbytes", (getter)View_size, nullptr, nullptr, nullptr},
    {nullptr, nullptr, nullptr, nullptr, nullptr},
};

PyTypeObject ViewType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

ViewObject* make_view(StoreObject* store, const uint8_t* id, uint64_t off,
                      uint64_t size, int readonly) {
  ViewObject* v = PyObject_New(ViewObject, &ViewType);
  if (!v) return nullptr;
  Py_INCREF((PyObject*)store);
  v->store = store;
  memcpy(v->id, id, RTS_ID_SIZE);
  v->ptr = rts_base(store->handle) + off;
  v->size = (Py_ssize_t)size;
  v->readonly = readonly;
  v->released = 0;
  store->live_views += 1;
  return v;
}

// ---- Store -----------------------------------------------------------------

void Store_dealloc(StoreObject* self) {
  store_do_close(self);
  Py_TYPE(self)->tp_free((PyObject*)self);
}

int parse_id(PyObject* obj, uint8_t* out) {
  char* buf;
  Py_ssize_t len;
  if (PyBytes_AsStringAndSize(obj, &buf, &len) != 0) return -1;
  if (len != RTS_ID_SIZE) {
    PyErr_SetString(PyExc_ValueError, "object id must be RTS_ID_SIZE bytes");
    return -1;
  }
  memcpy(out, buf, RTS_ID_SIZE);
  return 0;
}

int check_open(StoreObject* self) {
  if (!self->handle) {
    PyErr_SetString(PyExc_ValueError, "store is closed");
    return -1;
  }
  return 0;
}

PyObject* raise_status(int rc) {
  switch (rc) {
    case RTS_NOT_FOUND:
      PyErr_SetString(PyExc_KeyError, "object not found");
      break;
    case RTS_EXISTS:
      PyErr_SetString(PyExc_FileExistsError, "object already exists");
      break;
    case RTS_FULL:
      PyErr_SetString(PyExc_MemoryError, "object store full");
      break;
    case RTS_BAD_STATE:
      PyErr_SetString(PyExc_RuntimeError, "object in wrong state");
      break;
    case RTS_TABLE_FULL:
      PyErr_SetString(PyExc_MemoryError, "object table full");
      break;
    default:
      PyErr_SetString(PyExc_RuntimeError, "object store I/O error");
  }
  return nullptr;
}

PyObject* Store_alloc(StoreObject* self, PyObject* args) {
  PyObject* id_obj;
  unsigned long long size;
  if (!PyArg_ParseTuple(args, "OK", &id_obj, &size)) return nullptr;
  uint8_t id[RTS_ID_SIZE];
  if (parse_id(id_obj, id) != 0 || check_open(self) != 0) return nullptr;
  uint64_t off = 0;
  int rc = rts_alloc_pin(self->handle, id, size, (int32_t)getpid(), &off);
  if (rc != RTS_OK) return raise_status(rc);
  return (PyObject*)make_view(self, id, off, size, /*readonly=*/0);
}

PyObject* Store_seal(StoreObject* self, PyObject* args) {
  PyObject* id_obj;
  if (!PyArg_ParseTuple(args, "O", &id_obj)) return nullptr;
  uint8_t id[RTS_ID_SIZE];
  if (parse_id(id_obj, id) != 0 || check_open(self) != 0) return nullptr;
  int rc = rts_seal(self->handle, id);
  if (rc != RTS_OK) return raise_status(rc);
  Py_RETURN_NONE;
}

PyObject* Store_abort(StoreObject* self, PyObject* args) {
  PyObject* id_obj;
  if (!PyArg_ParseTuple(args, "O", &id_obj)) return nullptr;
  uint8_t id[RTS_ID_SIZE];
  if (parse_id(id_obj, id) != 0 || check_open(self) != 0) return nullptr;
  rts_abort(self->handle, id);
  Py_RETURN_NONE;
}

PyObject* Store_get(StoreObject* self, PyObject* args) {
  PyObject* id_obj;
  if (!PyArg_ParseTuple(args, "O", &id_obj)) return nullptr;
  uint8_t id[RTS_ID_SIZE];
  if (parse_id(id_obj, id) != 0 || check_open(self) != 0) return nullptr;
  uint64_t off = 0, size = 0;
  int rc = rts_get_pin(self->handle, id, (int32_t)getpid(), &off, &size);
  if (rc == RTS_NOT_FOUND || rc == RTS_BAD_STATE) Py_RETURN_NONE;
  if (rc != RTS_OK) return raise_status(rc);
  return (PyObject*)make_view(self, id, off, size, /*readonly=*/1);
}

PyObject* Store_contains(StoreObject* self, PyObject* args) {
  PyObject* id_obj;
  if (!PyArg_ParseTuple(args, "O", &id_obj)) return nullptr;
  uint8_t id[RTS_ID_SIZE];
  if (parse_id(id_obj, id) != 0 || check_open(self) != 0) return nullptr;
  uint32_t state = 0;
  int rc = rts_lookup(self->handle, id, nullptr, nullptr, &state);
  // Sealed (3) or pending-delete (4) objects are readable.
  if (rc == RTS_OK && (state == 3 || state == 4)) Py_RETURN_TRUE;
  Py_RETURN_FALSE;
}

PyObject* Store_delete(StoreObject* self, PyObject* args) {
  PyObject* id_obj;
  if (!PyArg_ParseTuple(args, "O", &id_obj)) return nullptr;
  uint8_t id[RTS_ID_SIZE];
  if (parse_id(id_obj, id) != 0 || check_open(self) != 0) return nullptr;
  rts_delete(self->handle, id);
  Py_RETURN_NONE;
}

PyObject* Store_evict(StoreObject* self, PyObject* args) {
  unsigned long long need;
  int max_n = 256;
  if (!PyArg_ParseTuple(args, "K|i", &need, &max_n)) return nullptr;
  if (check_open(self) != 0) return nullptr;
  if (max_n <= 0) max_n = 1;
  uint8_t* ids = (uint8_t*)PyMem_Malloc((size_t)max_n * RTS_ID_SIZE);
  if (!ids) return PyErr_NoMemory();
  int n = rts_evict(self->handle, need, ids, max_n);
  PyObject* out = PyList_New(n);
  for (int i = 0; i < n; ++i) {
    PyList_SET_ITEM(out, i, PyBytes_FromStringAndSize((char*)ids + i * RTS_ID_SIZE, RTS_ID_SIZE));
  }
  PyMem_Free(ids);
  return out;
}

PyObject* Store_purge_dead_pins(StoreObject* self, PyObject*) {
  if (check_open(self) != 0) return nullptr;
  rts_purge_dead_pins(self->handle);
  Py_RETURN_NONE;
}

PyObject* Store_used(StoreObject* self, PyObject*) {
  if (check_open(self) != 0) return nullptr;
  return PyLong_FromUnsignedLongLong(rts_used(self->handle));
}

PyObject* Store_capacity(StoreObject* self, PyObject*) {
  if (check_open(self) != 0) return nullptr;
  return PyLong_FromUnsignedLongLong(rts_capacity(self->handle));
}

PyObject* Store_count(StoreObject* self, PyObject*) {
  if (check_open(self) != 0) return nullptr;
  return PyLong_FromUnsignedLong(rts_count(self->handle));
}

PyObject* Store_close(StoreObject* self, PyObject*) {
  if (self->live_views > 0) {
    self->want_close = 1;  // deferred until the last View drops its pin
  } else {
    store_do_close(self);
  }
  Py_RETURN_NONE;
}

PyObject* Store_name(StoreObject* self, void*) {
  return PyUnicode_FromString(self->name);
}

PyMethodDef Store_methods[] = {
    {"alloc", (PyCFunction)Store_alloc, METH_VARARGS,
     "alloc(id, size) -> writable View (pinned; seal(id) when written)"},
    {"seal", (PyCFunction)Store_seal, METH_VARARGS, "seal(id)"},
    {"abort", (PyCFunction)Store_abort, METH_VARARGS, "abort(id)"},
    {"get", (PyCFunction)Store_get, METH_VARARGS,
     "get(id) -> readonly View or None"},
    {"contains", (PyCFunction)Store_contains, METH_VARARGS, "contains(id)"},
    {"delete", (PyCFunction)Store_delete, METH_VARARGS, "delete(id)"},
    {"evict", (PyCFunction)Store_evict, METH_VARARGS,
     "evict(need_bytes, max_n=256) -> [evicted ids]"},
    {"purge_dead_pins", (PyCFunction)Store_purge_dead_pins, METH_NOARGS, ""},
    {"used", (PyCFunction)Store_used, METH_NOARGS, ""},
    {"capacity", (PyCFunction)Store_capacity, METH_NOARGS, ""},
    {"count", (PyCFunction)Store_count, METH_NOARGS, ""},
    {"close", (PyCFunction)Store_close, METH_NOARGS, ""},
    {nullptr, nullptr, 0, nullptr},
};

PyGetSetDef Store_getset[] = {
    {"name", (getter)Store_name, nullptr, nullptr, nullptr},
    {nullptr, nullptr, nullptr, nullptr, nullptr},
};

PyTypeObject StoreType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

StoreObject* make_store(rts_store* handle, const char* name) {
  StoreObject* s = PyObject_New(StoreObject, &StoreType);
  if (!s) {
    rts_close(handle);
    return nullptr;
  }
  s->handle = handle;
  s->live_views = 0;
  s->want_close = 0;
  snprintf(s->name, sizeof(s->name), "%s", name);
  return s;
}

// ---- module ----------------------------------------------------------------

PyObject* mod_create(PyObject*, PyObject* args) {
  const char* name;
  unsigned long long capacity;
  unsigned int table_cap = 0;
  if (!PyArg_ParseTuple(args, "sK|I", &name, &capacity, &table_cap))
    return nullptr;
  char err[256] = {0};
  rts_store* h = rts_create(name, capacity, table_cap, err);
  if (!h) {
    PyErr_Format(PyExc_OSError, "rts_create: %s", err);
    return nullptr;
  }
  return (PyObject*)make_store(h, name);
}

PyObject* mod_attach(PyObject*, PyObject* args) {
  const char* name;
  if (!PyArg_ParseTuple(args, "s", &name)) return nullptr;
  char err[256] = {0};
  rts_store* h = rts_attach(name, err);
  if (!h) {
    PyErr_Format(PyExc_OSError, "rts_attach: %s", err);
    return nullptr;
  }
  return (PyObject*)make_store(h, name);
}

PyObject* mod_unlink(PyObject*, PyObject* args) {
  const char* name;
  if (!PyArg_ParseTuple(args, "s", &name)) return nullptr;
  rts_unlink(name);
  Py_RETURN_NONE;
}

PyMethodDef module_methods[] = {
    {"create", mod_create, METH_VARARGS,
     "create(name, capacity, table_cap=0) -> Store"},
    {"attach", mod_attach, METH_VARARGS, "attach(name) -> Store"},
    {"unlink", mod_unlink, METH_VARARGS, "unlink(name)"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef rtstore_module = {
    PyModuleDef_HEAD_INIT, "_rtstore",
    "Native shared-memory object store (plasma-equivalent).", -1,
    module_methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__rtstore(void) {
  StoreType.tp_name = "_rtstore.Store";
  StoreType.tp_basicsize = sizeof(StoreObject);
  StoreType.tp_dealloc = (destructor)Store_dealloc;
  StoreType.tp_flags = Py_TPFLAGS_DEFAULT;
  StoreType.tp_methods = Store_methods;
  StoreType.tp_getset = Store_getset;
  ViewType.tp_name = "_rtstore.View";
  ViewType.tp_basicsize = sizeof(ViewObject);
  ViewType.tp_dealloc = (destructor)View_dealloc;
  ViewType.tp_flags = Py_TPFLAGS_DEFAULT;
  ViewType.tp_as_buffer = &View_as_buffer;
  ViewType.tp_methods = View_methods;
  ViewType.tp_getset = View_getset;
  if (PyType_Ready(&StoreType) < 0 || PyType_Ready(&ViewType) < 0)
    return nullptr;
  PyObject* m = PyModule_Create(&rtstore_module);
  if (!m) return nullptr;
  Py_INCREF(&StoreType);
  PyModule_AddObject(m, "Store", (PyObject*)&StoreType);
  Py_INCREF(&ViewType);
  PyModule_AddObject(m, "View", (PyObject*)&ViewType);
  return m;
}
