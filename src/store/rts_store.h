// Native shared-memory object store (plasma-equivalent).
//
// Ref analogue: src/ray/object_manager/plasma/{store.h,plasma_allocator.cc,
// eviction_policy.h} in the reference — a node-wide arena of immutable,
// sealed-once objects read zero-copy by every process. TPU-first differences:
// no store daemon and no socket protocol — the allocator metadata and object
// table live *inside* the shared mapping guarded by a robust process-shared
// mutex, so any worker allocates/reads with a single lock acquisition instead
// of an IPC round trip (the hot path feeds jax.device_put, where an extra
// syscall per batch matters).
//
// Layout of the mapping:
//   [Header][Entry * table_cap][data region of `capacity` bytes]
//
// Data region: boundary-tag blocks (64-byte header chunk, 16-byte footer),
// explicit first-fit free list with coalescing. All payloads are 64-byte
// aligned (TPU host DMA prefers cacheline-aligned source buffers).
//
// Object lifecycle: CREATED (being written) -> SEALED (immutable, readable)
// -> freed via delete (or PENDING_DELETE while readers hold pins). Pins are
// (pid, count) slots so pins of crashed processes can be reclaimed.
#pragma once

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define RTS_ID_SIZE 20

typedef struct rts_store rts_store;

enum {
  RTS_OK = 0,
  RTS_NOT_FOUND = -1,
  RTS_EXISTS = -2,
  RTS_FULL = -3,
  RTS_BAD_STATE = -4,
  RTS_TABLE_FULL = -5,
  RTS_IO = -6,
};

// Create a new store backed by POSIX shm object `name` (e.g. "/rtpu-arena").
// `capacity` = data-region bytes; `table_cap` = max live objects (0 =>
// default 65536). On error returns NULL and fills err[256].
rts_store* rts_create(const char* name, uint64_t capacity, uint32_t table_cap,
                      char* err);

// Attach to an existing store. NULL + err on failure.
rts_store* rts_attach(const char* name, char* err);

// Unmap (does not unlink the shm object).
void rts_close(rts_store* s);

// Destroy the backing shm object (creator calls at shutdown).
int rts_unlink(const char* name);

// Allocate `size` bytes for object `id` (RTS_ID_SIZE bytes) and pin it for `pid`.
// Fills *off with the payload offset (relative to rts_base()).
int rts_alloc_pin(rts_store* s, const uint8_t* id, uint64_t size, int32_t pid,
                  uint64_t* off);

// Mark a CREATED object immutable and readable.
int rts_seal(rts_store* s, const uint8_t* id);

// Free a CREATED object after a failed write (drops the allocation).
int rts_abort(rts_store* s, const uint8_t* id);

// Look up a SEALED object and add a pin for `pid`. Fills *off and *size.
int rts_get_pin(rts_store* s, const uint8_t* id, int32_t pid, uint64_t* off,
                uint64_t* size);

// Look up without pinning (directory/introspection use).
int rts_lookup(rts_store* s, const uint8_t* id, uint64_t* off, uint64_t* size,
               uint32_t* state);

// Drop one pin held by `pid`; frees the block if the object was
// PENDING_DELETE and this was the last pin.
int rts_unpin(rts_store* s, const uint8_t* id, int32_t pid);

// Delete a sealed object: frees immediately when unpinned, else defers.
int rts_delete(rts_store* s, const uint8_t* id);

// Evict least-recently-used sealed+unpinned objects until `need` bytes are
// reclaimed (or candidates run out). Writes up to max_n evicted ids
// (16 bytes each) into out_ids. Returns the number evicted (>= 0).
int rts_evict(rts_store* s, uint64_t need, uint8_t* out_ids, int max_n);

// Drop pins belonging to processes that no longer exist.
void rts_purge_dead_pins(rts_store* s);

uint64_t rts_used(rts_store* s);
uint64_t rts_capacity(rts_store* s);
uint32_t rts_count(rts_store* s);
uint8_t* rts_base(rts_store* s);

#ifdef __cplusplus
}
#endif
