// Implementation of the native shared-memory object store. See rts_store.h.
#include "rts_store.h"

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <vector>

namespace {

constexpr uint64_t kMagic = 0x52545053544f5245ull;  // "RTPSTORE"
constexpr uint32_t kVersion = 1;
constexpr uint64_t kAlign = 64;
constexpr uint64_t kNil = ~0ull;
constexpr int kPinSlots = 8;
constexpr uint32_t kDefaultTableCap = 65536;

// Block geometry (offsets relative to the data region, all multiples of 64).
constexpr uint64_t kBlockHdr = 64;   // header chunk at block start
constexpr uint64_t kBlockFtr = 64;   // footer chunk at block end
constexpr uint64_t kBlockOverhead = kBlockHdr + kBlockFtr;
constexpr uint64_t kMinBlock = kBlockOverhead + kAlign;

enum State : uint32_t {
  kEmpty = 0,
  kTomb = 1,
  kCreated = 2,
  kSealed = 3,
  kPendingDelete = 4,
};

struct PinSlot {
  int32_t pid;
  int32_t count;
};

struct Entry {
  uint8_t id[RTS_ID_SIZE];
  uint64_t offset;  // payload offset into the data region
  uint64_t size;    // user-visible size
  uint32_t state;
  uint32_t reserved;
  uint64_t lru;
  int64_t pins;  // total pins (including any overflow beyond the slots)
  PinSlot slots[kPinSlots];
};
static_assert(sizeof(Entry) <= 128, "Entry grew past its slot");

struct Header {
  uint64_t magic;
  uint32_t version;
  volatile uint32_t inited;
  pthread_mutex_t mutex;
  uint64_t capacity;  // data-region bytes
  uint64_t used;      // bytes in allocated blocks (incl. overhead)
  uint64_t lru_tick;
  uint32_t table_cap;
  uint32_t count;
  uint64_t free_head;  // offset of first free block, kNil if none
  uint64_t table_off;  // from mapping base
  uint64_t data_off;   // from mapping base
  uint64_t total_map;  // full mapping size
};

struct BlockHdr {
  uint64_t size;  // whole block, incl. header+footer
  uint64_t free_;
  uint64_t next;  // free-list links (block offsets), kNil terminated
  uint64_t prev;
  uint8_t pad[32];
};
static_assert(sizeof(BlockHdr) == kBlockHdr, "block header must be 64B");

struct BlockFtr {
  uint64_t size;
  uint64_t free_;
};

inline uint64_t align_up(uint64_t n) { return (n + kAlign - 1) / kAlign * kAlign; }

}  // namespace

struct rts_store {
  int fd = -1;
  uint8_t* map = nullptr;
  uint64_t map_size = 0;
  bool creator = false;
  char name[128] = {0};

  Header* hdr() { return reinterpret_cast<Header*>(map); }
  Entry* table() { return reinterpret_cast<Entry*>(map + hdr()->table_off); }
  uint8_t* data() { return map + hdr()->data_off; }

  BlockHdr* block(uint64_t off) {
    return reinterpret_cast<BlockHdr*>(data() + off);
  }
  BlockFtr* footer(uint64_t off) {
    BlockHdr* b = block(off);
    return reinterpret_cast<BlockFtr*>(data() + off + b->size - sizeof(BlockFtr));
  }
};

namespace {

void set_err(char* err, const char* msg) {
  if (err) snprintf(err, 256, "%s (errno=%d %s)", msg, errno, strerror(errno));
}

// Robust lock: if the previous holder died mid-critical-section, take
// ownership and mark the mutex consistent. The metadata is updated with
// small, ordered writes so a torn update at worst leaks a block.
void lock(rts_store* s) {
  int rc = pthread_mutex_lock(&s->hdr()->mutex);
  if (rc == EOWNERDEAD) pthread_mutex_consistent(&s->hdr()->mutex);
}
void unlock(rts_store* s) { pthread_mutex_unlock(&s->hdr()->mutex); }

uint64_t hash_id(const uint8_t* id) {
  // Objects created by one task share a 16-byte prefix and differ only in
  // the trailing 4-byte index, so mix both ends of the id.
  uint64_t a, b;
  memcpy(&a, id, sizeof(a));
  memcpy(&b, id + RTS_ID_SIZE - sizeof(b), sizeof(b));
  uint64_t h = (a ^ (b * 0x9E3779B97F4A7C15ull));
  return h ? h : 1;
}

Entry* find_entry(rts_store* s, const uint8_t* id) {
  Header* h = s->hdr();
  Entry* t = s->table();
  uint64_t cap = h->table_cap;
  uint64_t i = hash_id(id) % cap;
  for (uint64_t probes = 0; probes < cap; ++probes, i = (i + 1) % cap) {
    Entry* e = &t[i];
    if (e->state == kEmpty) return nullptr;
    if (e->state != kTomb && memcmp(e->id, id, RTS_ID_SIZE) == 0) return e;
  }
  return nullptr;
}

Entry* insert_entry(rts_store* s, const uint8_t* id) {
  Header* h = s->hdr();
  Entry* t = s->table();
  uint64_t cap = h->table_cap;
  uint64_t i = hash_id(id) % cap;
  Entry* slot = nullptr;
  for (uint64_t probes = 0; probes < cap; ++probes, i = (i + 1) % cap) {
    Entry* e = &t[i];
    if (e->state == kEmpty) {
      if (!slot) slot = e;
      break;
    }
    if (e->state == kTomb) {
      if (!slot) slot = e;
      continue;
    }
    if (memcmp(e->id, id, RTS_ID_SIZE) == 0) return nullptr;  // exists
  }
  if (!slot) return nullptr;  // table full
  memset(slot, 0, sizeof(Entry));
  memcpy(slot->id, id, RTS_ID_SIZE);
  return slot;
}

// ---- free-list allocator ---------------------------------------------------

void freelist_remove(rts_store* s, uint64_t off) {
  Header* h = s->hdr();
  BlockHdr* b = s->block(off);
  if (b->prev != kNil)
    s->block(b->prev)->next = b->next;
  else
    h->free_head = b->next;
  if (b->next != kNil) s->block(b->next)->prev = b->prev;
}

void freelist_push(rts_store* s, uint64_t off) {
  Header* h = s->hdr();
  BlockHdr* b = s->block(off);
  b->free_ = 1;
  b->prev = kNil;
  b->next = h->free_head;
  if (h->free_head != kNil) s->block(h->free_head)->prev = off;
  h->free_head = off;
  BlockFtr* f = s->footer(off);
  f->size = b->size;
  f->free_ = 1;
}

void write_used(rts_store* s, uint64_t off, uint64_t size) {
  BlockHdr* b = s->block(off);
  b->size = size;
  b->free_ = 0;
  b->next = b->prev = kNil;
  BlockFtr* f = s->footer(off);
  f->size = size;
  f->free_ = 0;
}

// Returns block offset or kNil. First-fit with split.
uint64_t alloc_block(rts_store* s, uint64_t payload) {
  Header* h = s->hdr();
  uint64_t need = kBlockOverhead + align_up(payload);
  for (uint64_t off = h->free_head; off != kNil; off = s->block(off)->next) {
    BlockHdr* b = s->block(off);
    if (b->size < need) continue;
    freelist_remove(s, off);
    uint64_t rem = b->size - need;
    if (rem >= kMinBlock) {
      write_used(s, off, need);
      uint64_t rest = off + need;
      s->block(rest)->size = rem;
      freelist_push(s, rest);
    } else {
      write_used(s, off, b->size);
      need = b->size;
    }
    h->used += need;
    return off;
  }
  return kNil;
}

void free_block(rts_store* s, uint64_t off) {
  Header* h = s->hdr();
  BlockHdr* b = s->block(off);
  h->used -= b->size;
  uint64_t start = off, size = b->size;
  // Coalesce with previous physical block.
  if (start > 0) {
    BlockFtr* pf = reinterpret_cast<BlockFtr*>(s->data() + start - sizeof(BlockFtr));
    if (pf->free_) {
      uint64_t prev_off = start - pf->size;
      freelist_remove(s, prev_off);
      start = prev_off;
      size += pf->size;
    }
  }
  // Coalesce with next physical block.
  uint64_t next_off = off + b->size;
  if (next_off < h->capacity) {
    BlockHdr* nb = s->block(next_off);
    if (nb->free_) {
      freelist_remove(s, next_off);
      size += nb->size;
    }
  }
  s->block(start)->size = size;
  freelist_push(s, start);
}

bool pid_alive(int32_t pid) {
  if (pid <= 0) return false;
  return kill(pid, 0) == 0 || errno != ESRCH;
}

void drop_dead_pins(Entry* e) {
  for (int i = 0; i < kPinSlots; ++i) {
    if (e->slots[i].pid != 0 && !pid_alive(e->slots[i].pid)) {
      e->pins -= e->slots[i].count;
      e->slots[i].pid = 0;
      e->slots[i].count = 0;
    }
  }
  if (e->pins < 0) e->pins = 0;
}

void release_entry(rts_store* s, Entry* e) {
  free_block(s, e->offset - kBlockHdr);
  e->state = kTomb;
  s->hdr()->count -= 1;
}

}  // namespace

extern "C" {

rts_store* rts_create(const char* name, uint64_t capacity, uint32_t table_cap,
                      char* err) {
  if (table_cap == 0) table_cap = kDefaultTableCap;
  capacity = align_up(capacity);
  uint64_t table_bytes = align_up(uint64_t(table_cap) * sizeof(Entry));
  uint64_t hdr_bytes = align_up(sizeof(Header));
  uint64_t total = hdr_bytes + table_bytes + capacity;

  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    set_err(err, "shm_open failed");
    return nullptr;
  }
  if (ftruncate(fd, (off_t)total) != 0) {
    set_err(err, "ftruncate failed");
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* map = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (map == MAP_FAILED) {
    set_err(err, "mmap failed");
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  rts_store* s = new rts_store();
  s->fd = fd;
  s->map = static_cast<uint8_t*>(map);
  s->map_size = total;
  s->creator = true;
  snprintf(s->name, sizeof(s->name), "%s", name);

  Header* h = s->hdr();
  memset(h, 0, sizeof(Header));
  h->magic = kMagic;
  h->version = kVersion;
  h->capacity = capacity;
  h->table_cap = table_cap;
  h->table_off = hdr_bytes;
  h->data_off = hdr_bytes + table_bytes;
  h->total_map = total;
  h->free_head = kNil;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  // One big free block spans the whole data region.
  s->block(0)->size = capacity;
  freelist_push(s, 0);

  __atomic_store_n(&h->inited, 1, __ATOMIC_RELEASE);
  return s;
}

rts_store* rts_attach(const char* name, char* err) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) {
    set_err(err, "shm_open(attach) failed");
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)sizeof(Header)) {
    set_err(err, "fstat failed or store too small");
    close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (map == MAP_FAILED) {
    set_err(err, "mmap(attach) failed");
    close(fd);
    return nullptr;
  }
  rts_store* s = new rts_store();
  s->fd = fd;
  s->map = static_cast<uint8_t*>(map);
  s->map_size = st.st_size;
  s->creator = false;
  snprintf(s->name, sizeof(s->name), "%s", name);

  Header* h = s->hdr();
  for (int spin = 0; spin < 10000; ++spin) {
    if (__atomic_load_n(&h->inited, __ATOMIC_ACQUIRE) == 1) break;
    usleep(100);
  }
  if (h->magic != kMagic || !h->inited) {
    set_err(err, "store not initialized or bad magic");
    rts_close(s);
    return nullptr;
  }
  return s;
}

void rts_close(rts_store* s) {
  if (!s) return;
  if (s->map) munmap(s->map, s->map_size);
  if (s->fd >= 0) close(s->fd);
  delete s;
}

int rts_unlink(const char* name) { return shm_unlink(name) == 0 ? RTS_OK : RTS_IO; }

static void add_pin(Entry* e, int32_t pid) {
  e->pins += 1;
  for (int i = 0; i < kPinSlots; ++i) {
    if (e->slots[i].pid == pid) {
      e->slots[i].count += 1;
      return;
    }
  }
  for (int i = 0; i < kPinSlots; ++i) {
    if (e->slots[i].pid == 0) {
      e->slots[i].pid = pid;
      e->slots[i].count = 1;
      return;
    }
  }
  // Slots full: the pin still counts in e->pins but can't be reclaimed if
  // this pid dies. Bounded risk; 8 concurrent pinning pids per object.
}

int rts_alloc_pin(rts_store* s, const uint8_t* id, uint64_t size, int32_t pid,
                  uint64_t* off) {
  lock(s);
  if (find_entry(s, id)) {
    unlock(s);
    return RTS_EXISTS;
  }
  uint64_t boff = alloc_block(s, size);
  if (boff == kNil) {
    unlock(s);
    return RTS_FULL;
  }
  Entry* e = insert_entry(s, id);
  if (!e) {
    free_block(s, boff);
    unlock(s);
    return RTS_TABLE_FULL;
  }
  e->offset = boff + kBlockHdr;
  e->size = size;
  e->state = kCreated;
  e->lru = ++s->hdr()->lru_tick;
  add_pin(e, pid);
  s->hdr()->count += 1;
  *off = e->offset;
  unlock(s);
  return RTS_OK;
}

int rts_seal(rts_store* s, const uint8_t* id) {
  lock(s);
  Entry* e = find_entry(s, id);
  if (!e) {
    unlock(s);
    return RTS_NOT_FOUND;
  }
  if (e->state != kCreated) {
    unlock(s);
    return RTS_BAD_STATE;
  }
  e->state = kSealed;
  unlock(s);
  return RTS_OK;
}

int rts_abort(rts_store* s, const uint8_t* id) {
  lock(s);
  Entry* e = find_entry(s, id);
  if (!e) {
    unlock(s);
    return RTS_NOT_FOUND;
  }
  if (e->state != kCreated) {
    unlock(s);
    return RTS_BAD_STATE;
  }
  release_entry(s, e);
  unlock(s);
  return RTS_OK;
}

int rts_get_pin(rts_store* s, const uint8_t* id, int32_t pid, uint64_t* off,
                uint64_t* size) {
  lock(s);
  Entry* e = find_entry(s, id);
  if (!e) {
    unlock(s);
    return RTS_NOT_FOUND;
  }
  if (e->state != kSealed && e->state != kPendingDelete) {
    unlock(s);
    return RTS_BAD_STATE;
  }
  add_pin(e, pid);
  e->lru = ++s->hdr()->lru_tick;
  *off = e->offset;
  *size = e->size;
  unlock(s);
  return RTS_OK;
}

int rts_lookup(rts_store* s, const uint8_t* id, uint64_t* off, uint64_t* size,
               uint32_t* state) {
  lock(s);
  Entry* e = find_entry(s, id);
  if (!e) {
    unlock(s);
    return RTS_NOT_FOUND;
  }
  if (off) *off = e->offset;
  if (size) *size = e->size;
  if (state) *state = e->state;
  unlock(s);
  return RTS_OK;
}

int rts_unpin(rts_store* s, const uint8_t* id, int32_t pid) {
  lock(s);
  Entry* e = find_entry(s, id);
  if (!e) {
    unlock(s);
    return RTS_NOT_FOUND;
  }
  for (int i = 0; i < kPinSlots; ++i) {
    if (e->slots[i].pid == pid) {
      e->slots[i].count -= 1;
      if (e->slots[i].count <= 0) {
        e->slots[i].pid = 0;
        e->slots[i].count = 0;
      }
      break;
    }
  }
  if (e->pins > 0) e->pins -= 1;
  if (e->pins == 0 && e->state == kPendingDelete) release_entry(s, e);
  unlock(s);
  return RTS_OK;
}

int rts_delete(rts_store* s, const uint8_t* id) {
  lock(s);
  Entry* e = find_entry(s, id);
  if (!e) {
    unlock(s);
    return RTS_NOT_FOUND;
  }
  drop_dead_pins(e);
  if (e->pins > 0) {
    e->state = kPendingDelete;
    unlock(s);
    return RTS_OK;
  }
  release_entry(s, e);
  unlock(s);
  return RTS_OK;
}

int rts_evict(rts_store* s, uint64_t need, uint8_t* out_ids, int max_n) {
  lock(s);
  Header* h = s->hdr();
  Entry* t = s->table();
  std::vector<Entry*> candidates;
  for (uint32_t i = 0; i < h->table_cap; ++i) {
    Entry* e = &t[i];
    if (e->state != kSealed) continue;
    drop_dead_pins(e);
    if (e->pins == 0) candidates.push_back(e);
  }
  std::sort(candidates.begin(), candidates.end(),
            [](Entry* a, Entry* b) { return a->lru < b->lru; });
  uint64_t freed = 0;
  int n = 0;
  for (Entry* e : candidates) {
    if (freed >= need || n >= max_n) break;
    freed += e->size + kBlockOverhead;
    memcpy(out_ids + n * RTS_ID_SIZE, e->id, RTS_ID_SIZE);
    release_entry(s, e);
    ++n;
  }
  unlock(s);
  return n;
}

void rts_purge_dead_pins(rts_store* s) {
  lock(s);
  Header* h = s->hdr();
  Entry* t = s->table();
  for (uint32_t i = 0; i < h->table_cap; ++i) {
    Entry* e = &t[i];
    if (e->state == kCreated || e->state == kSealed ||
        e->state == kPendingDelete) {
      drop_dead_pins(e);
      if (e->pins == 0 && e->state == kPendingDelete) release_entry(s, e);
    }
  }
  unlock(s);
}

uint64_t rts_used(rts_store* s) {
  lock(s);
  uint64_t u = s->hdr()->used;
  unlock(s);
  return u;
}

uint64_t rts_capacity(rts_store* s) { return s->hdr()->capacity; }

uint32_t rts_count(rts_store* s) {
  lock(s);
  uint32_t c = s->hdr()->count;
  unlock(s);
  return c;
}

uint8_t* rts_base(rts_store* s) { return s->map + s->hdr()->data_off; }

}  // extern "C"
