// Native frame pump for the direct actor-call plane (ISSUE 8).
//
// Three pieces, mirrored by the pure-Python fallback in
// ray_tpu/core/frame_pump.py (byte-identical codec, same semantics):
//
//   rtp_chan  — a framed-channel read/write pump that OWNS a dup of the
//               socket fd: buffered reads slice many `u32-LE length |
//               payload` frames out of one read(2); batch sends coalesce a
//               burst of queued small frames into as few writev(2) calls
//               as possible (two iovec entries per frame: header+payload,
//               zero concatenation copies). The CPython binding releases
//               the GIL around every syscall.
//   rtp_seqq  — the per-channel monotonic-sequence dispatch queue:
//               in-order admission, out-of-order parking, duplicate drop
//               (seq below expected = a frame the worker already executed,
//               replayed after a channel failover).
//   wire      — byte-layout primitives for the compact call-frame codec
//               (constants + append/read helpers shared by the CPython
//               module, the C++ unit tests, and — layout-wise — the
//               Python mirror). Native frames start with RTP_MAGIC, which
//               can never collide with a pickle payload (protocol 2+
//               pickles start with 0x80), so pickle and native frames
//               interleave safely on one channel.
//
// Threading contract (matches how the Python side drives it): ONE reader
// thread may sit in rtp_chan_next/rtp_chan_read_exact while any number of
// sender threads — serialized by the caller's send lock — use
// rtp_chan_sendv. rtp_chan_shutdown may be called from any thread to wake
// a blocked reader. The inflight counter is atomic (the caller-side
// DIRECT_MAX_UNANSWERED backpressure accounting).

#ifndef RTS_PUMP_H_
#define RTS_PUMP_H_

#include <stddef.h>
#include <stdint.h>
#include <string.h>
#include <sys/uio.h>

#ifdef __cplusplus
extern "C" {
#endif

// ---- wire constants --------------------------------------------------------

#define RTP_MAGIC 0xA7u      // first byte of every native frame payload
// Negotiated as "npv" in the direct hello/welcome. v2 adds the optional
// RTP_CALL_HAS_TRACE block on F_CALL (trace_id + span_id strings after
// the flags byte); both sides speak min(offered, supported), so a v2
// encoder facing a v1 peer emits v1 frames (no trace flag) and the
// layouts stay compatible.
#define RTP_CODEC_VER 2u

#define RTP_F_CALL 0x01u       // compact direct call frame
#define RTP_F_DONE 0x02u       // task_done reply
#define RTP_F_DONE_BATCH 0x03u // u32 count + concatenated DONE bodies
#define RTP_F_FENCE 0x04u      // u64 msg_id
#define RTP_F_FENCE_ACK 0x05u  // u64 msg_id

#define RTP_ARG_REF 0u    // RefArg(ObjectID)
#define RTP_ARG_VALUE 1u  // ValueArg(bytes)

#define RTP_CALL_HAS_ARGS 0x01u
#define RTP_CALL_HAS_NESTED 0x02u
// Codec v2: (trace_id, span_id) ride the call frame as two u8-length-
// prefixed utf-8 strings immediately after the flags byte. Emitted only
// on channels that negotiated npv >= 2 — a v1 decoder never sees the bit.
#define RTP_CALL_HAS_TRACE 0x04u
#define RTP_DONE_FAILED 0x01u

// ---- status codes ----------------------------------------------------------

enum {
  RTP_OK = 0,
  RTP_BIG = 1,     // frame larger than the buffer: drain via read_exact
  RTP_EOF = -1,    // orderly close / shutdown
  RTP_ERR = -2,    // I/O error (errno set) or protocol violation
  RTP_AGAIN = -3,  // SO_RCVTIMEO/SO_SNDTIMEO expired
};

// ---- framed channel --------------------------------------------------------

typedef struct rtp_chan rtp_chan;

// Dups `fd` (the Python socket keeps its own); bufcap 0 = default 256 KiB.
rtp_chan* rtp_chan_new(int fd, size_t bufcap);
void rtp_chan_free(rtp_chan* c);
// shutdown(2) on the shared socket description: wakes a blocked reader on
// every dup. Safe from any thread, idempotent.
void rtp_chan_shutdown(rtp_chan* c);
int rtp_chan_fd(const rtp_chan* c);

// Next frame. RTP_OK: *ptr (into the internal buffer, valid until the next
// next/read_exact call) and *len are set. RTP_BIG: only *len is set — the
// payload exceeds the internal buffer and MUST be drained with
// rtp_chan_read_exact(len) before the next frame. RTP_EOF/RTP_ERR/RTP_AGAIN
// as above.
int rtp_chan_next(rtp_chan* c, const uint8_t** ptr, uint32_t* len);
int rtp_chan_read_exact(rtp_chan* c, uint8_t* dst, uint32_t len);
// Bytes already buffered beyond the consumed frames (a cheap "is another
// frame likely immediately available" probe for reply-batching decisions).
size_t rtp_chan_buffered(const rtp_chan* c);
// Whether a COMPLETE frame (header + full payload) is already buffered —
// a recv is then guaranteed not to block. Oversized (RTP_BIG) frames
// never satisfy this.
int rtp_chan_has_frame(const rtp_chan* c);

// Send `n` payloads as framed messages, coalesced: headers are stack
// iovecs interleaved with the payload iovecs and the whole batch goes out
// in as few writev calls as IOV_MAX allows. Returns RTP_OK / RTP_ERR /
// RTP_EOF (EPIPE) / RTP_AGAIN.
int rtp_chan_sendv(rtp_chan* c, const struct iovec* payloads, int n);

// Stats counters: which = 0 frames_in, 1 frames_out, 2 bytes_in,
// 3 bytes_out, 4 read_syscalls, 5 write_syscalls.
int64_t rtp_chan_counter(const rtp_chan* c, int which);
// Caller-side unanswered-call accounting (DIRECT_MAX_UNANSWERED
// backpressure): atomic add, returns the new value. delta 0 reads.
int64_t rtp_chan_inflight_add(rtp_chan* c, int64_t delta);

// ---- pending/replay table (ISSUE 12) ---------------------------------------
//
// The caller-side unanswered-call bookkeeping of one direct channel,
// sharded off the GIL: task-id -> submit sequence number, with the
// DIRECT_MAX_UNANSWERED backpressure wait as a native condition
// variable (the submitter blocks GIL-released until the reader's pops
// bring the table below the cap) and a seq-ordered drain snapshot for
// the failover replay path. rtp_pend_apply_done applies a whole
// DONE/DONE_BATCH frame payload — every contained task id popped, the
// condvar signalled once — without entering Python at all; this is how
// the pump's reader updates the table without taking the GIL per frame.
//
// Thread contract: any number of submitter threads (serialized by the
// caller's channel lock) add/wait; ONE reader thread pops/applies;
// fail/drain may come from any thread. All ops lock the table's own
// mutex — never the GIL.

typedef struct rtp_pend rtp_pend;

// Pending-table stats counters for rtp_pend_counter(): adds, pops,
// native frame applies (DONE/DONE_BATCH parsed off-GIL), condvar
// wakeups delivered to capped submitters, and pops that found no entry
// (pickle-dialect replies already handled in Python, or replays).
enum {
  RTP_PEND_ADDS = 0,
  RTP_PEND_POPS = 1,
  RTP_PEND_APPLIES = 2,
  RTP_PEND_WAKEUPS = 3,
  RTP_PEND_MISSES = 4,
};

rtp_pend* rtp_pend_new(void);
void rtp_pend_free(rtp_pend* p);
// Insert (tid, seq). Returns the new size. Duplicate tids overwrite
// (cannot happen on a live channel: task ids are unique per submit).
size_t rtp_pend_add(rtp_pend* p, const uint8_t* tid, size_t tid_len,
                    uint64_t seq);
// Remove one entry; 1 + *seq set when found, 0 otherwise. Signals a
// capped submitter when the table drops below its wait cap.
int rtp_pend_pop(rtp_pend* p, const uint8_t* tid, size_t tid_len,
                 uint64_t* seq);
size_t rtp_pend_size(const rtp_pend* p);
// Block (caller must NOT hold the GIL) until size < cap, the table is
// failed, or timeout_ms elapses. Returns the size observed at wake.
size_t rtp_pend_wait_below(rtp_pend* p, size_t cap, int timeout_ms);
// Mark failed and wake every waiter: the channel died, submitters must
// re-check their channel state instead of sleeping out the timeout.
void rtp_pend_fail(rtp_pend* p);
int rtp_pend_failed(const rtp_pend* p);
// Failover drain: atomically snapshot + clear, entries surfaced in seq
// order through the iterator pair. Begin returns the snapshot length;
// each next fills (*tid,*tid_len,*seq) until it returns 0. Only one
// drain may be in progress (the failure path is single-threaded).
size_t rtp_pend_drain_begin(rtp_pend* p);
int rtp_pend_drain_next(rtp_pend* p, const uint8_t** tid, size_t* tid_len,
                        uint64_t* seq);
// Parse a native DONE/DONE_BATCH frame payload and pop every contained
// task id (GIL-free completion application). Returns the number of
// entries popped, or -1 on a malformed frame. Non-done native frames
// and pickle payloads return 0 untouched.
int rtp_pend_apply_done(rtp_pend* p, const uint8_t* payload, size_t len);
int64_t rtp_pend_counter(const rtp_pend* p, int which);

// ---- sequence dispatch queue ----------------------------------------------

typedef struct rtp_seqq rtp_seqq;

rtp_seqq* rtp_seqq_new(void);
// drop() is called on every still-parked/ready item (the binding DECREFs).
void rtp_seqq_free(rtp_seqq* q, void (*drop)(void* item));
// Push one frame. Returns the number of items now runnable in order
// (pop them with rtp_seqq_pop); 0 with *dup=1 for a duplicate (seq below
// expected); 0 with *dup=0 for an out-of-order frame that was parked.
int rtp_seqq_push(rtp_seqq* q, uint64_t seq, void* item, int* dup);
void* rtp_seqq_pop(rtp_seqq* q);
uint64_t rtp_seqq_expected(const rtp_seqq* q);
size_t rtp_seqq_parked(const rtp_seqq* q);

// ---- byte-layout primitives ------------------------------------------------
// Little-endian throughout; f64 is IEEE-754 bits moved through a u64.

typedef struct {
  uint8_t* p;
  size_t len;
  size_t cap;
} rtp_wbuf;

int rtp_wbuf_init(rtp_wbuf* b, size_t cap);
void rtp_wbuf_freebuf(rtp_wbuf* b);
int rtp_wbuf_put(rtp_wbuf* b, const void* src, size_t n);

static inline int rtp_put_u8(rtp_wbuf* b, uint8_t v) {
  return rtp_wbuf_put(b, &v, 1);
}
static inline int rtp_put_u16(rtp_wbuf* b, uint16_t v) {
  uint8_t t[2] = {(uint8_t)(v & 0xff), (uint8_t)(v >> 8)};
  return rtp_wbuf_put(b, t, 2);
}
static inline int rtp_put_u32(rtp_wbuf* b, uint32_t v) {
  uint8_t t[4];
  for (int i = 0; i < 4; ++i) t[i] = (uint8_t)(v >> (8 * i));
  return rtp_wbuf_put(b, t, 4);
}
static inline int rtp_put_u64(rtp_wbuf* b, uint64_t v) {
  uint8_t t[8];
  for (int i = 0; i < 8; ++i) t[i] = (uint8_t)(v >> (8 * i));
  return rtp_wbuf_put(b, t, 8);
}
static inline int rtp_put_f64(rtp_wbuf* b, double v) {
  uint64_t bits;
  memcpy(&bits, &v, 8);
  return rtp_put_u64(b, bits);
}

typedef struct {
  const uint8_t* p;
  size_t len;
  size_t pos;
} rtp_rbuf;

static inline int rtp_get(rtp_rbuf* r, void* dst, size_t n) {
  if (r->pos + n > r->len) return RTP_ERR;
  memcpy(dst, r->p + r->pos, n);
  r->pos += n;
  return RTP_OK;
}
static inline int rtp_get_u8(rtp_rbuf* r, uint8_t* v) {
  return rtp_get(r, v, 1);
}
static inline int rtp_get_u16(rtp_rbuf* r, uint16_t* v) {
  uint8_t t[2];
  if (rtp_get(r, t, 2) != RTP_OK) return RTP_ERR;
  *v = (uint16_t)(t[0] | (t[1] << 8));
  return RTP_OK;
}
static inline int rtp_get_u32(rtp_rbuf* r, uint32_t* v) {
  uint8_t t[4];
  if (rtp_get(r, t, 4) != RTP_OK) return RTP_ERR;
  *v = 0;
  for (int i = 3; i >= 0; --i) *v = (*v << 8) | t[i];
  return RTP_OK;
}
static inline int rtp_get_u64(rtp_rbuf* r, uint64_t* v) {
  uint8_t t[8];
  if (rtp_get(r, t, 8) != RTP_OK) return RTP_ERR;
  *v = 0;
  for (int i = 7; i >= 0; --i) *v = (*v << 8) | t[i];
  return RTP_OK;
}
static inline int rtp_get_f64(rtp_rbuf* r, double* v) {
  uint64_t bits;
  if (rtp_get_u64(r, &bits) != RTP_OK) return RTP_ERR;
  memcpy(v, &bits, 8);
  return RTP_OK;
}
// Borrow `n` bytes without copying (pointer into the frame).
static inline int rtp_get_ref(rtp_rbuf* r, const uint8_t** dst, size_t n) {
  if (r->pos + n > r->len) return RTP_ERR;
  *dst = r->p + r->pos;
  r->pos += n;
  return RTP_OK;
}

#ifdef __cplusplus
}  // extern "C"
#endif

#endif  // RTS_PUMP_H_
