// Framed-channel pump + sequence dispatch queue (see rts_pump.h).

#include "rts_pump.h"

#include <errno.h>
#include <limits.h>
#include <stdlib.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <map>
#include <new>
#include <vector>

#ifndef IOV_MAX
#define IOV_MAX 1024
#endif

namespace {
constexpr size_t kDefaultBufCap = 256 * 1024;
constexpr uint32_t kMaxFrame = 0x7fffffffu;  // protocol.py MAX_FRAME
}  // namespace

// ---- framed channel --------------------------------------------------------

struct rtp_chan {
  int fd;
  uint8_t* buf;
  size_t cap;
  size_t start;  // first unconsumed byte
  size_t end;    // one past last valid byte
  // RTP_BIG bookkeeping: bytes of the oversized payload not yet drained.
  uint32_t big_remaining;
  std::atomic<int64_t> counters[6];
  std::atomic<int64_t> inflight;
};

rtp_chan* rtp_chan_new(int fd, size_t bufcap) {
  int dupfd = dup(fd);
  if (dupfd < 0) return nullptr;
  rtp_chan* c = new (std::nothrow) rtp_chan();
  if (!c) {
    close(dupfd);
    return nullptr;
  }
  c->fd = dupfd;
  c->cap = bufcap ? bufcap : kDefaultBufCap;
  c->buf = (uint8_t*)malloc(c->cap);
  if (!c->buf) {
    close(dupfd);
    delete c;
    return nullptr;
  }
  c->start = c->end = 0;
  c->big_remaining = 0;
  for (auto& a : c->counters) a.store(0, std::memory_order_relaxed);
  c->inflight.store(0, std::memory_order_relaxed);
  return c;
}

void rtp_chan_free(rtp_chan* c) {
  if (!c) return;
  if (c->fd >= 0) close(c->fd);
  free(c->buf);
  delete c;
}

void rtp_chan_shutdown(rtp_chan* c) {
  if (c && c->fd >= 0) shutdown(c->fd, SHUT_RDWR);
}

int rtp_chan_fd(const rtp_chan* c) { return c->fd; }

size_t rtp_chan_buffered(const rtp_chan* c) { return c->end - c->start; }

int rtp_chan_has_frame(const rtp_chan* c) {
  if (c->big_remaining) return 0;
  size_t have = c->end - c->start;
  if (have < 4) return 0;
  const uint8_t* h = c->buf + c->start;
  uint32_t n = (uint32_t)h[0] | ((uint32_t)h[1] << 8) |
               ((uint32_t)h[2] << 16) | ((uint32_t)h[3] << 24);
  return (size_t)n + 4 <= have;
}

int64_t rtp_chan_counter(const rtp_chan* c, int which) {
  if (which < 0 || which > 5) return 0;
  return c->counters[which].load(std::memory_order_relaxed);
}

int64_t rtp_chan_inflight_add(rtp_chan* c, int64_t delta) {
  if (delta == 0) return c->inflight.load(std::memory_order_relaxed);
  return c->inflight.fetch_add(delta, std::memory_order_relaxed) + delta;
}

static int chan_errno_status() {
  if (errno == EAGAIN || errno == EWOULDBLOCK) return RTP_AGAIN;
  if (errno == EPIPE || errno == ECONNRESET || errno == EBADF) return RTP_EOF;
  return RTP_ERR;
}

// Refill: ensure at least `need` unconsumed bytes are buffered, compacting
// or growing nothing — `need` is always <= cap here (caller guarantees).
static int chan_fill(rtp_chan* c, size_t need) {
  while (c->end - c->start < need) {
    if (c->start > 0 && c->end + 1 > c->cap) {
      // Compact so the tail of the buffer is free for the read below.
      size_t n = c->end - c->start;
      memmove(c->buf, c->buf + c->start, n);
      c->start = 0;
      c->end = n;
    }
    size_t room = c->cap - c->end;
    if (room == 0) {
      // Caller asked for more than fits contiguously: compact first.
      size_t n = c->end - c->start;
      memmove(c->buf, c->buf + c->start, n);
      c->start = 0;
      c->end = n;
      room = c->cap - c->end;
      if (room == 0) return RTP_ERR;  // need > cap: caller bug
    }
    ssize_t got;
    do {
      got = read(c->fd, c->buf + c->end, room);
    } while (got < 0 && errno == EINTR);
    if (got == 0) return RTP_EOF;
    if (got < 0) return chan_errno_status();
    c->end += (size_t)got;
    c->counters[2].fetch_add(got, std::memory_order_relaxed);
    c->counters[4].fetch_add(1, std::memory_order_relaxed);
  }
  return RTP_OK;
}

int rtp_chan_next(rtp_chan* c, const uint8_t** ptr, uint32_t* len) {
  if (c->big_remaining) return RTP_ERR;  // previous RTP_BIG not drained
  int rc = chan_fill(c, 4);
  if (rc != RTP_OK) return rc;
  const uint8_t* h = c->buf + c->start;
  uint32_t n = (uint32_t)h[0] | ((uint32_t)h[1] << 8) |
               ((uint32_t)h[2] << 16) | ((uint32_t)h[3] << 24);
  if (n > kMaxFrame) return RTP_ERR;
  if ((size_t)n + 4 > c->cap) {
    // Oversized frame: hand back the length; the caller drains the
    // payload straight into its own (e.g. PyBytes) buffer.
    c->start += 4;
    c->big_remaining = n;
    *len = n;
    return RTP_BIG;
  }
  rc = chan_fill(c, (size_t)n + 4);
  if (rc != RTP_OK) return rc;
  *ptr = c->buf + c->start + 4;
  *len = n;
  c->start += (size_t)n + 4;
  if (c->start == c->end) c->start = c->end = 0;
  c->counters[0].fetch_add(1, std::memory_order_relaxed);
  return RTP_OK;
}

int rtp_chan_read_exact(rtp_chan* c, uint8_t* dst, uint32_t len) {
  // Serve from the buffer first (the header read may have pulled in part
  // of the payload), then read the remainder directly into dst.
  // big_remaining is decremented as bytes are consumed, so a failure
  // mid-payload leaves consistent accounting (the caller treats a
  // partial oversized read as a dead channel either way — the consumed
  // bytes are gone).
  uint32_t want = len;
  int big = c->big_remaining != 0;
  size_t have = c->end - c->start;
  if (have) {
    size_t take = have < want ? have : want;
    memcpy(dst, c->buf + c->start, take);
    c->start += take;
    if (c->start == c->end) c->start = c->end = 0;
    dst += take;
    want -= (uint32_t)take;
    if (big) c->big_remaining -= (uint32_t)take;
  }
  while (want) {
    ssize_t got;
    do {
      got = read(c->fd, dst, want);
    } while (got < 0 && errno == EINTR);
    if (got == 0) return RTP_EOF;
    if (got < 0) return chan_errno_status();
    dst += got;
    want -= (uint32_t)got;
    if (big) c->big_remaining -= (uint32_t)got;
    c->counters[2].fetch_add(got, std::memory_order_relaxed);
    c->counters[4].fetch_add(1, std::memory_order_relaxed);
  }
  c->counters[0].fetch_add(1, std::memory_order_relaxed);
  return RTP_OK;
}

static int writev_all(rtp_chan* c, struct iovec* iov, int cnt) {
  while (cnt > 0) {
    int batch = cnt < IOV_MAX ? cnt : IOV_MAX;
    ssize_t sent;
    do {
      sent = writev(c->fd, iov, batch);
    } while (sent < 0 && errno == EINTR);
    if (sent < 0) return chan_errno_status();
    c->counters[3].fetch_add(sent, std::memory_order_relaxed);
    c->counters[5].fetch_add(1, std::memory_order_relaxed);
    // Advance past fully-written iovecs; trim a partially-written one.
    while (cnt > 0 && (size_t)sent >= iov->iov_len) {
      sent -= iov->iov_len;
      ++iov;
      --cnt;
    }
    if (cnt > 0 && sent > 0) {
      iov->iov_base = (uint8_t*)iov->iov_base + sent;
      iov->iov_len -= (size_t)sent;
    }
  }
  return RTP_OK;
}

int rtp_chan_sendv(rtp_chan* c, const struct iovec* payloads, int n) {
  if (n <= 0) return RTP_OK;
  std::vector<uint8_t> headers((size_t)n * 4);
  std::vector<struct iovec> iov((size_t)n * 2);
  for (int i = 0; i < n; ++i) {
    size_t len = payloads[i].iov_len;
    if (len > kMaxFrame) return RTP_ERR;
    uint8_t* h = headers.data() + (size_t)i * 4;
    h[0] = (uint8_t)(len & 0xff);
    h[1] = (uint8_t)((len >> 8) & 0xff);
    h[2] = (uint8_t)((len >> 16) & 0xff);
    h[3] = (uint8_t)((len >> 24) & 0xff);
    iov[(size_t)i * 2] = {h, 4};
    iov[(size_t)i * 2 + 1] = payloads[i];
  }
  int rc = writev_all(c, iov.data(), n * 2);
  if (rc == RTP_OK)
    c->counters[1].fetch_add(n, std::memory_order_relaxed);
  return rc;
}

// ---- sequence dispatch queue ----------------------------------------------

struct rtp_seqq {
  uint64_t expected = 1;
  std::map<uint64_t, void*> parked;
  std::vector<void*> ready;
  size_t ready_pos = 0;
};

rtp_seqq* rtp_seqq_new(void) { return new (std::nothrow) rtp_seqq(); }

void rtp_seqq_free(rtp_seqq* q, void (*drop)(void*)) {
  if (!q) return;
  if (drop) {
    for (auto& kv : q->parked) drop(kv.second);
    for (size_t i = q->ready_pos; i < q->ready.size(); ++i) drop(q->ready[i]);
  }
  delete q;
}

int rtp_seqq_push(rtp_seqq* q, uint64_t seq, void* item, int* dup) {
  *dup = 0;
  if (seq < q->expected) {
    *dup = 1;  // already executed (failover replay duplicate): drop
    return 0;
  }
  if (seq != q->expected) {
    // Out-of-order arrival: buffer until the gap fills. A seq already
    // parked is a duplicate delivery — report it as such (inserting
    // would silently drop the prior item without its drop callback).
    if (!q->parked.emplace(seq, item).second) {
      *dup = 1;
      return 0;
    }
    return 0;
  }
  if (q->ready_pos == q->ready.size()) {
    q->ready.clear();
    q->ready_pos = 0;
  }
  size_t before = q->ready.size();
  q->ready.push_back(item);
  q->expected += 1;
  auto it = q->parked.begin();
  while (it != q->parked.end() && it->first == q->expected) {
    q->ready.push_back(it->second);
    q->expected += 1;
    it = q->parked.erase(it);
  }
  return (int)(q->ready.size() - before);
}

void* rtp_seqq_pop(rtp_seqq* q) {
  if (q->ready_pos >= q->ready.size()) return nullptr;
  return q->ready[q->ready_pos++];
}

uint64_t rtp_seqq_expected(const rtp_seqq* q) { return q->expected; }

size_t rtp_seqq_parked(const rtp_seqq* q) { return q->parked.size(); }

// ---- write buffer ----------------------------------------------------------

int rtp_wbuf_init(rtp_wbuf* b, size_t cap) {
  if (cap < 64) cap = 64;
  b->p = (uint8_t*)malloc(cap);
  if (!b->p) return RTP_ERR;
  b->len = 0;
  b->cap = cap;
  return RTP_OK;
}

void rtp_wbuf_freebuf(rtp_wbuf* b) {
  free(b->p);
  b->p = nullptr;
  b->len = b->cap = 0;
}

int rtp_wbuf_put(rtp_wbuf* b, const void* src, size_t n) {
  if (b->len + n > b->cap) {
    size_t cap = b->cap * 2;
    while (cap < b->len + n) cap *= 2;
    uint8_t* p = (uint8_t*)realloc(b->p, cap);
    if (!p) return RTP_ERR;
    b->p = p;
    b->cap = cap;
  }
  memcpy(b->p + b->len, src, n);
  b->len += n;
  return RTP_OK;
}
