// Framed-channel pump + sequence dispatch queue (see rts_pump.h).

#include "rts_pump.h"

#include <errno.h>
#include <limits.h>
#include <stdlib.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <map>
#include <mutex>
#include <new>
#include <string>
#include <unordered_map>
#include <vector>

#ifndef IOV_MAX
#define IOV_MAX 1024
#endif

namespace {
constexpr size_t kDefaultBufCap = 256 * 1024;
constexpr uint32_t kMaxFrame = 0x7fffffffu;  // protocol.py MAX_FRAME
}  // namespace

// ---- framed channel --------------------------------------------------------

struct rtp_chan {
  int fd;
  uint8_t* buf;
  size_t cap;
  size_t start;  // first unconsumed byte
  size_t end;    // one past last valid byte
  // RTP_BIG bookkeeping: bytes of the oversized payload not yet drained.
  uint32_t big_remaining;
  std::atomic<int64_t> counters[6];
  std::atomic<int64_t> inflight;
};

rtp_chan* rtp_chan_new(int fd, size_t bufcap) {
  int dupfd = dup(fd);
  if (dupfd < 0) return nullptr;
  rtp_chan* c = new (std::nothrow) rtp_chan();
  if (!c) {
    close(dupfd);
    return nullptr;
  }
  c->fd = dupfd;
  c->cap = bufcap ? bufcap : kDefaultBufCap;
  c->buf = (uint8_t*)malloc(c->cap);
  if (!c->buf) {
    close(dupfd);
    delete c;
    return nullptr;
  }
  c->start = c->end = 0;
  c->big_remaining = 0;
  for (auto& a : c->counters) a.store(0, std::memory_order_relaxed);
  c->inflight.store(0, std::memory_order_relaxed);
  return c;
}

void rtp_chan_free(rtp_chan* c) {
  if (!c) return;
  if (c->fd >= 0) close(c->fd);
  free(c->buf);
  delete c;
}

void rtp_chan_shutdown(rtp_chan* c) {
  if (c && c->fd >= 0) shutdown(c->fd, SHUT_RDWR);
}

int rtp_chan_fd(const rtp_chan* c) { return c->fd; }

size_t rtp_chan_buffered(const rtp_chan* c) { return c->end - c->start; }

int rtp_chan_has_frame(const rtp_chan* c) {
  if (c->big_remaining) return 0;
  size_t have = c->end - c->start;
  if (have < 4) return 0;
  const uint8_t* h = c->buf + c->start;
  uint32_t n = (uint32_t)h[0] | ((uint32_t)h[1] << 8) |
               ((uint32_t)h[2] << 16) | ((uint32_t)h[3] << 24);
  return (size_t)n + 4 <= have;
}

int64_t rtp_chan_counter(const rtp_chan* c, int which) {
  if (which < 0 || which > 5) return 0;
  return c->counters[which].load(std::memory_order_relaxed);
}

int64_t rtp_chan_inflight_add(rtp_chan* c, int64_t delta) {
  if (delta == 0) return c->inflight.load(std::memory_order_relaxed);
  return c->inflight.fetch_add(delta, std::memory_order_relaxed) + delta;
}

static int chan_errno_status() {
  if (errno == EAGAIN || errno == EWOULDBLOCK) return RTP_AGAIN;
  if (errno == EPIPE || errno == ECONNRESET || errno == EBADF) return RTP_EOF;
  return RTP_ERR;
}

// Refill: ensure at least `need` unconsumed bytes are buffered, compacting
// or growing nothing — `need` is always <= cap here (caller guarantees).
static int chan_fill(rtp_chan* c, size_t need) {
  while (c->end - c->start < need) {
    if (c->start > 0 && c->end + 1 > c->cap) {
      // Compact so the tail of the buffer is free for the read below.
      size_t n = c->end - c->start;
      memmove(c->buf, c->buf + c->start, n);
      c->start = 0;
      c->end = n;
    }
    size_t room = c->cap - c->end;
    if (room == 0) {
      // Caller asked for more than fits contiguously: compact first.
      size_t n = c->end - c->start;
      memmove(c->buf, c->buf + c->start, n);
      c->start = 0;
      c->end = n;
      room = c->cap - c->end;
      if (room == 0) return RTP_ERR;  // need > cap: caller bug
    }
    ssize_t got;
    do {
      got = read(c->fd, c->buf + c->end, room);
    } while (got < 0 && errno == EINTR);
    if (got == 0) return RTP_EOF;
    if (got < 0) return chan_errno_status();
    c->end += (size_t)got;
    c->counters[2].fetch_add(got, std::memory_order_relaxed);
    c->counters[4].fetch_add(1, std::memory_order_relaxed);
  }
  return RTP_OK;
}

int rtp_chan_next(rtp_chan* c, const uint8_t** ptr, uint32_t* len) {
  if (c->big_remaining) return RTP_ERR;  // previous RTP_BIG not drained
  int rc = chan_fill(c, 4);
  if (rc != RTP_OK) return rc;
  const uint8_t* h = c->buf + c->start;
  uint32_t n = (uint32_t)h[0] | ((uint32_t)h[1] << 8) |
               ((uint32_t)h[2] << 16) | ((uint32_t)h[3] << 24);
  if (n > kMaxFrame) return RTP_ERR;
  if ((size_t)n + 4 > c->cap) {
    // Oversized frame: hand back the length; the caller drains the
    // payload straight into its own (e.g. PyBytes) buffer.
    c->start += 4;
    c->big_remaining = n;
    *len = n;
    return RTP_BIG;
  }
  rc = chan_fill(c, (size_t)n + 4);
  if (rc != RTP_OK) return rc;
  *ptr = c->buf + c->start + 4;
  *len = n;
  c->start += (size_t)n + 4;
  if (c->start == c->end) c->start = c->end = 0;
  c->counters[0].fetch_add(1, std::memory_order_relaxed);
  return RTP_OK;
}

int rtp_chan_read_exact(rtp_chan* c, uint8_t* dst, uint32_t len) {
  // Serve from the buffer first (the header read may have pulled in part
  // of the payload), then read the remainder directly into dst.
  // big_remaining is decremented as bytes are consumed, so a failure
  // mid-payload leaves consistent accounting (the caller treats a
  // partial oversized read as a dead channel either way — the consumed
  // bytes are gone).
  uint32_t want = len;
  int big = c->big_remaining != 0;
  size_t have = c->end - c->start;
  if (have) {
    size_t take = have < want ? have : want;
    memcpy(dst, c->buf + c->start, take);
    c->start += take;
    if (c->start == c->end) c->start = c->end = 0;
    dst += take;
    want -= (uint32_t)take;
    if (big) c->big_remaining -= (uint32_t)take;
  }
  while (want) {
    ssize_t got;
    do {
      got = read(c->fd, dst, want);
    } while (got < 0 && errno == EINTR);
    if (got == 0) return RTP_EOF;
    if (got < 0) return chan_errno_status();
    dst += got;
    want -= (uint32_t)got;
    if (big) c->big_remaining -= (uint32_t)got;
    c->counters[2].fetch_add(got, std::memory_order_relaxed);
    c->counters[4].fetch_add(1, std::memory_order_relaxed);
  }
  c->counters[0].fetch_add(1, std::memory_order_relaxed);
  return RTP_OK;
}

static int writev_all(rtp_chan* c, struct iovec* iov, int cnt) {
  while (cnt > 0) {
    int batch = cnt < IOV_MAX ? cnt : IOV_MAX;
    ssize_t sent;
    do {
      sent = writev(c->fd, iov, batch);
    } while (sent < 0 && errno == EINTR);
    if (sent < 0) return chan_errno_status();
    c->counters[3].fetch_add(sent, std::memory_order_relaxed);
    c->counters[5].fetch_add(1, std::memory_order_relaxed);
    // Advance past fully-written iovecs; trim a partially-written one.
    while (cnt > 0 && (size_t)sent >= iov->iov_len) {
      sent -= iov->iov_len;
      ++iov;
      --cnt;
    }
    if (cnt > 0 && sent > 0) {
      iov->iov_base = (uint8_t*)iov->iov_base + sent;
      iov->iov_len -= (size_t)sent;
    }
  }
  return RTP_OK;
}

int rtp_chan_sendv(rtp_chan* c, const struct iovec* payloads, int n) {
  if (n <= 0) return RTP_OK;
  std::vector<uint8_t> headers((size_t)n * 4);
  std::vector<struct iovec> iov((size_t)n * 2);
  for (int i = 0; i < n; ++i) {
    size_t len = payloads[i].iov_len;
    if (len > kMaxFrame) return RTP_ERR;
    uint8_t* h = headers.data() + (size_t)i * 4;
    h[0] = (uint8_t)(len & 0xff);
    h[1] = (uint8_t)((len >> 8) & 0xff);
    h[2] = (uint8_t)((len >> 16) & 0xff);
    h[3] = (uint8_t)((len >> 24) & 0xff);
    iov[(size_t)i * 2] = {h, 4};
    iov[(size_t)i * 2 + 1] = payloads[i];
  }
  int rc = writev_all(c, iov.data(), n * 2);
  if (rc == RTP_OK)
    c->counters[1].fetch_add(n, std::memory_order_relaxed);
  return rc;
}

// ---- pending/replay table --------------------------------------------------

struct rtp_pend {
  std::mutex mu;
  std::condition_variable not_full;
  // tid -> seq for O(1) completion pops; seq -> tid for the seq-ordered
  // failover drain. Seqs are unique per channel (monotonic submit
  // counter), so the two maps stay in lockstep.
  std::unordered_map<std::string, uint64_t> by_tid;
  std::map<uint64_t, std::string> by_seq;
  // Drain snapshot (seq order) handed out through the iterator pair.
  std::vector<std::pair<uint64_t, std::string>> drain;
  size_t drain_pos = 0;
  bool failed = false;
  // The smallest cap any submitter is currently waiting under; pops
  // only notify when they cross it (uncontended pops skip the syscall).
  size_t wait_cap = 0;
  std::atomic<int64_t> counters[5];
  rtp_pend() {
    for (auto& a : counters) a.store(0, std::memory_order_relaxed);
  }
};

rtp_pend* rtp_pend_new(void) { return new (std::nothrow) rtp_pend(); }

void rtp_pend_free(rtp_pend* p) { delete p; }

size_t rtp_pend_add(rtp_pend* p, const uint8_t* tid, size_t tid_len,
                    uint64_t seq) {
  std::lock_guard<std::mutex> g(p->mu);
  std::string key((const char*)tid, tid_len);
  auto it = p->by_tid.find(key);
  if (it != p->by_tid.end()) p->by_seq.erase(it->second);
  p->by_tid[key] = seq;
  p->by_seq[seq] = std::move(key);
  p->counters[RTP_PEND_ADDS].fetch_add(1, std::memory_order_relaxed);
  return p->by_tid.size();
}

static void pend_pop_locked(rtp_pend* p,
                            std::unordered_map<std::string,
                                               uint64_t>::iterator it) {
  p->by_seq.erase(it->second);
  p->by_tid.erase(it);
  p->counters[RTP_PEND_POPS].fetch_add(1, std::memory_order_relaxed);
  if (p->wait_cap && p->by_tid.size() < p->wait_cap) {
    p->counters[RTP_PEND_WAKEUPS].fetch_add(1, std::memory_order_relaxed);
    p->not_full.notify_all();
  }
}

int rtp_pend_pop(rtp_pend* p, const uint8_t* tid, size_t tid_len,
                 uint64_t* seq) {
  std::lock_guard<std::mutex> g(p->mu);
  auto it = p->by_tid.find(std::string((const char*)tid, tid_len));
  if (it == p->by_tid.end()) {
    p->counters[RTP_PEND_MISSES].fetch_add(1, std::memory_order_relaxed);
    return 0;
  }
  if (seq) *seq = it->second;
  pend_pop_locked(p, it);
  return 1;
}

size_t rtp_pend_size(const rtp_pend* p) {
  std::lock_guard<std::mutex> g(const_cast<rtp_pend*>(p)->mu);
  return p->by_tid.size();
}

size_t rtp_pend_wait_below(rtp_pend* p, size_t cap, int timeout_ms) {
  std::unique_lock<std::mutex> g(p->mu);
  if (p->by_tid.size() < cap || p->failed) return p->by_tid.size();
  if (p->wait_cap == 0 || cap < p->wait_cap) p->wait_cap = cap;
  // wait_until on system_clock (NOT wait_for): libstdc++ lowers
  // wait_for to pthread_cond_clockwait, which the TSAN runtime used by
  // `make native-tsan` does not intercept — its lock bookkeeping then
  // reports phantom double-locks. timedwait is intercepted everywhere.
  auto deadline = std::chrono::system_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  p->not_full.wait_until(g, deadline,
                         [&] { return p->by_tid.size() < cap || p->failed; });
  if (p->wait_cap == cap) p->wait_cap = 0;
  return p->by_tid.size();
}

void rtp_pend_fail(rtp_pend* p) {
  std::lock_guard<std::mutex> g(p->mu);
  p->failed = true;
  p->not_full.notify_all();
}

int rtp_pend_failed(const rtp_pend* p) {
  std::lock_guard<std::mutex> g(const_cast<rtp_pend*>(p)->mu);
  return p->failed ? 1 : 0;
}

size_t rtp_pend_drain_begin(rtp_pend* p) {
  std::lock_guard<std::mutex> g(p->mu);
  p->drain.clear();
  p->drain_pos = 0;
  p->drain.reserve(p->by_seq.size());
  for (auto& kv : p->by_seq) p->drain.emplace_back(kv.first, kv.second);
  p->by_seq.clear();
  p->by_tid.clear();
  // A capped submitter must wake: the table just emptied (it re-checks
  // the channel's failed flag before trusting the headroom).
  p->not_full.notify_all();
  return p->drain.size();
}

int rtp_pend_drain_next(rtp_pend* p, const uint8_t** tid, size_t* tid_len,
                        uint64_t* seq) {
  std::lock_guard<std::mutex> g(p->mu);
  if (p->drain_pos >= p->drain.size()) return 0;
  auto& e = p->drain[p->drain_pos++];
  *seq = e.first;
  *tid = (const uint8_t*)e.second.data();
  *tid_len = e.second.size();
  return 1;
}

// Walk one DONE body (without the magic/type prefix), popping its task
// id. Returns RTP_OK and advances *r, or RTP_ERR on malformed bytes.
static int pend_apply_body(rtp_pend* p, rtp_rbuf* r) {
  uint8_t idlen, flags;
  const uint8_t* idp;
  double duration;
  uint32_t nr;
  if (rtp_get_u8(r, &idlen) != RTP_OK ||
      rtp_get_ref(r, &idp, idlen) != RTP_OK ||
      rtp_get_u8(r, &flags) != RTP_OK ||
      rtp_get_f64(r, &duration) != RTP_OK ||
      rtp_get_u32(r, &nr) != RTP_OK)
    return RTP_ERR;
  (void)flags;
  (void)duration;
  for (uint32_t i = 0; i < nr; ++i) {
    uint8_t olen;
    uint32_t dlen;
    const uint8_t* skip;
    if (rtp_get_u8(r, &olen) != RTP_OK ||
        rtp_get_ref(r, &skip, olen) != RTP_OK ||
        rtp_get_u32(r, &dlen) != RTP_OK ||
        rtp_get_ref(r, &skip, dlen) != RTP_OK)
      return RTP_ERR;
  }
  uint64_t seq;
  rtp_pend_pop(p, idp, idlen, &seq);
  return RTP_OK;
}

int rtp_pend_apply_done(rtp_pend* p, const uint8_t* payload, size_t len) {
  rtp_rbuf r = {payload, len, 0};
  uint8_t magic, ftype;
  if (rtp_get_u8(&r, &magic) != RTP_OK || magic != RTP_MAGIC) return 0;
  if (rtp_get_u8(&r, &ftype) != RTP_OK) return 0;
  int applied = 0;
  if (ftype == RTP_F_DONE) {
    if (pend_apply_body(p, &r) != RTP_OK) return -1;
    applied = 1;
  } else if (ftype == RTP_F_DONE_BATCH) {
    uint32_t n;
    if (rtp_get_u32(&r, &n) != RTP_OK) return -1;
    for (uint32_t i = 0; i < n; ++i) {
      if (pend_apply_body(p, &r) != RTP_OK) return -1;
      ++applied;
    }
  } else {
    return 0;  // call/fence/ack frames: not completion traffic
  }
  p->counters[RTP_PEND_APPLIES].fetch_add(1, std::memory_order_relaxed);
  return applied;
}

int64_t rtp_pend_counter(const rtp_pend* p, int which) {
  if (which < 0 || which > 4) return 0;
  return p->counters[which].load(std::memory_order_relaxed);
}

// ---- sequence dispatch queue ----------------------------------------------

struct rtp_seqq {
  uint64_t expected = 1;
  std::map<uint64_t, void*> parked;
  std::vector<void*> ready;
  size_t ready_pos = 0;
};

rtp_seqq* rtp_seqq_new(void) { return new (std::nothrow) rtp_seqq(); }

void rtp_seqq_free(rtp_seqq* q, void (*drop)(void*)) {
  if (!q) return;
  if (drop) {
    for (auto& kv : q->parked) drop(kv.second);
    for (size_t i = q->ready_pos; i < q->ready.size(); ++i) drop(q->ready[i]);
  }
  delete q;
}

int rtp_seqq_push(rtp_seqq* q, uint64_t seq, void* item, int* dup) {
  *dup = 0;
  if (seq < q->expected) {
    *dup = 1;  // already executed (failover replay duplicate): drop
    return 0;
  }
  if (seq != q->expected) {
    // Out-of-order arrival: buffer until the gap fills. A seq already
    // parked is a duplicate delivery — report it as such (inserting
    // would silently drop the prior item without its drop callback).
    if (!q->parked.emplace(seq, item).second) {
      *dup = 1;
      return 0;
    }
    return 0;
  }
  if (q->ready_pos == q->ready.size()) {
    q->ready.clear();
    q->ready_pos = 0;
  }
  size_t before = q->ready.size();
  q->ready.push_back(item);
  q->expected += 1;
  auto it = q->parked.begin();
  while (it != q->parked.end() && it->first == q->expected) {
    q->ready.push_back(it->second);
    q->expected += 1;
    it = q->parked.erase(it);
  }
  return (int)(q->ready.size() - before);
}

void* rtp_seqq_pop(rtp_seqq* q) {
  if (q->ready_pos >= q->ready.size()) return nullptr;
  return q->ready[q->ready_pos++];
}

uint64_t rtp_seqq_expected(const rtp_seqq* q) { return q->expected; }

size_t rtp_seqq_parked(const rtp_seqq* q) { return q->parked.size(); }

// ---- write buffer ----------------------------------------------------------

int rtp_wbuf_init(rtp_wbuf* b, size_t cap) {
  if (cap < 64) cap = 64;
  b->p = (uint8_t*)malloc(cap);
  if (!b->p) return RTP_ERR;
  b->len = 0;
  b->cap = cap;
  return RTP_OK;
}

void rtp_wbuf_freebuf(rtp_wbuf* b) {
  free(b->p);
  b->p = nullptr;
  b->len = b->cap = 0;
}

int rtp_wbuf_put(rtp_wbuf* b, const void* src, size_t n) {
  if (b->len + n > b->cap) {
    size_t cap = b->cap * 2;
    while (cap < b->len + n) cap *= 2;
    uint8_t* p = (uint8_t*)realloc(b->p, cap);
    if (!p) return RTP_ERR;
    b->p = p;
    b->cap = cap;
  }
  memcpy(b->p + b->len, src, n);
  b->len += n;
  return RTP_OK;
}
