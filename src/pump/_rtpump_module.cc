// CPython binding for the native frame pump (rts_pump.h).
//
// Exposes:
//   Chan     — framed-channel pump over a dup of a socket fd: buffered
//              GIL-released reads (one read(2) yields many frames), batch
//              sends coalesced into writev(2), plus the caller-side
//              unanswered-call accounting (atomic inflight counter).
//   SeqQueue — the per-channel monotonic-seq dispatch queue (in-order
//              admission, out-of-order parking, duplicate drop) holding
//              Python frame objects.
//   codec    — encode_call / encode_done / encode_done_batch /
//              encode_fence / encode_fence_ack / decode for the direct
//              plane's hot frame dialect. decode() rebuilds the SAME dict
//              shapes pickle produced, so the channel readers cannot tell
//              the dialects apart; unsupported shapes make the encoders
//              return None and the caller falls back to pickle for that
//              frame. Python-side classes (RefArg, ValueArg, ObjectID,
//              TaskID, InlineLocation) are injected once via
//              register_types() — this module never imports pickle.
//
//   PendingTable — the caller-side pending/replay table of one direct
//              channel off the GIL (ISSUE 12): task-id -> seq map with
//              native condvar backpressure (wait_below releases the
//              GIL), seq-ordered failover drain, and GIL-free
//              completion application from DONE/DONE_BATCH payloads.
//   WaiterTable — the runtime's oid -> waiter-entry directory without a
//              Python lock round per call: every operation is one C
//              call (GIL-atomic), with the FIFO resolved-entry eviction
//              of the old OrderedDict path preserved.
//   Chan.recv_burst / recv_many — drain an arrived-together burst of
//              frames in ONE Python entry: the first read blocks with
//              the GIL released, buffered complete frames are sliced
//              out without re-entering Python between them, and
//              recv_burst applies native completions to a PendingTable
//              before the GIL is retaken.
//
// pybind11 is not available in this environment; plain CPython C API.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <string.h>

#include <deque>
#include <new>
#include <string>
#include <unordered_map>
#include <vector>

#include "rts_pump.h"

namespace {

// ---- registered Python types + interned keys -------------------------------

PyObject* g_refarg = nullptr;
PyObject* g_valuearg = nullptr;
PyObject* g_objectid = nullptr;
PyObject* g_taskid = nullptr;
PyObject* g_inlineloc = nullptr;

PyObject* s_type;
PyObject* s_t;
PyObject* s_i;
PyObject* s_q;
PyObject* s_a;
PyObject* s_n;
PyObject* s_d;
PyObject* s_tc;
PyObject* s_task_id;
PyObject* s_results;
PyObject* s_failed;
PyObject* s_duration_s;
PyObject* s_items;
PyObject* s_msg_id;
PyObject* s_duplicate;
PyObject* s_object_id;
PyObject* s_data;
PyObject* s_bytes_attr;  // "_bytes" (BaseID slot)
PyObject* v_execute;
PyObject* v_task_done;
PyObject* v_task_done_batch;
PyObject* v_fence;
PyObject* v_fence_ack;

PyObject* py_types_registered_err() {
  PyErr_SetString(PyExc_RuntimeError,
                  "_rtpump.register_types() has not been called");
  return nullptr;
}

// ---- Chan ------------------------------------------------------------------

struct ChanObject {
  PyObject_HEAD
  rtp_chan* chan;
};

extern PyTypeObject ChanType;
extern PyTypeObject SeqQueueType;

void Chan_dealloc(ChanObject* self) {
  if (self->chan) {
    rtp_chan_free(self->chan);
    self->chan = nullptr;
  }
  Py_TYPE(self)->tp_free((PyObject*)self);
}

int chan_check(ChanObject* self) {
  if (!self->chan) {
    PyErr_SetString(PyExc_ValueError, "pump channel is closed");
    return -1;
  }
  return 0;
}

PyObject* chan_raise(int rc) {
  switch (rc) {
    case RTP_EOF:
      PyErr_SetString(PyExc_ConnectionError, "pump channel closed");
      break;
    case RTP_AGAIN:
      PyErr_SetString(PyExc_TimeoutError, "pump channel timed out");
      break;
    default:
      if (errno)
        PyErr_SetFromErrno(PyExc_OSError);
      else
        PyErr_SetString(PyExc_OSError, "pump channel I/O error");
  }
  return nullptr;
}

PyObject* Chan_recv(ChanObject* self, PyObject*) {
  if (chan_check(self) != 0) return nullptr;
  const uint8_t* ptr = nullptr;
  uint32_t len = 0;
  int rc;
  Py_BEGIN_ALLOW_THREADS
  rc = rtp_chan_next(self->chan, &ptr, &len);
  Py_END_ALLOW_THREADS
  if (rc == RTP_OK)
    return PyBytes_FromStringAndSize((const char*)ptr, (Py_ssize_t)len);
  if (rc == RTP_BIG) {
    PyObject* out = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)len);
    if (!out) return nullptr;
    uint8_t* dst = (uint8_t*)PyBytes_AS_STRING(out);
    Py_BEGIN_ALLOW_THREADS
    rc = rtp_chan_read_exact(self->chan, dst, len);
    Py_END_ALLOW_THREADS
    if (rc != RTP_OK) {
      // A failure (even a timeout) mid-oversized-payload loses stream
      // framing — the consumed bytes are gone. Surface it as a dead
      // channel, never a resumable timeout.
      Py_DECREF(out);
      PyErr_SetString(PyExc_ConnectionError,
                      "pump channel broken mid-frame");
      return nullptr;
    }
    return out;
  }
  return chan_raise(rc);
}

PyObject* Chan_send(ChanObject* self, PyObject* arg) {
  if (chan_check(self) != 0) return nullptr;
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) != 0) return nullptr;
  struct iovec iov = {view.buf, (size_t)view.len};
  int rc;
  Py_BEGIN_ALLOW_THREADS
  rc = rtp_chan_sendv(self->chan, &iov, 1);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&view);
  if (rc != RTP_OK) return chan_raise(rc);
  Py_RETURN_NONE;
}

PyObject* Chan_send_many(ChanObject* self, PyObject* arg) {
  if (chan_check(self) != 0) return nullptr;
  PyObject* fast = PySequence_Fast(arg, "send_many expects a sequence");
  if (!fast) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  if (n == 0) {
    Py_DECREF(fast);
    Py_RETURN_NONE;
  }
  Py_buffer* views = (Py_buffer*)PyMem_Malloc(sizeof(Py_buffer) * (size_t)n);
  struct iovec* iov =
      (struct iovec*)PyMem_Malloc(sizeof(struct iovec) * (size_t)n);
  if (!views || !iov) {
    PyMem_Free(views);
    PyMem_Free(iov);
    Py_DECREF(fast);
    return PyErr_NoMemory();
  }
  Py_ssize_t got = 0;
  for (; got < n; ++got) {
    PyObject* item = PySequence_Fast_GET_ITEM(fast, got);
    if (PyObject_GetBuffer(item, &views[got], PyBUF_SIMPLE) != 0) break;
    iov[got].iov_base = views[got].buf;
    iov[got].iov_len = (size_t)views[got].len;
  }
  int rc = RTP_OK;
  if (got == n) {
    Py_BEGIN_ALLOW_THREADS
    rc = rtp_chan_sendv(self->chan, iov, (int)n);
    Py_END_ALLOW_THREADS
  }
  for (Py_ssize_t i = 0; i < got; ++i) PyBuffer_Release(&views[i]);
  PyMem_Free(views);
  PyMem_Free(iov);
  bool buf_err = got != n;
  Py_DECREF(fast);
  if (buf_err) return nullptr;
  if (rc != RTP_OK) return chan_raise(rc);
  Py_RETURN_NONE;
}

PyObject* Chan_shutdown(ChanObject* self, PyObject*) {
  if (self->chan) rtp_chan_shutdown(self->chan);
  Py_RETURN_NONE;
}

PyObject* Chan_buffered(ChanObject* self, PyObject*) {
  if (chan_check(self) != 0) return nullptr;
  return PyLong_FromSize_t(rtp_chan_buffered(self->chan));
}

PyObject* Chan_has_frame(ChanObject* self, PyObject*) {
  if (chan_check(self) != 0) return nullptr;
  return PyBool_FromLong(rtp_chan_has_frame(self->chan));
}

PyObject* Chan_fileno(ChanObject* self, PyObject*) {
  if (chan_check(self) != 0) return nullptr;
  return PyLong_FromLong(rtp_chan_fd(self->chan));
}

PyObject* Chan_inflight_add(ChanObject* self, PyObject* arg) {
  if (chan_check(self) != 0) return nullptr;
  long long d = PyLong_AsLongLong(arg);
  if (d == -1 && PyErr_Occurred()) return nullptr;
  return PyLong_FromLongLong(rtp_chan_inflight_add(self->chan, d));
}

PyObject* Chan_stats(ChanObject* self, PyObject*) {
  if (chan_check(self) != 0) return nullptr;
  static const char* names[6] = {"frames_in",     "frames_out",
                                 "bytes_in",      "bytes_out",
                                 "read_syscalls", "write_syscalls"};
  PyObject* d = PyDict_New();
  if (!d) return nullptr;
  for (int i = 0; i < 6; ++i) {
    PyObject* v = PyLong_FromLongLong(rtp_chan_counter(self->chan, i));
    if (!v || PyDict_SetItemString(d, names[i], v) != 0) {
      Py_XDECREF(v);
      Py_DECREF(d);
      return nullptr;
    }
    Py_DECREF(v);
  }
  return d;
}

// Implemented after the codec section (they reuse decode_done_body and
// the PendingTable type defined below).
PyObject* Chan_recv_burst(ChanObject* self, PyObject* args);
PyObject* Chan_recv_many(ChanObject* self, PyObject* args);

PyMethodDef Chan_methods[] = {
    {"recv_burst", (PyCFunction)Chan_recv_burst, METH_VARARGS,
     "recv_burst(pending=None, max_frames=1024) -> (dones, others): "
     "blocking first read then every buffered complete frame, ONE "
     "Python entry for the burst. Native DONE/DONE_BATCH payloads are "
     "applied to the pending table and decoded into the dones list "
     "(flattened); every other payload returns raw in others."},
    {"recv_many", (PyCFunction)Chan_recv_many, METH_VARARGS,
     "recv_many(max_frames=1024) -> [payload, ...]: blocking first "
     "read then every buffered complete frame, one Python entry"},
    {"recv", (PyCFunction)Chan_recv, METH_NOARGS,
     "recv() -> bytes payload of the next frame (GIL released; raises "
     "ConnectionError on close, TimeoutError on SO_RCVTIMEO expiry)"},
    {"send", (PyCFunction)Chan_send, METH_O,
     "send(payload) -> frame the payload and write it (writev, no copy)"},
    {"send_many", (PyCFunction)Chan_send_many, METH_O,
     "send_many([payloads]) -> coalesced writev of the whole burst"},
    {"shutdown", (PyCFunction)Chan_shutdown, METH_NOARGS,
     "shutdown() -> shutdown(2) the socket (wakes a blocked reader)"},
    {"buffered", (PyCFunction)Chan_buffered, METH_NOARGS,
     "buffered() -> bytes already read past the consumed frames"},
    {"has_frame", (PyCFunction)Chan_has_frame, METH_NOARGS,
     "has_frame() -> a COMPLETE frame is buffered (recv cannot block)"},
    {"fileno", (PyCFunction)Chan_fileno, METH_NOARGS, ""},
    {"inflight_add", (PyCFunction)Chan_inflight_add, METH_O,
     "inflight_add(delta) -> new value of the atomic unanswered-call "
     "counter (delta 0 reads)"},
    {"stats", (PyCFunction)Chan_stats, METH_NOARGS,
     "stats() -> {frames_in, frames_out, bytes_in, bytes_out, "
     "read_syscalls, write_syscalls}"},
    {nullptr, nullptr, 0, nullptr},
};

PyTypeObject ChanType = {PyVarObject_HEAD_INIT(nullptr, 0)};

PyObject* mod_chan(PyObject*, PyObject* args) {
  int fd;
  unsigned long long bufcap = 0;
  if (!PyArg_ParseTuple(args, "i|K", &fd, &bufcap)) return nullptr;
  rtp_chan* c = rtp_chan_new(fd, (size_t)bufcap);
  if (!c) {
    PyErr_SetFromErrno(PyExc_OSError);
    return nullptr;
  }
  ChanObject* self = PyObject_New(ChanObject, &ChanType);
  if (!self) {
    rtp_chan_free(c);
    return nullptr;
  }
  self->chan = c;
  return (PyObject*)self;
}

// ---- SeqQueue --------------------------------------------------------------

struct SeqQueueObject {
  PyObject_HEAD
  rtp_seqq* q;
};

void seqq_drop_pyobj(void* item) { Py_DECREF((PyObject*)item); }

void SeqQueue_dealloc(SeqQueueObject* self) {
  if (self->q) {
    rtp_seqq_free(self->q, seqq_drop_pyobj);
    self->q = nullptr;
  }
  Py_TYPE(self)->tp_free((PyObject*)self);
}

PyObject* SeqQueue_push(SeqQueueObject* self, PyObject* args) {
  unsigned long long seq;
  PyObject* item;
  if (!PyArg_ParseTuple(args, "KO", &seq, &item)) return nullptr;
  int dup = 0;
  Py_INCREF(item);  // the queue owns one ref while parked/ready
  int n = rtp_seqq_push(self->q, seq, item, &dup);
  if (dup) Py_DECREF(item);  // dropped: already executed
  PyObject* out = PyList_New(n);
  if (!out) return nullptr;
  for (int i = 0; i < n; ++i) {
    PyObject* o = (PyObject*)rtp_seqq_pop(self->q);
    PyList_SET_ITEM(out, i, o);  // steals the queue's ref
  }
  return out;
}

PyObject* SeqQueue_expected(SeqQueueObject* self, void*) {
  return PyLong_FromUnsignedLongLong(rtp_seqq_expected(self->q));
}

PyObject* SeqQueue_parked(SeqQueueObject* self, void*) {
  return PyLong_FromSize_t(rtp_seqq_parked(self->q));
}

PyMethodDef SeqQueue_methods[] = {
    {"push", (PyCFunction)SeqQueue_push, METH_VARARGS,
     "push(seq, frame) -> [frames now runnable in order] (empty for a "
     "parked out-of-order arrival or a dropped duplicate)"},
    {nullptr, nullptr, 0, nullptr},
};

PyGetSetDef SeqQueue_getset[] = {
    {"expected", (getter)SeqQueue_expected, nullptr,
     "next sequence number to execute", nullptr},
    {"parked", (getter)SeqQueue_parked, nullptr,
     "buffered out-of-order frames", nullptr},
    {nullptr, nullptr, nullptr, nullptr, nullptr},
};

PyTypeObject SeqQueueType = {PyVarObject_HEAD_INIT(nullptr, 0)};

PyObject* mod_seq_queue(PyObject*, PyObject*) {
  rtp_seqq* q = rtp_seqq_new();
  if (!q) return PyErr_NoMemory();
  SeqQueueObject* self = PyObject_New(SeqQueueObject, &SeqQueueType);
  if (!self) {
    rtp_seqq_free(q, nullptr);
    return nullptr;
  }
  self->q = q;
  return (PyObject*)self;
}

// ---- PendingTable ----------------------------------------------------------

struct PendObject {
  PyObject_HEAD
  rtp_pend* p;
};

extern PyTypeObject PendType;

void Pend_dealloc(PendObject* self) {
  if (self->p) {
    rtp_pend_free(self->p);
    self->p = nullptr;
  }
  Py_TYPE(self)->tp_free((PyObject*)self);
}

PyObject* Pend_add(PendObject* self, PyObject* args) {
  Py_buffer tid;
  unsigned long long seq;
  if (!PyArg_ParseTuple(args, "y*K", &tid, &seq)) return nullptr;
  size_t n = rtp_pend_add(self->p, (const uint8_t*)tid.buf,
                          (size_t)tid.len, seq);
  PyBuffer_Release(&tid);
  return PyLong_FromSize_t(n);
}

PyObject* Pend_pop(PendObject* self, PyObject* arg) {
  Py_buffer tid;
  if (PyObject_GetBuffer(arg, &tid, PyBUF_SIMPLE) != 0) return nullptr;
  uint64_t seq = 0;
  int found = rtp_pend_pop(self->p, (const uint8_t*)tid.buf,
                           (size_t)tid.len, &seq);
  PyBuffer_Release(&tid);
  if (!found) Py_RETURN_NONE;
  return PyLong_FromUnsignedLongLong(seq);
}

PyObject* Pend_size(PendObject* self, PyObject*) {
  return PyLong_FromSize_t(rtp_pend_size(self->p));
}

Py_ssize_t Pend_len(PendObject* self) {
  return (Py_ssize_t)rtp_pend_size(self->p);
}

PyObject* Pend_wait_below(PendObject* self, PyObject* args) {
  unsigned long long cap;
  double timeout_s;
  if (!PyArg_ParseTuple(args, "Kd", &cap, &timeout_s)) return nullptr;
  int ms = (int)(timeout_s * 1000.0);
  if (ms < 0) ms = 0;
  size_t n;
  Py_BEGIN_ALLOW_THREADS
  n = rtp_pend_wait_below(self->p, (size_t)cap, ms);
  Py_END_ALLOW_THREADS
  return PyLong_FromSize_t(n);
}

PyObject* Pend_fail(PendObject* self, PyObject*) {
  rtp_pend_fail(self->p);
  Py_RETURN_NONE;
}

PyObject* Pend_drain(PendObject* self, PyObject*) {
  size_t n = rtp_pend_drain_begin(self->p);
  PyObject* out = PyList_New((Py_ssize_t)n);
  if (!out) return nullptr;
  const uint8_t* tid;
  size_t tid_len;
  uint64_t seq;
  Py_ssize_t i = 0;
  while (rtp_pend_drain_next(self->p, &tid, &tid_len, &seq)) {
    if (i >= (Py_ssize_t)n) break;  // cannot happen: drain is exclusive
    PyObject* b = PyBytes_FromStringAndSize((const char*)tid,
                                            (Py_ssize_t)tid_len);
    if (!b) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i++, b);
  }
  if (i != (Py_ssize_t)n && PyList_SetSlice(out, i, (Py_ssize_t)n,
                                            nullptr) != 0) {
    Py_DECREF(out);
    return nullptr;
  }
  return out;
}

PyObject* Pend_apply_done(PendObject* self, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) != 0) return nullptr;
  int n;
  Py_BEGIN_ALLOW_THREADS
  n = rtp_pend_apply_done(self->p, (const uint8_t*)view.buf,
                          (size_t)view.len);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&view);
  if (n < 0) {
    PyErr_SetString(PyExc_ValueError, "malformed native frame");
    return nullptr;
  }
  return PyLong_FromLong(n);
}

PyObject* Pend_stats(PendObject* self, PyObject*) {
  static const char* names[5] = {"adds", "pops", "applies", "wakeups",
                                 "misses"};
  PyObject* d = PyDict_New();
  if (!d) return nullptr;
  for (int i = 0; i < 5; ++i) {
    PyObject* v = PyLong_FromLongLong(rtp_pend_counter(self->p, i));
    if (!v || PyDict_SetItemString(d, names[i], v) != 0) {
      Py_XDECREF(v);
      Py_DECREF(d);
      return nullptr;
    }
    Py_DECREF(v);
  }
  return d;
}

PyObject* Pend_native(PendObject*, void*) { Py_RETURN_TRUE; }

PyObject* Pend_failed(PendObject* self, void*) {
  return PyBool_FromLong(rtp_pend_failed(self->p));
}

PyMethodDef Pend_methods[] = {
    {"add", (PyCFunction)Pend_add, METH_VARARGS,
     "add(task_id, seq) -> new table size"},
    {"pop", (PyCFunction)Pend_pop, METH_O,
     "pop(task_id) -> seq | None (wakes a capped submitter)"},
    {"size", (PyCFunction)Pend_size, METH_NOARGS, "size() -> int"},
    {"wait_below", (PyCFunction)Pend_wait_below, METH_VARARGS,
     "wait_below(cap, timeout_s) -> size at wake (GIL released; wakes "
     "early when the table fails or drains below cap)"},
    {"fail", (PyCFunction)Pend_fail, METH_NOARGS,
     "fail() -> mark failed and wake every capped submitter"},
    {"drain", (PyCFunction)Pend_drain, METH_NOARGS,
     "drain() -> [task_id, ...] snapshot in seq order; table cleared"},
    {"apply_done", (PyCFunction)Pend_apply_done, METH_O,
     "apply_done(payload) -> entries popped from a DONE/DONE_BATCH "
     "frame (0 for non-done payloads; GIL released)"},
    {"stats", (PyCFunction)Pend_stats, METH_NOARGS,
     "stats() -> {adds, pops, applies, wakeups, misses}"},
    {nullptr, nullptr, 0, nullptr},
};

PyGetSetDef Pend_getset[] = {
    {"native", (getter)Pend_native, nullptr,
     "True: this table runs in the extension", nullptr},
    {"failed", (getter)Pend_failed, nullptr,
     "the table was marked failed (channel death)", nullptr},
    {nullptr, nullptr, nullptr, nullptr, nullptr},
};

PySequenceMethods Pend_as_sequence = {};

PyTypeObject PendType = {PyVarObject_HEAD_INIT(nullptr, 0)};

PyObject* mod_pending_table(PyObject*, PyObject*) {
  rtp_pend* p = rtp_pend_new();
  if (!p) return PyErr_NoMemory();
  PendObject* self = PyObject_New(PendObject, &PendType);
  if (!self) {
    rtp_pend_free(p);
    return nullptr;
  }
  self->p = p;
  return (PyObject*)self;
}

// ---- WaiterTable -----------------------------------------------------------
//
// oid bytes -> waiter entry (an arbitrary Python object), FIFO-ordered
// with resolved-entry eviction beyond a cap: the native replacement for
// runtime.py's OrderedDict + threading.Lock pair. Every operation is a
// single C call, so the GIL itself provides the atomicity the Python
// lock used to — no lock round per submit/get/wait.

struct WtEntry {
  std::string key;
  PyObject* obj;
  bool resolved;
  bool dead;
};

struct WaiterObject {
  PyObject_HEAD
  std::unordered_map<std::string, WtEntry*>* map;
  std::deque<WtEntry*>* fifo;
  Py_ssize_t cap;
  Py_ssize_t dead_count;  // tombstones still sitting in the fifo
};

extern PyTypeObject WaiterType;

void Waiter_dealloc(WaiterObject* self) {
  if (self->fifo) {
    for (WtEntry* e : *self->fifo) {
      if (!e->dead) Py_XDECREF(e->obj);
      delete e;
    }
    delete self->fifo;
    self->fifo = nullptr;
  }
  delete self->map;
  self->map = nullptr;
  Py_TYPE(self)->tp_free((PyObject*)self);
}

// Drop dead tombstones off the FIFO front so eviction scans stay O(64);
// when mid-queue tombstones outnumber live entries (one stuck call at
// the front would otherwise let them accumulate forever), rebuild the
// deque — amortized O(1) per pop.
void waiter_compact(WaiterObject* self) {
  while (!self->fifo->empty() && self->fifo->front()->dead) {
    delete self->fifo->front();
    self->fifo->pop_front();
    --self->dead_count;
  }
  if (self->dead_count > (Py_ssize_t)self->map->size() + 64) {
    std::deque<WtEntry*> keep;
    for (WtEntry* e : *self->fifo) {
      if (e->dead)
        delete e;
      else
        keep.push_back(e);
    }
    self->fifo->swap(keep);
    self->dead_count = 0;
  }
}

PyObject* Waiter_put(WaiterObject* self, PyObject* args) {
  Py_buffer key;
  PyObject* obj;
  if (!PyArg_ParseTuple(args, "y*O", &key, &obj)) return nullptr;
  std::string k((const char*)key.buf, (size_t)key.len);
  PyBuffer_Release(&key);
  waiter_compact(self);
  auto it = self->map->find(k);
  if (it != self->map->end()) {
    // Same key again keeps its FIFO position (OrderedDict semantics).
    PyObject* old = it->second->obj;
    Py_INCREF(obj);
    it->second->obj = obj;
    it->second->resolved = false;
    Py_DECREF(old);
    Py_RETURN_NONE;
  }
  WtEntry* e = new (std::nothrow) WtEntry{std::move(k), obj, false, false};
  if (!e) return PyErr_NoMemory();
  Py_INCREF(obj);
  self->fifo->push_back(e);
  (*self->map)[e->key] = e;
  if ((Py_ssize_t)self->map->size() > self->cap) {
    // Evict RESOLVED entries from the FIFO front (bounded scan, oldest
    // first); unresolved entries are live calls and are skipped.
    std::vector<PyObject*> drop;
    int scanned = 0;
    for (WtEntry* cand : *self->fifo) {
      if (cand->dead) continue;
      if (++scanned > 64) break;
      if (cand->resolved) {
        drop.push_back(cand->obj);
        cand->dead = true;
        ++self->dead_count;
        self->map->erase(cand->key);
      }
    }
    waiter_compact(self);
    for (PyObject* o : drop) Py_DECREF(o);
  }
  Py_RETURN_NONE;
}

PyObject* Waiter_get(WaiterObject* self, PyObject* arg) {
  Py_buffer key;
  if (PyObject_GetBuffer(arg, &key, PyBUF_SIMPLE) != 0) return nullptr;
  auto it = self->map->find(
      std::string((const char*)key.buf, (size_t)key.len));
  PyBuffer_Release(&key);
  if (it == self->map->end()) Py_RETURN_NONE;
  Py_INCREF(it->second->obj);
  return it->second->obj;
}

PyObject* Waiter_pop(WaiterObject* self, PyObject* arg) {
  Py_buffer key;
  if (PyObject_GetBuffer(arg, &key, PyBUF_SIMPLE) != 0) return nullptr;
  auto it = self->map->find(
      std::string((const char*)key.buf, (size_t)key.len));
  PyBuffer_Release(&key);
  if (it == self->map->end()) Py_RETURN_NONE;
  WtEntry* e = it->second;
  self->map->erase(it);
  e->dead = true;
  ++self->dead_count;
  PyObject* obj = e->obj;  // transfer the table's ref to the caller
  waiter_compact(self);
  return obj;
}

PyObject* Waiter_mark_resolved(WaiterObject* self, PyObject* arg) {
  Py_buffer key;
  if (PyObject_GetBuffer(arg, &key, PyBUF_SIMPLE) != 0) return nullptr;
  auto it = self->map->find(
      std::string((const char*)key.buf, (size_t)key.len));
  PyBuffer_Release(&key);
  if (it != self->map->end()) it->second->resolved = true;
  Py_RETURN_NONE;
}

Py_ssize_t Waiter_len(WaiterObject* self) {
  return (Py_ssize_t)self->map->size();
}

PyObject* Waiter_native(WaiterObject*, void*) { Py_RETURN_TRUE; }

PyMethodDef Waiter_methods[] = {
    {"put", (PyCFunction)Waiter_put, METH_VARARGS,
     "put(key, entry) -> None (evicts resolved entries beyond cap)"},
    {"get", (PyCFunction)Waiter_get, METH_O, "get(key) -> entry | None"},
    {"pop", (PyCFunction)Waiter_pop, METH_O, "pop(key) -> entry | None"},
    {"mark_resolved", (PyCFunction)Waiter_mark_resolved, METH_O,
     "mark_resolved(key) -> None (entry becomes evictable)"},
    {nullptr, nullptr, 0, nullptr},
};

PyGetSetDef Waiter_getset[] = {
    {"native", (getter)Waiter_native, nullptr,
     "True: this table runs in the extension", nullptr},
    {nullptr, nullptr, nullptr, nullptr, nullptr},
};

PySequenceMethods Waiter_as_sequence = {};

PyTypeObject WaiterType = {PyVarObject_HEAD_INIT(nullptr, 0)};

PyObject* mod_waiter_table(PyObject*, PyObject* args) {
  Py_ssize_t cap = 8192;
  if (!PyArg_ParseTuple(args, "|n", &cap)) return nullptr;
  if (cap < 1) cap = 1;
  WaiterObject* self = PyObject_New(WaiterObject, &WaiterType);
  if (!self) return nullptr;
  self->map = new (std::nothrow) std::unordered_map<std::string, WtEntry*>();
  self->fifo = new (std::nothrow) std::deque<WtEntry*>();
  self->cap = cap;
  self->dead_count = 0;
  if (!self->map || !self->fifo) {
    delete self->map;
    delete self->fifo;
    self->map = nullptr;
    self->fifo = nullptr;
    Py_DECREF(self);
    return PyErr_NoMemory();
  }
  return (PyObject*)self;
}

// ---- codec -----------------------------------------------------------------

// Append one bytes-like attr (already a bytes object) with u32 length.
int put_sized_bytes(rtp_wbuf* b, PyObject* bytes_obj) {
  char* p;
  Py_ssize_t n;
  if (PyBytes_AsStringAndSize(bytes_obj, &p, &n) != 0) return -1;
  if (rtp_put_u32(b, (uint32_t)n) != RTP_OK ||
      rtp_wbuf_put(b, p, (size_t)n) != RTP_OK) {
    PyErr_NoMemory();
    return -1;
  }
  return 0;
}

// Lower one arg (RefArg | ValueArg). Returns 0 ok, 1 unsupported, -1 error.
int put_arg(rtp_wbuf* b, PyObject* arg) {
  if ((PyObject*)Py_TYPE(arg) == g_refarg) {
    PyObject* oid = PyObject_GetAttr(arg, s_object_id);
    if (!oid) return -1;
    PyObject* raw = PyObject_GetAttr(oid, s_bytes_attr);
    Py_DECREF(oid);
    if (!raw) return -1;
    int rc = (rtp_put_u8(b, RTP_ARG_REF) != RTP_OK) ||
             (put_sized_bytes(b, raw) != 0);
    Py_DECREF(raw);
    return rc ? -1 : 0;
  }
  if ((PyObject*)Py_TYPE(arg) == g_valuearg) {
    PyObject* data = PyObject_GetAttr(arg, s_data);
    if (!data) return -1;
    if (!PyBytes_Check(data)) {
      Py_DECREF(data);
      return 1;
    }
    int rc = (rtp_put_u8(b, RTP_ARG_VALUE) != RTP_OK) ||
             (put_sized_bytes(b, data) != 0);
    Py_DECREF(data);
    return rc ? -1 : 0;
  }
  return 1;  // unknown arg shape: caller falls back to pickle
}

PyObject* wbuf_to_bytes(rtp_wbuf* b) {
  PyObject* out = PyBytes_FromStringAndSize((const char*)b->p,
                                            (Py_ssize_t)b->len);
  rtp_wbuf_freebuf(b);
  return out;
}

// encode_call(tmpl, task_id_bytes, seq, deadline, args, kwargs, nested,
//             trace=None) -> bytes | None (unsupported shape)
// `trace` is a (trace_id, span_id) str 2-tuple (codec v2, RTP_CALL_HAS_TRACE)
// or None; callers pass None on channels that negotiated npv < 2.
PyObject* mod_encode_call(PyObject*, PyObject* args) {
  unsigned int tmpl;
  Py_buffer tid;
  unsigned long long seq;
  double deadline;
  PyObject *a_args, *a_kwargs, *nested;
  PyObject* trace = Py_None;
  if (!PyArg_ParseTuple(args, "Iy*KdOOO|O", &tmpl, &tid, &seq, &deadline,
                        &a_args, &a_kwargs, &nested, &trace))
    return nullptr;
  if (!g_refarg) {
    PyBuffer_Release(&tid);
    return py_types_registered_err();
  }
  const char* trace_utf[2] = {nullptr, nullptr};
  Py_ssize_t trace_len[2] = {0, 0};
  int has_trace = trace != Py_None;
  if (has_trace) {
    if (!PyTuple_Check(trace) || PyTuple_GET_SIZE(trace) != 2) {
      PyBuffer_Release(&tid);
      Py_RETURN_NONE;
    }
    for (int i = 0; i < 2; ++i) {
      PyObject* part = PyTuple_GET_ITEM(trace, i);
      if (!PyUnicode_Check(part)) {
        PyBuffer_Release(&tid);
        Py_RETURN_NONE;
      }
      trace_utf[i] = PyUnicode_AsUTF8AndSize(part, &trace_len[i]);
      if (!trace_utf[i]) {
        PyBuffer_Release(&tid);
        return nullptr;
      }
      if (trace_len[i] > 255) {
        PyBuffer_Release(&tid);
        Py_RETURN_NONE;
      }
    }
  }
  if (tid.len > 255 || (a_args != Py_None && !PyList_Check(a_args)) ||
      (a_kwargs != Py_None && !PyDict_Check(a_kwargs)) ||
      (nested != Py_None && !PyTuple_Check(nested))) {
    PyBuffer_Release(&tid);
    Py_RETURN_NONE;
  }
  int has_args = (a_args != Py_None && PyList_GET_SIZE(a_args) > 0) ||
                 (a_kwargs != Py_None && PyDict_GET_SIZE(a_kwargs) > 0);
  int has_nested = nested != Py_None && PyTuple_GET_SIZE(nested) > 0;
  rtp_wbuf b;
  if (rtp_wbuf_init(&b, 128) != RTP_OK) {
    PyBuffer_Release(&tid);
    return PyErr_NoMemory();
  }
  rtp_put_u8(&b, RTP_MAGIC);
  rtp_put_u8(&b, RTP_F_CALL);
  rtp_put_u32(&b, tmpl);
  rtp_put_u64(&b, seq);
  rtp_put_u8(&b, (uint8_t)tid.len);
  rtp_wbuf_put(&b, tid.buf, (size_t)tid.len);
  PyBuffer_Release(&tid);
  rtp_put_f64(&b, deadline);
  uint8_t flags = (has_args ? RTP_CALL_HAS_ARGS : 0) |
                  (has_nested ? RTP_CALL_HAS_NESTED : 0) |
                  (has_trace ? RTP_CALL_HAS_TRACE : 0);
  rtp_put_u8(&b, flags);
  if (has_trace) {
    for (int i = 0; i < 2; ++i) {
      rtp_put_u8(&b, (uint8_t)trace_len[i]);
      rtp_wbuf_put(&b, trace_utf[i], (size_t)trace_len[i]);
    }
  }
  if (has_args) {
    if (a_args == Py_None || !PyList_Check(a_args) ||
        (a_kwargs != Py_None && !PyDict_Check(a_kwargs)))
      goto unsupported;
    {
      Py_ssize_t na = PyList_GET_SIZE(a_args);
      rtp_put_u32(&b, (uint32_t)na);
      for (Py_ssize_t i = 0; i < na; ++i) {
        int rc = put_arg(&b, PyList_GET_ITEM(a_args, i));
        if (rc < 0) goto error;
        if (rc > 0) goto unsupported;
      }
      Py_ssize_t nk =
          a_kwargs == Py_None ? 0 : PyDict_GET_SIZE(a_kwargs);
      rtp_put_u32(&b, (uint32_t)nk);
      if (nk) {
        PyObject *key, *value;
        Py_ssize_t pos = 0;
        while (PyDict_Next(a_kwargs, &pos, &key, &value)) {
          if (!PyUnicode_Check(key)) goto unsupported;
          Py_ssize_t klen;
          const char* kutf = PyUnicode_AsUTF8AndSize(key, &klen);
          if (!kutf) goto error;
          if (klen > 0xffff) goto unsupported;
          rtp_put_u16(&b, (uint16_t)klen);
          rtp_wbuf_put(&b, kutf, (size_t)klen);
          int rc = put_arg(&b, value);
          if (rc < 0) goto error;
          if (rc > 0) goto unsupported;
        }
      }
    }
  }
  if (has_nested) {
    Py_ssize_t nn = PyTuple_GET_SIZE(nested);
    rtp_put_u32(&b, (uint32_t)nn);
    for (Py_ssize_t i = 0; i < nn; ++i) {
      PyObject* oid = PyTuple_GET_ITEM(nested, i);
      if ((PyObject*)Py_TYPE(oid) != g_objectid) goto unsupported;
      PyObject* raw = PyObject_GetAttr(oid, s_bytes_attr);
      if (!raw) goto error;
      char* p;
      Py_ssize_t n;
      if (PyBytes_AsStringAndSize(raw, &p, &n) != 0 || n > 255) {
        Py_DECREF(raw);
        goto unsupported;
      }
      rtp_put_u8(&b, (uint8_t)n);
      rtp_wbuf_put(&b, p, (size_t)n);
      Py_DECREF(raw);
    }
  }
  return wbuf_to_bytes(&b);
unsupported:
  rtp_wbuf_freebuf(&b);
  Py_RETURN_NONE;
error:
  rtp_wbuf_freebuf(&b);
  return nullptr;
}

// Append one task_done body. Returns 0 ok, 1 unsupported, -1 error.
int put_done_body(rtp_wbuf* b, PyObject* done) {
  if (!PyDict_Check(done)) return 1;
  // Reject any key outside the hot success/failure shape — extra
  // bookkeeping (nested refs, error strings, resource usage) rides the
  // pickle dialect instead.
  PyObject *key, *value;
  Py_ssize_t pos = 0;
  PyObject* task_id = nullptr;
  PyObject* results = nullptr;
  int failed = 0;
  double duration = 0.0;
  while (PyDict_Next(done, &pos, &key, &value)) {
    if (!PyUnicode_Check(key)) return 1;
    if (PyUnicode_Compare(key, s_type) == 0) {
      if (PyUnicode_Compare(value, v_task_done) != 0) return 1;
    } else if (PyUnicode_Compare(key, s_task_id) == 0) {
      task_id = value;
    } else if (PyUnicode_Compare(key, s_results) == 0) {
      results = value;
    } else if (PyUnicode_Compare(key, s_failed) == 0) {
      failed = PyObject_IsTrue(value);
      if (failed < 0) return -1;
    } else if (PyUnicode_Compare(key, s_duration_s) == 0) {
      duration = PyFloat_AsDouble(value);
      if (duration == -1.0 && PyErr_Occurred()) return -1;
    } else if (PyUnicode_Compare(key, s_duplicate) == 0) {
      // Replay-dedup marker: semantically inert for the caller; drop.
    } else {
      if (PyErr_Occurred()) return -1;
      return 1;
    }
  }
  if (PyErr_Occurred()) return -1;
  if (!task_id || !results || failed) return 1;
  if ((PyObject*)Py_TYPE(task_id) != g_taskid) return 1;
  if (!PyList_Check(results)) return 1;
  PyObject* raw = PyObject_GetAttr(task_id, s_bytes_attr);
  if (!raw) return -1;
  char* p;
  Py_ssize_t n;
  if (PyBytes_AsStringAndSize(raw, &p, &n) != 0 || n > 255) {
    Py_DECREF(raw);
    return 1;
  }
  rtp_put_u8(b, (uint8_t)n);
  rtp_wbuf_put(b, p, (size_t)n);
  Py_DECREF(raw);
  rtp_put_u8(b, 0);  // flags: failed dones stay on the pickle dialect
  rtp_put_f64(b, duration);
  Py_ssize_t nr = PyList_GET_SIZE(results);
  rtp_put_u32(b, (uint32_t)nr);
  for (Py_ssize_t i = 0; i < nr; ++i) {
    PyObject* pair = PyList_GET_ITEM(results, i);
    if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) return 1;
    PyObject* oid = PyTuple_GET_ITEM(pair, 0);
    PyObject* loc = PyTuple_GET_ITEM(pair, 1);
    if ((PyObject*)Py_TYPE(oid) != g_objectid ||
        (PyObject*)Py_TYPE(loc) != g_inlineloc)
      return 1;
    PyObject* oraw = PyObject_GetAttr(oid, s_bytes_attr);
    if (!oraw) return -1;
    char* op;
    Py_ssize_t on;
    if (PyBytes_AsStringAndSize(oraw, &op, &on) != 0 || on > 255) {
      Py_DECREF(oraw);
      return 1;
    }
    rtp_put_u8(b, (uint8_t)on);
    rtp_wbuf_put(b, op, (size_t)on);
    Py_DECREF(oraw);
    PyObject* data = PyObject_GetAttr(loc, s_data);
    if (!data) return -1;
    if (!PyBytes_Check(data)) {
      Py_DECREF(data);
      return 1;
    }
    int rc = put_sized_bytes(b, data);
    Py_DECREF(data);
    if (rc != 0) return -1;
  }
  return 0;
}

PyObject* mod_encode_done(PyObject*, PyObject* done) {
  if (!g_taskid) return py_types_registered_err();
  rtp_wbuf b;
  if (rtp_wbuf_init(&b, 128) != RTP_OK) return PyErr_NoMemory();
  rtp_put_u8(&b, RTP_MAGIC);
  rtp_put_u8(&b, RTP_F_DONE);
  int rc = put_done_body(&b, done);
  if (rc < 0) {
    rtp_wbuf_freebuf(&b);
    return nullptr;
  }
  if (rc > 0) {
    rtp_wbuf_freebuf(&b);
    Py_RETURN_NONE;
  }
  return wbuf_to_bytes(&b);
}

PyObject* mod_encode_done_batch(PyObject*, PyObject* arg) {
  if (!g_taskid) return py_types_registered_err();
  PyObject* fast = PySequence_Fast(arg, "encode_done_batch expects a list");
  if (!fast) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  rtp_wbuf b;
  if (rtp_wbuf_init(&b, 256) != RTP_OK) {
    Py_DECREF(fast);
    return PyErr_NoMemory();
  }
  rtp_put_u8(&b, RTP_MAGIC);
  rtp_put_u8(&b, RTP_F_DONE_BATCH);
  rtp_put_u32(&b, (uint32_t)n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    int rc = put_done_body(&b, PySequence_Fast_GET_ITEM(fast, i));
    if (rc != 0) {
      rtp_wbuf_freebuf(&b);
      Py_DECREF(fast);
      if (rc < 0) return nullptr;
      Py_RETURN_NONE;  // one unsupported item: whole batch rides pickle
    }
  }
  Py_DECREF(fast);
  return wbuf_to_bytes(&b);
}

PyObject* encode_fence_frame(uint8_t ftype, PyObject* arg) {
  unsigned long long mid = PyLong_AsUnsignedLongLong(arg);
  if (mid == (unsigned long long)-1 && PyErr_Occurred()) return nullptr;
  rtp_wbuf b;
  if (rtp_wbuf_init(&b, 16) != RTP_OK) return PyErr_NoMemory();
  rtp_put_u8(&b, RTP_MAGIC);
  rtp_put_u8(&b, ftype);
  rtp_put_u64(&b, mid);
  return wbuf_to_bytes(&b);
}

PyObject* mod_encode_fence(PyObject*, PyObject* arg) {
  return encode_fence_frame(RTP_F_FENCE, arg);
}

PyObject* mod_encode_fence_ack(PyObject*, PyObject* arg) {
  return encode_fence_frame(RTP_F_FENCE_ACK, arg);
}

PyObject* decode_err() {
  PyErr_SetString(PyExc_ValueError, "malformed native frame");
  return nullptr;
}

// Build one arg object from the cursor. Returns new ref or nullptr.
PyObject* read_arg(rtp_rbuf* r) {
  uint8_t kind;
  uint32_t len;
  const uint8_t* p;
  if (rtp_get_u8(r, &kind) != RTP_OK || rtp_get_u32(r, &len) != RTP_OK ||
      rtp_get_ref(r, &p, len) != RTP_OK)
    return decode_err();
  PyObject* raw = PyBytes_FromStringAndSize((const char*)p, (Py_ssize_t)len);
  if (!raw) return nullptr;
  PyObject* out = nullptr;
  if (kind == RTP_ARG_REF) {
    PyObject* oid = PyObject_CallOneArg(g_objectid, raw);
    Py_DECREF(raw);
    if (!oid) return nullptr;
    out = PyObject_CallOneArg(g_refarg, oid);
    Py_DECREF(oid);
  } else if (kind == RTP_ARG_VALUE) {
    out = PyObject_CallOneArg(g_valuearg, raw);
    Py_DECREF(raw);
  } else {
    Py_DECREF(raw);
    return decode_err();
  }
  return out;
}

PyObject* decode_call(rtp_rbuf* r) {
  uint32_t tmpl;
  uint64_t seq;
  uint8_t idlen, flags;
  const uint8_t* idp;
  double deadline;
  if (rtp_get_u32(r, &tmpl) != RTP_OK || rtp_get_u64(r, &seq) != RTP_OK ||
      rtp_get_u8(r, &idlen) != RTP_OK ||
      rtp_get_ref(r, &idp, idlen) != RTP_OK ||
      rtp_get_f64(r, &deadline) != RTP_OK || rtp_get_u8(r, &flags) != RTP_OK)
    return decode_err();
  PyObject* out = PyDict_New();
  if (!out) return nullptr;
  PyObject* tid = PyBytes_FromStringAndSize((const char*)idp, idlen);
  PyObject* tmpl_o = PyLong_FromUnsignedLong(tmpl);
  PyObject* seq_o = PyLong_FromUnsignedLongLong(seq);
  if (!tid || !tmpl_o || !seq_o || PyDict_SetItem(out, s_type, v_execute) ||
      PyDict_SetItem(out, s_t, tmpl_o) || PyDict_SetItem(out, s_i, tid) ||
      PyDict_SetItem(out, s_q, seq_o))
    goto error;
  Py_CLEAR(tid);
  Py_CLEAR(tmpl_o);
  Py_CLEAR(seq_o);
  if (deadline != 0.0) {
    PyObject* d = PyFloat_FromDouble(deadline);
    if (!d || PyDict_SetItem(out, s_d, d)) {
      Py_XDECREF(d);
      goto error;
    }
    Py_DECREF(d);
  }
  if (flags & RTP_CALL_HAS_TRACE) {
    PyObject* parts[2] = {nullptr, nullptr};
    bool tc_ok = true;
    for (int i = 0; i < 2 && tc_ok; ++i) {
      uint8_t tlen;
      const uint8_t* tp;
      if (rtp_get_u8(r, &tlen) != RTP_OK ||
          rtp_get_ref(r, &tp, tlen) != RTP_OK) {
        decode_err();
        tc_ok = false;
        break;
      }
      parts[i] = PyUnicode_DecodeUTF8((const char*)tp, tlen, nullptr);
      if (!parts[i]) tc_ok = false;
    }
    PyObject* tc =
        tc_ok ? PyTuple_Pack(2, parts[0], parts[1]) : nullptr;
    Py_XDECREF(parts[0]);
    Py_XDECREF(parts[1]);
    if (!tc || PyDict_SetItem(out, s_tc, tc)) {
      Py_XDECREF(tc);
      goto error;
    }
    Py_DECREF(tc);
  }
  if (flags & RTP_CALL_HAS_ARGS) {
    uint32_t na;
    if (rtp_get_u32(r, &na) != RTP_OK) {
      decode_err();
      goto error;
    }
    PyObject* args_list = PyList_New((Py_ssize_t)na);
    if (!args_list) goto error;
    for (uint32_t i = 0; i < na; ++i) {
      PyObject* a = read_arg(r);
      if (!a) {
        Py_DECREF(args_list);
        goto error;
      }
      PyList_SET_ITEM(args_list, i, a);
    }
    uint32_t nk;
    if (rtp_get_u32(r, &nk) != RTP_OK) {
      Py_DECREF(args_list);
      decode_err();
      goto error;
    }
    PyObject* kw = PyDict_New();
    if (!kw) {
      Py_DECREF(args_list);
      goto error;
    }
    for (uint32_t i = 0; i < nk; ++i) {
      uint16_t klen;
      const uint8_t* kp;
      if (rtp_get_u16(r, &klen) != RTP_OK ||
          rtp_get_ref(r, &kp, klen) != RTP_OK) {
        Py_DECREF(args_list);
        Py_DECREF(kw);
        decode_err();
        goto error;
      }
      PyObject* key =
          PyUnicode_DecodeUTF8((const char*)kp, klen, nullptr);
      PyObject* v = key ? read_arg(r) : nullptr;
      if (!key || !v || PyDict_SetItem(kw, key, v)) {
        Py_XDECREF(key);
        Py_XDECREF(v);
        Py_DECREF(args_list);
        Py_DECREF(kw);
        goto error;
      }
      Py_DECREF(key);
      Py_DECREF(v);
    }
    PyObject* a_pair = PyTuple_Pack(2, args_list, kw);
    Py_DECREF(args_list);
    Py_DECREF(kw);
    if (!a_pair || PyDict_SetItem(out, s_a, a_pair)) {
      Py_XDECREF(a_pair);
      goto error;
    }
    Py_DECREF(a_pair);
  }
  if (flags & RTP_CALL_HAS_NESTED) {
    uint32_t nn;
    if (rtp_get_u32(r, &nn) != RTP_OK) {
      decode_err();
      goto error;
    }
    PyObject* tup = PyTuple_New((Py_ssize_t)nn);
    if (!tup) goto error;
    for (uint32_t i = 0; i < nn; ++i) {
      uint8_t olen;
      const uint8_t* op;
      if (rtp_get_u8(r, &olen) != RTP_OK ||
          rtp_get_ref(r, &op, olen) != RTP_OK) {
        Py_DECREF(tup);
        decode_err();
        goto error;
      }
      PyObject* raw = PyBytes_FromStringAndSize((const char*)op, olen);
      PyObject* oid = raw ? PyObject_CallOneArg(g_objectid, raw) : nullptr;
      Py_XDECREF(raw);
      if (!oid) {
        Py_DECREF(tup);
        goto error;
      }
      PyTuple_SET_ITEM(tup, i, oid);
    }
    if (PyDict_SetItem(out, s_n, tup)) {
      Py_DECREF(tup);
      goto error;
    }
    Py_DECREF(tup);
  }
  return out;
error:
  Py_XDECREF(tid);
  Py_XDECREF(tmpl_o);
  Py_XDECREF(seq_o);
  Py_DECREF(out);
  return nullptr;
}

PyObject* decode_done_body(rtp_rbuf* r) {
  uint8_t idlen, flags;
  const uint8_t* idp;
  double duration;
  uint32_t nr;
  if (rtp_get_u8(r, &idlen) != RTP_OK ||
      rtp_get_ref(r, &idp, idlen) != RTP_OK ||
      rtp_get_u8(r, &flags) != RTP_OK || rtp_get_f64(r, &duration) != RTP_OK ||
      rtp_get_u32(r, &nr) != RTP_OK)
    return decode_err();
  PyObject* raw = PyBytes_FromStringAndSize((const char*)idp, idlen);
  PyObject* tid = raw ? PyObject_CallOneArg(g_taskid, raw) : nullptr;
  Py_XDECREF(raw);
  if (!tid) return nullptr;
  PyObject* results = PyList_New((Py_ssize_t)nr);
  if (!results) {
    Py_DECREF(tid);
    return nullptr;
  }
  for (uint32_t i = 0; i < nr; ++i) {
    uint8_t olen;
    const uint8_t* op;
    uint32_t dlen;
    const uint8_t* dp;
    if (rtp_get_u8(r, &olen) != RTP_OK ||
        rtp_get_ref(r, &op, olen) != RTP_OK ||
        rtp_get_u32(r, &dlen) != RTP_OK ||
        rtp_get_ref(r, &dp, dlen) != RTP_OK) {
      Py_DECREF(tid);
      Py_DECREF(results);
      return decode_err();
    }
    PyObject* oraw = PyBytes_FromStringAndSize((const char*)op, olen);
    PyObject* oid = oraw ? PyObject_CallOneArg(g_objectid, oraw) : nullptr;
    Py_XDECREF(oraw);
    PyObject* draw = PyBytes_FromStringAndSize((const char*)dp,
                                               (Py_ssize_t)dlen);
    PyObject* loc = draw ? PyObject_CallOneArg(g_inlineloc, draw) : nullptr;
    Py_XDECREF(draw);
    PyObject* pair = (oid && loc) ? PyTuple_Pack(2, oid, loc) : nullptr;
    Py_XDECREF(oid);
    Py_XDECREF(loc);
    if (!pair) {
      Py_DECREF(tid);
      Py_DECREF(results);
      return nullptr;
    }
    PyList_SET_ITEM(results, i, pair);
  }
  PyObject* out = PyDict_New();
  PyObject* dur = PyFloat_FromDouble(duration);
  if (!out || !dur || PyDict_SetItem(out, s_type, v_task_done) ||
      PyDict_SetItem(out, s_task_id, tid) ||
      PyDict_SetItem(out, s_results, results) ||
      PyDict_SetItem(out, s_failed,
                     (flags & RTP_DONE_FAILED) ? Py_True : Py_False) ||
      PyDict_SetItem(out, s_duration_s, dur)) {
    Py_XDECREF(out);
    Py_XDECREF(dur);
    Py_DECREF(tid);
    Py_DECREF(results);
    return nullptr;
  }
  Py_DECREF(dur);
  Py_DECREF(tid);
  Py_DECREF(results);
  return out;
}

// ---- burst receive ---------------------------------------------------------

// Read every available frame into `out` without the GIL: the first read
// blocks; afterwards only COMPLETE buffered frames are sliced (never a
// partial — the loop cannot stall mid-burst). An error after the first
// frame returns what was collected; the stream error surfaces on the
// next call.
int burst_read_frames(rtp_chan* c, std::vector<std::string>& out,
                      unsigned long max_frames) {
  bool first = true;
  while (out.size() < max_frames) {
    if (!first && !rtp_chan_has_frame(c)) break;
    const uint8_t* ptr = nullptr;
    uint32_t len = 0;
    int rc = rtp_chan_next(c, &ptr, &len);
    if (rc == RTP_BIG) {
      std::string buf;
      buf.resize(len);
      rc = rtp_chan_read_exact(c, (uint8_t*)&buf[0], len);
      if (rc != RTP_OK)
        // Mid-payload failure: framing is lost; big_remaining stays
        // nonzero so the NEXT read reports the dead channel.
        return first ? RTP_ERR : RTP_OK;
      out.push_back(std::move(buf));
    } else if (rc == RTP_OK) {
      out.emplace_back((const char*)ptr, (size_t)len);
    } else {
      return first ? rc : RTP_OK;
    }
    first = false;
  }
  return RTP_OK;
}

bool payload_is_done(const std::string& s) {
  return s.size() >= 2 && (uint8_t)s[0] == RTP_MAGIC &&
         ((uint8_t)s[1] == RTP_F_DONE || (uint8_t)s[1] == RTP_F_DONE_BATCH);
}

PyObject* Chan_recv_burst(ChanObject* self, PyObject* args) {
  PyObject* pend_obj = Py_None;
  unsigned long max_frames = 1024;
  if (!PyArg_ParseTuple(args, "|Ok", &pend_obj, &max_frames)) return nullptr;
  rtp_pend* pend = nullptr;
  if (pend_obj != Py_None) {
    if (!PyObject_TypeCheck(pend_obj, &PendType)) {
      PyErr_SetString(PyExc_TypeError,
                      "recv_burst expects a _rtpump.PendingTable or None");
      return nullptr;
    }
    pend = ((PendObject*)pend_obj)->p;
  }
  if (chan_check(self) != 0) return nullptr;
  if (!g_taskid) return py_types_registered_err();
  std::vector<std::string> frames;
  std::vector<const std::string*> dones;
  std::vector<const std::string*> others;
  int rc = RTP_OK;
  bool oom = false;
  Py_BEGIN_ALLOW_THREADS
  try {
    rc = burst_read_frames(self->chan, frames, max_frames);
    if (rc == RTP_OK) {
      for (const std::string& f : frames) {
        if (payload_is_done(f)) {
          // GIL-free completion application: the pending table's pops
          // (and the backpressure condvar signal) happen HERE, before
          // Python is entered at all. A malformed frame falls to the
          // others list, where the Python-side decode raises and the
          // channel fails exactly as the per-frame path would.
          if (pend != nullptr &&
              rtp_pend_apply_done(pend, (const uint8_t*)f.data(),
                                  f.size()) < 0) {
            others.push_back(&f);
            continue;
          }
          dones.push_back(&f);
        } else {
          others.push_back(&f);
        }
      }
    }
  } catch (...) {
    oom = true;
  }
  Py_END_ALLOW_THREADS
  if (oom) return PyErr_NoMemory();
  if (rc != RTP_OK) return chan_raise(rc);
  PyObject* done_list = PyList_New(0);
  if (!done_list) return nullptr;
  for (const std::string* f : dones) {
    rtp_rbuf r = {(const uint8_t*)f->data(), f->size(), 2};  // skip magic+type
    if ((uint8_t)(*f)[1] == RTP_F_DONE) {
      PyObject* d = decode_done_body(&r);
      if (!d || PyList_Append(done_list, d) != 0) {
        Py_XDECREF(d);
        Py_DECREF(done_list);
        return nullptr;
      }
      Py_DECREF(d);
    } else {
      uint32_t n = 0;
      if (rtp_get_u32(&r, &n) != RTP_OK) {
        Py_DECREF(done_list);
        return decode_err();
      }
      for (uint32_t i = 0; i < n; ++i) {
        PyObject* d = decode_done_body(&r);
        if (!d || PyList_Append(done_list, d) != 0) {
          Py_XDECREF(d);
          Py_DECREF(done_list);
          return nullptr;
        }
        Py_DECREF(d);
      }
    }
  }
  PyObject* other_list = PyList_New((Py_ssize_t)others.size());
  if (!other_list) {
    Py_DECREF(done_list);
    return nullptr;
  }
  for (size_t i = 0; i < others.size(); ++i) {
    PyObject* b = PyBytes_FromStringAndSize(others[i]->data(),
                                            (Py_ssize_t)others[i]->size());
    if (!b) {
      Py_DECREF(done_list);
      Py_DECREF(other_list);
      return nullptr;
    }
    PyList_SET_ITEM(other_list, (Py_ssize_t)i, b);
  }
  PyObject* out = PyTuple_Pack(2, done_list, other_list);
  Py_DECREF(done_list);
  Py_DECREF(other_list);
  return out;
}

PyObject* Chan_recv_many(ChanObject* self, PyObject* args) {
  unsigned long max_frames = 1024;
  if (!PyArg_ParseTuple(args, "|k", &max_frames)) return nullptr;
  if (chan_check(self) != 0) return nullptr;
  std::vector<std::string> frames;
  int rc = RTP_OK;
  bool oom = false;
  Py_BEGIN_ALLOW_THREADS
  try {
    rc = burst_read_frames(self->chan, frames, max_frames);
  } catch (...) {
    oom = true;
  }
  Py_END_ALLOW_THREADS
  if (oom) return PyErr_NoMemory();
  if (rc != RTP_OK) return chan_raise(rc);
  PyObject* out = PyList_New((Py_ssize_t)frames.size());
  if (!out) return nullptr;
  for (size_t i = 0; i < frames.size(); ++i) {
    PyObject* b = PyBytes_FromStringAndSize(frames[i].data(),
                                            (Py_ssize_t)frames[i].size());
    if (!b) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, (Py_ssize_t)i, b);
  }
  return out;
}

PyObject* decode_fence(rtp_rbuf* r, PyObject* type_value) {
  uint64_t mid;
  if (rtp_get_u64(r, &mid) != RTP_OK) return decode_err();
  PyObject* out = PyDict_New();
  PyObject* m = PyLong_FromUnsignedLongLong(mid);
  if (!out || !m || PyDict_SetItem(out, s_type, type_value) ||
      PyDict_SetItem(out, s_msg_id, m)) {
    Py_XDECREF(out);
    Py_XDECREF(m);
    return nullptr;
  }
  Py_DECREF(m);
  return out;
}

PyObject* mod_decode(PyObject*, PyObject* arg) {
  if (!g_refarg) return py_types_registered_err();
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) != 0) return nullptr;
  rtp_rbuf r = {(const uint8_t*)view.buf, (size_t)view.len, 0};
  uint8_t magic, ftype;
  PyObject* out = nullptr;
  if (rtp_get_u8(&r, &magic) != RTP_OK || magic != RTP_MAGIC ||
      rtp_get_u8(&r, &ftype) != RTP_OK) {
    PyBuffer_Release(&view);
    return decode_err();
  }
  switch (ftype) {
    case RTP_F_CALL:
      out = decode_call(&r);
      break;
    case RTP_F_DONE:
      out = decode_done_body(&r);
      break;
    case RTP_F_DONE_BATCH: {
      uint32_t n;
      if (rtp_get_u32(&r, &n) != RTP_OK) {
        out = decode_err();
        break;
      }
      PyObject* items = PyList_New((Py_ssize_t)n);
      if (!items) break;
      bool ok = true;
      for (uint32_t i = 0; i < n && ok; ++i) {
        PyObject* d = decode_done_body(&r);
        if (!d) {
          ok = false;
          break;
        }
        PyList_SET_ITEM(items, i, d);
      }
      if (!ok) {
        Py_DECREF(items);
        break;
      }
      out = PyDict_New();
      if (!out || PyDict_SetItem(out, s_type, v_task_done_batch) ||
          PyDict_SetItem(out, s_items, items)) {
        Py_XDECREF(out);
        out = nullptr;
      }
      Py_DECREF(items);
      break;
    }
    case RTP_F_FENCE:
      out = decode_fence(&r, v_fence);
      break;
    case RTP_F_FENCE_ACK:
      out = decode_fence(&r, v_fence_ack);
      break;
    default:
      out = decode_err();
  }
  PyBuffer_Release(&view);
  return out;
}

PyObject* mod_register_types(PyObject*, PyObject* args) {
  PyObject *refarg, *valuearg, *objectid, *taskid, *inlineloc;
  if (!PyArg_ParseTuple(args, "OOOOO", &refarg, &valuearg, &objectid,
                        &taskid, &inlineloc))
    return nullptr;
  Py_INCREF(refarg);
  Py_XDECREF(g_refarg);
  g_refarg = refarg;
  Py_INCREF(valuearg);
  Py_XDECREF(g_valuearg);
  g_valuearg = valuearg;
  Py_INCREF(objectid);
  Py_XDECREF(g_objectid);
  g_objectid = objectid;
  Py_INCREF(taskid);
  Py_XDECREF(g_taskid);
  g_taskid = taskid;
  Py_INCREF(inlineloc);
  Py_XDECREF(g_inlineloc);
  g_inlineloc = inlineloc;
  Py_RETURN_NONE;
}

PyMethodDef module_methods[] = {
    {"chan", mod_chan, METH_VARARGS,
     "chan(fd, bufcap=0) -> Chan (dups fd; bufcap 0 = 256 KiB)"},
    {"seq_queue", mod_seq_queue, METH_NOARGS, "seq_queue() -> SeqQueue"},
    {"pending_table", mod_pending_table, METH_NOARGS,
     "pending_table() -> PendingTable (caller-side pending/replay "
     "bookkeeping off the GIL)"},
    {"waiter_table", mod_waiter_table, METH_VARARGS,
     "waiter_table(cap=8192) -> WaiterTable (oid -> waiter directory, "
     "FIFO resolved-entry eviction beyond cap)"},
    {"register_types", mod_register_types, METH_VARARGS,
     "register_types(RefArg, ValueArg, ObjectID, TaskID, InlineLocation)"},
    {"encode_call", mod_encode_call, METH_VARARGS,
     "encode_call(tmpl, task_id, seq, deadline, args, kwargs, nested, "
     "trace=None) -> bytes | None (unsupported shape: caller falls back "
     "to pickle; trace = (trace_id, span_id) strs, codec v2 only)"},
    {"encode_done", mod_encode_done, METH_O,
     "encode_done(task_done_dict) -> bytes | None"},
    {"encode_done_batch", mod_encode_done_batch, METH_O,
     "encode_done_batch([task_done_dict, ...]) -> bytes | None"},
    {"encode_fence", mod_encode_fence, METH_O,
     "encode_fence(msg_id) -> bytes"},
    {"encode_fence_ack", mod_encode_fence_ack, METH_O,
     "encode_fence_ack(msg_id) -> bytes"},
    {"decode", mod_decode, METH_O,
     "decode(payload) -> frame dict (same shapes the pickle dialect "
     "produces); raises ValueError on a malformed frame"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef rtpump_module = {
    PyModuleDef_HEAD_INIT,
    "_rtpump",
    "Native frame pump: framed-channel I/O, call-frame codec, per-channel "
    "seq dispatch.",
    -1,
    module_methods,
};

bool init_strings() {
  struct {
    PyObject** slot;
    const char* text;
  } strs[] = {
      {&s_type, "type"},       {&s_t, "t"},
      {&s_i, "i"},             {&s_q, "q"},
      {&s_a, "a"},             {&s_n, "n"},
      {&s_d, "d"},             {&s_tc, "tc"},
      {&s_task_id, "task_id"},
      {&s_results, "results"}, {&s_failed, "failed"},
      {&s_duration_s, "duration_s"}, {&s_items, "items"},
      {&s_msg_id, "msg_id"},   {&s_duplicate, "duplicate"},
      {&s_object_id, "object_id"},   {&s_data, "data"},
      {&s_bytes_attr, "_bytes"},     {&v_execute, "execute"},
      {&v_task_done, "task_done"},
      {&v_task_done_batch, "task_done_batch"},
      {&v_fence, "fence"},     {&v_fence_ack, "fence_ack"},
  };
  for (auto& e : strs) {
    *e.slot = PyUnicode_InternFromString(e.text);
    if (!*e.slot) return false;
  }
  return true;
}

}  // namespace

PyMODINIT_FUNC PyInit__rtpump(void) {
  ChanType.tp_name = "_rtpump.Chan";
  ChanType.tp_basicsize = sizeof(ChanObject);
  ChanType.tp_dealloc = (destructor)Chan_dealloc;
  ChanType.tp_flags = Py_TPFLAGS_DEFAULT;
  ChanType.tp_methods = Chan_methods;
  SeqQueueType.tp_name = "_rtpump.SeqQueue";
  SeqQueueType.tp_basicsize = sizeof(SeqQueueObject);
  SeqQueueType.tp_dealloc = (destructor)SeqQueue_dealloc;
  SeqQueueType.tp_flags = Py_TPFLAGS_DEFAULT;
  SeqQueueType.tp_methods = SeqQueue_methods;
  SeqQueueType.tp_getset = SeqQueue_getset;
  Pend_as_sequence.sq_length = (lenfunc)Pend_len;
  PendType.tp_name = "_rtpump.PendingTable";
  PendType.tp_basicsize = sizeof(PendObject);
  PendType.tp_dealloc = (destructor)Pend_dealloc;
  PendType.tp_flags = Py_TPFLAGS_DEFAULT;
  PendType.tp_methods = Pend_methods;
  PendType.tp_getset = Pend_getset;
  PendType.tp_as_sequence = &Pend_as_sequence;
  Waiter_as_sequence.sq_length = (lenfunc)Waiter_len;
  WaiterType.tp_name = "_rtpump.WaiterTable";
  WaiterType.tp_basicsize = sizeof(WaiterObject);
  WaiterType.tp_dealloc = (destructor)Waiter_dealloc;
  WaiterType.tp_flags = Py_TPFLAGS_DEFAULT;
  WaiterType.tp_methods = Waiter_methods;
  WaiterType.tp_getset = Waiter_getset;
  WaiterType.tp_as_sequence = &Waiter_as_sequence;
  if (PyType_Ready(&ChanType) < 0 || PyType_Ready(&SeqQueueType) < 0 ||
      PyType_Ready(&PendType) < 0 || PyType_Ready(&WaiterType) < 0)
    return nullptr;
  if (!init_strings()) return nullptr;
  PyObject* m = PyModule_Create(&rtpump_module);
  if (!m) return nullptr;
  PyModule_AddIntConstant(m, "MAGIC", RTP_MAGIC);
  PyModule_AddIntConstant(m, "CODEC_VER", RTP_CODEC_VER);
  Py_INCREF(&ChanType);
  PyModule_AddObject(m, "Chan", (PyObject*)&ChanType);
  Py_INCREF(&SeqQueueType);
  PyModule_AddObject(m, "SeqQueue", (PyObject*)&SeqQueueType);
  Py_INCREF(&PendType);
  PyModule_AddObject(m, "PendingTable", (PyObject*)&PendType);
  Py_INCREF(&WaiterType);
  PyModule_AddObject(m, "WaiterTable", (PyObject*)&WaiterType);
  return m;
}
