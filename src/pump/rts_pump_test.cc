// Assert-based unit test for the frame pump (run via `make native-test`;
// also compiled under TSAN/ASAN by `make native-tsan` / `make native-asan`).
#include "rts_pump.h"

#include <assert.h>
#include <pthread.h>
#include <sched.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>

// ---- framing: single frames, batches, buffered slicing ---------------------

static void test_framing_roundtrip() {
  int fds[2];
  assert(socketpair(AF_UNIX, SOCK_STREAM, 0, fds) == 0);
  rtp_chan* tx = rtp_chan_new(fds[0], 0);
  rtp_chan* rx = rtp_chan_new(fds[1], 4096);
  assert(tx && rx);
  close(fds[0]);
  close(fds[1]);  // the chans own dups

  // A batch of small frames goes out coalesced and arrives intact.
  const char* msgs[] = {"alpha", "b", "", "delta-delta-delta"};
  struct iovec iov[4];
  for (int i = 0; i < 4; ++i) {
    iov[i].iov_base = (void*)msgs[i];
    iov[i].iov_len = strlen(msgs[i]);
  }
  assert(rtp_chan_sendv(tx, iov, 4) == RTP_OK);
  // One writev for the whole burst (8 iovecs < IOV_MAX).
  assert(rtp_chan_counter(tx, 5) == 1);
  assert(rtp_chan_counter(tx, 1) == 4);

  for (int i = 0; i < 4; ++i) {
    const uint8_t* p;
    uint32_t n;
    assert(rtp_chan_next(rx, &p, &n) == RTP_OK);
    assert(n == strlen(msgs[i]));
    assert(memcmp(p, msgs[i], n) == 0);
  }
  // The 4-frame burst was buffered by the first read(2).
  assert(rtp_chan_counter(rx, 4) == 1);
  assert(rtp_chan_counter(rx, 0) == 4);
  assert(rtp_chan_buffered(rx) == 0);

  // Oversized frame (> rx buffer cap): RTP_BIG + read_exact drain.
  size_t big_n = 16000;
  uint8_t* big = (uint8_t*)malloc(big_n);
  for (size_t i = 0; i < big_n; ++i) big[i] = (uint8_t)(i * 7);
  struct iovec bv = {big, big_n};
  assert(rtp_chan_sendv(tx, &bv, 1) == RTP_OK);
  const uint8_t* p;
  uint32_t n;
  int rc = rtp_chan_next(rx, &p, &n);
  assert(rc == RTP_BIG && n == big_n);
  uint8_t* got = (uint8_t*)malloc(big_n);
  assert(rtp_chan_read_exact(rx, got, n) == RTP_OK);
  assert(memcmp(big, got, big_n) == 0);
  free(big);
  free(got);

  // EOF after peer shutdown.
  rtp_chan_shutdown(tx);
  assert(rtp_chan_next(rx, &p, &n) == RTP_EOF);
  rtp_chan_free(tx);
  rtp_chan_free(rx);
}

// ---- threaded pump: writer floods, reader drains (TSAN coverage) -----------

struct pump_thread_arg {
  rtp_chan* chan;
  int frames;
};

static void* writer_main(void* argp) {
  pump_thread_arg* a = (pump_thread_arg*)argp;
  uint8_t payload[512];
  for (int i = 0; i < a->frames; ++i) {
    memset(payload, i & 0xff, sizeof(payload));
    struct iovec iov[8];
    int burst = 1 + (i % 8);
    for (int j = 0; j < burst; ++j) {
      iov[j].iov_base = payload;
      iov[j].iov_len = (size_t)(1 + ((i + j) % sizeof(payload)));
    }
    if (rtp_chan_sendv(a->chan, iov, burst) != RTP_OK) return (void*)1;
    i += burst - 1;
    rtp_chan_inflight_add(a->chan, burst);
  }
  rtp_chan_shutdown(a->chan);
  return nullptr;
}

static void test_threaded_pump() {
  int fds[2];
  assert(socketpair(AF_UNIX, SOCK_STREAM, 0, fds) == 0);
  // Small send buffer to force partial writev paths.
  int snd = 8192;
  setsockopt(fds[0], SOL_SOCKET, SO_SNDBUF, &snd, sizeof(snd));
  rtp_chan* tx = rtp_chan_new(fds[0], 0);
  rtp_chan* rx = rtp_chan_new(fds[1], 8192);
  close(fds[0]);
  close(fds[1]);
  pump_thread_arg arg = {tx, 4000};
  pthread_t th;
  assert(pthread_create(&th, nullptr, writer_main, &arg) == 0);
  int64_t frames = 0;
  for (;;) {
    const uint8_t* p;
    uint32_t n;
    int rc = rtp_chan_next(rx, &p, &n);
    if (rc == RTP_EOF) break;
    assert(rc == RTP_OK);
    assert(n >= 1 && n <= 512);
    ++frames;
    rtp_chan_inflight_add(rx, 1);
  }
  void* wret = nullptr;
  pthread_join(th, &wret);
  assert(wret == nullptr);
  assert(frames == rtp_chan_counter(tx, 1));
  assert(frames == rtp_chan_counter(rx, 0));
  assert(rtp_chan_inflight_add(rx, 0) == frames);
  rtp_chan_free(tx);
  rtp_chan_free(rx);
}

// ---- sequence dispatch queue ----------------------------------------------

static void test_seqq() {
  rtp_seqq* q = rtp_seqq_new();
  int dup = 0;
  // In-order admission.
  assert(rtp_seqq_push(q, 1, (void*)1, &dup) == 1 && !dup);
  assert(rtp_seqq_pop(q) == (void*)1);
  assert(rtp_seqq_pop(q) == nullptr);
  // Out-of-order parking: 4 and 3 park until 2 fills the gap.
  assert(rtp_seqq_push(q, 4, (void*)4, &dup) == 0 && !dup);
  assert(rtp_seqq_push(q, 3, (void*)3, &dup) == 0 && !dup);
  assert(rtp_seqq_parked(q) == 2);
  assert(rtp_seqq_push(q, 2, (void*)2, &dup) == 3 && !dup);
  assert(rtp_seqq_pop(q) == (void*)2);
  assert(rtp_seqq_pop(q) == (void*)3);
  assert(rtp_seqq_pop(q) == (void*)4);
  assert(rtp_seqq_parked(q) == 0);
  assert(rtp_seqq_expected(q) == 5);
  // Duplicate drop (failover replay of an already-executed seq).
  assert(rtp_seqq_push(q, 2, (void*)2, &dup) == 0 && dup == 1);
  assert(rtp_seqq_expected(q) == 5);
  // Random-permutation drain stays totally ordered.
  uint64_t order[64];
  for (int i = 0; i < 64; ++i) order[i] = 5 + (uint64_t)i;
  srand(1234);
  for (int i = 63; i > 0; --i) {
    int j = rand() % (i + 1);
    uint64_t t = order[i];
    order[i] = order[j];
    order[j] = t;
  }
  uint64_t next_expect = 5;
  int drained = 0;
  for (int i = 0; i < 64; ++i) {
    int n = rtp_seqq_push(q, order[i], (void*)(uintptr_t)order[i], &dup);
    assert(!dup);
    for (int k = 0; k < n; ++k) {
      void* item = rtp_seqq_pop(q);
      assert((uint64_t)(uintptr_t)item == next_expect);
      ++next_expect;
      ++drained;
    }
  }
  assert(drained == 64 && rtp_seqq_parked(q) == 0);
  // Duplicate delivery of a still-PARKED seq: reported as duplicate,
  // the FIRST delivery stays parked (no silent overwrite/leak).
  assert(rtp_seqq_push(q, 100, (void*)100, &dup) == 0 && !dup);
  assert(rtp_seqq_push(q, 100, (void*)999, &dup) == 0 && dup == 1);
  assert(rtp_seqq_parked(q) == 1);
  // Fill the gap up to 99; when it closes, the retained first delivery
  // of 100 (value 100, not the duplicate's 999) drains last.
  uint64_t last = 0;
  for (uint64_t s = rtp_seqq_expected(q); s < 100; ++s) {
    int n = rtp_seqq_push(q, s, (void*)(uintptr_t)s, &dup);
    for (int k = 0; k < n; ++k)
      last = (uint64_t)(uintptr_t)rtp_seqq_pop(q);
  }
  assert(rtp_seqq_expected(q) == 101);
  assert(last == 100);
  assert(rtp_seqq_parked(q) == 0);
  rtp_seqq_free(q, nullptr);
}

static int g_dropped = 0;
static void count_drop(void*) { ++g_dropped; }

static void test_seqq_drop() {
  rtp_seqq* q = rtp_seqq_new();
  int dup;
  rtp_seqq_push(q, 5, (void*)5, &dup);  // parked
  rtp_seqq_push(q, 1, (void*)1, &dup);  // ready, never popped
  rtp_seqq_free(q, count_drop);
  assert(g_dropped == 2);  // parked + unpopped ready both released
}

// ---- wire primitives: the codec byte layout the Python mirror matches ------

static void test_wire_layout() {
  rtp_wbuf b;
  assert(rtp_wbuf_init(&b, 8) == RTP_OK);  // tiny: forces growth
  rtp_put_u8(&b, RTP_MAGIC);
  rtp_put_u8(&b, RTP_F_CALL);
  rtp_put_u32(&b, 7);              // tmpl id
  rtp_put_u64(&b, 0x1122334455ull);  // seq
  rtp_put_u8(&b, 16);
  uint8_t id[16];
  for (int i = 0; i < 16; ++i) id[i] = (uint8_t)i;
  rtp_wbuf_put(&b, id, 16);
  rtp_put_f64(&b, 1234.5);
  rtp_put_u8(&b, RTP_CALL_HAS_NESTED);
  rtp_put_u32(&b, 1);
  rtp_put_u8(&b, 16);
  rtp_wbuf_put(&b, id, 16);

  // Fixed prefix bytes (guards the little-endian layout the Python
  // mirror in frame_pump.py hard-codes with struct '<').
  assert(b.p[0] == 0xA7 && b.p[1] == 0x01);
  assert(b.p[2] == 7 && b.p[3] == 0 && b.p[4] == 0 && b.p[5] == 0);
  assert(b.p[6] == 0x55 && b.p[7] == 0x44 && b.p[8] == 0x33);

  rtp_rbuf r = {b.p, b.len, 0};
  uint8_t u8;
  uint32_t u32;
  uint64_t u64;
  double f64;
  assert(rtp_get_u8(&r, &u8) == RTP_OK && u8 == RTP_MAGIC);
  assert(rtp_get_u8(&r, &u8) == RTP_OK && u8 == RTP_F_CALL);
  assert(rtp_get_u32(&r, &u32) == RTP_OK && u32 == 7);
  assert(rtp_get_u64(&r, &u64) == RTP_OK && u64 == 0x1122334455ull);
  assert(rtp_get_u8(&r, &u8) == RTP_OK && u8 == 16);
  const uint8_t* ref;
  assert(rtp_get_ref(&r, &ref, 16) == RTP_OK && memcmp(ref, id, 16) == 0);
  assert(rtp_get_f64(&r, &f64) == RTP_OK && f64 == 1234.5);
  assert(rtp_get_u8(&r, &u8) == RTP_OK && u8 == RTP_CALL_HAS_NESTED);
  assert(rtp_get_u32(&r, &u32) == RTP_OK && u32 == 1);
  assert(rtp_get_u8(&r, &u8) == RTP_OK && u8 == 16);
  assert(rtp_get_ref(&r, &ref, 16) == RTP_OK);
  assert(r.pos == r.len);
  // Truncated read fails cleanly.
  assert(rtp_get_u32(&r, &u32) == RTP_ERR);
  rtp_wbuf_freebuf(&b);

  // u16 round trip (kwarg key length field).
  rtp_wbuf b2;
  assert(rtp_wbuf_init(&b2, 8) == RTP_OK);
  rtp_put_u16(&b2, 0xBEEF);
  rtp_rbuf r2 = {b2.p, b2.len, 0};
  uint16_t u16;
  assert(rtp_get_u16(&r2, &u16) == RTP_OK && u16 == 0xBEEF);
  assert(b2.p[0] == 0xEF && b2.p[1] == 0xBE);
  rtp_wbuf_freebuf(&b2);
}

// ---- pending/replay table ---------------------------------------------------

static void make_tid(uint8_t* tid, uint64_t seq) {
  memset(tid, 0, 16);
  memcpy(tid, &seq, sizeof(seq));
}

// Encode a minimal DONE frame payload for task id `tid` (no results).
static size_t make_done_frame(uint8_t* out, const uint8_t* tid) {
  size_t n = 0;
  out[n++] = RTP_MAGIC;
  out[n++] = RTP_F_DONE;
  out[n++] = 16;
  memcpy(out + n, tid, 16);
  n += 16;
  out[n++] = 0;  // flags
  memset(out + n, 0, 8);  // duration f64 = 0
  n += 8;
  memset(out + n, 0, 4);  // result count u32 = 0
  n += 4;
  return n;
}

static void test_pend_basic() {
  rtp_pend* p = rtp_pend_new();
  uint8_t tid[16];
  for (uint64_t s = 1; s <= 10; ++s) {
    make_tid(tid, s);
    assert(rtp_pend_add(p, tid, 16, s) == s);
  }
  assert(rtp_pend_size(p) == 10);
  // Pop out of order; misses counted, not fatal.
  make_tid(tid, 5);
  uint64_t seq = 0;
  assert(rtp_pend_pop(p, tid, 16, &seq) == 1 && seq == 5);
  assert(rtp_pend_pop(p, tid, 16, &seq) == 0);
  // Completion application straight from a DONE frame payload.
  make_tid(tid, 7);
  uint8_t frame[64];
  size_t fn = make_done_frame(frame, tid);
  assert(rtp_pend_apply_done(p, frame, fn) == 1);
  assert(rtp_pend_apply_done(p, frame, fn) == 1);  // miss: still parses
  assert(rtp_pend_counter(p, RTP_PEND_MISSES) >= 2);
  assert(rtp_pend_apply_done(p, frame, 4) == -1);  // truncated: malformed
  assert(rtp_pend_size(p) == 8);
  // Drain surfaces the remainder in seq order.
  assert(rtp_pend_drain_begin(p) == 8);
  uint64_t last = 0;
  const uint8_t* dt;
  size_t dl;
  while (rtp_pend_drain_next(p, &dt, &dl, &seq)) {
    assert(dl == 16);
    assert(seq > last);
    last = seq;
  }
  assert(last == 10 && rtp_pend_size(p) == 0);
  rtp_pend_free(p);
}

// Stress: a pipelined submitter thread blocked on the backpressure cap
// while a completer thread applies DONE frames, then an injected
// channel death (fail + drain) releases the submitter — the
// TSAN/ASAN/UBSAN builds of this test are the `make native-test` gate
// for the GIL-free dispatch core's locking.
struct pend_stress_arg {
  rtp_pend* p;
  int total;
  int cap;
  std::atomic<int> submitted;
};

static void* pend_submitter_main(void* argp) {
  pend_stress_arg* a = (pend_stress_arg*)argp;
  uint8_t tid[16];
  for (int i = 1; i <= a->total; ++i) {
    while (rtp_pend_size(a->p) >= (size_t)a->cap && !rtp_pend_failed(a->p))
      rtp_pend_wait_below(a->p, (size_t)a->cap, 50);
    if (rtp_pend_failed(a->p)) break;
    make_tid(tid, (uint64_t)i);
    rtp_pend_add(a->p, tid, 16, (uint64_t)i);
    a->submitted.store(i, std::memory_order_release);
  }
  return nullptr;
}

static void test_pend_stress_death() {
  rtp_pend* p = rtp_pend_new();
  pend_stress_arg a = {p, 100000, 64, {0}};
  pthread_t sub;
  pthread_create(&sub, nullptr, pend_submitter_main, &a);
  // Completer: apply DONE frames for roughly half the stream, then
  // inject a channel death mid-pipeline.
  uint8_t tid[16], frame[64];
  for (uint64_t s = 1; s <= 50000; ++s) {
    make_tid(tid, s);
    size_t fn = make_done_frame(frame, tid);
    // Spin until the submitter catches up (the table is the only
    // synchronization, as in the real reader).
    while (a.submitted.load(std::memory_order_acquire) < (int)s)
      sched_yield();
    assert(rtp_pend_apply_done(p, frame, fn) == 1);
  }
  rtp_pend_fail(p);  // injected death: capped submitter must wake NOW
  pthread_join(sub, nullptr);
  // Exactly-once accounting: every add is either popped or drained.
  size_t remaining = rtp_pend_drain_begin(p);
  int64_t adds = rtp_pend_counter(p, RTP_PEND_ADDS);
  int64_t pops = rtp_pend_counter(p, RTP_PEND_POPS);
  assert(adds == pops + (int64_t)remaining);
  assert(pops == 50000);
  // Drain order is seq order even after the chaos.
  uint64_t last = 0, seq;
  const uint8_t* dt;
  size_t dl;
  while (rtp_pend_drain_next(p, &dt, &dl, &seq)) {
    assert(seq > last);
    last = seq;
  }
  rtp_pend_free(p);
}

int main() {
  test_framing_roundtrip();
  test_threaded_pump();
  test_seqq();
  test_seqq_drop();
  test_wire_layout();
  test_pend_basic();
  test_pend_stress_death();
  printf("rts_pump_test OK\n");
  return 0;
}
