// Demo/test driver for the C++ client (cpp/rtpu_client.h), exercised
// by tests/test_cpp_client.py against a live single-node runtime:
//   rtpu_demo <session_dir>
// Performs: hello, zero-copy Put, GetBytes round-trip, Submit of the
// registered "cpp_add" entrypoint (JSON args), Submit consuming the
// native put as a task argument, GetJson, Free. Prints one
// "CPPDEMO <step> OK" line per step; exits nonzero on any failure.
#include <cstdio>
#include <cstring>
#include <string>

#include "rtpu_client.h"

int fail(const std::string& step, const std::string& err) {
  fprintf(stderr, "CPPDEMO %s FAILED: %s\n", step.c_str(),
          err.c_str());
  return 1;
}

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: rtpu_demo <session_dir>\n");
    return 2;
  }
  std::string err;
  rtpu::Client client(argv[1]);
  if (!client.Connect(&err)) return fail("connect", err);
  printf("CPPDEMO connect OK node=%s\n", client.node_id().c_str());

  // Zero-copy put + read-back.
  const char payload[] = "native payload \x01\x02\x03";
  rtpu::ObjectRef ref;
  if (!client.Put(payload, sizeof(payload), &ref, &err))
    return fail("put", err);
  const uint8_t* data = nullptr;
  uint64_t size = 0;
  if (!client.GetBytes(ref, &data, &size, &err))
    return fail("get_bytes", err);
  if (size != sizeof(payload) || memcmp(data, payload, size) != 0)
    return fail("get_bytes", "payload mismatch");
  client.Release(ref);
  printf("CPPDEMO put_get OK bytes=%llu\n",
         static_cast<unsigned long long>(size));

  // Submit a registered Python entrypoint with JSON args.
  rtpu::ObjectRef result;
  if (!client.Submit("cpp_add", "[40, 2]", &result, &err))
    return fail("submit", err);
  std::string value;
  if (!client.GetJson(result, 60.0, &value, &err))
    return fail("get_json", err);
  if (value.find("42") == std::string::npos)
    return fail("get_json", "expected 42, got " + value);
  printf("CPPDEMO submit OK value=%s\n", value.c_str());
  if (!client.Free(result, &err)) return fail("free", err);

  // A Python task consuming the NATIVE put as a bytes argument.
  rtpu::ObjectRef len_result;
  if (!client.Submit("cpp_len",
                     "[{\"__object_id__\": \"" + ref.hex + "\"}]",
                     &len_result, &err))
    return fail("submit_ref", err);
  if (!client.GetJson(len_result, 60.0, &value, &err))
    return fail("get_json_ref", err);
  char want[16];
  snprintf(want, sizeof(want), "%zu", sizeof(payload));
  if (value.find(want) == std::string::npos)
    return fail("get_json_ref",
                std::string("expected ") + want + ", got " + value);
  printf("CPPDEMO submit_ref OK value=%s\n", value.c_str());

  if (!client.Free(ref, &err)) return fail("free_put", err);
  printf("CPPDEMO all OK\n");
  return 0;
}
