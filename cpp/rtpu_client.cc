// C++ client frontend implementation. See rtpu_client.h.
//
// JSON handling is deliberately minimal: requests are assembled by
// string building (all dynamic pieces are hex ids / numbers / caller-
// provided JSON), replies are scanned with a tiny extractor that
// handles the flat {"key": value} shapes capi_server.py emits.

#include "rtpu_client.h"

#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <cstdio>
#include <random>

#include "../src/store/rts_store.h"

namespace rtpu {

namespace {

// Framed-object layout constants (core/serialization.py):
//   <u32 magic><u32 nbufs><u64 pickle_len>[pad to 16][pickle][pad 64]
constexpr uint32_t kMagic = 0x52545055;  // "RTPU" — serialization.MAGIC
constexpr uint64_t kAlign = 64;

uint64_t AlignUp(uint64_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

// Pickled `bytes` object: PROTO 3 | BINBYTES <u32 len> <payload> | STOP
uint64_t PickledBytesLen(uint64_t n) { return 2 + 5 + n + 1; }

void WritePickledBytes(uint8_t* dst, const void* data, uint64_t n) {
  dst[0] = 0x80;  // PROTO
  dst[1] = 3;
  dst[2] = 'B';  // BINBYTES
  uint32_t len32 = static_cast<uint32_t>(n);
  memcpy(dst + 3, &len32, 4);
  memcpy(dst + 7, data, n);
  dst[7 + n] = '.';  // STOP
}

std::string RandomHex(int chars) {
  static thread_local std::mt19937_64 rng{std::random_device{}()};
  static const char* kHex = "0123456789abcdef";
  std::string out(chars, '0');
  for (int i = 0; i < chars; i++) out[i] = kHex[rng() & 0xF];
  return out;
}

bool HexToBytes(const std::string& hex, uint8_t* out, size_t n) {
  if (hex.size() != n * 2) return false;
  auto nib = [](char c) -> int {
    if (c >= '0' && c <= '9') return c - '0';
    if (c >= 'a' && c <= 'f') return c - 'a' + 10;
    if (c >= 'A' && c <= 'F') return c - 'A' + 10;
    return -1;
  };
  for (size_t i = 0; i < n; i++) {
    int hi = nib(hex[2 * i]), lo = nib(hex[2 * i + 1]);
    if (hi < 0 || lo < 0) return false;
    out[i] = static_cast<uint8_t>(hi << 4 | lo);
  }
  return true;
}

// Extract a string value for "key" from a flat JSON object.
bool JsonStr(const std::string& json, const std::string& key,
             std::string* out) {
  std::string pat = "\"" + key + "\"";
  size_t k = json.find(pat);
  if (k == std::string::npos) return false;
  size_t colon = json.find(':', k + pat.size());
  if (colon == std::string::npos) return false;
  size_t q1 = json.find('"', colon + 1);
  if (q1 == std::string::npos) return false;
  std::string val;
  for (size_t i = q1 + 1; i < json.size(); i++) {
    char c = json[i];
    if (c == '\\' && i + 1 < json.size()) {
      val += json[++i];
      continue;
    }
    if (c == '"') {
      *out = val;
      return true;
    }
    val += c;
  }
  return false;
}

}  // namespace

Client::Client(const std::string& session_dir)
    : session_dir_(session_dir) {}

Client::~Client() {
  if (fd_ >= 0) close(fd_);
  if (store_ != nullptr) rts_close(static_cast<rts_store*>(store_));
}

bool Client::Rpc(const std::string& request, std::string* reply,
                 std::string* err) {
  uint32_t len = static_cast<uint32_t>(request.size());
  if (write(fd_, &len, 4) != 4 ||
      write(fd_, request.data(), len) != static_cast<ssize_t>(len)) {
    *err = "capi socket write failed";
    return false;
  }
  uint32_t rlen = 0;
  size_t got = 0;
  auto read_exact = [&](void* dst, size_t n) {
    size_t off = 0;
    while (off < n) {
      ssize_t r = read(fd_, static_cast<uint8_t*>(dst) + off, n - off);
      if (r <= 0) return false;
      off += static_cast<size_t>(r);
    }
    return true;
  };
  (void)got;
  if (!read_exact(&rlen, 4)) {
    *err = "capi socket read failed";
    return false;
  }
  reply->resize(rlen);
  if (!read_exact(&(*reply)[0], rlen)) {
    *err = "capi socket read failed";
    return false;
  }
  // Server contract: "ok" is always the FIRST key, so failure is
  // detected from the frame prefix — value payloads containing an
  // "error" key cannot be mistaken for RPC failures.
  if (reply->rfind("{\"ok\": false", 0) == 0 ||
      reply->rfind("{\"ok\":false", 0) == 0) {
    std::string e;
    if (!JsonStr(*reply, "error", &e)) e = *reply;
    *err = e;
    return false;
  }
  return true;
}

bool Client::Connect(std::string* err) {
  std::string sock_path = session_dir_ + "/capi.sock";
  fd_ = socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd_ < 0) {
    *err = "socket() failed";
    return false;
  }
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  snprintf(addr.sun_path, sizeof(addr.sun_path), "%s",
           sock_path.c_str());
  if (connect(fd_, reinterpret_cast<sockaddr*>(&addr),
              sizeof(addr)) != 0) {
    *err = "connect(" + sock_path + ") failed";
    return false;
  }
  std::string reply;
  if (!Rpc("{\"op\": \"hello\"}", &reply, err)) return false;
  JsonStr(reply, "node_id", &node_id_);
  JsonStr(reply, "arena", &arena_);
  if (!arena_.empty()) {
    char cerr[256];
    store_ = rts_attach(arena_.c_str(), cerr);
    if (store_ == nullptr) {
      *err = std::string("arena attach failed: ") + cerr;
      return false;
    }
  }
  return true;
}

bool Client::Put(const void* data, uint64_t size, ObjectRef* out,
                 std::string* err) {
  if (store_ == nullptr) {
    *err = "no arena store on this node";
    return false;
  }
  rts_store* s = static_cast<rts_store*>(store_);
  out->hex = RandomHex(40);
  uint8_t id[RTS_ID_SIZE];
  HexToBytes(out->hex, id, RTS_ID_SIZE);

  uint64_t pickle_len = PickledBytesLen(size);
  uint64_t header_len = 16;  // <u32><u32><u64> for nbufs=0
  uint64_t total = AlignUp(header_len) + AlignUp(pickle_len);
  uint64_t off = 0;
  int rc = rts_alloc_pin(s, id, total, getpid(), &off);
  if (rc != RTS_OK) {
    *err = "arena alloc failed rc=" + std::to_string(rc);
    return false;
  }
  uint8_t* dst = rts_base(s) + off;
  memset(dst, 0, AlignUp(header_len));
  uint32_t magic = kMagic, nbufs = 0;
  memcpy(dst, &magic, 4);
  memcpy(dst + 4, &nbufs, 4);
  memcpy(dst + 8, &pickle_len, 8);
  WritePickledBytes(dst + AlignUp(header_len), data, size);
  rc = rts_seal(s, id);
  if (rc != RTS_OK) {
    *err = "seal failed rc=" + std::to_string(rc);
    return false;
  }
  rts_unpin(s, id, getpid());

  std::string reply;
  std::string req = "{\"op\": \"register_put\", \"object_id\": \"" +
                    out->hex + "\", \"size\": " +
                    std::to_string(total) + "}";
  return Rpc(req, &reply, err);
}

bool Client::GetBytes(const ObjectRef& ref, const uint8_t** data,
                      uint64_t* size, std::string* err) {
  if (store_ == nullptr) {
    *err = "no arena store";
    return false;
  }
  rts_store* s = static_cast<rts_store*>(store_);
  uint8_t id[RTS_ID_SIZE];
  if (!HexToBytes(ref.hex, id, RTS_ID_SIZE)) {
    *err = "bad object id";
    return false;
  }
  uint64_t off = 0, total = 0;
  int rc = rts_get_pin(s, id, getpid(), &off, &total);
  if (rc != RTS_OK) {
    *err = "object not in local arena rc=" + std::to_string(rc);
    return false;
  }
  const uint8_t* base = rts_base(s) + off;
  uint32_t magic = 0, nbufs = 0;
  uint64_t pickle_len = 0;
  memcpy(&magic, base, 4);
  memcpy(&nbufs, base + 4, 4);
  memcpy(&pickle_len, base + 8, 8);
  const uint8_t* p = base + AlignUp(16);
  if (magic != kMagic || nbufs != 0 || pickle_len < 8 ||
      p[0] != 0x80 || p[2] != 'B') {
    rts_unpin(s, id, getpid());
    *err = "object is not a native pickled-bytes payload (use GetJson)";
    return false;
  }
  uint32_t len32 = 0;
  memcpy(&len32, p + 3, 4);
  *data = p + 7;
  *size = len32;
  return true;  // pin held until Release()
}

void Client::Release(const ObjectRef& ref) {
  if (store_ == nullptr) return;
  uint8_t id[RTS_ID_SIZE];
  if (!HexToBytes(ref.hex, id, RTS_ID_SIZE)) return;
  rts_unpin(static_cast<rts_store*>(store_), id, getpid());
}

bool Client::Submit(const std::string& name,
                    const std::string& args_json, ObjectRef* out,
                    std::string* err) {
  std::string reply;
  std::string req = "{\"op\": \"submit\", \"name\": \"" + name +
                    "\", \"args\": " + args_json + "}";
  if (!Rpc(req, &reply, err)) return false;
  if (!JsonStr(reply, "object_id", &out->hex)) {
    *err = "submit reply missing object_id: " + reply;
    return false;
  }
  return true;
}

bool Client::GetJson(const ObjectRef& ref, double timeout_s,
                     std::string* json_out, std::string* err) {
  std::string reply;
  char buf[64];
  snprintf(buf, sizeof(buf), "%.3f", timeout_s);
  std::string req = "{\"op\": \"get_value\", \"object_id\": \"" +
                    ref.hex + "\", \"timeout\": " + buf + "}";
  if (!Rpc(req, &reply, err)) return false;
  size_t k = reply.find("\"value\":");
  if (k == std::string::npos) {
    *err = "reply missing value: " + reply;
    return false;
  }
  // Value extends to the last '}' minus the trailing req_id field; the
  // server emits {"value": <json>, "req_id": ...}.
  size_t end = reply.rfind(", \"req_id\"");
  if (end == std::string::npos) end = reply.rfind('}');
  *json_out = reply.substr(k + 8, end - (k + 8));
  return true;
}

bool Client::Free(const ObjectRef& ref, std::string* err) {
  std::string reply;
  return Rpc("{\"op\": \"free\", \"object_id\": \"" + ref.hex + "\"}",
             &reply, err);
}

}  // namespace rtpu
