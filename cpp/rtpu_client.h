// C++ client frontend for the ray_tpu runtime.
//
// Ref analogue: the reference's C++ worker API (cpp/include/ray/api.h —
// ray::Init/Put/Get/Task over the core worker). This client covers the
// native-interop surface:
//   * zero-copy object plane: Put/GetBytes go straight to the node's
//     shared-memory arena (src/store/rts_store.h) — no socket on the
//     data path;
//   * control plane: a JSON-framed unix-socket channel to the node
//     manager (core/capi_server.py) for object registration, task
//     submission of registered Python entrypoints, and JSON results.
//
// Interop contract: Put() frames the payload as a pickled `bytes`
// object inside the store's framed-object layout, so Python tasks
// receive native puts as ordinary bytes arguments.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace rtpu {

struct ObjectRef {
  std::string hex;  // 40-char object id
};

class Client {
 public:
  // session_dir: the node's session directory (capi.sock + arena name
  // come from the hello handshake).
  explicit Client(const std::string& session_dir);
  ~Client();

  bool Connect(std::string* err);

  // Zero-copy put: allocates in the shm arena, frames the payload as a
  // pickled bytes object, seals, registers with the node manager.
  bool Put(const void* data, uint64_t size, ObjectRef* out,
           std::string* err);

  // Zero-copy read of an object PUT BY A NATIVE CLIENT (pickled-bytes
  // framing). Returns a pointer into the arena (valid while the client
  // holds the pin; call Release when done).
  bool GetBytes(const ObjectRef& ref, const uint8_t** data,
                uint64_t* size, std::string* err);
  void Release(const ObjectRef& ref);

  // Submit a registered Python entrypoint with JSON-encoded args
  // (args_json must be a JSON array, e.g. "[1, \"x\"]"; object refs
  // ride as {"__object_id__": "<hex>"}). Returns the result ref.
  bool Submit(const std::string& name, const std::string& args_json,
              ObjectRef* out, std::string* err);

  // Block until the object exists, then fetch its value as JSON.
  bool GetJson(const ObjectRef& ref, double timeout_s,
               std::string* json_out, std::string* err);

  // Drop this client's reference.
  bool Free(const ObjectRef& ref, std::string* err);

  const std::string& node_id() const { return node_id_; }

 private:
  bool Rpc(const std::string& request, std::string* reply,
           std::string* err);

  std::string session_dir_;
  std::string node_id_;
  std::string arena_;
  int fd_ = -1;
  void* store_ = nullptr;  // rts_store*
  uint64_t req_counter_ = 0;
};

}  // namespace rtpu
