"""Benchmark entry point (driver contract).

Measures steady-state training throughput of the flagship Llama model on the
available accelerator (single TPU chip under the driver) and prints ONE JSON
line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no TPU tokens/sec numbers (BASELINE.md — published
set is empty; north-star metrics are established by our own harness), so
``vs_baseline`` reports model FLOPs utilization (achieved / peak hardware
FLOPs): a hardware-normalized score that is comparable across rounds and
chips. Higher is better; 1.0 would be the hardware roofline.
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def peak_flops_per_chip(backend: str) -> float:
    if backend == "tpu" or backend == "axon":
        # TPU v5e (v5 lite): 197 TFLOPs bf16 per chip. Conservative default
        # for unknown TPU generations.
        return 197e12
    return 1e12  # CPU placeholder so MFU stays finite in dev runs


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import (
        LlamaConfig,
        causal_lm_loss,
        init_params,
        num_params,
    )

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    if on_accel:
        cfg = LlamaConfig(
            vocab_size=32_768,
            hidden_size=1024,
            intermediate_size=3584,
            num_layers=16,
            num_heads=16,
            num_kv_heads=8,
            dtype=jnp.bfloat16,
        )
        batch, seqlen, measure_steps = 8, 1024, 10
    else:
        cfg = LlamaConfig(
            vocab_size=1024, hidden_size=128, intermediate_size=256,
            num_layers=2, num_heads=4, num_kv_heads=2, dtype=jnp.float32,
        )
        batch, seqlen, measure_steps = 4, 256, 3

    params = init_params(cfg, jax.random.PRNGKey(0))
    p_count = num_params(params)
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def data(step):
        return jax.random.randint(
            jax.random.PRNGKey(step), (batch, seqlen + 1), 0, cfg.vocab_size
        )

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: causal_lm_loss(p, tokens, cfg)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))

    # Warmup/compile. A host read of the loss (not just block_until_ready)
    # guarantees execution completed — the tunneled TPU backend's
    # block_until_ready can return before the computation lands.
    tokens = data(0)
    params, opt_state, loss = step(params, opt_state, tokens)
    assert float(loss) == float(loss), "warmup loss is NaN"

    t0 = time.perf_counter()
    last = 0.0
    for i in range(1, measure_steps + 1):
        params, opt_state, loss = step(params, opt_state, data(i))
        last = float(loss)  # host fetch serializes each step
    dt = time.perf_counter() - t0
    assert last == last, "loss went NaN during measurement"

    tokens_per_step = batch * seqlen
    tokens_per_sec = tokens_per_step * measure_steps / dt
    # Training FLOPs/token: 6*P for the dense path + attention term
    # 12*L*S*H*Dh (fwd 2x QK^T/AV matmuls, x3 for bwd).
    flops_per_token = 6 * p_count + 12 * cfg.num_layers * seqlen * (
        cfg.num_heads * cfg.dh
    )
    mfu = tokens_per_sec * flops_per_token / peak_flops_per_chip(backend)

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu, 4),
    }))


if __name__ == "__main__":
    main()
