"""Benchmark entry point (driver contract).

Measures steady-state training throughput of the flagship Llama model
THROUGH THE FRAMEWORK: a JaxTrainer gang (1 TPU worker actor) trains on
batches streamed by ray_tpu.data's iter_jax_batches device-prefetch path,
reporting through the session channel — the same path a user's training
job takes (VERDICT r1: the bench must exercise the framework, not raw
jax). Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no TPU tokens/sec numbers (BASELINE.md — published
set is empty; north-star metrics are established by our own harness), so
``vs_baseline`` reports model FLOPs utilization (achieved / peak hardware
FLOPs): a hardware-normalized score that is comparable across rounds and
chips. Higher is better; 1.0 would be the hardware roofline.

On the accelerator the model is 8B-SHAPED: Llama-8B layer geometry
(hidden 4096, intermediate 14336, 32 heads / 8 KV heads) with the layer
count cut to fit one chip's HBM alongside optimizer state — per-layer MXU
utilization (what MFU measures) is that of the 8B flagship.
"""

from __future__ import annotations

import json
import os
import sys

_REPO = os.path.dirname(os.path.abspath(__file__))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def peak_flops_per_chip(backend: str) -> float:
    if backend == "tpu" or backend == "axon":
        # TPU v5e (v5 lite): 197 TFLOPs bf16 per chip. Conservative default
        # for unknown TPU generations.
        return 197e12
    return 1e12  # CPU placeholder so MFU stays finite in dev runs


def bench_train_loop(config=None):
    """Runs inside the TPU train worker actor (the framework's compute
    process — the driver never touches jax)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu import train as rt_train
    from ray_tpu.models import (
        LlamaConfig,
        causal_lm_loss,
        init_params,
        num_params,
    )

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    # A/B knobs (PERF harness): flash kernel on/off, remat policy, batch.
    # Defaults = the measured-best single-chip config (r5 A/B matrix):
    # remat=dots + unrolled layers + chunked cross-entropy. Unrolling
    # removes the scan's stacked [L, ...] residual buffers whose
    # fragmentation OOM'd dots in r4 (46% frag at 10 G HLO temp); the
    # chunked loss removes the [B, S, V] fp32 logits cliff (b16 ran at
    # 0.31 MFU in r4, 0.59 now). b8/dots/noscan/chunked: 0.649 MFU vs
    # r4's 0.596.
    use_flash = os.environ.get("RAY_TPU_BENCH_FLASH", "1") == "1"
    remat_policy = os.environ.get("RAY_TPU_BENCH_REMAT", "dots")
    loss_chunk = int(os.environ.get("RAY_TPU_BENCH_LOSS_CHUNK", "512"))
    scan_layers = os.environ.get("RAY_TPU_BENCH_SCAN", "0") == "1"
    if on_accel:
        # 8B-shaped layers (Llama-8B geometry), depth cut to fit one
        # chip. Full-depth 8B does not fit a single v5e: 8.0B params ×
        # (2 bf16 param + 2 bf16 grad + 4 adamw m/v bf16) ≈ 64 GB vs
        # 16 GB HBM; 4 layers ≈ 1.14B params ≈ 9.2 GB + activations.
        cfg = LlamaConfig(
            vocab_size=32_768,
            hidden_size=4096,
            intermediate_size=14_336,
            num_layers=4,
            num_heads=32,
            num_kv_heads=8,
            dtype=jnp.bfloat16,
            use_flash=use_flash,
            remat_policy=remat_policy,
            loss_chunk=loss_chunk,
            scan_layers=scan_layers,
        )
        batch, seqlen, measure_steps = (
            int(os.environ.get("RAY_TPU_BENCH_BATCH", "8")), 2048,
            int(os.environ.get("RAY_TPU_BENCH_STEPS", "16")))
    else:
        cfg = LlamaConfig(
            vocab_size=1024, hidden_size=128, intermediate_size=256,
            num_layers=2, num_heads=4, num_kv_heads=2, dtype=jnp.float32,
        )
        batch, seqlen, measure_steps = 4, 256, 3

    params = init_params(cfg, jax.random.PRNGKey(0))
    p_count = num_params(params)
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)

    # Ingest through the framework: a Dataset of synthetic token batches
    # streamed via iter_jax_batches (HBM double-buffering path).
    from ray_tpu import data as rd
    from ray_tpu.data.context import DataContext

    # The bench worker IS the compute process; block tasks execute inline.
    DataContext.get_current().use_remote_tasks = False
    num_batches = measure_steps + 2
    rng = np.random.RandomState(0)
    all_tokens = rng.randint(
        0, cfg.vocab_size, size=(num_batches * batch, seqlen + 1)
    ).astype(np.int32)
    ds = rd.from_numpy(all_tokens, column="tokens")

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: causal_lm_loss(p, tokens, cfg)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))

    it = ds.iter_jax_batches(batch_size=batch, drop_last=True)
    # Warmup/compile. A host read of the loss (not just block_until_ready)
    # guarantees execution completed — the tunneled TPU backend's
    # block_until_ready can return before the computation lands.
    first = next(it)["tokens"]
    params, opt_state, loss = step(params, opt_state, first)
    assert float(loss) == float(loss), "warmup loss is NaN"

    # Measured window: steps dispatch asynchronously (XLA pipelines
    # compute with the host-side batch feed); ONE host fetch of the last
    # loss closes the window — it transitively waits on every prior step
    # (each step donates/consumes the previous step's params), so the
    # timing is exact without a per-step sync (VERDICT r3 weak #2).
    t0 = time.perf_counter()
    loss = None
    steps_done = 0
    for batch_dict in it:
        if steps_done >= measure_steps:
            break
        params, opt_state, loss = step(
            params, opt_state, batch_dict["tokens"]
        )
        steps_done += 1
    assert loss is not None, "measured window ran zero steps"
    last = float(loss)  # single sync: completes the whole window
    dt = time.perf_counter() - t0
    assert last == last, "loss went NaN during measurement"

    tokens_per_step = batch * seqlen
    tokens_per_sec = tokens_per_step * steps_done / dt
    # Training FLOPs/token: 6*P for the dense path + attention term
    # 12*L*S*H*Dh (fwd 2x QK^T/AV matmuls, x3 for bwd).
    flops_per_token = 6 * p_count + 12 * cfg.num_layers * seqlen * (
        cfg.num_heads * cfg.dh
    )
    mfu = tokens_per_sec * flops_per_token / peak_flops_per_chip(backend)
    rt_train.report({
        "tokens_per_sec": tokens_per_sec,
        "mfu": mfu,
        "backend": backend,
        "num_params": p_count,
        "steps": steps_done,
    })


def main():
    import ray_tpu
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    # The driver must not initialize jax (the worker owns the chip).
    ray_tpu.init(num_cpus=2, num_tpus=1,
                 system_config={"log_to_driver": False})
    try:
        trainer = JaxTrainer(
            bench_train_loop,
            scaling_config=ScalingConfig(num_workers=1, use_tpu=True),
            run_config=RunConfig(name="bench"),
        )
        result = trainer.fit()
        if result.error is not None:
            raise result.error
        m = result.metrics
        print(json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": round(m["tokens_per_sec"], 2),
            "unit": "tokens/s",
            "vs_baseline": round(m["mfu"], 4),
        }))
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
