"""Benchmark entry point (driver contract).

Measures steady-state training throughput of the flagship Llama model
THROUGH THE FRAMEWORK: a JaxTrainer gang (1 TPU worker actor) trains on
batches streamed by ray_tpu.data's iter_jax_batches device-prefetch path,
stepping through the fused compiled train step
(ray_tpu/train/compiled_step.py: pjit + donation + chunked-scan
schedule), reporting through the session channel — the same path a
user's training job takes (VERDICT r1: the bench must exercise the
framework, not raw jax). Prints ONE JSON line:
{"metric", "value", "unit", "vs_baseline"}.

The reference publishes no TPU tokens/sec numbers (BASELINE.md — published
set is empty; north-star metrics are established by our own harness), so
``vs_baseline`` reports model FLOPs utilization (achieved / peak hardware
FLOPs): a hardware-normalized score that is comparable across rounds and
chips. Higher is better; 1.0 would be the hardware roofline.

On the accelerator the model is 8B-SHAPED: Llama-8B layer geometry
(hidden 4096, intermediate 14336, 32 heads / 8 KV heads) with the layer
count cut to fit one chip's HBM alongside optimizer state — per-layer MXU
utilization (what MFU measures) is that of the 8B flagship.

A/B matrix mode (``RAY_TPU_BENCH_AB=1``, `make perf-train`): sweeps
scan × chunk-size × remat-policy × donation × depth, one fresh worker
gang per row (a clean chip between rows — an OOM row cannot poison the
next), and writes per-config rows (tokens/s, MFU, peak HBM, allocator
fragmentation from ``device.memory_stats()``) plus the machine-picked
winners into ``BENCH_AB.json``. The default single-config run stays
byte-compatible with the existing harness and — when a sweep record for
THIS backend exists — runs the sweep's best config instead of the
hand-picked default (env knobs still win).
"""

from __future__ import annotations

import json
import os
import sys

_REPO = os.path.dirname(os.path.abspath(__file__))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

AB_OUT_DEFAULT = os.path.join(_REPO, "BENCH_AB.json")


def peak_flops_per_chip(backend: str) -> float:
    if backend == "tpu" or backend == "axon":
        # TPU v5e (v5 lite): 197 TFLOPs bf16 per chip. Conservative default
        # for unknown TPU generations.
        return 197e12
    return 1e12  # CPU placeholder so MFU stays finite in dev runs


def _resolve_knobs(config, backend: str, on_accel: bool):
    """Layered config resolution, most-specific first: an explicit AB-row
    dict (sweep mode) > env knobs > the machine-picked best from a prior
    sweep of the SAME backend > defaults."""
    config = config or {}
    row = config.get("row") or {}
    ab = config.get("ab_best") or {}
    ab_cfg = ab.get("config") or {} if ab.get("backend") == backend else {}

    def get(key, env, default):
        if key in row:
            return row[key]
        v = os.environ.get(env)
        if v is not None:
            return v
        if key in ab_cfg:
            return ab_cfg[key]
        return default

    knobs = {
        "flash": str(get("flash", "RAY_TPU_BENCH_FLASH", "1")) == "1",
        "remat": str(get("remat", "RAY_TPU_BENCH_REMAT", "dots")),
        "loss_chunk": int(get("loss_chunk", "RAY_TPU_BENCH_LOSS_CHUNK",
                              "512")),
        # Scan is the default-on path now: with the layer-chunked
        # schedule (scan_chunk) the compiled program at chunk=L is the
        # old unrolled winner, and smaller chunks are what full depth
        # needs. RAY_TPU_BENCH_SCAN=0 forces the python-unrolled loop.
        "scan": str(get("scan", "RAY_TPU_BENCH_SCAN", "1")) == "1",
        "scan_chunk": int(get("scan_chunk", "RAY_TPU_BENCH_SCAN_CHUNK",
                              "0")),
        "layers": int(get("layers", "RAY_TPU_BENCH_LAYERS",
                          "4" if on_accel else "2")),
        "batch": int(get("batch", "RAY_TPU_BENCH_BATCH",
                         "8" if on_accel else "4")),
        "steps": int(get("steps", "RAY_TPU_BENCH_STEPS",
                         "16" if on_accel else "3")),
        "donate": str(get("donate", "RAY_TPU_BENCH_DONATE", "1")) == "1",
    }
    layers_clamped = False
    if not on_accel:
        # Tiny-geometry dev shapes; keep the schedule knobs meaningful.
        clamped = min(knobs["layers"], 4)
        layers_clamped = clamped != knobs["layers"]
        knobs["layers"] = clamped
        knobs["steps"] = min(knobs["steps"], 4)
    if knobs["scan"]:
        k = knobs["scan_chunk"]
        if k <= 0 or (layers_clamped and knobs["layers"] % k):
            # Auto (or a requested chunk invalidated by the dev-shape
            # depth clamp): the largest divisor <= 4. At bench depth
            # (L=4) that is K=L — one chunk, which XLA's while-loop
            # simplifier turns into the straight-line (unrolled)
            # program; at real depth it caps the unrolled chunk body
            # while shrinking the stacked residuals by 4x. An
            # EXPLICITLY requested non-divisor passes through untouched
            # so scan_chunks() raises rather than silently measuring a
            # different schedule than the env asked for.
            if k <= 0:
                k = min(knobs["layers"], 4)
            while knobs["layers"] % k:
                k -= 1  # nearest divisor below; terminates at 1
        knobs["scan_chunk"] = k
    return knobs


def bench_train_loop(config=None):
    """Runs inside the TPU train worker actor (the framework's compute
    process — the driver never touches jax)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax  # noqa: F401  (the step owns the optimizer)

    from ray_tpu import train as rt_train
    from ray_tpu.models import LlamaConfig
    from ray_tpu.train.compiled_step import CompiledTrainStep

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    knobs = _resolve_knobs(config, backend, on_accel)
    if on_accel:
        # 8B-shaped layers (Llama-8B geometry), depth cut to fit one
        # chip. Full-depth 8B does not fit a single v5e: 8.0B params ×
        # (2 bf16 param + 2 bf16 grad + 4 adamw m/v bf16) ≈ 64 GB vs
        # 16 GB HBM; 4 layers ≈ 1.14B params ≈ 9.2 GB + activations.
        # The ab_matrix's depth ladder finds the deepest scan-chunked
        # config that still fits beside the optimizer state.
        cfg = LlamaConfig(
            vocab_size=32_768,
            hidden_size=4096,
            intermediate_size=14_336,
            num_layers=knobs["layers"],
            num_heads=32,
            num_kv_heads=8,
            dtype=jnp.bfloat16,
            use_flash=knobs["flash"],
            remat_policy=knobs["remat"],
            loss_chunk=knobs["loss_chunk"],
            scan_layers=knobs["scan"],
            scan_chunk=knobs["scan_chunk"] if knobs["scan"] else 0,
        )
        batch, seqlen = knobs["batch"], 2048
    else:
        cfg = LlamaConfig(
            vocab_size=1024, hidden_size=128, intermediate_size=256,
            num_layers=knobs["layers"], num_heads=4, num_kv_heads=2,
            dtype=jnp.float32,
            use_flash=knobs["flash"],
            remat_policy=knobs["remat"],
            loss_chunk=0,
            scan_layers=knobs["scan"],
            scan_chunk=knobs["scan_chunk"] if knobs["scan"] else 0,
        )
        batch, seqlen = knobs["batch"], 256
    measure_steps = knobs["steps"]

    # The fused step: fwd/bwd/optimizer (+ GSPMD collectives under a
    # mesh) in ONE donated XLA program; params + optimizer state
    # materialize via the step's compiled init so every persistent
    # buffer gets its final, donation-friendly layout in one allocator
    # pass (no host-staged arrays fragmenting the arena).
    step = CompiledTrainStep(cfg, donate=knobs["donate"])
    params, opt_state = step.init(jax.random.PRNGKey(0))
    p_count = step.num_params(params)

    # Ingest through the framework: a Dataset of synthetic token batches
    # streamed via iter_jax_batches (HBM double-buffering path).
    from ray_tpu import data as rd
    from ray_tpu.data.context import DataContext

    # The bench worker IS the compute process; block tasks execute inline.
    DataContext.get_current().use_remote_tasks = False
    num_batches = measure_steps + 2
    rng = np.random.RandomState(0)
    all_tokens = rng.randint(
        0, cfg.vocab_size, size=(num_batches * batch, seqlen + 1)
    ).astype(np.int32)
    ds = rd.from_numpy(all_tokens, column="tokens")

    it = ds.iter_jax_batches(batch_size=batch, drop_last=True)
    # Warmup/compile. A host read of the loss (not just block_until_ready)
    # guarantees execution completed — the tunneled TPU backend's
    # block_until_ready can return before the computation lands.
    first = next(it)["tokens"]
    params, opt_state, loss = step(params, opt_state, first)
    assert float(loss) == float(loss), "warmup loss is NaN"

    # Measured window: steps dispatch asynchronously (XLA pipelines
    # compute with the host-side batch feed); ONE host fetch of the last
    # loss closes the window — it transitively waits on every prior step
    # (each step donates/consumes the previous step's params), so the
    # timing is exact without a per-step sync (VERDICT r3 weak #2).
    t0 = time.perf_counter()
    loss = None
    steps_done = 0
    for batch_dict in it:
        if steps_done >= measure_steps:
            break
        params, opt_state, loss = step(
            params, opt_state, batch_dict["tokens"]
        )
        steps_done += 1
    assert loss is not None, "measured window ran zero steps"
    last = float(loss)  # single sync: completes the whole window
    dt = time.perf_counter() - t0
    assert last == last, "loss went NaN during measurement"

    tokens_per_step = batch * seqlen
    tokens_per_sec = tokens_per_step * steps_done / dt
    # Training FLOPs/token: 6*P for the dense path + attention term
    # 12*L*S*H*Dh (fwd 2x QK^T/AV matmuls, x3 for bwd).
    flops_per_token = 6 * p_count + 12 * cfg.num_layers * seqlen * (
        cfg.num_heads * cfg.dh
    )
    mfu = tokens_per_sec * flops_per_token / peak_flops_per_chip(backend)
    hbm = step.memory_snapshot()  # allocator probe: live/peak/frag
    rt_train.report({
        "tokens_per_sec": tokens_per_sec,
        "mfu": mfu,
        "backend": backend,
        "num_params": p_count,
        "steps": steps_done,
        "config": {
            "scan": int(knobs["scan"]),
            "scan_chunk": knobs["scan_chunk"] if knobs["scan"] else 0,
            "remat": knobs["remat"],
            "donate": int(knobs["donate"]),
            "layers": cfg.num_layers,
            "batch": batch,
            "flash": int(knobs["flash"]),
            "loss_chunk": cfg.loss_chunk,
        },
        "hbm": hbm,
        "compile": step.compile_stats(),
    })


def _fit_once(train_loop_config=None):
    """One JaxTrainer gang (fresh worker process = fresh chip state)
    running the bench loop; returns the Result."""
    from ray_tpu.train import (
        FailureConfig, JaxTrainer, RunConfig, ScalingConfig,
    )

    trainer = JaxTrainer(
        bench_train_loop,
        train_loop_config=train_loop_config,
        scaling_config=ScalingConfig(num_workers=1, use_tpu=True),
        run_config=RunConfig(
            name="bench",
            failure_config=FailureConfig(max_failures=0),
        ),
    )
    return trainer.fit()


def _load_ab_best():
    """The machine-picked best config from a prior sweep, if recorded."""
    path = os.environ.get("RAY_TPU_BENCH_AB_OUT", AB_OUT_DEFAULT)
    try:
        with open(path) as f:
            rec = json.load(f)
        best = rec.get("best") or {}
        if rec.get("backend") and best.get("config"):
            return {"backend": rec["backend"], "config": best["config"]}
    except (OSError, ValueError):
        pass
    return None


def ab_rows():
    """The sweep: scan × chunk × remat × donation × depth. Headline
    contenders at bench depth first, then the full-depth viability
    ladder (the deepest 8B-shaped stack that fits 16 GB HBM beside
    adamw state — 32 true layers is ~64 GB and can never fit one v5e,
    so depth itself is a swept dimension)."""
    return [
        {"label": "unrolled dots (r5 winner)",
         "scan": 0, "remat": "dots", "layers": 4},
        {"label": "chunked scan K=L (degenerate==unrolled)",
         "scan": 1, "scan_chunk": 4, "remat": "dots", "layers": 4},
        {"label": "chunked scan K=2",
         "scan": 1, "scan_chunk": 2, "remat": "dots", "layers": 4},
        {"label": "classic scan K=1 (r5 OOM row)",
         "scan": 1, "scan_chunk": 1, "remat": "dots", "layers": 4},
        {"label": "chunked scan K=2, donation OFF",
         "scan": 1, "scan_chunk": 2, "remat": "dots", "layers": 4,
         "donate": 0},
        {"label": "depth 6, K=2, dots",
         "scan": 1, "scan_chunk": 2, "remat": "dots", "layers": 6},
        {"label": "depth 6, K=2, mlp",
         "scan": 1, "scan_chunk": 2, "remat": "mlp", "layers": 6},
        {"label": "depth 6, K=3, mlp",
         "scan": 1, "scan_chunk": 3, "remat": "mlp", "layers": 6},
        {"label": "depth 8, K=2, mlp",
         "scan": 1, "scan_chunk": 2, "remat": "mlp", "layers": 8},
        {"label": "depth 8, K=2, full",
         "scan": 1, "scan_chunk": 2, "remat": "full", "layers": 8},
        {"label": "depth 8, K=4, full",
         "scan": 1, "scan_chunk": 4, "remat": "full", "layers": 8},
        {"label": "depth 8, classic scan, full (control)",
         "scan": 1, "scan_chunk": 1, "remat": "full", "layers": 8},
    ]


def _row_record(row, result):
    rec = {"label": row.get("label", ""), "requested": row}
    err = result.error
    if err is not None:
        msg = str(err)
        rec["ok"] = False
        rec["oom"] = ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
                      or "out of memory" in msg)
        rec["error"] = msg[-400:]
        return rec
    m = result.metrics
    hbm = m.get("hbm") or {}
    rec.update({
        "ok": True,
        "config": m.get("config", {}),
        "tokens_per_sec": round(m["tokens_per_sec"], 2),
        "mfu": round(m["mfu"], 4),
        "num_params": m.get("num_params"),
        "peak_hbm_gb": (round(hbm["peak_bytes_in_use"] / 2**30, 3)
                        if "peak_bytes_in_use" in hbm else None),
        "fragmentation_pct": (round(100 * hbm["fragmentation"], 1)
                              if "fragmentation" in hbm else None),
        "hbm": hbm,
        "backend": m.get("backend"),
    })
    return rec


def main_ab() -> None:
    """A/B matrix mode: every row on a fresh gang, rows + machine-picked
    winners written to BENCH_AB.json (and echoed as they land)."""
    import ray_tpu

    rows = ab_rows()
    limit = int(os.environ.get("RAY_TPU_BENCH_AB_ROWS", "0"))
    if limit > 0:
        rows = rows[:limit]
    steps = os.environ.get("RAY_TPU_BENCH_AB_STEPS", "8")
    out_path = os.environ.get("RAY_TPU_BENCH_AB_OUT", AB_OUT_DEFAULT)

    ray_tpu.init(num_cpus=2, num_tpus=1,
                 system_config={"log_to_driver": False})
    records = []
    try:
        for i, row in enumerate(rows):
            row = dict(row)
            row.setdefault("steps", int(steps))
            result = _fit_once({"row": row})
            rec = _row_record(row, result)
            records.append(rec)
            print(f"[ab {i + 1}/{len(rows)}] {rec['label']}: "
                  + (f"mfu={rec['mfu']} tok/s={rec['tokens_per_sec']} "
                     f"peak={rec['peak_hbm_gb']}GB "
                     f"frag={rec['fragmentation_pct']}%"
                     if rec["ok"] else
                     ("OOM" if rec.get("oom") else "ERROR")),
                  file=sys.stderr)
    finally:
        ray_tpu.shutdown()

    ok = [r for r in records if r["ok"]]
    backend = ok[0]["backend"] if ok else None
    best = max(ok, key=lambda r: r["mfu"], default=None)
    # Deepest viable scan config (full-depth winner): most layers first,
    # then MFU — the row that proves the scan path survives real depth.
    scan_ok = [r for r in ok if r["config"].get("scan")]
    best_full = max(
        scan_ok, key=lambda r: (r["config"].get("layers", 0), r["mfu"]),
        default=None,
    )
    record = {
        "metric": "llama_train_ab_matrix",
        "backend": backend,
        "rows": records,
        "best": best,
        "best_full_depth": best_full,
    }
    with open(out_path, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(json.dumps({
        "metric": "llama_train_ab_matrix",
        "rows": len(records),
        "ok": len(ok),
        "best_mfu": best["mfu"] if best else None,
        "best_full_depth_layers": (best_full["config"].get("layers")
                                   if best_full else None),
        "best_full_depth_mfu": best_full["mfu"] if best_full else None,
        "out": out_path,
    }))


def main():
    if os.environ.get("RAY_TPU_BENCH_AB") == "1":
        return main_ab()
    import ray_tpu

    # The driver must not initialize jax (the worker owns the chip).
    ray_tpu.init(num_cpus=2, num_tpus=1,
                 system_config={"log_to_driver": False})
    try:
        ab_best = _load_ab_best()
        cfg = {"ab_best": ab_best} if ab_best else None
        result = _fit_once(cfg)
        if result.error is not None:
            raise result.error
        m = result.metrics
        print(json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": round(m["tokens_per_sec"], 2),
            "unit": "tokens/s",
            "vs_baseline": round(m["mfu"], 4),
        }))
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
