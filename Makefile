# Native components of ray_tpu. `make native` builds the CPython extension
# in-place; ray_tpu/_native auto-invokes this on first import if the .so is
# missing (g++ is part of the supported toolchain).

PY       ?= python3
PY_INC   := $(shell $(PY) -c "import sysconfig; print(sysconfig.get_paths()['include'])")
CXX      ?= g++
CXXFLAGS ?= -O2 -g -std=c++17 -fPIC -Wall -Wextra
LDLIBS   := -lpthread -lrt

STORE_SRC := src/store/rts_store.cc
EXT       := ray_tpu/_native/_rtstore.so

.PHONY: native native-test clean

native: $(EXT)

$(EXT): $(STORE_SRC) src/store/_rtstore_module.cc src/store/rts_store.h
	$(CXX) $(CXXFLAGS) -shared -I$(PY_INC) -Isrc/store \
	  $(STORE_SRC) src/store/_rtstore_module.cc -o $@ $(LDLIBS)

build/rts_store_test: $(STORE_SRC) src/store/rts_store_test.cc src/store/rts_store.h
	@mkdir -p build
	$(CXX) $(CXXFLAGS) -Isrc/store $(STORE_SRC) src/store/rts_store_test.cc \
	  -o $@ $(LDLIBS)

native-test: build/rts_store_test
	./build/rts_store_test

clean:
	rm -rf build $(EXT)
