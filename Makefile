# Native components of ray_tpu. `make native` builds the CPython extension
# in-place; ray_tpu/_native auto-invokes this on first import if the .so is
# missing (g++ is part of the supported toolchain).

PY       ?= python3
PY_INC   := $(shell $(PY) -c "import sysconfig; print(sysconfig.get_paths()['include'])")
CXX      ?= g++
CXXFLAGS ?= -O2 -g -std=c++17 -fPIC -Wall -Wextra
LDLIBS   := -lpthread -lrt

STORE_SRC := src/store/rts_store.cc
EXT       := ray_tpu/_native/_rtstore.so
PUMP_SRC  := src/pump/rts_pump.cc
PUMP_EXT  := ray_tpu/_native/_rtpump.so

.PHONY: native native-test native-ubsan cpp-client clean check check-slow check-obs check-metrics rtlint perf-transfer perf-actor perf-native perf-dispatch perf-train train-smoke train-chaos chaos overload

# Static analysis: the rtlint distributed-invariant analyzer (pass
# catalog: python -m tools.rtlint --list). Exits non-zero on any
# finding that is neither baselined (tools/rtlint/baseline.json) nor
# pragma-suppressed (# rtlint: disable=<pass>).
rtlint:
	$(PY) -m tools.rtlint

# Observability lint (the "obs" pass group of rtlint; the old
# tools/check_metric_names.py entry point remains as an alias shim):
# every Counter/Gauge/Histogram the package declares at import time
# plus event emit sites, chaos registry, pickle bans, serve hot path.
check-obs:
	$(PY) -m tools.rtlint --passes obs

# Historical alias for check-obs.
check-metrics: check-obs

# Fast CPU smoke of the compiled training step (2-layer, chunk=1, one
# fused pjit step with donation): a pjit/scan regression fails here in
# seconds, before any TPU bench run sees it.
train-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/run_train_smoke.py

# Training-step A/B matrix: sweeps scan x chunk x remat x donation x
# depth through bench.py's worker gang (fresh chip state per row) and
# writes per-config rows + the machine-picked winners to BENCH_AB.json
# (tokens/s, MFU, peak HBM, allocator fragmentation). On a TPU host run
# WITHOUT JAX_PLATFORMS=cpu.
perf-train:
	RAY_TPU_BENCH_AB=1 $(PY) bench.py

# CI umbrella: the full static-analysis plane + the sanitized native
# build/tests + the compiled-train-step smoke. Tier-1 docs point here.
# (rtlint already includes the obs pass group, so check-obs is not
# repeated.)
check: rtlint native-test train-smoke

# Slow tier of `make check`: the multi-minute acceptance suites — the
# chaos partition matrix, the overload closed loop, and the elastic
# train-gang chaos run (gang restart + checkpoint fallback + rolling
# restart under an active fit -> MULTICHIP_r06.json).
check-slow: check chaos overload train-chaos

# Elastic gang lifecycle acceptance: multi-process jax.distributed
# rendezvous (2 procs x 4 virtual devices, GCS-KV-brokered
# coordinator), rank killed mid-step -> restart from the last COMMITTED
# checkpoint (trajectory must match an uninterrupted run), a
# checkpoint_io fault during save -> fall back to the previous commit,
# and Cluster.rolling_restart() under an active fit (<= 1 step lost).
# Records MULTICHIP_r06.json.
train-chaos:
	JAX_PLATFORMS=cpu $(PY) tools/run_train_chaos.py MULTICHIP_r06.json
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_train_elastic.py -q \
	  -p no:cacheprovider

# Chaos plane acceptance suite: the full fault-injection partition
# matrix (every registered point proves its advertised degradation path
# with exactly-once semantics) plus the drain + rolling-restart tests
# (every worker node of a live 3-node cluster replaced under a serving
# deployment with zero failed requests).
# The fencing half (tests/test_fencing.py + tools/run_fence_chaos.py)
# proves the asymmetric-partition scenario end to end — sticky
# heartbeat partition, node fenced at a membership epoch, actor
# restarted on a survivor with zero double-executions and zero stale
# results, zombie self-termination + fresh-incarnation rejoin — and
# records the numbers into OVERLOAD_r02.json.
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_faults.py \
	  tests/test_fencing.py -q -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) tools/run_fence_chaos.py OVERLOAD_r02.json

# Overload-control acceptance: the request-robustness test matrix
# (deadline refusal/cancellation, adaptive shedding, breaker
# open/half-open/close under chaos-armed latency on one replica) plus
# the closed-loop overload bench recorded to OVERLOAD_r01.json.
overload:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_overload.py -q \
	  -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) tools/run_overload_bench.py OVERLOAD_r01.json

# Cross-node transfer bench: 2-node loopback, 256 MiB object through the
# striped data plane, JSON GB/s + concurrent control-plane ping p99.
perf-transfer:
	JAX_PLATFORMS=cpu $(PY) tools/run_transfer_bench.py

# Direct actor-call plane bench: loaded + unloaded sync round-trips over
# the GIL-free dispatch core (native pump + pending/waiter tables
# engaged AND RTPU_NO_NATIVE=1 fallback) vs the NM-mediated path, the
# per-phase GIL-handoff probe, the 1M-queued drain row with driver RSS,
# fallback-injection recovery, and the rpc dispatch micro-bench —
# merged into PERF_r09.json.
perf-actor:
	JAX_PLATFORMS=cpu $(PY) tools/run_actor_bench.py PERF_r09.json

# Native frame-pump bench: codec microbench vs pickle on the compact
# call frame, pump framing throughput, and the queued-task drain probe
# — merged into PERF_r09.json beside the perf-actor record.
perf-native:
	JAX_PLATFORMS=cpu $(PY) tools/run_native_bench.py PERF_r09.json

# Control-plane dispatch bench: per-op stage p50/p99 for the NM/GCS
# frame loops under a mixed workload (the numbers `rtpu rpc` shows),
# event-loop lag + GIL-proxy series liveness, and the obs_overhead row
# (instrumented vs RTPU_NO_DISPATCH_OBS=1, bar <= 3%).
perf-dispatch:
	JAX_PLATFORMS=cpu $(PY) tools/run_dispatch_bench.py PERF_r10_baseline.json

native: $(EXT) $(PUMP_EXT)

# C++ client frontend (ref analogue: the reference's cpp/ worker API):
# zero-copy arena object plane + JSON control channel. `make cpp-client`
# builds the demo driver tests/test_cpp_client.py runs.
build/rtpu_demo: cpp/rtpu_client.cc cpp/rtpu_demo.cc cpp/rtpu_client.h $(STORE_SRC)
	@mkdir -p build
	$(CXX) $(CXXFLAGS) -Isrc/store -Icpp cpp/rtpu_client.cc cpp/rtpu_demo.cc \
	  $(STORE_SRC) -o $@ $(LDLIBS)

cpp-client: build/rtpu_demo

$(EXT): $(STORE_SRC) src/store/_rtstore_module.cc src/store/rts_store.h
	$(CXX) $(CXXFLAGS) -shared -I$(PY_INC) -Isrc/store \
	  $(STORE_SRC) src/store/_rtstore_module.cc -o $@ $(LDLIBS)

$(PUMP_EXT): $(PUMP_SRC) src/pump/_rtpump_module.cc src/pump/rts_pump.h
	$(CXX) $(CXXFLAGS) -shared -I$(PY_INC) -Isrc/pump \
	  $(PUMP_SRC) src/pump/_rtpump_module.cc -o $@ $(LDLIBS)

build/rts_store_test: $(STORE_SRC) src/store/rts_store_test.cc src/store/rts_store.h
	@mkdir -p build
	$(CXX) $(CXXFLAGS) -Isrc/store $(STORE_SRC) src/store/rts_store_test.cc \
	  -o $@ $(LDLIBS)

build/rts_pump_test: $(PUMP_SRC) src/pump/rts_pump_test.cc src/pump/rts_pump.h
	@mkdir -p build
	$(CXX) $(CXXFLAGS) -Isrc/pump $(PUMP_SRC) src/pump/rts_pump_test.cc \
	  -o $@ $(LDLIBS)

# CI-ready native gate: every C++ unit test (store + pump) plain AND
# under all three sanitizers — any report fails the target
# (halt_on_error / -fno-sanitize-recover). The pump test includes the
# ISSUE 12 pending-table stress (a pipelined submitter parked on the
# backpressure condvar vs a completer applying DONE frames, then an
# injected channel death mid-stream with exactly-once accounting) —
# the TSAN/ASAN/UBSAN builds are the lock-discipline gate for the
# GIL-free dispatch core.
native-test: build/rts_store_test build/rts_pump_test native-tsan native-asan native-ubsan
	./build/rts_store_test
	./build/rts_pump_test

clean:
	rm -rf build $(EXT) $(PUMP_EXT)

# Sanitizer builds of the C++ unit tests (ref analogue: the reference's
# TSAN/ASAN CI jobs over the C++ core). `make native-tsan native-asan`
# runs the store AND pump tests under each sanitizer.
build/rts_store_test_tsan: $(STORE_SRC) src/store/rts_store_test.cc src/store/rts_store.h
	@mkdir -p build
	$(CXX) $(CXXFLAGS) -fsanitize=thread -Isrc/store $(STORE_SRC) \
	  src/store/rts_store_test.cc -o $@ $(LDLIBS)

build/rts_store_test_asan: $(STORE_SRC) src/store/rts_store_test.cc src/store/rts_store.h
	@mkdir -p build
	$(CXX) $(CXXFLAGS) -fsanitize=address,undefined -Isrc/store $(STORE_SRC) \
	  src/store/rts_store_test.cc -o $@ $(LDLIBS)

build/rts_pump_test_tsan: $(PUMP_SRC) src/pump/rts_pump_test.cc src/pump/rts_pump.h
	@mkdir -p build
	$(CXX) $(CXXFLAGS) -fsanitize=thread -Isrc/pump $(PUMP_SRC) \
	  src/pump/rts_pump_test.cc -o $@ $(LDLIBS)

build/rts_pump_test_asan: $(PUMP_SRC) src/pump/rts_pump_test.cc src/pump/rts_pump.h
	@mkdir -p build
	$(CXX) $(CXXFLAGS) -fsanitize=address,undefined -Isrc/pump $(PUMP_SRC) \
	  src/pump/rts_pump_test.cc -o $@ $(LDLIBS)

native-tsan: build/rts_store_test_tsan build/rts_pump_test_tsan
	TSAN_OPTIONS=halt_on_error=1 ./build/rts_store_test_tsan
	TSAN_OPTIONS=halt_on_error=1 ./build/rts_pump_test_tsan

native-asan: build/rts_store_test_asan build/rts_pump_test_asan
	ASAN_OPTIONS=detect_leaks=1:halt_on_error=1 ./build/rts_store_test_asan
	ASAN_OPTIONS=detect_leaks=1:halt_on_error=1 ./build/rts_pump_test_asan

# Standalone UBSAN builds (the ASAN combo above folds undefined in, but
# a dedicated -fsanitize=undefined build catches UB that ASAN's shadow
# memory masks, and -fno-sanitize-recover=undefined turns every report
# into a hard failure instead of a log line).
build/rts_store_test_ubsan: $(STORE_SRC) src/store/rts_store_test.cc src/store/rts_store.h
	@mkdir -p build
	$(CXX) $(CXXFLAGS) -fsanitize=undefined -fno-sanitize-recover=undefined \
	  -Isrc/store $(STORE_SRC) src/store/rts_store_test.cc -o $@ $(LDLIBS)

build/rts_pump_test_ubsan: $(PUMP_SRC) src/pump/rts_pump_test.cc src/pump/rts_pump.h
	@mkdir -p build
	$(CXX) $(CXXFLAGS) -fsanitize=undefined -fno-sanitize-recover=undefined \
	  -Isrc/pump $(PUMP_SRC) src/pump/rts_pump_test.cc -o $@ $(LDLIBS)

native-ubsan: build/rts_store_test_ubsan build/rts_pump_test_ubsan
	UBSAN_OPTIONS=print_stacktrace=1:halt_on_error=1 ./build/rts_store_test_ubsan
	UBSAN_OPTIONS=print_stacktrace=1:halt_on_error=1 ./build/rts_pump_test_ubsan

sanitize: native-tsan native-asan native-ubsan
