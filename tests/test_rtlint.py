"""rtlint framework + pass tests.

Each pass gets fixture files: known-bad snippets must produce the
expected finding, known-good ones must stay clean. The framework tests
cover the baseline round-trip (line-move tolerant fingerprints), inline
pragmas, and the CLI contract the Makefile relies on. The codec-drift
test mutates a field key in a temp copy of the real codec surface and
asserts detection — the exact skew the pass exists to catch.

Pure stdlib + tools.rtlint: no cluster, no jax, tier-1 fast.
"""

import json
import os
import shutil
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.rtlint.cli import main as rtlint_main, select_passes, build_passes  # noqa: E402
from tools.rtlint.core import Context, load_baseline  # noqa: E402
from tools.rtlint.passes.codec_mirror import CodecMirrorPass  # noqa: E402
from tools.rtlint.passes.lock_order import LockOrderPass  # noqa: E402
from tools.rtlint.passes.loop_blocking import LoopBlockingPass  # noqa: E402
from tools.rtlint.passes.swallowed_failure import SwallowedFailurePass  # noqa: E402


def _write(root, rel, content):
    path = os.path.join(root, *rel.split("/"))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(textwrap.dedent(content))
    return path


def _run_pass(pass_obj, root):
    return pass_obj.run(Context(str(root)))


# ---------------------------------------------------------- loop-blocking


class FixtureLoopPass(LoopBlockingPass):
    modules = ("nm.py",)


def test_loop_blocking_flags_reachable_blocking_calls(tmp_path):
    _write(tmp_path, "nm.py", """\
        import subprocess
        import time

        class NM:
            async def _dispatch(self):
                self._helper()

            def _helper(self):
                time.sleep(0.5)
                subprocess.Popen(["true"])
    """)
    findings = _run_pass(FixtureLoopPass(), tmp_path)
    labels = {f.message for f in findings}
    assert any("time.sleep()" in m for m in labels), labels
    assert any("subprocess.Popen()" in m for m in labels), labels
    # The chain names the async root and the helper hop.
    assert any("_dispatch -> _helper" in m for m in labels), labels


def test_loop_blocking_acquire_and_unawaited_attrs(tmp_path):
    _write(tmp_path, "nm.py", """\
        class NM:
            async def _serve(self):
                self._lock.acquire()
                data = self._conn.recv()
                return data

            async def _bounded(self):
                self._lock.acquire(timeout=1.0)
    """)
    findings = _run_pass(FixtureLoopPass(), tmp_path)
    msgs = [f.message for f in findings]
    assert any(".acquire() without timeout" in m for m in msgs), msgs
    assert any(".recv()" in m for m in msgs), msgs
    # acquire(timeout=...) is bounded: not flagged.
    assert not any(f.line > 6 for f in findings), msgs


def test_loop_blocking_clean_patterns(tmp_path):
    _write(tmp_path, "nm.py", """\
        import asyncio
        import time

        def _blocking_helper():
            time.sleep(5)  # executor-only: not loop-reachable

        class NM:
            async def _dispatch(self):
                await asyncio.sleep(0.1)
                await self._loop.run_in_executor(None, _blocking_helper)
                await self._peer.request({"type": "ping"})

            def _thread_main(self):
                time.sleep(1.0)  # never called from a coroutine
    """)
    assert _run_pass(FixtureLoopPass(), tmp_path) == []


def test_loop_blocking_callback_roots(tmp_path):
    _write(tmp_path, "nm.py", """\
        import time

        class NM:
            def _arm(self):
                self._loop.call_soon(self._tick)

            def _tick(self):
                time.sleep(0.2)
    """)
    findings = _run_pass(FixtureLoopPass(), tmp_path)
    assert any("time.sleep()" in f.message for f in findings)


# ------------------------------------------------------------- lock-order


class FixtureLockPass(LockOrderPass):
    scan_dirs = ("pkg",)


def test_lock_order_inversion_detected(tmp_path):
    _write(tmp_path, "pkg/mod.py", """\
        import threading

        class Table:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def fwd(self):
                with self._a:
                    with self._b:
                        pass

            def rev(self):
                with self._b:
                    with self._a:
                        pass
    """)
    findings = _run_pass(FixtureLockPass(), tmp_path)
    assert len(findings) == 1
    f = findings[0]
    assert "lock-order inversion" in f.message
    assert "Table._a" in f.message and "Table._b" in f.message


def test_lock_order_self_deadlock_through_call(tmp_path):
    _write(tmp_path, "pkg/mod.py", """\
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()

            def put(self):
                with self._lock:
                    self._evict()

            def _evict(self):
                with self._lock:
                    pass
    """)
    findings = _run_pass(FixtureLockPass(), tmp_path)
    assert len(findings) == 1
    assert "guaranteed deadlock" in findings[0].message


def test_lock_order_reentrant_and_ordered_nesting_clean(tmp_path):
    _write(tmp_path, "pkg/mod.py", """\
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.RLock()
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._cv = threading.Condition(self._a)

            def put(self):
                with self._lock:
                    self._evict()

            def _evict(self):
                with self._lock:
                    pass

            def consistent_one(self):
                with self._a:
                    with self._b:
                        pass

            def consistent_two(self):
                with self._cv:  # aliases _a: same order as consistent_one
                    with self._b:
                        pass
    """)
    assert _run_pass(FixtureLockPass(), tmp_path) == []


def test_lock_order_native_wait_under_lock(tmp_path):
    # ISSUE 12 convention: the pending table's wait_below (a native
    # condvar signalled by the reader's completion path) must be
    # entered lock-free — direct and one-call-hop violations flagged,
    # the lock-free shape clean.
    _write(tmp_path, "pkg/mod.py", """\
        import threading

        class Chan:
            def __init__(self, table):
                self._lock = threading.Lock()
                self.table = table

            def bad_direct(self):
                with self._lock:
                    self.table.wait_below(1024, 0.25)

            def bad_one_hop(self):
                with self._lock:
                    self._park()

            def _park(self):
                self.table.wait_below(1024, 0.25)

            def good(self):
                self._park()
                with self._lock:
                    pass
    """)
    findings = _run_pass(FixtureLockPass(), tmp_path)
    assert len(findings) == 2
    assert all("native dispatch-core wait" in f.message for f in findings)
    assert any("native wait inside" in f.message for f in findings)


def test_codec_mirror_detects_table_api_drift():
    """Deleting a shared dispatch-table method from the mirror (or its
    C binding) is a finding — the two implementations are one API."""
    from tools.rtlint.passes import codec_mirror as cm

    class Probe(CodecMirrorPass):
        pass

    ctx = Context(REPO_ROOT)
    saved = cm.TABLE_API
    try:
        cm.TABLE_API = dict(saved)
        cm.TABLE_API["PyPendingTable"] = saved["PyPendingTable"] + (
            "not_a_real_method",
        )
        findings = Probe().run(ctx)
        keys = {f.key for f in findings}
        assert "table-method:PyPendingTable.not_a_real_method" in keys
        assert "table-native:not_a_real_method" in keys
    finally:
        cm.TABLE_API = saved


def test_lock_order_condition_alias_inversion(tmp_path):
    # with cv: nests _b, elsewhere with _b: nests the *aliased* lock —
    # the alias map must fold cv onto _a for the cycle to appear.
    _write(tmp_path, "pkg/mod.py", """\
        import threading

        class Q:
            def __init__(self):
                self._a = threading.Lock()
                self._cv = threading.Condition(self._a)
                self._b = threading.Lock()

            def fwd(self):
                with self._cv:
                    with self._b:
                        pass

            def rev(self):
                with self._b:
                    with self._a:
                        pass
    """)
    findings = _run_pass(FixtureLockPass(), tmp_path)
    assert len(findings) == 1
    assert "inversion" in findings[0].message


# ------------------------------------------------------------ codec-mirror

CODEC_FILES = (
    "src/pump/rts_pump.h",
    "src/pump/_rtpump_module.cc",
    "ray_tpu/core/frame_pump.py",
    "ray_tpu/core/protocol.py",
    "ray_tpu/core/runtime.py",
    "ray_tpu/core/worker_main.py",
)


def _codec_tree(tmp_path):
    for rel in CODEC_FILES:
        src = os.path.join(REPO_ROOT, *rel.split("/"))
        dst = os.path.join(tmp_path, *rel.split("/"))
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copy(src, dst)
    return tmp_path


def test_codec_mirror_clean_on_repo():
    assert _run_pass(CodecMirrorPass(), REPO_ROOT) == []


def test_codec_mirror_detects_field_key_drift(tmp_path):
    root = _codec_tree(tmp_path)
    mirror = os.path.join(root, "ray_tpu", "core", "frame_pump.py")
    with open(mirror) as f:
        src = f.read()
    # Rename the seq field key in the mirror's decoded call dict: the
    # native decoder still interns/produces "q".
    assert '"q": seq' in src
    with open(mirror, "w") as f:
        f.write(src.replace('"q": seq', '"qq": seq'))
    findings = _run_pass(CodecMirrorPass(), root)
    assert any('"q"' in f.message for f in findings), \
        [f.message for f in findings]


def test_codec_mirror_detects_magic_drift(tmp_path):
    root = _codec_tree(tmp_path)
    mirror = os.path.join(root, "ray_tpu", "core", "frame_pump.py")
    with open(mirror) as f:
        src = f.read()
    with open(mirror, "w") as f:
        f.write(src.replace("MAGIC = 0xA7", "MAGIC = 0xA8", 1))
    findings = _run_pass(CodecMirrorPass(), root)
    assert any("drift" in f.message and "MAGIC" in f.message
               for f in findings)


def test_codec_mirror_detects_hardcoded_handshake_ver(tmp_path):
    root = _codec_tree(tmp_path)
    runtime = os.path.join(root, "ray_tpu", "core", "runtime.py")
    with open(runtime) as f:
        src = f.read()
    assert '"ver": DIRECT_PROTO_VER' in src
    with open(runtime, "w") as f:
        f.write(src.replace('"ver": DIRECT_PROTO_VER', '"ver": 3', 1))
    findings = _run_pass(CodecMirrorPass(), root)
    assert any("hard-coded" in f.message and "ver" in f.message
               for f in findings)


# -------------------------------------------------------- swallowed-failure


class FixtureSwallowPass(SwallowedFailurePass):
    modules = ("ctl.py",)


def test_swallowed_failure_flags_silent_excepts(tmp_path):
    _write(tmp_path, "ctl.py", """\
        def reconcile():
            try:
                step()
            except Exception:
                pass

        def cleanup():
            try:
                close()
            except:
                x = 1
    """)
    findings = _run_pass(FixtureSwallowPass(), tmp_path)
    assert len(findings) == 2
    assert {"broad except", "bare except"} == {
        f.message.split(" swallows")[0] for f in findings}


def test_swallowed_failure_accepts_surfacing_handlers(tmp_path):
    _write(tmp_path, "ctl.py", """\
        import logging

        log = logging.getLogger(__name__)

        def a():
            try:
                step()
            except Exception:
                raise

        def b():
            try:
                step()
            except Exception as e:
                events.emit(events.WARNING, events.SERVE, str(e))

        def c():
            try:
                step()
            except Exception:
                log.warning("step failed")

        def d():
            try:
                step()
            except Exception:
                FAILURES.inc()

        def e():
            try:
                step()
            except ValueError:
                pass  # narrow except: out of scope for this pass
    """)
    assert _run_pass(FixtureSwallowPass(), tmp_path) == []


def test_swallowed_failure_inner_handler_does_not_surface_outer(tmp_path):
    """A log/raise inside a NESTED except-handler (or a deferred nested
    def) executes for the inner failure, not the outer one — the outer
    broad except still swallows."""
    _write(tmp_path, "ctl.py", """\
        import logging

        log = logging.getLogger(__name__)

        def reconcile():
            try:
                step()
            except Exception:
                try:
                    cleanup()
                except OSError:
                    log.warning("cleanup failed")

        def deferred():
            try:
                step()
            except Exception:
                def _later():
                    raise RuntimeError("never on the handler path")

        def surfaced_by_own_body():
            try:
                step()
            except Exception:
                try:
                    log.warning("step failed")  # handler's own path
                finally:
                    cleanup()
    """)
    findings = _run_pass(FixtureSwallowPass(), tmp_path)
    lines = sorted(f.line for f in findings)
    assert len(findings) == 2, [f"{f.line}: {f.message}" for f in findings]
    assert all(ln < 22 for ln in lines)  # only the first two handlers


def test_update_baseline_refuses_pass_crashes(tmp_path, monkeypatch):
    import tools.rtlint.cli as cli

    class _CrashingPass(SwallowedFailurePass):
        name = "swallowed-failure"

        def run(self, ctx):
            raise RuntimeError("AST API changed")

    monkeypatch.setattr(cli, "build_passes", lambda: [_CrashingPass()])
    baseline = str(tmp_path / "baseline.json")
    rc = cli.main(["--root", str(tmp_path), "--baseline", baseline,
                   "--update-baseline", "-q"])
    assert rc == 1
    assert not os.path.exists(baseline)


def test_swallowed_failure_clean_on_fixed_modules():
    """The PR's satellite fixes (controller reconcile, autoscaler
    reconcile, drain_and_kill) must stay event-emitting."""
    ctx = Context(REPO_ROOT)
    findings = SwallowedFailurePass().run(ctx)
    fixed = {
        ("ray_tpu/serve/controller.py", "reconcile"),
        ("ray_tpu/autoscaler/autoscaler.py", "reconcile"),
    }
    for path, _ in fixed:
        src = open(os.path.join(REPO_ROOT, *path.split("/"))).read()
        assert "reconcile" in src
    # The two reconcile loops emit WARNING events now — no finding may
    # point at those handlers anymore (their except bodies call emit).
    for f in findings:
        ln = f.line
        lines = ctx.lines(f.path)
        window = "\n".join(lines[ln - 1:ln + 8])
        assert "reconcile failed" not in window


# ------------------------------------------------- framework: pragmas, CLI


def test_pragma_suppresses_finding(tmp_path, monkeypatch):
    import tools.rtlint.cli as cli

    _write(tmp_path, "nm.py", """\
        import time

        class NM:
            async def _tick(self):
                time.sleep(0)  # rtlint: disable=loop-blocking

            async def _tock(self):
                # pragma on the line above also suppresses
                # rtlint: disable=loop-blocking
                time.sleep(0)

            async def _naked(self):
                time.sleep(0)
    """)
    monkeypatch.setattr(cli, "build_passes", lambda: [FixtureLoopPass()])
    rc = cli.main(["--root", str(tmp_path), "--no-baseline", "-q"])
    assert rc == 1  # _naked's finding survives
    # Suppress the last one too -> clean.
    src = open(tmp_path / "nm.py").read()
    with open(tmp_path / "nm.py", "w") as f:
        f.write(src.replace(
            "    async def _naked(self):\n        time.sleep(0)\n",
            "    async def _naked(self):\n"
            "        time.sleep(0)  # rtlint: disable=all\n"))
    rc = cli.main(["--root", str(tmp_path), "--no-baseline", "-q"])
    assert rc == 0


def test_cli_list_and_unknown_pass():
    assert rtlint_main(["--list"]) == 0
    assert rtlint_main(["--passes", "no-such-pass", "--list"]) == 0
    assert rtlint_main(["--passes", "no-such-pass"]) == 1


def test_cli_group_selection():
    passes = build_passes()
    obs = select_passes(passes, "obs")
    assert obs and all(p.group == "obs" for p in obs)
    core = select_passes(passes, "core")
    names = {p.name for p in core}
    assert {"loop-blocking", "lock-order", "codec-mirror",
            "swallowed-failure"} <= names
    with pytest.raises(ValueError):
        select_passes(passes, "nope")


def test_repo_core_passes_clean_with_baseline():
    """The acceptance bar: the analyzer's core group exits 0 on the
    repo itself with the checked-in baseline."""
    rc = rtlint_main(["--passes", "core", "-q"])
    assert rc == 0


# ------------------------------------------------- framework: baseline


BAD_CTL = """\
def reconcile():
    try:
        step()
    except Exception:
        pass
"""


class _BaselineSwallowPass(SwallowedFailurePass):
    modules = ("ctl.py",)


def _main_with_fixture_registry(tmp_path, monkeypatch, args):
    """Run the CLI against a registry of fixture-scoped passes."""
    import tools.rtlint.cli as cli

    monkeypatch.setattr(
        cli, "build_passes", lambda: [_BaselineSwallowPass()])
    return cli.main(args)


def test_baseline_round_trip_and_line_move(tmp_path, monkeypatch):
    _write(tmp_path, "ctl.py", BAD_CTL)
    baseline = str(tmp_path / "baseline.json")
    args = ["--root", str(tmp_path), "--baseline", baseline, "-q"]

    # New finding -> exit 1. Record it -> exit 0. Re-run -> still 0.
    assert _main_with_fixture_registry(tmp_path, monkeypatch, args) == 1
    assert _main_with_fixture_registry(
        tmp_path, monkeypatch, args + ["--update-baseline"]) == 0
    assert _main_with_fixture_registry(tmp_path, monkeypatch, args) == 0

    entries = load_baseline(baseline)
    assert len(entries) == 1
    ((pass_name, path, key),) = entries.keys()
    assert pass_name == "swallowed-failure" and path == "ctl.py"
    assert key == "except Exception:"

    # Line-move tolerance: shifting the finding does not break the
    # baseline fingerprint.
    _write(tmp_path, "ctl.py", "# moved\n\n\n" + BAD_CTL)
    assert _main_with_fixture_registry(tmp_path, monkeypatch, args) == 0

    # A SECOND violation exceeds the recorded count -> exit 1.
    _write(tmp_path, "ctl.py", BAD_CTL + """\

def other():
    try:
        step()
    except Exception:
        pass
""")
    assert _main_with_fixture_registry(tmp_path, monkeypatch, args) == 1


def test_subset_update_baseline_preserves_other_passes(tmp_path,
                                                       monkeypatch):
    """--passes <subset> --update-baseline must not wipe the recorded
    debt of passes that did not run."""
    import tools.rtlint.cli as cli

    _write(tmp_path, "ctl.py", BAD_CTL)
    _write(tmp_path, "nm.py", """\
        import time

        class NM:
            async def _tick(self):
                time.sleep(0)
    """)

    class _LoopPass(LoopBlockingPass):
        modules = ("nm.py",)

    baseline = str(tmp_path / "baseline.json")
    monkeypatch.setattr(
        cli, "build_passes",
        lambda: [_BaselineSwallowPass(), _LoopPass()])
    args = ["--root", str(tmp_path), "--baseline", baseline, "-q"]
    # Record both passes' findings, then refresh ONLY loop-blocking.
    assert cli.main(args + ["--update-baseline"]) == 0
    assert cli.main(args + ["--passes", "loop-blocking",
                            "--update-baseline"]) == 0
    entries = load_baseline(baseline)
    assert {fp[0] for fp in entries} == {"swallowed-failure",
                                         "loop-blocking"}
    # Full run still clean: the swallowed entry survived the subset
    # rewrite.
    assert cli.main(args) == 0


def test_baseline_file_format_documents_policy():
    path = os.path.join(REPO_ROOT, "tools", "rtlint", "baseline.json")
    with open(path) as f:
        data = json.load(f)
    assert data["version"] == 1
    assert "debt marker" in data["policy"]
    for entry in data["entries"]:
        assert set(entry) == {"pass", "path", "key", "count"}
        # This PR's baseline carries only the legacy swallowed-failure
        # debt: every other pass runs clean (loop-blocking findings were
        # fixed or pragma-justified in the same change).
        assert entry["pass"] == "swallowed-failure"
