"""Pipeline parallelism + MoE tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.parallel import make_mesh  # noqa: E402
from ray_tpu.parallel.pipeline import pipeline_apply  # noqa: E402
from ray_tpu.parallel.moe import moe_ffn, top_k_routing  # noqa: E402


def _require_8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")


def test_pipeline_matches_sequential():
    _require_8()
    mesh = make_mesh(dp=1, pp=4)
    n_stages, B, D = 4, 8, 16
    rng = np.random.RandomState(0)
    # Each stage: x @ W + b, tanh.
    Ws = jnp.asarray(rng.randn(n_stages, D, D) * 0.1, dtype=jnp.float32)
    bs = jnp.asarray(rng.randn(n_stages, D) * 0.1, dtype=jnp.float32)
    x = jnp.asarray(rng.randn(B, D), dtype=jnp.float32)

    def stage_fn(params, h):
        W, b = params
        return jnp.tanh(h @ W + b)

    expected = x
    for i in range(n_stages):
        expected = stage_fn((Ws[i], bs[i]), expected)

    got = pipeline_apply(
        stage_fn, (Ws, bs), x, mesh, n_microbatches=4
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_grad_flows():
    _require_8()
    mesh = make_mesh(dp=1, pp=4)
    n_stages, B, D = 4, 4, 8
    rng = np.random.RandomState(1)
    Ws = jnp.asarray(rng.randn(n_stages, D, D) * 0.1, dtype=jnp.float32)
    bs = jnp.zeros((n_stages, D), dtype=jnp.float32)
    x = jnp.asarray(rng.randn(B, D), dtype=jnp.float32)

    def stage_fn(params, h):
        W, b = params
        return jnp.tanh(h @ W + b)

    def loss(params):
        out = pipeline_apply(stage_fn, params, x, mesh, n_microbatches=2)
        return (out ** 2).mean()

    g = jax.grad(loss)((Ws, bs))
    assert np.isfinite(np.asarray(g[0])).all()
    assert float(jnp.abs(g[0]).sum()) > 0


def test_top_k_routing_shapes_and_capacity():
    T, E, k, C = 16, 4, 2, 8
    rng = np.random.RandomState(2)
    logits = jnp.asarray(rng.randn(T, E), dtype=jnp.float32)
    dispatch, combine, aux = top_k_routing(logits, k, C)
    assert dispatch.shape == (T, E, C)
    assert combine.shape == (T, E, C)
    # No expert slot double-booked: each (e, c) bucket holds <= 1 token.
    assert float(dispatch.sum(axis=0).max()) <= 1.0 + 1e-6
    # Each token dispatched at most k times.
    assert float(dispatch.sum(axis=(1, 2)).max()) <= k + 1e-6
    assert np.isfinite(float(aux))


def test_moe_ffn_runs_and_differentiates():
    B, S, M, E, F = 2, 8, 16, 4, 32
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(B, S, M) * 0.1, dtype=jnp.float32)
    router_w = jnp.asarray(rng.randn(M, E) * 0.1, dtype=jnp.float32)
    w_in = jnp.asarray(rng.randn(E, M, F) * 0.1, dtype=jnp.float32)
    w_gate = jnp.asarray(rng.randn(E, M, F) * 0.1, dtype=jnp.float32)
    w_out = jnp.asarray(rng.randn(E, F, M) * 0.1, dtype=jnp.float32)

    def loss(ws):
        out, aux = moe_ffn(x, ws[0], ws[1], ws[3], k=2, w_gate=ws[2])
        return (out ** 2).mean() + 0.01 * aux

    val, g = jax.value_and_grad(loss)((router_w, w_in, w_gate, w_out))
    assert np.isfinite(float(val))
    for gi in g:
        assert np.isfinite(np.asarray(gi)).all()


def test_moe_sharded_on_mesh():
    _require_8()
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(dp=2, ep=4)
    B, S, M, E, F = 4, 8, 16, 4, 32
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(B, S, M) * 0.1, dtype=jnp.float32)
    router_w = jnp.asarray(rng.randn(M, E) * 0.1, dtype=jnp.float32)
    w_in = jnp.asarray(rng.randn(E, M, F) * 0.1, dtype=jnp.float32)
    w_out = jnp.asarray(rng.randn(E, F, M) * 0.1, dtype=jnp.float32)
    expected, _ = moe_ffn(x, router_w, w_in, w_out, k=1)

    with jax.set_mesh(mesh) if hasattr(jax, "set_mesh") else mesh:
        xs = jax.device_put(x, NamedSharding(mesh, P("dp")))
        wi = jax.device_put(w_in, NamedSharding(mesh, P("ep")))
        wo = jax.device_put(w_out, NamedSharding(mesh, P("ep")))

        @jax.jit
        def f(x, rw, wi, wo):
            out, aux = moe_ffn(x, rw, wi, wo, k=1)
            return out

        got = f(xs, router_w, wi, wo)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-5, rtol=1e-5)
