"""Model tests: tiny configs, forward/loss/grad, sharded execution."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import (  # noqa: E402
    LlamaConfig,
    causal_lm_loss,
    forward,
    init_params,
    param_logical_axes,
    resnet18,
)


def test_llama_tiny_forward():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 16)))
    logits, aux = jax.jit(lambda p, t: forward(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 16, 256)
    assert np.isfinite(np.asarray(logits)).all()


def test_llama_tiny_loss_and_grad():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 17)))
    loss, grads = jax.jit(
        jax.value_and_grad(lambda p: causal_lm_loss(p, tokens, cfg))
    )(params)
    assert np.isfinite(float(loss))
    flat = jax.tree.leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in flat)
    assert float(loss) > 0


def test_llama_moe_tiny():
    cfg = LlamaConfig.tiny(moe=True)
    params = init_params(cfg, jax.random.PRNGKey(1))
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 256, (2, 9)))
    loss = jax.jit(lambda p: causal_lm_loss(p, tokens, cfg))(params)
    assert np.isfinite(float(loss))


def test_llama_causality():
    """Changing future tokens must not change past logits."""
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(2)
    t1 = rng.randint(0, 256, (1, 12))
    t2 = t1.copy()
    t2[0, -1] = (t2[0, -1] + 7) % 256
    l1, _ = forward(params, jnp.asarray(t1), cfg)
    l2, _ = forward(params, jnp.asarray(t2), cfg)
    np.testing.assert_allclose(
        np.asarray(l1[0, :-1]), np.asarray(l2[0, :-1]), atol=1e-5
    )


def test_llama_sharded_matches_single_device():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from ray_tpu.parallel import make_mesh
    from ray_tpu.parallel.sharding import shard_pytree

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(3).randint(0, 256, (4, 16)))
    expected, _ = forward(params, tokens, cfg)

    mesh = make_mesh(dp=2, sp=2, tp=2)
    sharded_params = shard_pytree(params, mesh, param_logical_axes(cfg))

    @jax.jit
    def f(p, t):
        return forward(p, t, cfg, mesh)[0]

    got = f(sharded_params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=3e-4, rtol=3e-4)


@pytest.mark.slow
def test_resnet18_forward_and_grad():
    import optax

    model = resnet18(num_classes=10, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    x = jnp.asarray(np.random.RandomState(0).rand(4, 32, 32, 3), jnp.float32)
    y = jnp.asarray([0, 1, 2, 3])
    variables = model.init(rng, x, train=True)

    def loss_fn(params):
        logits, updates = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"],
        )
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    loss, g = jax.value_and_grad(loss_fn)(variables["params"])
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))
