"""DAG API tests (ref analogue: python/ray/dag/tests/)."""

import ray_tpu
from ray_tpu.dag import InputNode


def test_function_dag_diamond(ray_tpu_start):
    @ray_tpu.remote
    def double(x):
        return x * 2

    @ray_tpu.remote
    def inc(x):
        return x + 1

    @ray_tpu.remote
    def add(a, b):
        return a + b

    with InputNode() as inp:
        d = double.bind(inp)
        i = inc.bind(inp)
        dag = add.bind(d, i)
    assert ray_tpu.get(dag.execute(10)) == 20 + 11
    # Re-executable with different inputs.
    assert ray_tpu.get(dag.execute(1)) == 2 + 2


def test_shared_node_executes_once(ray_tpu_start):
    import numpy as np

    @ray_tpu.remote
    def noisy():
        return np.random.RandomState().randint(1 << 30)

    @ray_tpu.remote
    def pair(a, b):
        return (a, b)

    shared = noisy.bind()
    dag = pair.bind(shared, shared)
    a, b = ray_tpu.get(dag.execute())
    assert a == b  # one execution, result reused


def test_actor_dag(ray_tpu_start):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.v = start

        def add(self, x):
            self.v += x
            return self.v

    with InputNode() as inp:
        counter = Counter.bind(100)
        dag = counter.add.bind(inp)
    assert ray_tpu.get(dag.execute(5)) == 105
