"""Mesh/sharding/collectives/ring-attention tests on the virtual 8-device
CPU mesh (SURVEY.md §4: multi-chip semantics tested on one machine)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from ray_tpu.parallel import (  # noqa: E402
    MeshConfig,
    make_mesh,
    logical_to_spec,
    prune_spec,
    named_sharding,
    ring_attention,
)
from ray_tpu.ops.attention import mha_attention  # noqa: E402


def _require_8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")


def test_mesh_config_resolve():
    cfg = MeshConfig(dp=-1, tp=2).resolve(8)
    assert cfg.dp == 4 and cfg.tp == 2
    with pytest.raises(ValueError):
        MeshConfig(dp=3, tp=3).resolve(8)  # needs 9 > 8 devices
    with pytest.raises(ValueError):
        MeshConfig(dp=-1, tp=3).resolve(8)  # 8 not divisible by 3
    # Explicit sub-mesh is allowed (uses a device subset).
    cfg2 = MeshConfig(dp=2, tp=2).resolve(8)
    assert cfg2.dp == 2 and cfg2.tp == 2


def test_make_mesh_axes():
    _require_8()
    mesh = make_mesh(dp=2, sp=2, tp=2)
    assert mesh.shape["dp"] == 2
    assert mesh.shape["tp"] == 2
    assert mesh.shape["fsdp"] == 1


def test_logical_to_spec_rules():
    spec = logical_to_spec(("batch", "seq", "heads", "head_dim"))
    assert spec == P(("dp", "fsdp"), "sp", "tp", None)
    # duplicate mesh axis consumed once
    spec2 = logical_to_spec(("heads", "vocab"))
    assert spec2 == P("tp", None)


def test_prune_spec():
    _require_8()
    mesh = make_mesh(dp=8)  # all other axes size 1
    spec = prune_spec(mesh, P(("dp", "fsdp"), "sp", "tp", None))
    assert spec == P("dp")


def test_shard_array_across_mesh():
    _require_8()
    mesh = make_mesh(dp=4, tp=2)
    x = jnp.arange(64.0).reshape(8, 8)
    sharded = jax.device_put(x, named_sharding(mesh, ("batch", "heads")))
    assert len(sharded.addressable_shards) == 8
    assert sharded.addressable_shards[0].data.shape == (2, 4)
    np.testing.assert_array_equal(np.asarray(sharded), np.asarray(x))


def test_psum_under_shard_map():
    _require_8()
    from ray_tpu.parallel import allreduce

    mesh = make_mesh(dp=8)

    def f(x):
        return allreduce(x, "dp")

    x = jnp.arange(8.0)
    out = jax.shard_map(
        f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"), check_vma=False
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_local(causal):
    _require_8()
    mesh = make_mesh(sp=8)
    B, S, H, D = 2, 64, 4, 16
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S, H, D), dtype=jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), dtype=jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), dtype=jnp.float32)
    expected = mha_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_local(causal):
    _require_8()
    mesh = make_mesh(sp=4)
    B, S, H, D = 2, 32, 8, 8
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(B, S, H, D), dtype=jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), dtype=jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), dtype=jnp.float32)
    expected = mha_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, causal=causal, impl="ulysses")
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_gqa():
    _require_8()
    mesh = make_mesh(sp=4)  # dp absorbs the other 2 devices
    B, S, H, Hkv, D = 2, 32, 8, 2, 8
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(B, S, H, D), dtype=jnp.float32)
    k = jnp.asarray(rng.randn(B, S, Hkv, D), dtype=jnp.float32)
    v = jnp.asarray(rng.randn(B, S, Hkv, D), dtype=jnp.float32)
    expected = mha_attention(q, k, v, causal=True)
    from functools import partial
    from ray_tpu.parallel import ring_attention_shard
    from ray_tpu.parallel.sharding import prune_spec as ps

    spec = ps(mesh, P(("dp", "fsdp"), "sp", None, None))
    got = jax.shard_map(
        partial(ring_attention_shard, causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)


def test_collective_group_barrier(ray_tpu_start):
    import ray_tpu

    @ray_tpu.remote
    def rank_task(world, rank):
        from ray_tpu.parallel import init_collective_group

        g = init_collective_group(world, rank, "test_group")
        g.barrier(timeout_s=30)
        val = g.broadcast_obj({"x": 42} if rank == 0 else None, root=0)
        return val["x"]

    out = ray_tpu.get([rank_task.remote(3, r) for r in range(3)])
    assert out == [42, 42, 42]
