"""Operator-DAG plans (zip/union fan-in) + resource-aware backpressure
(ref analogue: the operator graph in
data/_internal/execution/streaming_executor_state.py and the policies in
data/_internal/execution/backpressure_policy/)."""

import time

import numpy as np
import pytest

import ray_tpu.data as rd
from ray_tpu.data.context import DataContext


def test_union_local():
    a = rd.from_items([{"x": i} for i in range(6)])
    b = rd.from_items([{"x": 100 + i} for i in range(4)])
    u = a.union(b)
    xs = [r["x"] for r in u.take_all()]
    assert xs == list(range(6)) + [100 + i for i in range(4)]
    assert u.count() == 10
    assert u.num_blocks() == a.num_blocks() + b.num_blocks()


def test_union_multiway_with_transform_local():
    a = rd.range(5).map(lambda r: {"id": r["id"] * 10})
    b = rd.range(3)
    c = rd.range(2).map(lambda r: {"id": -r["id"]})
    u = a.union(b, c).map(lambda r: {"id": r["id"] + 1})
    ids = [r["id"] for r in u.take_all()]
    assert ids == [1, 11, 21, 31, 41, 1, 2, 3, 1, 0]


def test_zip_local():
    # from_items stripes rows across blocks; both sides stripe
    # identically, so zip stays row-aligned (y == 2x pairwise).
    a = rd.from_items([{"x": i} for i in range(8)], override_num_blocks=4)
    b = rd.from_items([{"y": i * 2} for i in range(8)],
                      override_num_blocks=4)
    z = a.zip(b)
    rows = z.take_all()
    assert sorted(r["x"] for r in rows) == list(range(8))
    assert all(r["y"] == 2 * r["x"] for r in rows)


def test_zip_name_collision_suffix_local():
    a = rd.from_items([{"v": i} for i in range(4)], override_num_blocks=2)
    b = rd.from_items([{"v": -i} for i in range(4)],
                      override_num_blocks=2)
    rows = a.zip(b).take_all()
    assert sorted(r["v"] for r in rows) == [0, 1, 2, 3]
    assert all(r["v_1"] == -r["v"] for r in rows)


def test_zip_block_mismatch_raises_local():
    a = rd.from_items([{"x": i} for i in range(8)], override_num_blocks=4)
    b = rd.from_items([{"y": i} for i in range(8)], override_num_blocks=2)
    with pytest.raises(ValueError, match="zip"):
        a.zip(b).take_all()


def test_union_zip_distributed(ray_tpu_start):
    a = rd.range(6, override_num_blocks=3).map(
        lambda r: {"id": r["id"], "sq": r["id"] ** 2}
    )
    b = rd.range(6, override_num_blocks=3).map(
        lambda r: {"cube": r["id"] ** 3}
    )
    z = a.zip(b)
    rows = z.take_all()
    assert [r["sq"] for r in rows] == [i * i for i in range(6)]
    assert [r["cube"] for r in rows] == [i ** 3 for i in range(6)]

    u = a.union(a).map(lambda r: {"id": r["id"]})
    assert u.count() == 12
    # downstream global op over a DAG plan (forces the materialize path)
    assert sorted(r["id"] for r in u.random_shuffle().take_all()) == sorted(
        list(range(6)) * 2
    )


def test_union_streams_without_driver_materialize(ray_tpu_start):
    """Union output arrives as refs (streaming fan-in), and stats record
    the union node."""
    a = rd.range(4, override_num_blocks=2)
    b = rd.range(4, override_num_blocks=2)
    u = a.union(b)
    total = u.count()
    assert total == 8
    s = u.stats()
    assert "Union" in s


def test_streaming_split_over_union(ray_tpu_start):
    """streaming_split of a DAG plan goes through the shared coordinator
    (no upfront materialize): every row arrives exactly once across
    shards, consumed concurrently."""
    import threading

    a = rd.range(8, override_num_blocks=4)
    b = rd.range(8, override_num_blocks=4).map(
        lambda r: {"id": r["id"] + 100}
    )
    u = a.union(b)
    shards = u.streaming_split(2)
    got = [[], []]

    def consume(i):
        for row in shards[i].iter_rows():
            got[i].append(row["id"])

    ts = [threading.Thread(target=consume, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    allv = sorted(got[0] + got[1])
    assert allv == sorted(list(range(8)) + [i + 100 for i in range(8)])
    assert got[0] and got[1]  # both shards actually consumed


def test_store_backpressure_bounds_producer():
    """A slow consumer must bound producer memory: with the store-usage
    policy active, in-store bytes stay under the cap while blocks are
    consumed one at a time (ref: resource-aware backpressure policies)."""
    import ray_tpu
    from ray_tpu.core.runtime_context import current_runtime

    ray_tpu.init(num_cpus=2, object_store_memory=256 * 1024 * 1024,
                 system_config={"log_to_driver": False,
                                "gc_grace_period_s": 0.5})
    ctx = DataContext.get_current()
    old_frac, old_inflight = (ctx.store_usage_cap_fraction,
                              ctx.max_in_flight_tasks)
    ctx.store_usage_cap_fraction = 0.25
    ctx.max_in_flight_tasks = 16  # without the store policy: way ahead
    try:
        nm = current_runtime()._nm
        cap = nm.directory.capacity_bytes
        assert cap > 0
        block_bytes = 2 * 1024 * 1024
        nblocks = 40
        window = ctx.max_in_flight_tasks

        def gen_block(r):
            return {"data": np.zeros(block_bytes // 8, dtype=np.float64)}

        def run_consumer():
            ds = rd.range(nblocks, override_num_blocks=nblocks).map_batches(
                gen_block, batch_size=None
            )
            peak = seen = 0
            for ref in ds.iter_blocks_refs():
                peak = max(peak, nm.directory.used_bytes)
                seen += 1
                time.sleep(0.04)  # slow consumer
                del ref
            assert seen == nblocks
            return peak

        peak_on = run_consumer()
        # Hard bound: once usage crosses cap*frac, submission stops;
        # only the already-in-flight window can still land.
        assert peak_on <= cap * 0.25 + window * block_bytes, (
            f"peak {peak_on} vs cap {cap}*0.25 + {window} blocks"
        )
        # Contrast: without the store policy the producer free-runs and
        # its peak footprint is materially higher.
        ctx.store_usage_cap_fraction = 0.0
        time.sleep(1.5)  # let the previous run's blocks GC
        peak_off = run_consumer()
        assert peak_off > peak_on, (peak_off, peak_on)
    finally:
        ctx.store_usage_cap_fraction = old_frac
        ctx.max_in_flight_tasks = old_inflight
        ray_tpu.shutdown()
