"""Observability: tracing pipeline, metrics merge/exposition, serve
request telemetry, device metrics (ref analogue: test_metrics_agent.py +
test_tracing.py + serve's metrics tests)."""

import importlib.util
import json
import os
import re
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.util import metrics, prometheus

# ray_tpu.core re-exports the timeline() FUNCTION under the same name as
# the module; grab the module itself.
import ray_tpu.core.timeline  # noqa: E402
import sys  # noqa: E402

timeline = sys.modules["ray_tpu.core.timeline"]


@pytest.fixture
def serve_cluster(ray_tpu_start):
    yield ray_tpu_start
    serve.shutdown()


def _poll(fn, timeout=12.0, interval=0.2):
    """Poll fn() until it returns a truthy value (workers flush metric
    and span buffers on a 0.5s cadence)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(interval)
    return fn()


# ------------------------------------------------------------- tracing


def test_otlp_parent_child_linkage(ray_tpu_start):
    """Nested task spans share one trace; the child's parentSpanId is
    the submitting span's hashed id (satellite: timeline_otlp linkage)."""

    @ray_tpu.remote
    def inner():
        return 1

    @ray_tpu.remote
    def outer():
        return ray_tpu.get(inner.remote())

    assert ray_tpu.get(outer.remote(), timeout=30) == 1

    def spans_ready():
        evs = timeline.timeline()
        names = {e["name"] for e in evs}
        if {"outer", "inner"} <= names:
            return evs
        return None

    evs = _poll(spans_ready)
    by_name = {e["name"]: e for e in evs if e["name"] in ("outer", "inner")}
    assert set(by_name) == {"outer", "inner"}, by_name
    o, i = by_name["outer"]["args"], by_name["inner"]["args"]
    assert o["trace_id"] and o["trace_id"] == i["trace_id"]
    assert i["parent_id"] == o["span_id"]

    payload = timeline.timeline_otlp()
    spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    named = {s["name"]: s for s in spans if s["name"] in ("outer", "inner")}
    assert named["inner"]["parentSpanId"] == named["outer"]["spanId"]
    assert named["inner"]["traceId"] == named["outer"]["traceId"]
    assert named["inner"]["parentSpanId"] == timeline._otlp_id(
        o["span_id"], 8
    )


def test_timeline_chrome_rows_grouped_by_node(ray_tpu_start):
    """Chrome-trace rows group by node (pid) and worker process (tid)
    (satellite: chrome-trace grouping was untested)."""

    @ray_tpu.remote
    def work():
        return os.getpid()

    ray_tpu.get([work.remote() for _ in range(4)], timeout=30)
    evs = _poll(lambda: [e for e in timeline.timeline()
                         if e["name"] == "work"] or None)
    node8 = ray_tpu_start.node_id.hex()[:8]
    for e in evs:
        assert e["pid"] == f"node:{node8}"
        assert e["tid"].startswith("worker:")
        assert e["ph"] == "X"


def test_trace_propagation_proxy_to_replica(serve_cluster):
    """Acceptance: one HTTP request yields a single trace spanning
    proxy -> replica with correct parentSpanId links, honoring the
    incoming W3C traceparent."""

    @serve.deployment
    def obs(x):
        return x

    handle = serve.run(obs.bind(), route_prefix="obs")
    ext_trace = "a" * 32
    ext_span = "b" * 16
    req = urllib.request.Request(
        f"http://127.0.0.1:{handle.http_port}/obs",
        data=json.dumps(7).encode(),
        headers={"Content-Type": "application/json",
                 "traceparent": f"00-{ext_trace}-{ext_span}-01"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert json.loads(resp.read()) == {"result": 7}

    def linked():
        evs = timeline.timeline()
        proxies = [e for e in evs if e["name"] == "http:obs"]
        if not proxies:
            return None
        proxy = proxies[0]["args"]
        children = [
            e for e in evs
            if e["args"]["parent_id"] == proxy["span_id"]
            and e["name"] != "http:obs"
        ]
        return (proxy, children) if children else None

    proxy, children = _poll(linked)
    # The proxy span joined the EXTERNAL trace and parents to it.
    assert proxy["trace_id"] == ext_trace
    assert proxy["parent_id"] == ext_span
    replica_span = children[0]["args"]
    assert replica_span["trace_id"] == ext_trace

    payload = timeline.timeline_otlp()
    spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    trace_spans = [
        s for s in spans if s["traceId"] == timeline._otlp_id(ext_trace, 16)
    ]
    assert len(trace_spans) >= 2  # proxy + replica execution
    proxy_otlp = next(s for s in trace_spans if s["name"] == "http:obs")
    assert proxy_otlp["parentSpanId"] == timeline._otlp_id(ext_span, 8)
    child_otlp = [
        s for s in trace_spans
        if s.get("parentSpanId") == proxy_otlp["spanId"]
    ]
    assert child_otlp, trace_spans


def test_traceparent_parse_and_format():
    assert timeline.parse_traceparent(None) is None
    assert timeline.parse_traceparent("garbage") is None
    assert timeline.parse_traceparent("00-" + "0" * 32 + "-" + "1" * 16
                                      + "-01") is None  # all-zero trace
    tid, sid = "ab" * 16, "cd" * 8
    assert timeline.parse_traceparent(f"00-{tid}-{sid}-01") == (tid, sid)
    hdr = timeline.format_traceparent("1234abcd" * 2, "feed" * 4)
    assert timeline.parse_traceparent(hdr) is not None


# ------------------------------------------------------------- metrics


def test_histogram_merge_union_bounds(ray_tpu_start):
    """Satellite regression: two processes observing one histogram with
    DIFFERENT boundaries merge on the union instead of zip-truncating."""
    import cloudpickle

    h = metrics.Histogram("merge_hist_seconds", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(5.0)
    # A second "process" flushed the same metric with other bounds.
    fake = {
        "merge_hist_seconds": (
            "histogram",
            {(): {"count": 2, "sum": 0.4, "bounds": [0.2],
                  "buckets": [1, 1]}},
            "",
        )
    }
    ray_tpu_start.kv_put("__metrics__/999999", cloudpickle.dumps(fake))
    series = metrics.get_metrics_report()["merge_hist_seconds"]["series"][()]
    assert series["bounds"] == [0.1, 0.2, 1.0]
    assert series["count"] == 4
    assert series["sum"] == pytest.approx(5.45)
    # 0.05 -> le=0.1; fake's (0, 0.2] -> le=0.2; overflows add up.
    assert series["buckets"] == [1, 1, 0, 2]
    assert sum(series["buckets"]) == series["count"]


def test_metric_kind_conflict_warns_and_keeps_first():
    """Satellite: re-registering a name under another kind warns once
    and does NOT corrupt the original series."""
    c = metrics.Counter("kindconflict_metric_total")
    c.inc(2)
    with pytest.warns(UserWarning, match="conflicting kind"):
        g = metrics.Gauge("kindconflict_metric_total")
        g.set(99.0)
    with metrics._registry.lock:
        kind, series = metrics._registry.metrics[
            "kindconflict_metric_total"
        ]
    assert kind == "counter"
    assert series[()] == 2.0  # the gauge write was dropped, not merged


def test_user_lines_help_and_newline_escaping():
    """Satellite: user metrics get # HELP lines; newlines in label
    values are escaped (raw ones corrupt the exposition document)."""
    report = {
        "app_things_total": {
            "type": "counter",
            "help": "Line one\nline two",
            "series": {(("path", 'a\nb"c\\d'),): 3},
        }
    }
    text = "\n".join(prometheus._user_lines(report))
    assert "# HELP app_things_total Line one\\nline two" in text
    assert '# TYPE app_things_total counter' in text
    assert 'path="a\\nb\\"c\\\\d"' in text
    # Exactly 3 lines: HELP, TYPE, and ONE sample (the raw newline in
    # the label value did not split the sample line).
    assert len(text.split("\n")) == 3


def test_serve_request_telemetry(serve_cluster):
    """Acceptance: after a test_serve-style workload the exposition
    contains the serve latency histogram (cumulative, with +Inf),
    ongoing-request gauge, and at least one device series."""
    from ray_tpu.util import device_metrics

    @serve.deployment
    def double(x):
        return x * 2

    handle = serve.run(double.bind(), route_prefix="double")
    futs = [handle.remote(i) for i in range(8)]
    assert [f.result(timeout=30) for f in futs] == [i * 2 for i in range(8)]
    for _ in range(3):
        req = urllib.request.Request(
            f"http://127.0.0.1:{handle.http_port}/double",
            data=json.dumps(21).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert json.loads(resp.read()) == {"result": 42}

    import jax  # noqa: F401 — device sampling is gated on jax presence

    device_metrics._last_sample = 0.0  # defeat the sampling throttle
    text = prometheus.render()
    assert re.search(
        r'ray_tpu_serve_request_latency_seconds_bucket\{deployment="double"'
        r',protocol="http",le="0\.005"\} \d+', text), text[:2000]
    inf = re.search(
        r'ray_tpu_serve_request_latency_seconds_bucket\{deployment="double"'
        r',protocol="http",le="\+Inf"\} (\d+)', text)
    cnt = re.search(
        r'ray_tpu_serve_request_latency_seconds_count\{deployment="double"'
        r',protocol="http"\} (\d+)', text)
    # Cumulative buckets: +Inf equals _count; 3 requests observed here
    # (the process-wide registry may carry observations from other tests
    # in this process, so >= not ==).
    assert inf and cnt and inf.group(1) == cnt.group(1)
    assert int(cnt.group(1)) >= 3
    ok = re.search(
        r'ray_tpu_serve_requests_total\{code="200",deployment="double"'
        r',protocol="http"\} (\d+(\.\d+)?)', text)
    assert ok and float(ok.group(1)) >= 3
    assert "ray_tpu_serve_ongoing_requests" in text
    assert "ray_tpu_device_" in text
    assert "# HELP ray_tpu_serve_request_latency_seconds " in text
    # Core per-task-duration histogram joined the exposition.
    assert "ray_tpu_task_duration_seconds_bucket" in text
    assert re.search(r"ray_tpu_task_duration_seconds_count \d+", text)


def test_replica_queue_and_processing_metrics(serve_cluster):
    """Replica-side queue-wait and execution-time histograms flow back
    through the KV pipeline from the replica worker process."""

    @serve.deployment
    def slowish(x):
        time.sleep(0.02)
        return x

    handle = serve.run(slowish.bind())
    futs = [handle.remote(i) for i in range(6)]
    assert [f.result(timeout=30) for f in futs] == list(range(6))

    def replica_series():
        report = metrics.get_metrics_report()
        proc = report.get("ray_tpu_serve_replica_processing_seconds")
        wait = report.get("ray_tpu_serve_queue_wait_seconds")
        if not proc or not wait:
            return None
        total = sum(v["count"] for v in proc["series"].values())
        return (proc, wait) if total >= 6 else None

    proc, wait = _poll(replica_series)
    (tags_key, point) = next(iter(proc["series"].items()))
    tags = dict(tags_key)
    assert tags["deployment"] == "slowish"
    assert point["sum"] >= 6 * 0.02 * 0.5  # execution time was measured
    assert sum(v["count"] for v in wait["series"].values()) >= 6


def test_dashboard_serve_and_device_routes(serve_cluster):
    """New dashboard JSON routes: /api/serve_metrics and /api/devices."""
    from ray_tpu import dashboard

    @serve.deployment
    def ping(x):
        return x

    handle = serve.run(ping.bind())
    assert handle.remote(1).result(timeout=30) == 1
    port = dashboard.start_dashboard(port=0)
    try:
        def fetch(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=30) as r:
                return json.loads(r.read())

        sm = _poll(lambda: (fetch("/api/serve_metrics")["metrics"]
                            or None))
        assert any(k.startswith("ray_tpu_serve_") for k in sm)
        devices = fetch("/api/devices")
        assert devices["local"], devices  # 8 virtual CPU devices
        assert all("device" in d for d in devices["local"])
    finally:
        dashboard.stop_dashboard()


def test_device_metrics_sample_and_jit_counter(ray_tpu_start):
    """device_metrics: sample() publishes per-device gauges;
    instrumented_jit counts compiles (one per new input shape)."""
    import jax.numpy as jnp

    from ray_tpu.util import device_metrics

    snap = device_metrics.sample(force=True)
    assert len(snap) >= 1
    with metrics._registry.lock:
        kind, series = metrics._registry.metrics["ray_tpu_device_count"]
    assert kind == "gauge"
    # One series per (node, platform); the process registry may carry
    # tags from earlier clusters in this pytest process.
    node = device_metrics.node_tag()
    assert series[
        (("node", node), ("platform", snap[0]["platform"]))
    ] == len(snap)

    calls = {"n": 0}

    def f(x):
        calls["n"] += 1
        return x + 1

    jf = device_metrics.instrumented_jit(f)
    jf(jnp.ones((2,)))
    jf(jnp.ones((2,)))  # cache hit
    jf(jnp.ones((3,)))  # new shape -> recompile
    if not hasattr(jf.__wrapped_jit__, "_cache_size"):
        pytest.skip("jax version lacks _cache_size")
    assert calls["n"] == 2  # traced twice, cached once
    with metrics._registry.lock:
        _, series = metrics._registry.metrics[
            "ray_tpu_device_jit_compiles_total"
        ]
    assert sum(v for k, v in series.items()
               if ("fn", "f") in k) >= 2
    with metrics._registry.lock:
        _, secs = metrics._registry.metrics[
            "ray_tpu_device_jit_compile_seconds_total"
        ]
    assert sum(v for k, v in secs.items() if ("fn", "f") in k) > 0


def test_collective_counters(ray_tpu_start):
    """In-graph collectives count once per trace; host-level broadcast
    counts payload bytes."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.parallel import collectives

    mesh_devices = jax.local_devices()

    @jax.jit
    def summed(x):
        return jax.shard_map(
            lambda v: collectives.allreduce(v, axis="dp"),
            mesh=jax.make_mesh((len(mesh_devices),), ("dp",)),
            in_specs=jax.sharding.PartitionSpec("dp"),
            out_specs=jax.sharding.PartitionSpec("dp"),
        )(x)

    try:
        summed(jnp.ones((len(mesh_devices) * 2,)))
    except Exception:
        # shard_map API drift across jax versions: fall back to counting
        # via the host-level path only.
        pass
    g = collectives.init_collective_group(1, 0, "obs_grp")
    g.barrier(timeout_s=10)
    g.broadcast_obj({"x": 1}, root=0)
    report = metrics.get_metrics_report()
    calls = report.get("ray_tpu_device_collective_calls_total")
    assert calls is not None
    ops = {dict(k).get("op") for k in calls["series"]}
    assert "host_barrier" in ops and "host_broadcast" in ops
    assert "ray_tpu_device_collective_bytes_total" in report


# ------------------------------------------------------------- tooling


def _load_checker():
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "check_metric_names.py")
    spec = importlib.util.spec_from_file_location(
        "check_metric_names", os.path.abspath(path)
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_metric_names_rules():
    """CI lint rules: invalid names, counters missing _total, and kind
    conflicts are all reported; the package's own metrics pass."""
    mod = _load_checker()
    fails = mod.validate(
        {"bad name": ("gauge", ""), "requests": ("counter", ""),
         "ok_total": ("counter", ""), "fine_seconds": ("histogram", "")},
        {"dup": ("counter", "gauge")},
    )
    assert len(fails) == 3
    assert any("bad name" in f for f in fails)
    assert any("requests" in f and "_total" in f for f in fails)
    assert any("dup" in f for f in fails)
    # Everything this test process has declared so far (the whole serve +
    # device metric surface) is lint-clean, except names test cases above
    # registered deliberately.
    declared = {
        k: v for k, v in metrics.declared_metrics().items()
        if k.startswith("ray_tpu_")
    }
    assert declared, "package metrics should be registered by now"
    assert mod.validate(declared, {}) == []
